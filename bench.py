#!/usr/bin/env python
"""Benchmark driver: the five BASELINE.md configs (LeNet-5/MNIST,
VGG-16/CIFAR-10, Inception-v1/ImageNet, LSTM text classifier,
ResNet-50/ImageNet) under the reference's synthetic-data protocol
(``models/utils/DistriOptimizerPerf.scala:33-124`` / LocalOptimizerPerf:
device-resident synthetic data, fixed batch, records/sec after warmup),
plus an efficiency account: per-step FLOPs from XLA's cost analysis,
achieved TFLOP/s, and MFU against the chip's peak.

Prints ONE JSON line: the headline metric (Inception-v1 ImageNet
throughput, the BASELINE.json north star) with a ``configs`` field
carrying every config's images/sec + FLOPs + TFLOP/s + MFU.
The reference publishes no numeric baselines (BASELINE.json
``"published": {}``), so vs_baseline is null.

Env knobs: BENCH_CONFIGS=comma,list  BENCH_ITERS,
BENCH_PEAK_TFLOPS (override the per-chip peak table),
BENCH_BACKEND_TIMEOUT (seconds to wait for backend init before emitting
a backend_init_failed line, default 300).  Warmup is one full (untimed)
scan dispatch — there is no separate warmup knob.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.utils.engine import enable_compile_cache

# at import so every tool built on bench.make_step (profile_bench,
# hlo_dump, batch_sweep, the experiments) inherits the persistent
# executable cache — a cache hit skips the remote-compile RPC, the
# tunnel's observed wedge point.  Implicit: accelerator-only (plain
# CPU opts in via BIGDL_COMPILE_CACHE; see docs/compile.md) and never
# the first backend touch — probe_backend keeps that role; with the
# platform undecidable here, the aot_scan-time call enables it before
# the first real compile anyway
enable_compile_cache(implicit=True)

from bench_constants import HEADLINE, ROUND3_BEST  # shared with tooling

#: peak dense bf16 TFLOP/s per chip (public spec sheets)
PEAK_TFLOPS = {
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v4": 275.0,
    "TPU v5p": 459.0,
    "TPU v5": 459.0,
    "TPU v6e": 918.0,
    "TPU v6 lite": 918.0,
    "TPU v7": 4614.0,
}

#: int8 MXU rate as a multiple of the bf16 peak — 2x on the chips that
#: advertise a doubled int8 rate (v5e: 394 TOPS vs 197 TFLOP/s), 1x
#: where int8 runs at the bf16 rate (v4); the int8 utilization
#: denominator uses this so a genuine win is never misread
INT8_RATIO = {
    "TPU v4": 1.0,
}


def int8_peak_ratio() -> float:
    import jax as _jax

    kind = _jax.devices()[0].device_kind
    for name, r in INT8_RATIO.items():
        if kind.lower().startswith(name.lower()):
            return r
    return 2.0


def zipf_indices(rng, shape, vocab: int, a: float = 1.05) -> np.ndarray:
    """Zipfian ids over ``[0, vocab)``: rank r drawn with P(r) ~ r^-a —
    the hot-row skew real token/id traffic actually has.  The uniform
    sampler the bench used before is the BEST case for an embedding
    (every row equally warm, no hot-row cache/contention behaviour and
    maximal unique rows per batch); embedding legs sample zipfian so the
    sparse-sync win and hot-row behaviour are measured under realistic
    skew (docs/sparse.md)."""
    ranks = np.arange(1, vocab + 1, dtype=np.float64)
    p = ranks ** -a
    p /= p.sum()
    return rng.choice(vocab, size=shape, p=p).astype(np.int32)


def _configs():
    """name -> (build_model, build_batch, criterion, batch).
    ``build_batch(batch, seq=None)``: token configs honor a sequence
    override (the bucketed lstm protocol); image configs ignore it."""
    from bigdl_tpu import models
    import bigdl_tpu.nn as nn

    rng = np.random.default_rng(0)

    def img(batch, c, h, w, classes):
        x = jnp.asarray(rng.normal(size=(batch, c, h, w)).astype(np.float32))
        y = jnp.asarray(rng.integers(0, classes, batch))
        return x, y

    def tokens(batch, seq, vocab, classes, seq_targets=False, zipf=None):
        if zipf is not None:
            x = jnp.asarray(zipf_indices(rng, (batch, seq), vocab, zipf))
        else:
            x = jnp.asarray(rng.integers(0, vocab, (batch, seq),
                                         dtype=np.int32))
        if seq_targets:  # LM: a target token per position
            y = jnp.asarray(rng.integers(0, classes, (batch, seq), dtype=np.int32))
        else:
            y = jnp.asarray(rng.integers(0, classes, batch))
        return x, y

    def dlrm_batch(batch):
        # Criteo-style: 13 integer count features + 8 zipfian
        # categorical ids, one per 50000-row table (models/dlrm.py)
        dense = rng.integers(0, 100, (batch, 13), dtype=np.int32)
        cat = zipf_indices(rng, (batch, 8), 50000, 1.05)
        x = jnp.asarray(np.concatenate([dense, cat], axis=1))
        y = jnp.asarray(rng.integers(0, 2, batch))
        return x, y

    return {
        "lenet_mnist": (
            lambda: models.build_lenet5(10),
            lambda b: img(b, 1, 28, 28, 10), nn.ClassNLLCriterion(), 1024),
        "vgg16_cifar10": (
            lambda: models.build_vgg_for_cifar10(10),
            lambda b: img(b, 3, 32, 32, 10), nn.ClassNLLCriterion(), 512),
        "inception_v1_imagenet": (
            lambda: models.build_inception_v1(1000),
            lambda b: img(b, 3, 224, 224, 1000), nn.ClassNLLCriterion(), 256),
        # zipfian ids since r15 (realistic hot-row skew; uniform was the
        # embedding's best case) and the BUCKETED variable-length
        # protocol (LSTM_BUCKETS below; BENCH_LSTM_BUCKETS=0 restores
        # the fixed-200 leg for old-round comparisons)
        "lstm_text": (
            lambda: models.build_lstm_classifier(5000, class_num=20),
            lambda b, s=None: tokens(b, s or 200, 5000, 20, zipf=1.05),
            nn.ClassNLLCriterion(), 256),
        # representative large recurrent shape: the tiny config above is
        # latency-bound (see BASELINE.md roofline note); this one feeds
        # the MXU a 1536x4096 fused-gate matmul per scan step.  Its
        # 102400-lookup batch touches the whole 20000-row table, so the
        # sparse auto rule keeps its sync DENSE (docs/sparse.md "when
        # dense wins") — the sparse-sync proof shape is `dlrm`
        "lstm_text_large": (
            lambda: models.build_lstm_classifier(
                20000, embed_dim=512, hidden_size=1024, num_layers=2,
                class_num=20),
            lambda b, s=None: tokens(b, s or 200, 20000, 20, zipf=1.05),
            nn.ClassNLLCriterion(), 512),
        # recsys ranking (models/dlrm.py, docs/sparse.md): 8 x 50000-row
        # embedding bags + MLPs + pairwise interaction; a batch touches
        # <= 512 of each table's 50000 rows, so the sparse sync moves
        # ~2% of the dense table all-reduce — the measured sparse win
        "dlrm": (
            lambda: models.build_dlrm(),
            lambda b, s=None: dlrm_batch(b), nn.ClassNLLCriterion(), 512),
        "resnet50_imagenet": (
            lambda: models.build_resnet(50, 1000),
            lambda b: img(b, 3, 224, 224, 1000), nn.ClassNLLCriterion(), 128),
        # decoder-only LM through the Pallas flash-attention path:
        # [batch, seq] tokens -> per-position next-token NLL
        "transformer_lm": (
            lambda: models.build_transformer_lm(
                32000, num_layers=6, embed_dim=512, num_heads=8, max_len=512),
            lambda b: tokens(b, 512, 32000, 32000, seq_targets=True),
            nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), size_average=True),
            32),
        # long-context single-chip: flash attention (O(S) memory) +
        # per-block rematerialization at seq 4096
        "transformer_lm_long": (
            lambda: models.build_transformer_lm(
                32000, num_layers=6, embed_dim=512, num_heads=8,
                max_len=4096, remat=True),
            lambda b: tokens(b, 4096, 32000, 32000, seq_targets=True),
            nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), size_average=True),
            4),
    }


def peak_flops_per_sec():
    if os.environ.get("BENCH_PEAK_TFLOPS"):
        return float(os.environ["BENCH_PEAK_TFLOPS"]) * 1e12
    kind = jax.devices()[0].device_kind
    for name, peak in PEAK_TFLOPS.items():
        if kind.lower().startswith(name.lower()):
            return peak * 1e12
    if kind != "cpu":  # cpu has no meaningful MFU denominator
        print(f"# WARNING: unknown device kind {kind!r} — not in the "
              "PEAK_TFLOPS table, so no 'mfu' field will be reported "
              "(set BENCH_PEAK_TFLOPS to override)", file=sys.stderr)
    return None


def make_step(name: str, batch: int = None, seq: int = None):
    """Build the exact train step a config benches — the shared setup
    recipe (seed, graph passes, SGD 0.9-momentum, bf16 compute) for
    bench.run_config, tools/profile_bench.py, and tools/hlo_dump.py so
    their runtime and compiler views stay views of the SAME program.
    ``seq`` overrides the sequence length on token configs that honor it
    (the bucketed lstm protocol).  Returns (step, x, y)."""
    import bigdl_tpu.optim as optim
    from bigdl_tpu.nn.fuse import optimize_for_tpu
    from bigdl_tpu.parallel.train_step import TrainStep
    from bigdl_tpu.utils.rng import RNG

    build_model, build_batch, criterion, default_batch = _configs()[name]
    RNG.set_seed(0)
    model = optimize_for_tpu(build_model())
    step = TrainStep(model, criterion,
                     optim.SGD(learning_rate=0.01, momentum=0.9),
                     compute_dtype=jnp.bfloat16)
    if seq is None:
        x, y = build_batch(batch or default_batch)
    else:
        x, y = build_batch(batch or default_batch, seq)
    return step, x, y


def make_drain(step):
    """Value-fetch sync: a params-derived scalar forces every queued
    dispatch INCLUDING its optimizer updates (the loss alone only depends
    on params from the previous iteration).  Shared with
    ``tools/scaling_bench.py`` so the timing protocol stays in one place."""
    def drain():
        float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    return drain


def _compile_heartbeat(name, stop_event, max_s=1200.0):
    """Stderr heartbeat while a (legitimately slow) compile is in
    flight, so the leg runner's stall watchdog doesn't kill a healthy
    heavy-compile config (lstm_text_large / transformer_lm_long).
    BOUNDED: after ``max_s`` the heartbeat stops, so a wedged compile
    RPC still stalls out rather than being kept alive forever."""
    t0 = time.monotonic()
    while not stop_event.wait(60.0):
        dt = time.monotonic() - t0
        if dt > max_s:
            return
        # also feed the in-process wedge watchdog: a legit heavy compile
        # is progress; the bound above keeps a wedged RPC mortal
        _last_progress[0] = time.monotonic()
        print(f"# compiling {name}: {dt:.0f}s", file=sys.stderr,
              flush=True)


#: attention geometry per transformer config: (layers, heads, head_dim,
#: seq).  XLA's cost analysis cannot see inside the Pallas flash custom
#: call, so when the auto backend routes a config to flash its S^2
#: matmul FLOPs vanish from the count and MFU is UNDERSTATED (measured:
#: dense seq-512 counted 3,816 GF, flash 3,492 GF for the same model).
#: The correction adds the DENSE-equivalent algorithmic FLOPs
#: (12*L*B*H*S^2*D: 4 fwd + 8 bwd matmul terms — flash's extra
#: recompute is deliberately NOT counted, matching standard MFU
#: practice of counting model FLOPs, not rematerialization).
ATTN_GEOM = {
    "transformer_lm": (6, 8, 64, 512),
    "transformer_lm_long": (6, 8, 64, 4096),
}


def _flash_attn_flops(name, batch):
    geom = ATTN_GEOM.get(name)
    if not geom:
        return 0.0
    # THE routing predicate, shared with MultiHeadAttention (round-5
    # advisor: re-deriving it here silently drifted when the rule or
    # the BIGDL_KERNELS knob changed it)
    from bigdl_tpu.ops.attention import flash_auto

    layers, heads, d, s = geom
    if not flash_auto(s, s):
        return 0.0  # dense path: cost analysis already counts it
    return 12.0 * layers * batch * heads * float(s) * s * d


#: configs riding the bucketed variable-length protocol (dataset/
#: text.py BucketedPadding boundaries): batches are drawn per length
#: bucket instead of always padding to max seq, and MFU stops crediting
#: pad positions.  BENCH_LSTM_BUCKETS=0 restores the fixed-length leg
#: (comparisons against pre-r15 banked rounds).
LSTM_BUCKETS = {"lstm_text": (32, 64, 128, 200)}


def run_config(name, batch, iters):
    from bigdl_tpu import telemetry

    with telemetry.span(f"bench/{name}", batch=batch, iters=iters):
        if name in LSTM_BUCKETS \
                and os.environ.get("BENCH_LSTM_BUCKETS", "1") != "0":
            return _run_config_bucketed(name, batch, iters,
                                        LSTM_BUCKETS[name])
        return _run_config_timed(name, batch, iters)


def _time_leg(name, step, x, y, iters):
    """The shared timing core: one AOT scan compile (heartbeat-guarded),
    cost analysis, an untimed warmup dispatch, then the timed window.
    Returns ``(wall_s, compile_s, stages, flops_per_iter)`` —
    ``flops_per_iter`` is the raw XLA count (pad masking is the
    caller's accounting)."""
    import threading

    flops = None
    t_c0 = time.perf_counter()
    stop_hb = threading.Event()
    hb = threading.Thread(target=_compile_heartbeat, args=(name, stop_hb),
                          daemon=True)
    hb.start()
    try:
        cost = step.aot_scan(x, y, jax.random.key(0), iters)
    finally:
        stop_hb.set()
    from bigdl_tpu.telemetry.device import normalize_cost_analysis

    cost = normalize_cost_analysis(cost)
    compile_s = time.perf_counter() - t_c0
    if cost and cost.get("flops"):
        flops = float(cost["flops"])

    drain = make_drain(step)

    losses = step.run_scan(x, y, jax.random.key(1), iters)  # warmup
    if not bool(jnp.isfinite(losses).all()):
        raise FloatingPointError("non-finite loss during warmup")
    drain()  # the warmup scan's LAST param update must not leak into t0

    t0 = time.perf_counter()
    xs, ys = step._shard_batch(x, y)
    t_h2d = time.perf_counter()
    step.run_scan_sharded(xs, ys, jax.random.key(2))
    t_dispatch = time.perf_counter()
    drain()
    wall = time.perf_counter() - t0
    stages = {"compile": round(compile_s, 3),
              "h2d": round(t_h2d - t0, 4),
              "dispatch": round(t_dispatch - t_h2d, 4),
              "device": round(wall - (t_dispatch - t0), 4)}
    return wall, compile_s, stages, flops


def _bucket_lengths(rng, n, max_len):
    """Realistic sentence lengths for the bucketed lstm leg: lognormal
    (median ~45 tokens, long tail clipped at the model's max seq) — the
    shape short-text classification corpora actually have, instead of
    every row exactly max_len."""
    ln = np.round(rng.lognormal(np.log(45.0), 0.8, size=n))
    return np.clip(ln, 4, max_len).astype(int)


def _run_config_bucketed(name, batch, iters, boundaries):
    """The variable-length protocol (dataset/text.py BucketedPadding):
    sample realistic lengths, assign each row to its bucket, run the
    timed scan once per bucket holding >= 5% of rows (iterations split
    by share), aggregate.  MFU accounting multiplies each bucket's XLA
    FLOPs by its valid-token fraction — pad positions compute but no
    longer count as useful work."""
    from bigdl_tpu import telemetry
    from bigdl_tpu.dataset.text import BucketedPadding

    bp = BucketedPadding(boundaries)
    rng = np.random.default_rng(7)
    lengths = _bucket_lengths(rng, 4096, boundaries[-1])
    by_bucket = {}
    for ln in lengths:
        by_bucket.setdefault(bp.bucket_of(int(ln)), []).append(int(ln))
    shares = {b: len(v) / len(lengths) for b, v in by_bucket.items()}
    legs = {b: v for b, v in by_bucket.items() if shares[b] >= 0.05}
    scale = sum(shares[b] for b in legs)  # renormalize dropped tails
    total_rows = 0
    total_wall = 0.0
    useful_flops = 0.0
    compile_s_total = 0.0
    stages_total = {"compile": 0.0, "h2d": 0.0, "dispatch": 0.0,
                    "device": 0.0}
    buckets_out = {}
    peak_hbm = None
    for b_seq in sorted(legs):
        iters_b = max(2, int(round(iters * shares[b_seq] / scale)))
        step, x, y = make_step(name, batch, seq=b_seq)
        # end-pad each row past its sampled valid length with index 0
        # (the dataset convention) so the content matches what a
        # bucketed input pipeline would feed
        row_lens = rng.choice(np.asarray(legs[b_seq]), size=batch)
        row_lens = np.minimum(row_lens, b_seq)
        xm = np.asarray(x)
        mask = np.arange(b_seq)[None, :] < row_lens[:, None]
        xm = np.where(mask, xm, 0).astype(xm.dtype)
        x = jnp.asarray(xm)
        valid_frac = float(row_lens.sum()) / float(batch * b_seq)
        wall, compile_s, stages, flops = _time_leg(
            f"{name}[s{b_seq}]", step, x, y, iters_b)
        total_rows += batch * iters_b
        total_wall += wall
        compile_s_total += compile_s
        for k in stages_total:
            stages_total[k] += stages[k]
        if flops:
            useful_flops += flops * valid_frac * iters_b
        try:
            from bigdl_tpu.telemetry import memory as _tmem

            mrow = _tmem.analyze_hlo_memory(step._scan_cache[1].as_text())
            peak_hbm = max(peak_hbm or 0, int(mrow["peak_bytes"]))
        except Exception:  # noqa: BLE001 - the snapshot is an observer
            pass
        buckets_out[str(b_seq)] = {
            "share": round(shares[b_seq] / scale, 3), "iters": iters_b,
            "images_per_sec": round(batch * iters_b / wall, 2),
            "valid_token_frac": round(valid_frac, 3),
            "compile_s": round(compile_s, 3),
        }
    rate = total_rows / total_wall
    telemetry.counter(f"bench/{name}/images_per_sec", rate)
    out = {"images_per_sec": round(rate, 2), "batch": batch,
           "compile_s": round(compile_s_total, 3),
           "stages_s": {k: round(v, 4) for k, v in stages_total.items()},
           "buckets": buckets_out,
           "valid_token_frac": round(
               sum(r["valid_token_frac"] * r["share"]
                   for r in buckets_out.values()), 3)}
    if useful_flops:
        achieved = useful_flops / total_wall
        out["step_gflops"] = round(useful_flops / max(1, total_rows
                                                      // batch) / 1e9, 2)
        out["achieved_tflops"] = round(achieved / 1e12, 2)
        peak = peak_flops_per_sec()
        if peak:
            # pad positions excluded: this MFU counts USEFUL tokens only
            out["mfu"] = round(achieved / peak, 4)
    if peak_hbm:
        out["peak_hbm_bytes"] = peak_hbm
    return out


def _run_config_timed(name, batch, iters):
    from bigdl_tpu import telemetry

    step, x, y = make_step(name, batch)

    # ALL timed iterations run inside ONE dispatch (lax.scan over the
    # step) — per-dispatch latency is a property of the host link, not of
    # the training program, and a real TPU deployment amortizes it the
    # same way.  The AOT compile also yields XLA's cost analysis (scan
    # body counted once).
    wall, compile_s, stages, flops = _time_leg(name, step, x, y, iters)
    t_h2d_s = stages["h2d"]
    t_dispatch_s = stages["dispatch"]
    flash_flops = 0.0
    if flops:
        flash_flops = _flash_attn_flops(name, batch)
        flops += flash_flops

    rate = batch * iters / wall
    # same numbers, second consumer: the telemetry event log (when a run
    # is active) carries the stage split + throughput next to the
    # aot_scan compile/device_facts events TrainStep already emitted
    telemetry.stage("h2d", t_h2d_s)
    telemetry.stage("dispatch", t_dispatch_s)
    telemetry.stage("device", stages["device"])
    telemetry.counter(f"bench/{name}/images_per_sec", rate)
    out = {"images_per_sec": round(rate, 2), "batch": batch,
           # the compile budget's input (docs/compile.md): per-leg
           # compile seconds as a first-class field so
           # `--diff-against --compile-budget` gates the lenet-445s
           # class of outlier instead of it hiding inside stages_s
           "compile_s": round(compile_s, 3),
           # host-loop stage breakdown (optim/Metrics.scala:31-130
           # re-scope; see docs/straggler.md): compile / h2d / dispatch /
           # device-sync seconds for the timed window
           "stages_s": stages}
    if flops:
        achieved = flops * iters / wall
        out["step_gflops"] = round(flops / 1e9, 2)
        out["achieved_tflops"] = round(achieved / 1e12, 2)
        if flash_flops:
            out["flash_gflops_added"] = round(flash_flops / 1e9, 2)
        peak = peak_flops_per_sec()
        if peak:
            out["mfu"] = round(achieved / peak, 4)
    # comms snapshot off the scan executable (telemetry/comms.py): the
    # scan body holds each collective once, so these are per-iteration
    # numbers — `--diff-against` then gates bytes-moved regressions
    # (.comms_bytes/.comms_s) exactly like MFU, which is what the
    # ZeRO/pipeline PRs need to prove "the reduce-scatter is hidden"
    try:
        from bigdl_tpu.telemetry import comms as _comms

        cf = _comms.comms_facts(step._scan_cache[1], mesh=step.mesh,
                                model=step.model)
        if cf["count"] or step.mesh is not None:
            out["comms_bytes"] = cf["bytes"]
            out["comms_collectives"] = cf["count"]
            if cf.get("by_axis"):
                out["comms_by_axis"] = cf["by_axis"]
            if cf.get("expected_s") is not None:
                out["comms_s"] = round(cf["expected_s"], 6)
    except Exception:  # noqa: BLE001 - the snapshot is an observer
        pass
    # memory snapshot off the SAME scan executable (telemetry/memory.py
    # while-body recursion reports the peak INSIDE the scanned step):
    # `--diff-against --memory-budget` gates per-device HBM exactly
    # like MFU — the "ZeRO-1 drops optimizer HBM" CI claim
    try:
        from bigdl_tpu.telemetry import memory as _tmem

        mrow = _tmem.analyze_hlo_memory(step._scan_cache[1].as_text())
        out["peak_hbm_bytes"] = int(mrow["peak_bytes"])
        out["hbm_categories"] = {
            k: int(v) for k, v in mrow["categories"].items() if v}
    except Exception:  # noqa: BLE001 - the snapshot is an observer
        pass
    return out


def _local_sgd_leg(mode, h, iters, mesh, batch=128):
    """One side of the local-SGD pair: train the registry LeNet on a
    data-axis mesh for ``iters`` steps under ``mode``, measure the
    effective per-step collective bytes off the EXACT compiled programs
    that ran (the scan executable; plus the averaging executable,
    amortized over H, for the local leg), and record the achieved
    loss."""
    import bigdl_tpu.optim as optim
    from bigdl_tpu.nn.fuse import optimize_for_tpu
    from bigdl_tpu.parallel.train_step import TrainStep
    from bigdl_tpu.telemetry import comms as _comms
    from bigdl_tpu.utils.rng import RNG

    build_model, build_batch, criterion, _ = _configs()["lenet_mnist"]
    RNG.set_seed(0)
    model = optimize_for_tpu(build_model())
    step = TrainStep(model, criterion,
                     optim.SGD(learning_rate=0.05, momentum=0.9),
                     mesh=mesh, parameter_sync=mode,
                     compute_dtype=jnp.bfloat16)
    x, y = build_batch(batch)
    key = jax.random.key(0)
    # AOT first: installs the scan EXECUTABLE (not just the jit) so the
    # comms walker below reads the exact program that ran
    step.aot_scan(x, y, key, h if mode == "local" else iters)
    t0 = time.perf_counter()
    losses = []
    if mode == "local":
        # scan in H-step chunks with a parameter averaging between
        # chunks — the local-SGD schedule itself (parallel/local_sync.py
        # drives the same rhythm in the training loop)
        for r in range(max(1, iters // h)):
            chunk = step.run_scan(x, y, jax.random.fold_in(key, r), h)
            losses.append(np.asarray(chunk))
            step.average_islands()
    else:
        losses.append(np.asarray(step.run_scan(x, y, key, iters)))
    wall = time.perf_counter() - t0
    if not all(np.isfinite(c).all() for c in losses):
        raise FloatingPointError(f"non-finite loss in local-SGD "
                                 f"{mode} leg")
    row = {"batch": batch, "h": h if mode == "local" else 1,
           "sync": mode,
           "final_loss": round(float(np.mean(losses[-1])), 6),
           "images_per_sec": round(batch * iters / wall, 2)}
    nbytes = float(_comms.comms_facts(step._scan_cache[1],
                                      mesh=mesh)["bytes"])
    if mode == "local" and step._avg_cache is not None:
        nbytes += float(_comms.comms_facts(step._avg_cache,
                                           mesh=mesh)["bytes"]) / h
    row["comms_bytes"] = nbytes
    return row


def run_local_sgd_pair(iters, h=None):
    """The local-SGD evidence pair (docs/fault_tolerance.md "Straggler
    tolerance"): the same registry model trained synchronously and with
    H local steps between averagings on a 2-device data mesh.  The
    ``local_sgd_sync`` / ``local_sgd_local`` rows ride the artifact's
    ``configs`` table, so ``--diff-against`` gates BOTH sides of the
    trade: ``.comms_bytes`` (the ≈H× reduction must not erode) and
    ``.final_loss`` (H=10^6 would zero the comms and junk the model)."""
    from bigdl_tpu.parallel.mesh import make_mesh

    h = int(h or os.environ.get("BENCH_LOCAL_SGD_H", "8"))
    if len(jax.devices()) < 2:
        raise RuntimeError("local-SGD pair needs >= 2 devices")
    mesh = make_mesh((2,), ("data",))
    iters = max(iters, 2 * h)
    return {
        "local_sgd_sync": _local_sgd_leg("allreduce", h, iters, mesh),
        "local_sgd_local": _local_sgd_leg("local", h, iters, mesh),
    }


#: inference configs for the int8-vs-bf16 comparison (the bigquant
#: capability's headline claim: int8 doubles MXU throughput on v5e —
#: 394 TOPS int8 vs 197 TFLOP/s bf16; nn/quantized.py)
INFER_CONFIGS = {"inception_v1_imagenet": 256, "vgg16_cifar10": 512}


def run_infer_config(name, batch, iters, quantized):
    """Inference img/s + op-throughput accounting for one config, bf16
    or int8-quantized — the measured check on nn/quantized.py's
    throughput claim (VERDICT r4 Weak #4: 'the throughput feature is
    currently a comment').  ``utilization`` divides achieved op/s by
    the matching peak: the chip's bf16 peak for the float leg, 2x it
    for the int8 leg (the MXU's int8 rate on v5e: 394 TOPS vs 197
    TFLOP/s) — so an int8 leg that merely MATCHES bf16 img/s shows
    half the utilization, making a non-win visible."""
    from bigdl_tpu.nn.module import state_dict
    from bigdl_tpu.nn.quantized import quantize
    from bigdl_tpu.parallel.train_step import EvalStep
    from bigdl_tpu.utils.rng import RNG

    build_model, build_batch, _, _ = _configs()[name]
    RNG.set_seed(0)
    model = build_model().evaluate()
    x, _ = build_batch(batch)
    if quantized:
        from bigdl_tpu.nn.quantized import calibrate

        model = quantize(model)
        # calibrated static activation scales (BASELINE.md round-6 fix):
        # the dynamic per-conv amax reduce was the int8 regression —
        # production serving calibrates, so the bench leg measures the
        # calibrated path (one eager forward on the measurement batch)
        calibrate(model, [np.asarray(x)])
        es = EvalStep(model)  # int8 path owns its own dtypes
    else:
        es = EvalStep(model, compute_dtype=jnp.bfloat16)
    # ONE AOT compile serves the cost analysis AND the timed loop (the
    # run_config aot_scan pattern) — es.run would jit the same program
    # a second time
    state = state_dict(model)
    xj = jnp.asarray(x)
    compiled = es._build().lower(state, xj).compile()
    ops = None
    try:
        from bigdl_tpu.telemetry.device import normalize_cost_analysis

        cost = normalize_cost_analysis(compiled.cost_analysis())
        ops = float(cost.get("flops") or 0) or None
    except Exception:  # noqa: BLE001 — accounting must not sink the leg
        pass
    jax.block_until_ready(compiled(state, xj))  # warmup
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = compiled(state, xj)
    jax.block_until_ready(out)
    wall = time.perf_counter() - t0
    row = {"img_s": round(batch * iters / wall, 2)}
    if ops:
        achieved = ops * iters / wall
        row["achieved_tops"] = round(achieved / 1e12, 2)
        peak = peak_flops_per_sec()
        if peak:
            denom = peak * (int8_peak_ratio() if quantized else 1.0)
            row["utilization"] = round(achieved / denom, 4)
    return row


def run_infer_table(iters):
    """{config: {bf16_*, int8_*, int8_speedup}} — one table per config;
    errors isolated per leg."""
    table = {}
    for name, batch in INFER_CONFIGS.items():
        row = {}
        for tag, q in (("bf16", False), ("int8", True)):
            try:
                leg = run_infer_config(name, batch, iters, q)
                row.update({f"{tag}_{k}": v for k, v in leg.items()})
            except Exception as e:  # noqa: BLE001
                row[f"{tag}_error"] = f"{type(e).__name__}: {e}"
        if "bf16_img_s" in row and "int8_img_s" in row:
            row["int8_speedup"] = round(row["int8_img_s"] / row["bf16_img_s"], 3)
        table[name] = row
        print(f"# infer {name}: {row}", file=sys.stderr, flush=True)
        _last_progress[0] = time.monotonic()
    return table


def _banked_path():
    """Newest banked TPU measurement for the replay fallback: the
    ``BENCH_BANKED`` env override, else the lexically-newest committed
    ``BENCH_banked_*.json`` (round-stamped, so newer rounds win without
    a code edit)."""
    if os.environ.get("BENCH_BANKED"):
        return os.environ["BENCH_BANKED"]
    import glob
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    banked = glob.glob(os.path.join(here, "BENCH_banked_*.json"))

    def round_no(p):  # numeric sort: r10 must beat r5 (lexical fails)
        m = re.search(r"_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    banked.sort(key=round_no)
    return banked[-1] if banked else os.path.join(here, "BENCH_banked.json")


#: heartbeat for the wedge watchdog: monotonic time of the last sign of
#: benchmark progress (init done / config finished); the live-results
#: dict is shared so a mid-run wedge can still emit completed configs
_last_progress = [None]
_live_results: dict = {}


def _replay_or(error_line: dict, reason: str):
    """Emit the banked measurement (clearly marked ``replayed``) when the
    live tunnel cannot produce one, else the error line.  The axon
    tunnel wedges per-client and transiently (round-5 contact log:
    probe + headline leg OK, next client blocked forever inside its
    first compile RPC) — a real, committed number measured hours earlier
    beats a bare ``backend_init_failed`` record, as long as the artifact
    says exactly what it is.  Exits NONZERO either way: a replay is
    still an infrastructure failure and must read as one; the driver
    records the printed line regardless of exit code (BENCH_r04.json
    carries the rc=3 line's parse)."""
    only = os.environ.get("BENCH_CONFIGS")
    try:
        with open(_banked_path()) as f:
            line = json.load(f)
        # replaying a headline number against a run that asked for
        # DIFFERENT configs would mislabel the measurement — error out
        # instead (the driver's full sweep sets no BENCH_CONFIGS)
        banked_cfg = (line.get("metric") or "").replace(
            "_train_throughput", "")
        if only and banked_cfg not in [c.strip() for c in only.split(",")]:
            raise ValueError(
                f"banked metric {line.get('metric')!r} not in "
                f"BENCH_CONFIGS={only!r}")
        line["replayed"] = True
        line["replay_reason"] = reason
        line["live_error"] = error_line.get("error")
    except (OSError, ValueError) as e:
        line = dict(error_line)
        line.setdefault("replay_unavailable", f"{type(e).__name__}: {e}")
    print(json.dumps(line))
    sys.stdout.flush()
    os._exit(3)


def _emit_partial_and_die(reason: str):
    """Mid-run wedge with completed configs in hand: emit THOSE (live,
    current data beats any banked artifact), marked incomplete; with
    nothing measured yet, fall back to the banked replay."""
    # snapshot: the main thread may still be inserting into the shared
    # dict when the watchdog fires (dict-resize during iteration would
    # kill this daemon thread silently — and with it the bail-out path)
    snap = dict(_live_results)
    done = {k: v for k, v in snap.items() if "error" not in v}
    if not done:
        _replay_or(
            {"metric": "backend_wedged_midrun", "value": None,
             "unit": "images/sec", "vs_baseline": None, "error": reason},
            f"{reason}; emitting last banked measurement")
    head_name = HEADLINE if HEADLINE in done else next(iter(done))
    head = done[head_name]
    print(json.dumps({
        "metric": f"{head_name}_train_throughput",
        "value": head.get("images_per_sec"), "unit": "images/sec",
        "vs_baseline": None, "mfu": head.get("mfu"),
        "source": _source_state(), "incomplete": True,
        "wedged": reason, "configs": snap}))
    sys.stdout.flush()
    os._exit(3)


def _start_wedge_watchdog(iters: int):
    """The observed wedge mode evades probe_backend: ``jax.devices()``
    answers, then the FIRST compile RPC blocks forever (~0.5% CPU in
    wait_woken), so a driver-side timeout would kill the process with NO
    json line at all.  A daemon thread watches the per-config heartbeat
    and bails the run out if it stalls (``BENCH_WEDGE_TIMEOUT`` seconds
    without finishing a config; the default scales with BENCH_ITERS
    above the protocol's 24 so a long-sample run isn't misread as a
    wedge — at 24 iters: 900s, well above the slowest observed
    compile+run, ~90s)."""
    import threading

    try:
        deadline = float(os.environ.get("BENCH_WEDGE_TIMEOUT") or
                         900.0 * max(1.0, iters / 24.0))
    except ValueError:
        deadline = 900.0
    if deadline <= 0:
        return
    _last_progress[0] = time.monotonic()

    def watch():
        while True:
            time.sleep(15)
            last = _last_progress[0]
            if last is not None and time.monotonic() - last > deadline:
                _emit_partial_and_die(
                    f"no config finished in {deadline:.0f}s "
                    "(tunnel wedged inside a compile RPC)")

    threading.Thread(target=watch, name="bigdl-bench-wedge-watchdog",
                     daemon=True).start()


def _init_backend_or_die():
    """Bounded backend init (``Engine.probe_backend``, which owns the
    BENCH_BACKEND_TIMEOUT knob): on a wedged device tunnel emit an
    explicit one-line JSON error and exit nonzero instead of hanging
    the driver.  The singleton claim WAITS (default 210s, override via
    BIGDL_SINGLETON_WAIT) instead of failing fast: the only legitimate
    lock holder is the TPU-health watcher, whose probe claim is bounded
    at 60s — fail-fast here cost round 4 its headline number.  When
    /tmp/TPU_BACK exists the watcher is running its post-contact runbook
    harvest (tools/tpu_watch.sh), whose LEGS hold the claim for up to
    ~30 min each — wait out one full leg rather than lose the round's
    measurement to our own harvest."""
    from bigdl_tpu.utils.engine import Engine

    try:
        # harvest mode only while the sentinel is FRESH (the watcher
        # never deletes it) and long enough to outlast the harvest's
        # longest holder: its own 3600s bench sweep, not just the
        # 1800s+30s legs
        default_wait = 210
        try:
            if time.time() - os.path.getmtime("/tmp/TPU_BACK") < 4 * 3600:
                default_wait = 3700
        except OSError:
            pass
        try:
            wait = float(os.environ.get("BIGDL_SINGLETON_WAIT")
                         or default_wait)
        except ValueError:
            wait = float(default_wait)
        Engine.probe_backend(lock_wait_s=wait)
    except RuntimeError as e:
        # probe thread may be stuck in native code, hence os._exit
        _replay_or({"metric": "backend_init_failed", "value": None,
                    "unit": "images/sec", "vs_baseline": None,
                    "error": str(e)},
                   f"live backend init failed ({e}); emitting last "
                   "banked measurement")


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="bigdl_tpu benchmark driver (env knobs: BENCH_CONFIGS,"
                    " BENCH_ITERS, ... — see module docstring)")
    ap.add_argument("--diff-against", default=None, metavar="BASELINE.json",
                    help="after the sweep, compare this run's line against"
                         " a prior bench JSON (or a telemetry run log) via"
                         " python -m bigdl_tpu.telemetry diff; exit 4 on a"
                         " regression — the CI perf gate")
    ap.add_argument("--diff-threshold-pct", type=float, default=None,
                    help="regression threshold for --diff-against "
                         "(default: the diff engine's)")
    ap.add_argument("--compile-budget", type=float, default=None,
                    metavar="PCT",
                    help="compile budget for --diff-against: a config "
                         "whose compile_s grew more than PCT%% over the "
                         "baseline exits 4 like any other regression "
                         "(default: the diff engine's compile threshold,"
                         " 50%%)")
    ap.add_argument("--memory-budget", type=float, default=None,
                    metavar="PCT",
                    help="memory budget for --diff-against: a config "
                         "whose peak_hbm_bytes grew more than PCT%% "
                         "over the baseline exits 4 like any other "
                         "regression (default: the diff engine's "
                         "memory threshold, 10%%)")
    args = ap.parse_args(argv)
    _init_backend_or_die()
    # BIGDL_TELEMETRY routes the sweep's per-config stage timings,
    # compiles, and device facts into one JSONL run log (the instrumented
    # path replacing this file's former ad-hoc-only timing story)
    from bigdl_tpu import telemetry

    with telemetry.maybe_run(meta={"cmd": "bench"}) as owned_log:
        line = _sweep()
    if owned_log:
        print(f"# telemetry run log: {owned_log}", file=sys.stderr)
    if args.diff_against:
        from bigdl_tpu.telemetry import diff as tdiff

        base = tdiff.load_metrics(args.diff_against)
        cur = tdiff.bench_metrics(line, path="<this sweep>")
        kwargs = {}
        if args.diff_threshold_pct is not None:
            kwargs["threshold_pct"] = args.diff_threshold_pct
        if args.compile_budget is not None:
            kwargs["compile_threshold_pct"] = args.compile_budget
        if args.memory_budget is not None:
            kwargs["memory_threshold_pct"] = args.memory_budget
        rows = tdiff.diff_metrics(base, cur, **kwargs)
        print(tdiff.format_diff(rows, base, cur), file=sys.stderr)
        if not rows:
            # nothing comparable (every config errored, or a disjoint
            # baseline) must FAIL the gate, not silently pass it — the
            # same contract as `telemetry diff` exit 2
            print("error: --diff-against found nothing comparable",
                  file=sys.stderr)
            sys.exit(2)
        if any(r["regressed"] for r in rows):
            # distinct from the wedge/replay exit 3: this sweep RAN, it
            # just got slower than the baseline
            sys.exit(4)


def _sweep():
    iters = int(os.environ.get("BENCH_ITERS", "24"))
    _start_wedge_watchdog(iters)
    cfgs = _configs()
    only = os.environ.get("BENCH_CONFIGS")
    names = [n.strip() for n in only.split(",")] if only else list(cfgs)

    results = _live_results
    for name in names:
        try:
            *_, batch = cfgs[name]
            results[name] = run_config(name, batch, iters)
        except Exception as e:  # noqa: BLE001 — one config must not sink the rest
            results[name] = {"error": f"{type(e).__name__}: {e}"}
        print(f"# {name}: {results[name]}", file=sys.stderr, flush=True)
        _last_progress[0] = time.monotonic()

    # local-SGD comms/convergence pair: on for the full sweep whenever
    # a 2-device data mesh is possible, opt-in/out via BENCH_LOCAL_SGD
    want_ls = os.environ.get("BENCH_LOCAL_SGD")
    if want_ls == "1" or (want_ls != "0" and not only
                          and len(jax.devices()) >= 2):
        try:
            results.update(run_local_sgd_pair(iters))
        except Exception as e:  # noqa: BLE001 — one leg must not sink the sweep
            results["local_sgd_local"] = {
                "error": f"{type(e).__name__}: {e}"}
        for n in ("local_sgd_sync", "local_sgd_local"):
            if n in results:
                print(f"# {n}: {results[n]}", file=sys.stderr, flush=True)
        _last_progress[0] = time.monotonic()

    # int8-vs-bf16 inference table: on for the full sweep (the driver's
    # default invocation), opt-in/out via BENCH_INFER=1/0
    infer = None
    want_infer = os.environ.get("BENCH_INFER")
    if want_infer == "1" or (want_infer != "0" and not only):
        infer = run_infer_table(max(8, iters // 2))

    # the metric name must say what was actually measured: the north-star
    # Inception config when it ran, else the first selected config
    head_name = HEADLINE if HEADLINE in results else next(iter(results))
    head = results[head_name]
    line = {
        "metric": f"{head_name}_train_throughput",
        "value": head.get("images_per_sec"),
        "unit": "images/sec",
        "vs_baseline": None,
        "mfu": head.get("mfu"),
        "device": jax.devices()[0].device_kind,
        "source": _source_state(),
        # the reference publishes no numbers (BASELINE.md) so vs_baseline
        # stays None; track progress against our own best measured round
        # number instead (round 3: 4,853 img/s Inception-v1, BASELINE.md)
        "vs_round3_best": (round(head["images_per_sec"] / ROUND3_BEST, 3)
                           if head_name == HEADLINE
                           and head.get("images_per_sec") else None),
        "configs": results,
    }
    try:
        from bigdl_tpu.utils import compile_cache as _cc

        # the sweep's persistent-cache story rides the artifact: a warm
        # round shows hits ~= requests, and the ingredients explain any
        # surprise cold round (docs/compile.md)
        line["compile_cache"] = _cc.monitor().snapshot()
        line["compile_cache_ingredients"] = _cc.cache_key_ingredients()
    except Exception:  # noqa: BLE001 — accounting must not sink the sweep
        pass
    if infer is not None:
        line["infer_int8_vs_bf16"] = infer
    try:
        from bigdl_tpu import telemetry as _tel

        # the run is still open here, so read the live ledger rather
        # than the (unwritten) run log — diff gates compare goodput_pct
        # / badput_s across rounds like any other metric
        gp = _tel.goodput()
        if gp and gp.get("wall_s"):
            line["goodput_pct"] = gp["goodput_pct"]
            line["badput_s"] = gp["badput_s"]
            line["badput"] = gp["badput"]
    except Exception:  # noqa: BLE001 — accounting must not sink the sweep
        pass
    print(json.dumps(line))
    return line


def _source_state():
    """Commit + dirty flag of the tree that produced the number — a bench
    artifact certifies nothing unless it names the exact source state (the
    round-2 maxpool regression hid for a full round because the committed
    tree diverged from the benched tree)."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    try:
        rev = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             cwd=here, capture_output=True, text=True,
                             timeout=10).stdout.strip()
        dirty = subprocess.run(["git", "status", "--porcelain"],
                               cwd=here, capture_output=True, text=True,
                               timeout=10).stdout.strip()
        return {"commit": rev or None, "dirty": bool(dirty)}
    except Exception:
        return {"commit": None, "dirty": None}


if __name__ == "__main__":
    main()
