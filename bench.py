#!/usr/bin/env python
"""Benchmark driver: Inception-v1 synthetic-ImageNet training throughput on
the local accelerator — the reference's benchmark protocol
(``models/utils/DistriOptimizerPerf.scala:33-124`` / LocalOptimizerPerf:
synthetic data, fixed batch, records/sec after warmup) on the north-star
model from BASELINE.json.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no numeric baseline (BASELINE.json "published": {}),
so vs_baseline is reported against the reference's qualitative claim anchor:
null.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    iters = int(os.environ.get("BENCH_ITERS", "24"))
    warmup = int(os.environ.get("BENCH_WARMUP", "8"))

    from bigdl_tpu import models
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.parallel.train_step import TrainStep

    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(0)
    model = models.build_inception_v1(1000)
    crit = nn.ClassNLLCriterion()
    step = TrainStep(model, crit, optim.SGD(learning_rate=0.01, momentum=0.9),
                     compute_dtype=jnp.bfloat16)

    rng = np.random.default_rng(0)
    # device-resident batch: the protocol measures training compute, not
    # host->device transfer (the reference's synthetic-data perf harness
    # likewise keeps data in memory)
    x = jnp.asarray(rng.normal(size=(batch, 3, 224, 224)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 1000, batch))

    # warmup, then drain the async queue with a value round-trip — over a
    # tunneled device a value fetch is the only reliable sync barrier
    for i in range(warmup):
        step.run(x, y, jax.random.key(i))
    if warmup:
        # params-derived fetch: drains the queue INCLUDING the last warmup
        # iteration's optimizer update (float(loss) would leave it pending)
        float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))

    t0 = time.perf_counter()
    for i in range(iters):
        step.run(x, y, jax.random.key(100 + i))
    # chain end: fetch a params-derived scalar so the LAST iteration's
    # optimizer update is forced inside the timed window (loss_i only
    # depends on params_{i-1}); value-fetch-only sync protocol
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    wall = time.perf_counter() - t0

    images_per_sec = batch * iters / wall
    print(json.dumps({
        "metric": "inception_v1_imagenet_train_throughput",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": None,
    }))


if __name__ == "__main__":
    main()
