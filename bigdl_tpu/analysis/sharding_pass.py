"""Pass 2 — sharding validation: PartitionSpecs vs. the actual mesh.

The cross-replica weight-update sharding literature (arxiv 2004.13336)
shows sharding-spec mistakes are a *silent* correctness/perf hazard: an
unknown axis name or an indivisible dim either errors deep inside pjit
or quietly degrades to replication.  This pass checks specs — from a raw
``{path: PartitionSpec}`` dict, or pulled out of a live ``TrainStep``'s
parameter metadata (``check_train_step``) — against the mesh *before*
compile.  Rules: ``shard/unknown-axis``, ``shard/duplicate-axis``,
``shard/indivisible``, ``shard/rank-mismatch``, ``shard/rule-error``,
``shard/replicated-large``.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Tuple

import numpy as np

from bigdl_tpu.analysis.diagnostics import Report

__all__ = ["check_partition_specs", "check_sharding_rules",
           "check_train_step", "REPLICATED_LARGE_THRESHOLD"]

#: parameters at/above this element count trigger shard/replicated-large
#: when fully replicated on a multi-device mesh (1M f32 elems = 4 MiB per
#: device, times every device on the mesh).
REPLICATED_LARGE_THRESHOLD = 1 << 20


def _spec_entries(spec) -> Tuple:
    """PartitionSpec -> tuple of per-dim entries (None | axis | tuple)."""
    return tuple(spec)


def _axes_of(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


def _shape_of(arr) -> Optional[Tuple[int, ...]]:
    if arr is None:
        return None
    if hasattr(arr, "shape"):
        return tuple(arr.shape)
    if isinstance(arr, (tuple, list)):
        return tuple(int(s) for s in arr)
    return None


def check_partition_specs(mesh, specs: Dict[str, Any],
                          shapes: Optional[Dict[str, Any]] = None,
                          suppress: Iterable[str] = (),
                          large_threshold: int = REPLICATED_LARGE_THRESHOLD,
                          ) -> Report:
    """Validate ``{name: PartitionSpec}`` against ``mesh``.

    ``shapes`` maps the same names to arrays (or shape tuples); without it
    only axis-name validity can be checked.
    """
    report = Report(suppress=suppress)
    mesh_axes = dict(zip(mesh.axis_names,
                         (int(s) for s in mesh.devices.shape)))
    multi_device = int(np.prod(mesh.devices.shape)) > 1
    for name, spec in specs.items():
        entries = _spec_entries(spec)
        shape = _shape_of((shapes or {}).get(name))
        seen_axes = []
        for dim, entry in enumerate(entries):
            for ax in _axes_of(entry):
                if ax not in mesh_axes:
                    report.add(
                        "shard/unknown-axis",
                        f"PartitionSpec{tuple(entries)} names mesh axis "
                        f"{ax!r} but the mesh has axes "
                        f"{sorted(mesh_axes)}",
                        where=name,
                        hint="axis names must match the mesh built by "
                             "parallel/mesh.py make_mesh()")
                    continue
                if ax in seen_axes:
                    report.add(
                        "shard/duplicate-axis",
                        f"PartitionSpec{tuple(entries)} uses mesh axis "
                        f"{ax!r} more than once",
                        where=name)
                seen_axes.append(ax)
            if shape is not None and dim < len(shape):
                div = 1
                for ax in _axes_of(entry):
                    div *= mesh_axes.get(ax, 1)
                if div > 1 and shape[dim] % div != 0:
                    report.add(
                        "shard/indivisible",
                        f"dim {dim} of shape {shape} is split over "
                        f"{_axes_of(entry)} (total {div} shards) but "
                        f"{shape[dim]} % {div} != 0",
                        where=name,
                        hint="pad the dimension or move the sharding to "
                             "a divisible axis")
        if shape is not None and len(entries) > len(shape):
            report.add(
                "shard/rank-mismatch",
                f"PartitionSpec{tuple(entries)} has {len(entries)} "
                f"entries but the array is rank {len(shape)}",
                where=name)
        if shape is not None and multi_device \
                and all(not _axes_of(e) for e in entries):
            n = int(np.prod(shape)) if shape else 0
            if n >= large_threshold:
                report.add(
                    "shard/replicated-large",
                    f"parameter of {n} elements is fully replicated on a "
                    f"{dict(mesh_axes)} mesh",
                    where=name,
                    hint="consider parameter_sync='sharded'/'fsdp' or an "
                         "extra_sharding_rules TP spec for this weight")
    return report


def check_sharding_rules(mesh, params, rules,
                         suppress: Iterable[str] = ()) -> Report:
    """Pre-flight validation of an ``extra_sharding_rules`` callable
    against a ``{path: array}`` param dict *before* TrainStep
    construction — a bad axis name would otherwise explode deep inside
    ``device_put``/pjit with no parameter path in the error."""
    report = Report(suppress=suppress)
    specs: Dict[str, Any] = {}
    shapes: Dict[str, Any] = {}
    for path, arr in params.items():
        try:
            spec = rules(path, arr)
        except Exception as e:  # noqa: BLE001 - rule bugs are findings
            report.add("shard/rule-error",
                       f"sharding rule raised for this parameter: "
                       f"{type(e).__name__}: {e}", where=path)
            continue
        if spec is not None:
            specs[path] = spec
            shapes[path] = arr
    report.extend(check_partition_specs(mesh, specs, shapes,
                                        suppress=suppress))
    return report


def check_train_step(step, suppress: Iterable[str] = ()) -> Report:
    """Validate a ``TrainStep``'s parameter shardings (the specs its
    ``_param_sharding``/``extra_sharding_rules`` machinery will request)
    against its mesh — before the first compile."""
    report = Report(suppress=suppress)
    mesh = step.mesh
    if mesh is None:
        return report
    specs: Dict[str, Any] = {}
    shapes: Dict[str, Any] = {}
    for path, arr in step.params.items():
        shapes[path] = arr
        rule_spec = None
        if step.extra_sharding_rules is not None:
            try:
                rule_spec = step.extra_sharding_rules(path, arr)
            except Exception as e:  # noqa: BLE001 - rule bugs are findings
                report.add("shard/rule-error",
                           f"extra_sharding_rules raised for this "
                           f"parameter: {type(e).__name__}: {e}",
                           where=path)
                continue
        if rule_spec is not None:
            specs[path] = rule_spec
        else:
            sharding = step._param_sharding(path, arr)
            spec = getattr(sharding, "spec", None)
            if spec is None:
                continue
            # pad the spec to the array rank so replicated-large sees a
            # per-dim view
            entries = tuple(spec) + (None,) * (arr.ndim - len(tuple(spec)))
            from jax.sharding import PartitionSpec as P

            specs[path] = P(*entries)
    report.extend(check_partition_specs(mesh, specs, shapes,
                                        suppress=suppress))
    return report
