"""CLI: ``python -m bigdl_tpu.analysis <model-name|all|path...>``.

Model targets (names from ``models/registry.py``, or ``all``) run the
static shape/dtype pass over the freshly built model; path targets run
the tracer-leak AST lint.  Exit status is nonzero when any
error-severity diagnostic fires (``--fail-on`` adjusts the bar), so the
command drops straight into CI.

Examples::

    python -m bigdl_tpu.analysis resnet            # one zoo model
    python -m bigdl_tpu.analysis all -v            # every model, verbose
    python -m bigdl_tpu.analysis bigdl_tpu/ tools/ # AST lint
    python -m bigdl_tpu.analysis --list-rules      # the rule catalog
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from bigdl_tpu.analysis.diagnostics import RULES, Report, Severity


def _list_rules() -> None:
    width = max(len(r) for r in RULES)
    for rule, (severity, desc) in sorted(RULES.items()):
        print(f"{rule:<{width}}  {str(severity):<7}  {desc}")


def _check_one_model(name: str, args) -> Report:
    from bigdl_tpu.analysis.api import check_model
    from bigdl_tpu.analysis.shape_pass import format_spec
    from bigdl_tpu.models import registry

    text = not args.json  # --json must emit NOTHING but the JSON array
    if text:
        print(f"== {name} ==")
    try:
        model = registry.build_model(name, args.num_classes)
        spec = registry.input_spec(name, args.batch)
    except Exception as e:  # noqa: BLE001 - construction errors are findings
        report = Report(suppress=args.suppress)
        report.add("shape/mismatch",
                   f"model construction failed: "
                   f"{type(e).__name__}: {e}")  # main() prefixes the name
        if text:
            print(report.format())
        return report
    res = check_model(model, spec, suppress=args.suppress)
    if text and args.verbose:
        for row in res.layers:
            print(f"  {row.path:<60} {format_spec(row.out)}")
    if text and res.out is not None:
        print(f"  input  {format_spec(spec)}")
        print(f"  output {format_spec(res.out)}")
    if text:
        print(res.report.format())
    return res.report


def main(argv=None) -> int:
    # BEFORE any jax touch: honor a user-pinned JAX_PLATFORMS even when
    # an externally-registered PJRT plugin tries to override it (same
    # guard as models/cli.py)
    from bigdl_tpu.utils.engine import honor_platform_request

    honor_platform_request()

    p = argparse.ArgumentParser(
        prog="bigdl_tpu.analysis",
        description="static graph checker + tracer-leak linter")
    p.add_argument("targets", nargs="*",
                   help="model names (see models/registry.py), 'all', "
                        "or file/directory paths to AST-lint")
    p.add_argument("--lint", action="store_true",
                   help="treat every target as a path to lint")
    p.add_argument("-b", "--batch", type=int, default=2,
                   help="batch size for the abstract input spec")
    p.add_argument("--num-classes", type=int, default=0)
    p.add_argument("-v", "--verbose", action="store_true",
                   help="print the per-layer output-spec table")
    p.add_argument("--json", action="store_true",
                   help="emit diagnostics as JSON")
    p.add_argument("--suppress", action="append", default=[],
                   metavar="RULE", help="suppress a rule id (repeatable)")
    p.add_argument("--fail-on", choices=("error", "warning", "never"),
                   default="error")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        _list_rules()
        return 0
    if not args.targets:
        p.error("no targets; pass model names, 'all', or paths")

    from bigdl_tpu.models import registry

    model_targets: List[str] = []
    path_targets: List[str] = []
    for t in args.targets:
        if not args.lint and t == "all":
            model_targets.extend(registry.model_names())
        elif not args.lint and t in registry.MODELS:
            model_targets.append(t)
        elif os.path.exists(t):
            path_targets.append(t)
        else:
            p.error(f"target {t!r} is neither a registry model "
                    f"({registry.model_names()}) nor an existing path")

    combined = Report(suppress=args.suppress)
    for name in model_targets:
        report = _check_one_model(name, args)
        for d in report:  # combined/JSON view must name the model
            d.where = f"{name}:{d.where}" if d.where else name
        combined.extend(report)
    if path_targets:
        from bigdl_tpu.analysis.ast_lint import lint_paths

        report = lint_paths(path_targets, suppress=args.suppress)
        if not args.json:
            print(report.format())
        combined.extend(report)

    if args.json:
        print(combined.to_json())
    if args.fail_on == "never":
        return 0
    bar = Severity.ERROR if args.fail_on == "error" else Severity.WARNING
    return 1 if any(d.severity >= bar for d in combined) else 0


if __name__ == "__main__":
    sys.exit(main())
