"""Pass 1 — static shape/dtype inference over a module tree.

Walks a model with ``jax.eval_shape`` (XLA abstract evaluation: no FLOPs,
no memory, no compile) and reports per-layer output
``ShapeDtypeStruct``s.  ``Sequential`` chains and ``Graph`` DAGs
(via ``Graph._topo_sort``'s node order) are walked layer-by-layer so a
failure is pinned to the exact module path; other containers (``Concat``
etc.) are evaluated atomically.  Rules:

- ``shape/mismatch`` — a layer fails abstract evaluation for its
  (statically inferred) input spec;
- ``shape/f64`` — a layer whose inputs are not float64 emits float64
  (the silent promotion the ROADMAP bans from hot paths);
- ``shape/dead-node`` — a Graph node fed by the inputs that contributes
  to no output;
- ``shape/input-arity`` — the input spec does not match the graph's
  input-node count.

Also home of the fuse-pass invariant: :func:`output_spec` before/after a
graph rewrite proves the rewrite preserved every output's shape+dtype
(``nn/fuse.py:optimize_for_tpu`` runs this by default).
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.analysis.diagnostics import Report
from bigdl_tpu.nn.graph import Graph
from bigdl_tpu.nn.module import Module, Sequential, functional_call, state_dict

__all__ = ["LayerSpec", "ShapeCheckResult", "check_shapes", "output_spec",
           "infer_input_spec", "infer_input_output", "specs_equal",
           "format_spec"]


class LayerSpec(NamedTuple):
    """One row of the per-layer report."""

    path: str
    out: Any  # pytree of jax.ShapeDtypeStruct


class ShapeCheckResult(NamedTuple):
    report: Report
    layers: List[LayerSpec]
    out: Any  # whole-model output spec pytree, or None when the walk failed


def _as_spec(x):
    """Concrete arrays (example inputs) -> abstract ShapeDtypeStructs."""
    def leaf(a):
        if isinstance(a, jax.ShapeDtypeStruct):
            return a
        a = jnp.asarray(a)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)

    return jax.tree.map(leaf, x)


def format_spec(spec) -> str:
    def one(s):
        return f"{jnp.dtype(s.dtype).name}[{','.join(map(str, s.shape))}]"

    leaves = jax.tree.leaves(spec)
    if len(leaves) == 1 and spec is leaves[0]:
        return one(leaves[0])
    return str(jax.tree.map(one, spec))


def _eval_module(module: Module, in_spec):
    """Abstract-evaluate one module via its pure functional view."""
    state = state_dict(module)

    def fwd(x):
        out, _ = functional_call(module, state, x)
        return out

    return jax.eval_shape(fwd, in_spec)


def _has_f64(spec) -> bool:
    return any(jnp.dtype(s.dtype) == jnp.dtype("float64")
               for s in jax.tree.leaves(spec)
               if hasattr(s, "dtype"))


def _check_f64(path: str, in_spec, out, report: Report) -> None:
    if _has_f64(out) and not _has_f64(in_spec):
        report.add("shape/f64",
                   f"output is float64 ({format_spec(out)}) while inputs "
                   f"are not — silent f64 promotion",
                   where=path,
                   hint="cast to float32/bfloat16, or audit np.float64 "
                        "constants in this layer")


def _err_text(e: BaseException) -> str:
    txt = f"{type(e).__name__}: {e}"
    return txt if len(txt) <= 400 else txt[:400] + " ..."


def _walk(module: Module, in_spec, path: str, rows: List[LayerSpec],
          report: Report):
    """Returns the module's output spec pytree, or None after reporting."""
    if type(module) is Sequential or (
            isinstance(module, Sequential) and
            type(module).update_output is Sequential.update_output):
        spec = in_spec
        for name, child in module.__dict__["_modules"].items():
            child_path = f"{path}.{name}" if path else name
            spec = _walk(child, spec, child_path, rows, report)
            if spec is None:
                return None
        return spec
    if isinstance(module, Graph):
        return _walk_graph(module, in_spec, path, rows, report)
    try:
        out = _eval_module(module, in_spec)
    except Exception as e:  # noqa: BLE001 - every trace error is a finding
        report.add("shape/mismatch",
                   f"abstract evaluation failed for input "
                   f"{format_spec(_as_spec(in_spec))}: {_err_text(e)}",
                   where=path or module.get_name(),
                   hint="the layer's expected input shape/dtype disagrees "
                        "with what the model feeds it")
        return None
    rows.append(LayerSpec(path or module.get_name(), out))
    _check_f64(path or module.get_name(), _as_spec(in_spec), out, report)
    return out


def _graph_dead_nodes(g: Graph) -> List[str]:
    """Nodes reachable forward from the inputs that are not ancestors of
    any output (``_topo_sort`` only keeps output ancestors)."""
    live = {n.id for n in g._sorted} | {n.id for n in g.input_nodes}
    dead, seen, stack = [], set(), list(g.input_nodes)
    while stack:
        n = stack.pop()
        if n.id in seen:
            continue
        seen.add(n.id)
        if n.id not in live:
            dead.append(n.element.get_name())
        stack.extend(n.next)
    return dead


def _walk_graph(g: Graph, in_spec, path: str, rows: List[LayerSpec],
                report: Report):
    inputs = list(in_spec) if isinstance(in_spec, (list, tuple)) \
        else [in_spec]
    if len(inputs) != len(g.input_nodes):
        report.add("shape/input-arity",
                   f"graph has {len(g.input_nodes)} input node(s) but the "
                   f"input spec provides {len(inputs)}",
                   where=path or g.get_name())
        return None
    for name in _graph_dead_nodes(g):
        report.add("shape/dead-node",
                   f"node {name!r} is fed by the graph inputs but reaches "
                   f"no output — it will never execute",
                   where=f"{path}.{name}" if path else name,
                   hint="remove the node or add it to the graph outputs")
    specs = {}
    for n, s in zip(g.input_nodes, inputs):
        specs[n.id] = s
    input_ids = {n.id for n in g.input_nodes}
    for n in g._sorted:
        if n.id in input_ids:
            continue
        gathered = []
        for p, idx in n.prev:
            v = specs[p.id]
            if idx is not None:
                v = v[idx]
            gathered.append(v)
        node_in = gathered[0] if len(gathered) == 1 else gathered
        node_path = f"{path}.{n.element.get_name()}" if path \
            else n.element.get_name()
        out = _walk(n.element, node_in, node_path, rows, report)
        if out is None:
            return None
        specs[n.id] = out
    outs = [specs[o.id] for o in g.output_nodes]
    return outs[0] if len(outs) == 1 else outs


def check_shapes(model: Module, input_spec, suppress=()) -> ShapeCheckResult:
    """Run the shape/dtype pass; ``input_spec`` is a (pytree of)
    ``jax.ShapeDtypeStruct`` or example arrays."""
    report = Report(suppress=suppress)
    rows: List[LayerSpec] = []
    spec = _as_spec(input_spec)
    out = _walk(model, spec, "", rows, report)
    return ShapeCheckResult(report, rows, out)


def output_spec(model: Module, input_spec) -> Optional[Any]:
    """Whole-model output spec pytree via one abstract evaluation, or
    ``None`` when the model cannot be abstractly evaluated for this input
    (nothing to prove then)."""
    try:
        return _eval_module(model, _as_spec(input_spec))
    except Exception:  # noqa: BLE001 - "cannot prove" is a valid outcome
        return None


def specs_equal(a, b) -> bool:
    if a is None or b is None:
        return False
    ta, tb = jax.tree.structure(a), jax.tree.structure(b)
    if ta != tb:
        return False
    return all(tuple(x.shape) == tuple(y.shape)
               and jnp.dtype(x.dtype) == jnp.dtype(y.dtype)
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


# -- input-spec inference ---------------------------------------------------

#: (H, W) candidates for convolutional models, most common first.
_IMG_SIZES: Tuple[Tuple[int, int], ...] = ((224, 224), (32, 32), (28, 28),
                                           (299, 299))


def _first_leaf(module: Module) -> Optional[Module]:
    from bigdl_tpu.nn.module import Container

    m = module
    while isinstance(m, Container):
        if isinstance(m, Graph):
            nxt = m.input_nodes[0].next if m.input_nodes else []
            if not nxt:
                return None
            m = nxt[0].element
            continue
        layers = m.layers
        if not layers:
            return None
        m = layers[0]
    return m


def infer_input_spec(model: Module, batch: int = 2) -> Optional[Any]:
    """Best-effort canonical input spec from the model's first consuming
    layer — used when a caller (``optimize_for_tpu``) has no example
    input.  Returns ``None`` when no candidate abstractly evaluates; the
    model-zoo registry (``models/registry.py``) holds exact specs."""
    found = infer_input_output(model, batch)
    return found[0] if found is not None else None


def infer_input_output(model: Module, batch: int = 2
                       ) -> Optional[Tuple[Any, Any]]:
    """Like :func:`infer_input_spec` but returns ``(input_spec,
    output_spec)`` — the successful candidate's abstract evaluation is the
    proof it fits, so callers needing both (the fuse invariant) avoid a
    second whole-model walk."""
    from bigdl_tpu.nn.layers.conv import SpatialConvolution

    leaf = _first_leaf(model)
    if leaf is None:
        return None
    candidates: List[Any] = []
    if isinstance(leaf, SpatialConvolution):
        c = leaf.n_input_plane
        for h, w in _IMG_SIZES:
            shape = (batch, c, h, w) if leaf.format == "NCHW" \
                else (batch, h, w, c)
            candidates.append(jax.ShapeDtypeStruct(shape, jnp.float32))
    else:
        d = leaf.__dict__
        if "size" in d and isinstance(d["size"], (tuple, list)):  # Reshape
            import numpy as np

            n = int(np.prod(d["size"]))
            candidates.append(jax.ShapeDtypeStruct((batch, n), jnp.float32))
        elif "n_input" in d or "input_size" in d:  # Linear-like
            n = d.get("n_input", d.get("input_size"))
            if isinstance(n, int):
                candidates.append(
                    jax.ShapeDtypeStruct((batch, n), jnp.float32))
        elif "n_index" in d or "vocab_size" in d:  # LookupTable-like
            candidates.append(
                jax.ShapeDtypeStruct((batch, 16), jnp.int32))
    for spec in candidates:
        out = output_spec(model, spec)
        if out is not None:
            return spec, out
    return None
