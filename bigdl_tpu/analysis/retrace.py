"""Pass 3 — retrace/recompile detection for TrainStep/EvalStep.

``with trace_retraces() as mon:`` registers a monitor on the dispatch
hook points inside ``parallel/train_step.py``.  Every ``run``/
``run_scan``/``EvalStep.run`` reports its raw host arguments; the monitor
computes each leaf's *effective abstract value* (shape, dtype, weak
typing — exactly the jit cache key ingredients) and, when a later
dispatch differs, emits a Diagnostic naming the argument and the cause:

- ``retrace/shape-change``   — new static shape (or pytree structure),
- ``retrace/dtype-change``   — new dtype,
- ``retrace/weak-type``      — weak/strong flip for the same dtype,
- ``retrace/python-scalar``  — the flip came from a Python scalar
  alternating with an array,
- ``retrace/recompile``      — the jit executable cache grew with no
  visible argument change (hyperparameter edit / structural re-trace).

This replaces staring at ``jax.log_compiles`` output with an answer to
the actual question: *which argument* caused the retrace.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import numpy as np

from bigdl_tpu.analysis import hooks
from bigdl_tpu.analysis.diagnostics import Report

__all__ = ["trace_retraces", "RetraceMonitor"]


class _LeafSig(NamedTuple):
    shape: Tuple[int, ...]
    dtype: str
    weak: bool
    py_scalar: bool


def _leaf_signature(x) -> _LeafSig:
    import jax.numpy as jnp

    if isinstance(x, (bool, int, float, complex)):
        # a Python scalar enters jit as a weak-typed 0-d constant
        return _LeafSig((), jnp.result_type(type(x)).name, True, True)
    if isinstance(x, np.ndarray) or np.isscalar(x):
        a = np.asarray(x)
        return _LeafSig(tuple(a.shape), a.dtype.name, False, False)
    shape = tuple(getattr(x, "shape", ()))
    dtype = getattr(x, "dtype", None)
    weak = bool(getattr(x, "weak_type", False))
    # str(), not jnp.dtype(): PRNG keys carry extended dtypes ('key<fry>')
    # that numpy's dtype constructor rejects
    return _LeafSig(shape, str(dtype) if dtype is not None else "object",
                    weak, False)


def _signature(args: Dict[str, Any]) -> Dict[str, _LeafSig]:
    import jax

    out: Dict[str, _LeafSig] = {}
    for name, tree in args.items():
        if name.startswith("static:"):
            # static (Python-level) arguments enter the compile key by
            # VALUE, not abstract type — e.g. run_scan's n
            out[name] = _LeafSig((), f"static={tree!r}", False, False)
            continue
        leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
        for path, leaf in leaves:
            key = name + "".join(str(p) for p in path)
            out[key] = _leaf_signature(leaf)
    return out


class RetraceMonitor:
    """Collects retrace diagnostics; use via :func:`trace_retraces`."""

    def __init__(self, suppress=()):
        self.report = Report(suppress=suppress)
        self._seen: Dict[Tuple[int, str], Dict[str, _LeafSig]] = {}
        self._cache_sizes: Dict[Tuple[int, str], int] = {}
        self._dispatch_flagged: Dict[Tuple[int, str], bool] = {}
        self.dispatches = 0

    # -- hook callbacks ----------------------------------------------------
    def on_dispatch(self, owner, kind: str, args: Dict[str, Any]) -> None:
        self.dispatches += 1
        key = (id(owner), kind)
        sig = _signature(args)
        prev = self._seen.get(key)
        self._seen[key] = sig
        flagged = False
        if prev is not None:
            flagged = self._diff(kind, prev, sig)
        self._dispatch_flagged[key] = flagged

    def on_cache(self, owner, kind: str, size: int) -> None:
        key = (id(owner), kind)
        prev = self._cache_sizes.get(key)
        self._cache_sizes[key] = size
        if prev is not None and size > prev \
                and not self._dispatch_flagged.get(key, False):
            self.report.add(
                "retrace/recompile",
                f"{kind} recompiled (jit cache {prev} -> {size}) with no "
                f"argument shape/dtype change",
                where=kind,
                hint="a module hyperparameter or structure edit between "
                     "dispatches forces a re-trace")

    # -- diffing -----------------------------------------------------------
    def _diff(self, kind: str, prev: Dict[str, _LeafSig],
              cur: Dict[str, _LeafSig]) -> bool:
        flagged = False
        if set(prev) != set(cur):
            added = sorted(set(cur) - set(prev))
            gone = sorted(set(prev) - set(cur))
            self.report.add(
                "retrace/shape-change",
                f"argument pytree structure changed "
                f"(+{added or '[]'} -{gone or '[]'}) — every structure "
                f"recompiles",
                where=kind)
            return True
        for name in sorted(cur):
            p, c = prev[name], cur[name]
            if p == c:
                continue
            where = f"{kind}({name})"
            if p.dtype.startswith("static=") or \
                    c.dtype.startswith("static="):
                self.report.add(
                    "retrace/shape-change",
                    f"static argument changed {p.dtype[7:]} -> "
                    f"{c.dtype[7:]}; each distinct value compiles its "
                    f"own executable",
                    where=where,
                    hint="hold static/config arguments constant across "
                         "the hot loop")
                flagged = True
                continue
            if p.shape != c.shape:
                self.report.add(
                    "retrace/shape-change",
                    f"shape changed {list(p.shape)} -> {list(c.shape)}; "
                    f"each distinct shape compiles its own executable",
                    where=where,
                    hint="pad/bucket batches to a fixed set of shapes")
            elif p.dtype != c.dtype:
                self.report.add(
                    "retrace/dtype-change",
                    f"dtype changed {p.dtype} -> {c.dtype}",
                    where=where,
                    hint="convert once at the input pipeline boundary, "
                         "not per-step")
            elif p.weak != c.weak:
                if p.py_scalar or c.py_scalar:
                    self.report.add(
                        "retrace/python-scalar",
                        f"a Python scalar ({p.dtype}) alternates with an "
                        f"array here; the weak/strong type flip "
                        f"recompiles every flip",
                        where=where,
                        hint="pass jnp.asarray(value, dtype) consistently")
                else:
                    self.report.add(
                        "retrace/weak-type",
                        f"weak_type flipped {p.weak} -> {c.weak} for "
                        f"dtype {c.dtype}",
                        where=where,
                        hint="jnp.asarray with an explicit dtype makes "
                             "the type strong")
            else:
                continue
            flagged = True
        return flagged


class trace_retraces:
    """Context manager: ``with trace_retraces() as mon: ... mon.report``."""

    def __init__(self, suppress=()):
        self.monitor = RetraceMonitor(suppress=suppress)

    def __enter__(self) -> RetraceMonitor:
        hooks.register(self.monitor)
        return self.monitor

    def __exit__(self, *exc) -> Optional[bool]:
        hooks.unregister(self.monitor)
        return None
