"""``bigdl_tpu.analysis`` — static graph checker and tracer-leak linter.

Four passes over one shared diagnostics core (:class:`Diagnostic`
records with severity, rule id, module path, fix hint):

1. shape/dtype inference (``shape_pass``) — per-layer output specs via
   ``jax.eval_shape``; shape mismatches, f64 promotion, dead DAG nodes;
2. sharding validation (``sharding_pass``) — PartitionSpecs vs. the
   actual mesh axes;
3. retrace detection (``retrace``) — which argument caused each
   TrainStep/EvalStep recompile;
4. tracer-leak AST lint (``ast_lint``) — Python branches on tracers,
   ``np.*`` on tracers, host calls inside jitted regions.

CLI: ``python -m bigdl_tpu.analysis <model-name|all|path...>``.
Library: :func:`check_model`, :func:`lint_sources`,
:func:`trace_retraces`, :func:`check_partition_specs`.

This ``__init__`` stays import-light (PEP 562 lazy attributes): the
dispatch hook points in ``parallel/train_step.py`` import
``analysis.hooks`` on every process, and must not drag the whole
analyzer (or jax tracing helpers) in with them.
"""

from __future__ import annotations

from bigdl_tpu.analysis.diagnostics import (  # noqa: F401 - re-export
    RULES, Diagnostic, Report, Severity, rule_severity,
)

__all__ = [
    "Diagnostic", "Report", "Severity", "RULES", "rule_severity",
    "check_model", "lint_sources", "lint_source", "check_shapes",
    "output_spec", "infer_input_spec", "check_partition_specs",
    "check_train_step", "trace_retraces", "ModelCheckResult",
]

_LAZY = {
    "check_model": "bigdl_tpu.analysis.api",
    "ModelCheckResult": "bigdl_tpu.analysis.api",
    "lint_sources": "bigdl_tpu.analysis.api",
    "lint_source": "bigdl_tpu.analysis.ast_lint",
    "check_shapes": "bigdl_tpu.analysis.shape_pass",
    "output_spec": "bigdl_tpu.analysis.shape_pass",
    "infer_input_spec": "bigdl_tpu.analysis.shape_pass",
    "check_partition_specs": "bigdl_tpu.analysis.sharding_pass",
    "check_train_step": "bigdl_tpu.analysis.sharding_pass",
    "trace_retraces": "bigdl_tpu.analysis.retrace",
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
