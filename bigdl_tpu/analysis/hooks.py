"""Dispatch-observation hook points for the retrace detector.

Deliberately dependency-free (no jax import): ``parallel/train_step.py``
imports this at module load, and when no monitor is registered the
per-dispatch cost is one falsy check.  ``analysis.retrace.trace_retraces``
registers/unregisters monitors around a ``with`` block.
"""

from __future__ import annotations

from typing import Any, Dict, List

__all__ = ["hooks_active", "register", "unregister", "dispatch_event",
           "cache_event"]

_monitors: List[Any] = []


def hooks_active() -> bool:
    return bool(_monitors)


def register(monitor) -> None:
    _monitors.append(monitor)


def unregister(monitor) -> None:
    try:
        _monitors.remove(monitor)
    except ValueError:
        pass


def dispatch_event(owner, kind: str, args: Dict[str, Any]) -> None:
    """A step object is about to dispatch its compiled function with
    ``args`` (the raw, pre-placement host arguments)."""
    for m in list(_monitors):
        try:
            m.on_dispatch(owner, kind, args)
        except Exception:  # noqa: BLE001 - observers never kill the step
            pass


def cache_event(owner, kind: str, cache_size) -> None:
    """Post-dispatch: the owner's jit executable cache now holds
    ``cache_size`` entries (None when the jit internals are unavailable)."""
    if cache_size is None:
        return
    for m in list(_monitors):
        try:
            m.on_cache(owner, kind, cache_size)
        except Exception:  # noqa: BLE001 - observers never kill the step
            pass
