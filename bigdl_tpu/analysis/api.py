"""High-level analyzer entry points: ``check_model`` / ``lint_sources``.

Library twins of the ``python -m bigdl_tpu.analysis`` CLI — run the
static passes over a built model (or sources) and get one combined
:class:`~bigdl_tpu.analysis.diagnostics.Report` back.
"""

from __future__ import annotations

from typing import Any, Iterable, List, NamedTuple, Optional, Sequence

from bigdl_tpu.analysis.ast_lint import lint_paths
from bigdl_tpu.analysis.diagnostics import Report
from bigdl_tpu.analysis.shape_pass import LayerSpec, check_shapes
from bigdl_tpu.analysis.sharding_pass import check_train_step

__all__ = ["ModelCheckResult", "check_model", "lint_sources"]


class ModelCheckResult(NamedTuple):
    report: Report
    layers: List[LayerSpec]
    out: Any  # whole-model output spec, or None when the shape walk failed

    @property
    def ok(self) -> bool:
        return not self.report.errors


def check_model(model, input_spec, step=None,
                suppress: Iterable[str] = ()) -> ModelCheckResult:
    """Run the static passes over a built model *without executing it*.

    ``input_spec``: (pytree of) ``jax.ShapeDtypeStruct`` or example
    arrays — see ``models/registry.py`` for the zoo's canonical specs.
    ``step``: optionally a ``TrainStep`` whose parameter shardings are
    validated against its mesh (pass 2).
    """
    shape_res = check_shapes(model, input_spec, suppress=suppress)
    report = shape_res.report
    if step is not None:
        report.extend(check_train_step(step, suppress=suppress))
    return ModelCheckResult(report, shape_res.layers, shape_res.out)


def lint_sources(paths: Sequence[str],
                 suppress: Iterable[str] = ()) -> Report:
    """Tracer-leak AST lint (pass 4) over files/directories."""
    return lint_paths(paths, suppress=suppress)
