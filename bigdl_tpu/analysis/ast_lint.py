"""Pass 4 — tracer-leak AST lint over Python sources.

Finds the classic JAX footguns *statically*, before a trace ever runs:

- ``lint/tracer-branch`` — Python ``if``/``while``/``assert`` (or
  ``int()``/``float()``/``bool()`` concretization) on a traced value
  inside a jitted region;
- ``lint/tracer-numpy``  — ``np.*`` host calls consuming traced values
  inside a jitted region;
- ``lint/host-call``     — ``time.*`` / ``random.*`` / ``np.random.*``
  inside a jitted region (baked in as trace-time constants).

"Jitted region" is resolved lexically: a function decorated with
``jax.jit``-family decorators, or a local ``def``/``lambda`` passed to a
JAX transform (``jit``, ``grad``, ``vjp``, ``vmap``, ``eval_shape``,
``checkpoint``, ``lax.scan/while_loop/cond/fori_loop/switch``, ...).
Nested functions inherit region status and the enclosing taint set.
Inside a region, the function's parameters are *tainted* (they are
tracers); taint propagates through assignments — but NOT through the
static accessors (``.shape``/``.ndim``/``.dtype``/``len()``/
``isinstance()``/``x is None``), which is what keeps the usual
``if x.ndim == 3`` idiom clean.

Suppress a finding with a ``# noqa: <rule-id>`` comment on the line.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from bigdl_tpu.analysis.diagnostics import Report

__all__ = ["lint_source", "lint_paths", "DEFAULT_LINT_DIRS"]

DEFAULT_LINT_DIRS = ("bigdl_tpu", "tools", "examples")

#: decorator / call targets that make the wrapped function traced code
_TRANSFORMS = {
    "jit", "pjit", "grad", "value_and_grad", "vjp", "jvp", "linearize",
    "vmap", "pmap", "eval_shape", "make_jaxpr", "checkpoint", "remat",
    "scan", "while_loop", "cond", "fori_loop", "switch",
    "associative_scan", "custom_vjp", "custom_jvp", "shard_map",
}
_TRANSFORM_ROOTS = {"jax", "lax"}

#: attribute reads on a tracer that yield static (host) values
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type", "aval",
                 "itemsize", "nbytes"}
#: builtins that stay host-side regardless of argument
_STATIC_CALLS = {"len", "isinstance", "type", "getattr", "hasattr",
                 "callable", "issubclass"}
#: builtins that force a tracer to a concrete host value (leak)
_CONCRETIZING = {"int", "float", "bool", "complex"}
#: np.* functions that only touch static metadata
_NP_STATIC = {"shape", "ndim", "size", "result_type", "issubdtype",
              "promote_types", "dtype", "isscalar"}

_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<rules>[\w/,\s-]+))?", re.I)


def _collect_noqa(src: str) -> Dict[int, Optional[Set[str]]]:
    """line no -> None (blanket noqa) or set of rule ids."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, line in enumerate(src.splitlines(), 1):
        m = _NOQA_RE.search(line)
        if not m:
            continue
        rules = m.group("rules")
        out[i] = None if rules is None else \
            {r.strip() for r in rules.split(",") if r.strip()}
    return out


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_transform(func: ast.AST) -> bool:
    dotted = _dotted(func)
    if dotted is None:
        return False
    parts = dotted.split(".")
    return parts[-1] in _TRANSFORMS and \
        (len(parts) == 1 or parts[0] in _TRANSFORM_ROOTS)


def _is_jit_decorator(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        # @partial(jax.jit, ...) / @jax.jit(...) / @jax.checkpoint(...)
        dotted = _dotted(dec.func)
        if dotted in ("functools.partial", "partial") and dec.args:
            return _is_transform(dec.args[0])
        return _is_transform(dec.func)
    return _is_transform(dec)


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
#: ast.TryStar (except*) only exists on Python >= 3.11
_TRY_NODES = (ast.Try,) + ((ast.TryStar,) if hasattr(ast, "TryStar")
                           else ())


def _find_regions(tree: ast.AST) -> Set[ast.AST]:
    """All function/lambda nodes that are traced-code regions."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    def scope_of(node: ast.AST) -> Optional[ast.AST]:
        """Nearest enclosing function/lambda, or None for module level."""
        p = parents.get(node)
        while p is not None and not isinstance(p, _FUNC_NODES):
            p = parents.get(p)
        return p

    defs_by_scope: Dict[Tuple[str, Optional[ast.AST]], List[ast.AST]] = {}
    regions: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_scope.setdefault((node.name, scope_of(node)),
                                     []).append(node)
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                regions.add(node)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not _is_transform(node.func):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Lambda):
                regions.add(arg)
            elif isinstance(arg, ast.Name):
                # resolve like Python does: innermost enclosing scope that
                # defines the name wins — a module-level host helper must
                # NOT become a region because a local def shares its name
                scope: Optional[ast.AST] = scope_of(node)
                while True:
                    found = defs_by_scope.get((arg.id, scope))
                    if found:
                        regions.update(found)
                        break
                    if scope is None:
                        break
                    scope = scope_of(scope)
    return regions


class _RegionLinter:
    """Taint-tracking scan of one traced-code region."""

    def __init__(self, report: Report, filename: str,
                 noqa: Dict[int, Optional[Set[str]]]):
        self.report = report
        self.filename = filename
        self.noqa = noqa

    # -- reporting ---------------------------------------------------------
    def _emit(self, rule: str, node: ast.AST, message: str,
              hint: str = "") -> None:
        line = getattr(node, "lineno", 0)
        if line in self.noqa:
            rules = self.noqa[line]
            if rules is None or rule in rules:
                return
        self.report.add(rule, message,
                        where=f"{self.filename}:{line}", hint=hint)

    # -- traced-value analysis --------------------------------------------
    def _traced(self, node: ast.AST, tainted: Set[str]) -> bool:
        """Does this expression yield a traced value?"""
        t = self._traced
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return t(node.value, tainted)
        if isinstance(node, ast.Call):
            fname = _dotted(node.func)
            if fname in _STATIC_CALLS or fname in _CONCRETIZING:
                return False  # host value (concretization flagged elsewhere)
            args_traced = any(t(a, tainted) for a in node.args) or \
                any(t(kw.value, tainted) for kw in node.keywords)
            func_traced = isinstance(node.func, ast.Attribute) and \
                t(node.func.value, tainted)
            return args_traced or func_traced
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False  # identity checks are host-safe
            return t(node.left, tainted) or \
                any(t(c, tainted) for c in node.comparators)
        if isinstance(node, ast.BoolOp):
            return any(t(v, tainted) for v in node.values)
        if isinstance(node, (ast.BinOp,)):
            return t(node.left, tainted) or t(node.right, tainted)
        if isinstance(node, ast.UnaryOp):
            return t(node.operand, tainted)
        if isinstance(node, ast.Subscript):
            return t(node.value, tainted) or t(node.slice, tainted)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(t(e, tainted) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(t(v, tainted) for v in node.values if v is not None)
        if isinstance(node, ast.Starred):
            return t(node.value, tainted)
        if isinstance(node, ast.IfExp):
            return t(node.body, tainted) or t(node.orelse, tainted)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            inner = set(tainted)
            for gen in node.generators:
                if t(gen.iter, inner):
                    self._taint_target(gen.target, inner)
            return t(node.elt, inner)
        if isinstance(node, ast.DictComp):
            inner = set(tainted)
            for gen in node.generators:
                if t(gen.iter, inner):
                    self._taint_target(gen.target, inner)
            return t(node.key, inner) or t(node.value, inner)
        if isinstance(node, ast.NamedExpr):
            return t(node.value, tainted)
        if isinstance(node, ast.Slice):
            return any(t(x, tainted) for x in
                       (node.lower, node.upper, node.step) if x is not None)
        return False

    @staticmethod
    def _taint_target(target: ast.AST, tainted: Set[str]) -> None:
        for n in ast.walk(target):
            if isinstance(n, ast.Name):
                tainted.add(n.id)

    # -- per-expression rule checks ---------------------------------------
    def _check_calls(self, expr: ast.AST, tainted: Set[str]) -> None:
        for node in ast.walk(expr):
            if isinstance(node, _FUNC_NODES):
                continue  # nested functions handled by region recursion
            if isinstance(node, ast.IfExp) and \
                    self._traced(node.test, tainted):
                self._emit("lint/tracer-branch", node,
                           "conditional expression selects on a traced "
                           "value inside a jitted region",
                           hint="use jnp.where / lax.select")
            if not isinstance(node, ast.Call):
                continue
            fname = _dotted(node.func)
            if fname is None:
                continue
            parts = fname.split(".")
            args_traced = any(self._traced(a, tainted) for a in node.args) \
                or any(self._traced(kw.value, tainted)
                       for kw in node.keywords)
            if parts[0] in ("time", "datetime") or \
                    parts[0] == "random" and len(parts) > 1 or \
                    (parts[0] in ("np", "numpy") and len(parts) > 2
                     and parts[1] == "random"):
                self._emit("lint/host-call", node,
                           f"host call {fname}() inside a jitted region "
                           f"executes once at trace time and is baked in "
                           f"as a constant",
                           hint="hoist it out of the traced function; for "
                                "randomness thread a jax.random key")
            elif parts[0] in ("np", "numpy") and \
                    parts[-1] not in _NP_STATIC and args_traced:
                self._emit("lint/tracer-numpy", node,
                           f"{fname}() consumes a traced value inside a "
                           f"jitted region — numpy cannot operate on "
                           f"tracers",
                           hint="use the jnp equivalent")
            elif fname in _CONCRETIZING and args_traced:
                self._emit("lint/tracer-branch", node,
                           f"{fname}() concretizes a traced value inside "
                           f"a jitted region (ConcretizationTypeError at "
                           f"trace time)",
                           hint="keep the value abstract, or mark the "
                                "argument static")

    # -- statement walk ----------------------------------------------------
    def scan(self, fn: ast.AST, closure_taint: Set[str]) -> None:
        tainted = set(closure_taint)
        args = fn.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            if a.arg not in ("self", "cls"):
                tainted.add(a.arg)
        if isinstance(fn, ast.Lambda):
            self._check_calls(fn.body, tainted)
            return
        self._scan_stmts(fn.body, tainted)

    def _scan_stmts(self, stmts: Sequence[ast.stmt],
                    tainted: Set[str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.scan(stmt, tainted)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                if self._traced(stmt.test, tainted):
                    kind = "if" if isinstance(stmt, ast.If) else "while"
                    self._emit(
                        "lint/tracer-branch", stmt,
                        f"Python `{kind}` branches on a traced value "
                        f"inside a jitted region "
                        f"(TracerBoolConversionError at trace time)",
                        hint="use lax.cond / lax.while_loop / jnp.where")
                self._check_calls(stmt.test, tainted)
                self._scan_stmts(stmt.body, tainted)
                self._scan_stmts(stmt.orelse, tainted)
                continue
            if isinstance(stmt, ast.Assert):
                if self._traced(stmt.test, tainted):
                    self._emit("lint/tracer-branch", stmt,
                               "assert on a traced value inside a jitted "
                               "region",
                               hint="use checkify or debug.check")
                self._check_calls(stmt.test, tainted)
                continue
            if isinstance(stmt, ast.For):
                self._check_calls(stmt.iter, tainted)
                if self._traced(stmt.iter, tainted):
                    self._taint_target(stmt.target, tainted)
                self._scan_stmts(stmt.body, tainted)
                self._scan_stmts(stmt.orelse, tainted)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._check_calls(item.context_expr, tainted)
                    if item.optional_vars is not None and \
                            self._traced(item.context_expr, tainted):
                        self._taint_target(item.optional_vars, tainted)
                self._scan_stmts(stmt.body, tainted)
                continue
            if isinstance(stmt, _TRY_NODES):
                self._scan_stmts(stmt.body, tainted)
                for h in stmt.handlers:
                    self._scan_stmts(h.body, tainted)
                self._scan_stmts(stmt.orelse, tainted)
                self._scan_stmts(stmt.finalbody, tainted)
                continue
            if isinstance(stmt, ast.Match):
                if self._traced(stmt.subject, tainted):
                    self._emit("lint/tracer-branch", stmt,
                               "match on a traced value inside a jitted "
                               "region (structural matching concretizes "
                               "the tracer)",
                               hint="use lax.switch / jnp.where")
                self._check_calls(stmt.subject, tainted)
                for case in stmt.cases:
                    if case.guard is not None:
                        if self._traced(case.guard, tainted):
                            self._emit("lint/tracer-branch", case.guard,
                                       "match-case guard on a traced "
                                       "value inside a jitted region")
                        self._check_calls(case.guard, tainted)
                    self._scan_stmts(case.body, tainted)
                continue
            # taint propagation through assignments
            if isinstance(stmt, ast.Assign):
                self._check_calls(stmt.value, tainted)
                if self._traced(stmt.value, tainted):
                    for target in stmt.targets:
                        self._taint_target(target, tainted)
                continue
            if isinstance(stmt, ast.AugAssign):
                self._check_calls(stmt.value, tainted)
                if self._traced(stmt.value, tainted):
                    self._taint_target(stmt.target, tainted)
                continue
            if isinstance(stmt, ast.AnnAssign):
                if stmt.value is not None:
                    self._check_calls(stmt.value, tainted)
                    if self._traced(stmt.value, tainted):
                        self._taint_target(stmt.target, tainted)
                continue
            if isinstance(stmt, (ast.Return, ast.Expr)):
                if stmt.value is not None:
                    self._check_calls(stmt.value, tainted)
                continue
            # everything else (pass, break, imports, raise, ...): still
            # sweep any embedded expressions for rule hits
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._check_calls(child, tainted)


def lint_source(src: str, filename: str = "<string>",
                suppress: Iterable[str] = ()) -> Report:
    """Lint one Python source text; returns the findings Report."""
    report = Report(suppress=suppress)
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as e:
        report.add("lint/tracer-branch",
                   f"file does not parse: {e}", where=f"{filename}:"
                   f"{e.lineno or 0}")
        return report
    noqa = _collect_noqa(src)
    regions = _find_regions(tree)
    # only lint top-level regions; nested defs are visited via recursion
    # with the enclosing taint (a region inside a region must inherit it)
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    def enclosed_in_region(node: ast.AST) -> bool:
        p = parents.get(node)
        while p is not None:
            if p in regions:
                return True
            p = parents.get(p)
        return False

    linter = _RegionLinter(report, filename, noqa)
    for region in sorted(regions, key=lambda n: n.lineno):
        if not enclosed_in_region(region):
            linter.scan(region, set())
    return report


def lint_paths(paths: Sequence[str],
               suppress: Iterable[str] = ()) -> Report:
    """Lint every ``*.py`` under the given files/directories."""
    report = Report(suppress=suppress)
    files: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, names in os.walk(path):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                files.extend(os.path.join(root, n) for n in sorted(names)
                             if n.endswith(".py"))
        elif os.path.isfile(path):
            # an EXPLICIT file target is linted whatever its name
            # (extensionless scripts); only the directory walk filters
            files.append(path)
    for f in sorted(set(files)):
        try:
            with open(f, "r", encoding="utf-8") as fh:
                src = fh.read()
        except OSError as e:
            report.add("lint/tracer-branch", f"cannot read: {e}", where=f)
            continue
        report.extend(lint_source(src, filename=f, suppress=suppress))
    return report
