"""Shared diagnostics core for the static analyzer.

Every pass (shape inference, sharding validation, retrace detection, the
AST lint) reports through the same :class:`Diagnostic` record so tooling —
the ``python -m bigdl_tpu.analysis`` CLI, ``tools/lint_graft.py``, the
pytest wiring — renders and filters findings uniformly.  The reference
has no analogue: model-construction errors there surface at runtime as
Spark executor exceptions (``LayerException`` wrapping deep inside a
task); here XLA's abstract evaluation lets every rule run *before* the
first expensive compile.
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional

__all__ = ["Severity", "Diagnostic", "Report", "RULES", "rule_severity"]


class Severity(enum.IntEnum):
    """Ordered so ``max(severities)`` is the worst finding."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # 'error', not 'Severity.ERROR'
        return self.name.lower()


#: The rule catalog: id -> (default severity, one-line description).
#: Ids are stable API — tests assert on them and suppressions name them.
RULES: Dict[str, tuple] = {
    # shape/dtype inference pass (analysis.shape_pass)
    "shape/mismatch": (Severity.ERROR,
                       "a layer fails abstract evaluation (shape or dtype "
                       "error) for the given input spec"),
    "shape/f64": (Severity.ERROR,
                  "a layer promotes a non-float64 input to float64 "
                  "(silent 2x memory + off-MXU compute on TPU)"),
    "shape/dead-node": (Severity.WARNING,
                        "a graph node is fed by the inputs but contributes "
                        "to no output (dead code in the model DAG)"),
    "shape/input-arity": (Severity.ERROR,
                          "the input spec arity differs from the graph's "
                          "input-node count"),
    # graph construction (nn.graph raises these as GraphBuildError)
    "graph/duplicate-name": (Severity.ERROR,
                             "two distinct modules in one Graph share an "
                             "explicit name (lookups/stop_gradient would "
                             "silently pick one)"),
    "graph/cycle": (Severity.ERROR,
                    "the module DAG contains a cycle (use ops.control "
                    "while/cond for loops)"),
    # sharding validation pass (analysis.sharding_pass)
    "shard/unknown-axis": (Severity.ERROR,
                           "a PartitionSpec names a mesh axis that does not "
                           "exist on the mesh"),
    "shard/indivisible": (Severity.ERROR,
                          "a sharded dimension is not divisible by the "
                          "product of its mesh axis sizes"),
    "shard/rank-mismatch": (Severity.ERROR,
                            "a PartitionSpec has more entries than the "
                            "array has dimensions"),
    "shard/duplicate-axis": (Severity.ERROR,
                             "a PartitionSpec uses the same mesh axis in "
                             "more than one dimension"),
    "shard/rule-error": (Severity.ERROR,
                         "a sharding-rules callable raised instead of "
                         "returning a PartitionSpec/None"),
    "shard/replicated-large": (Severity.WARNING,
                               "a large parameter is fully replicated on a "
                               "multi-device mesh (candidate for ZeRO/TP "
                               "sharding)"),
    # retrace detection (analysis.retrace)
    "retrace/shape-change": (Severity.WARNING,
                             "an argument's shape (or pytree structure) "
                             "changed between dispatches — each new shape "
                             "recompiles"),
    "retrace/dtype-change": (Severity.WARNING,
                             "an argument's dtype changed between "
                             "dispatches — each new dtype recompiles"),
    "retrace/weak-type": (Severity.WARNING,
                          "an argument flipped between weak and strong "
                          "typing between dispatches"),
    "retrace/python-scalar": (Severity.WARNING,
                              "a Python scalar argument alternates with an "
                              "array (weak/strong flip) — pass a jnp array "
                              "of fixed dtype"),
    "retrace/recompile": (Severity.WARNING,
                          "the jit cache grew without a visible argument "
                          "change (hyperparameter edit or structural "
                          "change re-traced the step)"),
    # tracer-leak AST lint (analysis.ast_lint)
    "lint/tracer-branch": (Severity.ERROR,
                           "Python if/while branches on a traced value "
                           "inside a jitted region (TracerBoolConversion "
                           "at runtime; use lax.cond/select)"),
    "lint/tracer-numpy": (Severity.ERROR,
                          "a numpy host function consumes a traced value "
                          "inside a jitted region (forces a host sync or "
                          "fails under trace; use jnp)"),
    "lint/host-call": (Severity.ERROR,
                       "a host side-effect (time.*, random.*, np.random.*) "
                       "inside a jitted region is baked in as a constant "
                       "at trace time"),
}


def rule_severity(rule: str) -> Severity:
    return RULES[rule][0] if rule in RULES else Severity.ERROR


@dataclass
class Diagnostic:
    """One finding: where, what rule, how bad, and how to fix it."""

    rule: str
    message: str
    #: module path ("features.3.conv1") or file location ("x.py:12")
    where: str = ""
    severity: Optional[Severity] = None
    hint: str = ""

    def __post_init__(self):
        if self.severity is None:
            self.severity = rule_severity(self.rule)

    def format(self) -> str:
        loc = f"{self.where}: " if self.where else ""
        txt = f"{loc}{self.severity}: [{self.rule}] {self.message}"
        if self.hint:
            txt += f"\n    hint: {self.hint}"
        return txt

    def to_json(self) -> dict:
        return {"rule": self.rule, "severity": str(self.severity),
                "where": self.where, "message": self.message,
                "hint": self.hint}


class Report:
    """An ordered collection of diagnostics with filtering/suppression."""

    def __init__(self, suppress: Iterable[str] = ()):
        self.diagnostics: List[Diagnostic] = []
        self._suppress = set(suppress)

    def add(self, rule: str, message: str, where: str = "",
            hint: str = "", severity: Optional[Severity] = None) -> None:
        if rule in self._suppress:
            return
        self.diagnostics.append(
            Diagnostic(rule=rule, message=message, where=where, hint=hint,
                       severity=severity))

    def extend(self, other: "Report") -> None:
        for d in other.diagnostics:
            if d.rule not in self._suppress:
                self.diagnostics.append(d)

    def __iter__(self) -> Iterator[Diagnostic]:
        return iter(self.diagnostics)

    def __len__(self) -> int:
        return len(self.diagnostics)

    def by_severity(self, severity: Severity) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == severity]

    @property
    def errors(self) -> List[Diagnostic]:
        return self.by_severity(Severity.ERROR)

    @property
    def warnings(self) -> List[Diagnostic]:
        return self.by_severity(Severity.WARNING)

    def rules_fired(self) -> List[str]:
        return [d.rule for d in self.diagnostics]

    def format(self) -> str:
        lines = [d.format() for d in self.diagnostics]
        lines.append(f"{len(self.errors)} error(s), "
                     f"{len(self.warnings)} warning(s)")
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps([d.to_json() for d in self.diagnostics], indent=2)
