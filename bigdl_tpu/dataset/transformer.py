"""Composable data transformers (``dataset/Transformer.scala:44-86``).

A Transformer maps ``Iterator[A] -> Iterator[B]`` and composes with ``>>``
(the reference's ``->``) into a ChainedTransformer.  Transformers are
host-side (numpy) — the device only ever sees finished MiniBatches.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional

import numpy as np

__all__ = ["Transformer", "ChainedTransformer", "SampleToMiniBatch", "Identity"]


class Transformer:
    def apply(self, it: Iterator) -> Iterator:
        raise NotImplementedError

    def __call__(self, it: Iterable) -> Iterator:
        return self.apply(iter(it))

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        return ChainedTransformer(self, other)

    def clone_transformer(self) -> "Transformer":
        import copy

        return copy.deepcopy(self)


class ChainedTransformer(Transformer):
    def __init__(self, first: Transformer, second: Transformer):
        self.first, self.second = first, second

    def apply(self, it):
        return self.second(self.first(it))


class Identity(Transformer):
    def apply(self, it):
        return it


class SampleToMiniBatch(Transformer):
    """Batch Samples into MiniBatches with optional padding
    (``dataset/Transformer.scala:309`` SampleToMiniBatch + the padding
    strategies of ``dataset/MiniBatch.scala:333-452``).

    ``feature_padding_param``/``label_padding_param`` pad variable-length
    samples to a common shape; ``fixed_length`` pads every batch to the same
    length — essential on TPU to avoid per-batch recompilation."""

    def __init__(self, batch_size: int, feature_padding_param=None,
                 label_padding_param=None, partition_num: Optional[int] = None,
                 drop_last: bool = False):
        self.batch_size = batch_size
        self.feature_padding = feature_padding_param
        self.label_padding = label_padding_param
        self.drop_last = drop_last

    def apply(self, it):
        from bigdl_tpu.dataset.minibatch import MiniBatch

        buf: List = []
        for sample in it:
            buf.append(sample)
            if len(buf) == self.batch_size:
                yield MiniBatch.from_samples(buf, self.feature_padding, self.label_padding)
                buf = []
        if buf and not self.drop_last:
            yield MiniBatch.from_samples(buf, self.feature_padding, self.label_padding)
