"""MiniBatch (``dataset/MiniBatch.scala:33``): stacked batch of Samples
with ``size/slice/get_input/get_target`` and the padding strategies."""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from bigdl_tpu.dataset.sample import PaddingParam, Sample

__all__ = ["MiniBatch"]


def _pad_stack(arrays: List[np.ndarray], param: Optional[PaddingParam]) -> np.ndarray:
    """Stack arrays, padding the leading axis (and any ragged trailing axes)
    to a common shape."""
    shapes = [a.shape for a in arrays]
    if len(set(shapes)) == 1 and (param is None or param.fixed_length is None):
        return np.stack(arrays)
    pad_value = param.padding_value if param else 0.0
    ndim = arrays[0].ndim
    target = [max(s[d] for s in shapes) for d in range(ndim)]
    if param is not None and param.fixed_length is not None:
        if param.fixed_length < target[0]:
            raise ValueError(
                f"fixed_length {param.fixed_length} < longest sample {target[0]}")
        target[0] = param.fixed_length
    out = np.full((len(arrays), *target), pad_value, dtype=arrays[0].dtype)
    for i, a in enumerate(arrays):
        sl = (i,) + tuple(slice(0, d) for d in a.shape)
        out[sl] = a
    return out


class MiniBatch:
    def __init__(self, inputs, targets=None):
        self.inputs: List[np.ndarray] = inputs if isinstance(inputs, list) else [inputs]
        self.targets: List[np.ndarray] = (targets if isinstance(targets, list) else [targets]) \
            if targets is not None else []

    @staticmethod
    def from_samples(samples: Sequence[Sample],
                     feature_padding: Optional[PaddingParam] = None,
                     label_padding: Optional[PaddingParam] = None) -> "MiniBatch":
        n_feat = len(samples[0].features)
        n_lab = len(samples[0].labels)
        inputs = [_pad_stack([s.features[i] for s in samples], feature_padding)
                  for i in range(n_feat)]
        targets = [_pad_stack([s.labels[i] for s in samples], label_padding)
                   for i in range(n_lab)]
        return MiniBatch(inputs, targets or None)

    def size(self) -> int:
        return self.inputs[0].shape[0]

    def get_input(self):
        return self.inputs[0] if len(self.inputs) == 1 else self.inputs

    def get_target(self):
        if not self.targets:
            return None
        return self.targets[0] if len(self.targets) == 1 else self.targets

    def slice(self, offset: int, length: int) -> "MiniBatch":
        return MiniBatch([a[offset:offset + length] for a in self.inputs],
                         [a[offset:offset + length] for a in self.targets] or None)

    def __repr__(self):
        return f"MiniBatch(inputs={[a.shape for a in self.inputs]}, " \
               f"targets={[a.shape for a in self.targets]})"
