"""Text transforms (SURVEY §2.6, ``dataset/text/`` — 8 files).

The reference's text path: sentence split/tokenize (OpenNLP) → Dictionary
→ TextToLabeledSentence (token→index) → LabeledSentenceToSample (one-hot
or index features, shifted-label targets for LM) → padded batching.
Re-expressed here with a regex tokenizer and NumPy; variable lengths are
handled by bucketed padding so jit shapes stay static (SURVEY §7
"variable-length sequences")."""

from __future__ import annotations

import re
from collections import Counter
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer

__all__ = [
    "SentenceSplitter", "SentenceTokenizer", "Dictionary",
    "TextToLabeledSentence", "LabeledSentence", "LabeledSentenceToSample",
    "SentenceBiPadding", "BucketedPadding",
]

_SENT_RE = re.compile(r"(?<=[.!?])\s+")
_TOKEN_RE = re.compile(r"[A-Za-z0-9']+|[.,!?;]")

SENTENCE_START = "SENTENCE_START"
SENTENCE_END = "SENTENCE_END"


class SentenceSplitter(Transformer):
    """text → sentences (``SentenceSplitter.scala``; regex instead of
    OpenNLP's learned splitter)."""

    def apply(self, it: Iterator[str]) -> Iterator[str]:
        for text in it:
            for s in _SENT_RE.split(text.strip()):
                if s:
                    yield s


class SentenceTokenizer(Transformer):
    """sentence → token list (``SentenceTokenizer.scala``)."""

    def __init__(self, lower: bool = True):
        self.lower = lower

    def apply(self, it: Iterator[str]) -> Iterator[List[str]]:
        for s in it:
            if self.lower:
                s = s.lower()
            toks = _TOKEN_RE.findall(s)
            if toks:
                yield toks


class SentenceBiPadding(Transformer):
    """Wrap token lists with SENTENCE_START/SENTENCE_END markers
    (``SentenceBiPadding.scala``)."""

    def apply(self, it: Iterator[List[str]]) -> Iterator[List[str]]:
        for toks in it:
            yield [SENTENCE_START] + toks + [SENTENCE_END]


class Dictionary:
    """Vocabulary with frequency-ranked indices and an UNK bucket
    (``Dictionary.scala``: vocabSize keeps the top-k words, the rest map
    to an out-of-vocab index)."""

    UNK = "<unk>"

    def __init__(self, sentences: Optional[Iterable[List[str]]] = None,
                 vocab_size: Optional[int] = None):
        self.word2index: Dict[str, int] = {}
        self.index2word: List[str] = []
        if sentences is not None:
            counts = Counter(tok for s in sentences for tok in s)
            top = counts.most_common(vocab_size)
            for w, _ in top:
                self.word2index[w] = len(self.index2word)
                self.index2word.append(w)
        if self.UNK not in self.word2index:
            self.word2index[self.UNK] = len(self.index2word)
            self.index2word.append(self.UNK)

    @property
    def vocab_size(self) -> int:
        return len(self.index2word)

    def index(self, word: str) -> int:
        return self.word2index.get(word, self.word2index[self.UNK])

    def word(self, idx: int) -> str:
        return self.index2word[idx]

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            for w in self.index2word:
                f.write(w + "\n")

    @classmethod
    def load(cls, path: str) -> "Dictionary":
        d = cls()
        d.word2index, d.index2word = {}, []
        with open(path) as f:
            for line in f:
                w = line.rstrip("\n")
                d.word2index[w] = len(d.index2word)
                d.index2word.append(w)
        if cls.UNK not in d.word2index:
            d.word2index[cls.UNK] = len(d.index2word)
            d.index2word.append(cls.UNK)
        return d


class LabeledSentence:
    """Token-index sequence + label sequence (``LabeledSentence.scala``)."""

    __slots__ = ("data", "label")

    def __init__(self, data: np.ndarray, label: np.ndarray):
        self.data = np.asarray(data, np.int64)
        self.label = np.asarray(label, np.int64)


class TextToLabeledSentence(Transformer):
    """Token list → LabeledSentence.  Language-model convention like the
    reference (``TextToLabeledSentence.scala``): data = tokens[:-1],
    label = tokens[1:] (next-word targets)."""

    def __init__(self, dictionary: Dictionary):
        self.dictionary = dictionary

    def apply(self, it: Iterator[List[str]]) -> Iterator[LabeledSentence]:
        for toks in it:
            idx = np.asarray([self.dictionary.index(t) for t in toks],
                             np.int64)
            if len(idx) < 2:
                continue
            yield LabeledSentence(idx[:-1], idx[1:])


class LabeledSentenceToSample(Transformer):
    """LabeledSentence → Sample, optionally one-hot features
    (``LabeledSentenceToSample.scala``).  Fixed-length padding keeps jit
    shapes static; pad index 0 like the reference's padding value."""

    def __init__(self, vocab_size: int, fixed_length: Optional[int] = None,
                 one_hot: bool = False):
        self.vocab_size = vocab_size
        self.fixed_length = fixed_length
        self.one_hot = one_hot

    def apply(self, it: Iterator[LabeledSentence]) -> Iterator[Sample]:
        for s in it:
            data, label = s.data, s.label
            if self.fixed_length is not None:
                L = self.fixed_length
                data = np.pad(data[:L], (0, max(0, L - len(data))))
                label = np.pad(label[:L], (0, max(0, L - len(label))))
            if self.one_hot:
                feat = np.zeros((len(data), self.vocab_size), np.float32)
                feat[np.arange(len(data)), data] = 1.0
            else:
                feat = data
            yield Sample(feat, label)


class BucketedPadding(Transformer):
    """Group sentences into length buckets and pad within the bucket —
    bounded shape-polymorphism so XLA compiles one program per bucket,
    not per length (SURVEY §7 hard-parts list).

    Sentences longer than the largest boundary are TRUNCATED to it (the
    largest boundary acts as max sequence length); a warning is logged the
    first time this happens."""

    def __init__(self, boundaries: Sequence[int]):
        self.boundaries = sorted(boundaries)
        self._warned_truncation = False

    def bucket_of(self, n: int) -> int:
        for b in self.boundaries:
            if n <= b:
                return b
        return self.boundaries[-1]

    def apply(self, it: Iterator[LabeledSentence]) -> Iterator[LabeledSentence]:
        import logging

        for s in it:
            b = self.bucket_of(len(s.data))
            if len(s.data) > b and not self._warned_truncation:
                logging.getLogger("bigdl_tpu.dataset").warning(
                    "BucketedPadding: sentence of length %d truncated to "
                    "largest bucket %d", len(s.data), b)
                self._warned_truncation = True
            data = np.pad(s.data[:b], (0, max(0, b - len(s.data))))
            label = np.pad(s.label[:b], (0, max(0, b - len(s.label))))
            yield LabeledSentence(data, label)
