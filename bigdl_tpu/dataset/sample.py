"""Sample — one labeled record (``dataset/Sample.scala:31,126``)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

__all__ = ["Sample", "PaddingParam"]


class Sample:
    """Feature tensor(s) + label tensor(s), host-side numpy."""

    def __init__(self, features, labels=None):
        self.features: List[np.ndarray] = [np.asarray(f) for f in _as_list(features)]
        self.labels: List[np.ndarray] = [np.asarray(l) for l in _as_list(labels)] \
            if labels is not None else []

    @property
    def feature(self) -> np.ndarray:
        return self.features[0]

    @property
    def label(self) -> np.ndarray:
        return self.labels[0]

    def feature_size(self):
        return [f.shape for f in self.features]

    def label_size(self):
        return [l.shape for l in self.labels]

    def __repr__(self):
        return f"Sample(features={[f.shape for f in self.features]}, " \
               f"labels={[l.shape for l in self.labels]})"


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class PaddingParam:
    """Padding strategy (``dataset/MiniBatch.scala`` PaddingParam /
    DefaultPadding): pad value per tensor and optional fixed target length
    along the first (time) axis."""

    def __init__(self, padding_value: float = 0.0,
                 fixed_length: Optional[int] = None):
        self.padding_value = padding_value
        self.fixed_length = fixed_length
