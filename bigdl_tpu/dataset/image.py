"""Image transforms (SURVEY §2.6, ``dataset/image/`` — 24 files).

The reference's image pipeline is a chain of ``Transformer`` stages over
label-carrying image records: bytes decode → normalize → crop → flip →
color jitter → PCA lighting → batch.  Here the record type is
:class:`LabeledImage` (uint8/float32 HWC array + label), the stages are
the same capabilities re-expressed over NumPy, and the multithreaded
batcher (``MTLabeledBGRImgToBatch.scala``) rides the native C++ assembler
(``bigdl_tpu.native.batch_crop_normalize``)."""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils.rng import RNG

__all__ = [
    "LabeledImage", "BytesToImage", "ImageNormalizer", "CenterCropper",
    "RandomCropper", "HFlip", "ColorJitter", "Lighting", "ImageToSample",
    "GreyImgNormalizer", "GreyImgToSample", "MTImageToBatch",
    "channel_mean_std",
]


class LabeledImage:
    """One image record: HWC ndarray (uint8 or float32) + float label
    (the reference's ``LabeledBGRImage``/``LabeledGreyImage``)."""

    __slots__ = ("data", "label")

    def __init__(self, data: np.ndarray, label: float = 0.0):
        self.data = data
        self.label = label

    @property
    def height(self) -> int:
        return self.data.shape[0]

    @property
    def width(self) -> int:
        return self.data.shape[1]


class BytesToImage(Transformer):
    """(bytes, label) → LabeledImage.  The reference decodes JPEG via
    javax.imageio (``BytesToBGRImg.scala``); here raw byte records carry a
    (h, w, c) header-free layout supplied at construction, or decode via
    PIL when available."""

    def __init__(self, height: Optional[int] = None,
                 width: Optional[int] = None, channels: int = 3):
        self.height, self.width, self.channels = height, width, channels

    def apply(self, it: Iterator) -> Iterator[LabeledImage]:
        for rec in it:
            data, label = rec
            if isinstance(data, np.ndarray):
                yield LabeledImage(data, label)
                continue
            if self.height is not None:
                arr = np.frombuffer(data, np.uint8).reshape(
                    self.height, self.width, self.channels)
                yield LabeledImage(arr, label)
            else:
                import io

                from PIL import Image  # optional path

                arr = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
                yield LabeledImage(arr, label)


class ImageNormalizer(Transformer):
    """Per-channel (x - mean) / std, uint8 → float32
    (``BGRImgNormalizer.scala``)."""

    def __init__(self, mean: Sequence[float], std: Sequence[float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def apply(self, it: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        for img in it:
            data = (img.data.astype(np.float32) - self.mean) / self.std
            yield LabeledImage(data, img.label)


class CenterCropper(Transformer):
    """Deterministic center crop (``BGRImgCropper`` CropCenter)."""

    def __init__(self, crop_h: int, crop_w: int):
        self.crop_h, self.crop_w = crop_h, crop_w

    def apply(self, it: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        for img in it:
            oy = (img.height - self.crop_h) // 2
            ox = (img.width - self.crop_w) // 2
            yield LabeledImage(
                img.data[oy:oy + self.crop_h, ox:ox + self.crop_w],
                img.label)


class RandomCropper(Transformer):
    """Uniform random crop (``BGRImgRdmCropper.scala``)."""

    def __init__(self, crop_h: int, crop_w: int):
        self.crop_h, self.crop_w = crop_h, crop_w

    def apply(self, it: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        for img in it:
            oy = int(RNG.randint(0, img.height - self.crop_h + 1))
            ox = int(RNG.randint(0, img.width - self.crop_w + 1))
            yield LabeledImage(
                img.data[oy:oy + self.crop_h, ox:ox + self.crop_w],
                img.label)


class HFlip(Transformer):
    """Random horizontal flip with probability p (``HFlip.scala``)."""

    def __init__(self, p: float = 0.5):
        self.p = p

    def apply(self, it: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        for img in it:
            if RNG.uniform() < self.p:
                yield LabeledImage(img.data[:, ::-1], img.label)
            else:
                yield img


class ColorJitter(Transformer):
    """Random brightness/contrast/saturation in random order
    (``ColorJitter.scala``): each scales toward/away from a reference
    statistic by alpha ~ U[1-var, 1+var]."""

    def __init__(self, brightness: float = 0.4, contrast: float = 0.4,
                 saturation: float = 0.4):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation

    @staticmethod
    def _grayscale(x: np.ndarray) -> np.ndarray:
        # luma weights over the last (channel) axis, broadcast back
        g = x @ np.asarray([0.299, 0.587, 0.114], np.float32)
        return np.repeat(g[..., None], x.shape[-1], axis=-1)

    def _blend(self, x, target, var):
        alpha = 1.0 + (RNG.uniform() * 2.0 - 1.0) * var
        return alpha * x + (1.0 - alpha) * target

    def apply(self, it: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        for img in it:
            x = img.data.astype(np.float32)
            order = RNG.permutation(3)
            for op in order:
                if op == 0 and self.brightness > 0:
                    x = self._blend(x, 0.0, self.brightness)
                elif op == 1 and self.contrast > 0:
                    x = self._blend(x, self._grayscale(x).mean(),
                                    self.contrast)
                elif op == 2 and self.saturation > 0:
                    x = self._blend(x, self._grayscale(x), self.saturation)
            yield LabeledImage(x, img.label)


class Lighting(Transformer):
    """AlexNet-style PCA lighting noise (``Lighting.scala``): add
    eigvec @ (alpha * eigval), alpha ~ N(0, 0.1) per channel."""

    # ImageNet RGB eigen decomposition (public constants)
    EIGVAL = np.asarray([0.2175, 0.0188, 0.0045], np.float32)
    EIGVEC = np.asarray([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alpha_std: float = 0.1):
        self.alpha_std = alpha_std

    def apply(self, it: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        for img in it:
            alpha = np.asarray(RNG.normal(0.0, self.alpha_std, size=3),
                               np.float32)
            noise = self.EIGVEC @ (alpha * self.EIGVAL)
            yield LabeledImage(img.data.astype(np.float32) + noise,
                               img.label)


class ImageToSample(Transformer):
    """LabeledImage → Sample with CHW feature layout
    (``BGRImgToSample.scala``); labels stay 0-based int64."""

    def apply(self, it: Iterator[LabeledImage]) -> Iterator[Sample]:
        for img in it:
            feat = np.ascontiguousarray(
                img.data.astype(np.float32).transpose(2, 0, 1))
            yield Sample(feat, np.int64(img.label))


class GreyImgNormalizer(Transformer):
    """Single-channel (x - mean) / std (``GreyImgNormalizer.scala``,
    the MNIST path)."""

    def __init__(self, mean: float, std: float):
        self.mean, self.std = float(mean), float(std)

    def apply(self, it: Iterator[LabeledImage]) -> Iterator[LabeledImage]:
        for img in it:
            yield LabeledImage(
                (img.data.astype(np.float32) - self.mean) / self.std,
                img.label)


class GreyImgToSample(Transformer):
    """[H,W] or [H,W,1] grey image → Sample [1,H,W]."""

    def apply(self, it: Iterator[LabeledImage]) -> Iterator[Sample]:
        for img in it:
            d = img.data.astype(np.float32)
            if d.ndim == 3:
                d = d[..., 0]
            yield Sample(d[None, :, :], np.int64(img.label))


class MTImageToBatch(Transformer):
    """Multithreaded crop+normalize+flip straight into an NCHW float32
    batch via the native C++ assembler — the reference's
    ``MTLabeledBGRImgToBatch.scala`` hot path.  Consumes uint8
    LabeledImages of uniform size; emits (features, labels) ndarray
    pairs."""

    def __init__(self, batch_size: int, crop_h: int, crop_w: int,
                 mean: Sequence[float], std: Sequence[float],
                 random_crop: bool = True, hflip: bool = True,
                 num_threads: int = 0):
        self.batch_size = batch_size
        self.crop_h, self.crop_w = crop_h, crop_w
        self.mean, self.std = mean, std
        self.random_crop = random_crop
        self.hflip = hflip
        self.num_threads = num_threads

    def apply(self, it: Iterator[LabeledImage]):
        from bigdl_tpu import native

        buf: List[LabeledImage] = []
        for img in it:
            buf.append(img)
            if len(buf) == self.batch_size:
                yield self._assemble(native, buf)
                buf = []
        if buf:
            yield self._assemble(native, buf)

    def _assemble(self, native, buf: List[LabeledImage]):
        n = len(buf)
        imgs = np.stack([b.data for b in buf])
        h, w = imgs.shape[1], imgs.shape[2]
        if self.random_crop:
            oy = np.asarray(RNG.randint(0, h - self.crop_h + 1, size=n),
                            np.int32)
            ox = np.asarray(RNG.randint(0, w - self.crop_w + 1, size=n),
                            np.int32)
        else:
            oy = np.full(n, (h - self.crop_h) // 2, np.int32)
            ox = np.full(n, (w - self.crop_w) // 2, np.int32)
        flip = (np.asarray(RNG.uniform(size=n)) < 0.5) \
            if self.hflip else np.zeros(n, bool)
        feats = native.batch_crop_normalize(
            imgs, self.crop_h, self.crop_w, oy, ox,
            flip.astype(np.uint8), self.mean, self.std, self.num_threads)
        labels = np.asarray([b.label for b in buf], np.int64)
        return feats, labels


def channel_mean_std(images: Iterator[LabeledImage]
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Dataset-wide per-channel statistics (the reference computes these
    offline for BGRImgNormalizer configs)."""
    count = 0
    s = s2 = 0.0
    for img in images:
        x = img.data.astype(np.float64)
        x = x.reshape(-1, 1) if x.ndim == 2 else x.reshape(-1, x.shape[-1])
        s = s + x.sum(axis=0)
        s2 = s2 + (x * x).sum(axis=0)
        count += x.shape[0]
    mean = s / count
    std = np.sqrt(s2 / count - mean * mean)
    return mean.astype(np.float32), std.astype(np.float32)
