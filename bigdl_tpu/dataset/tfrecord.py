"""TFRecord reading and a minimal TF ``Example`` proto parser.

The reference reads TFRecord files through ``TFRecordIterator`` and the
``ParseExample`` op (``utils/tf/Session.scala:150``,
``ops/ParseExample.scala``), with generated protobuf classes.  Here the
record framing (length + masked CRC32C, shared with the TensorBoard
writer) and the tiny subset of proto wire format that ``Example``
needs are decoded directly — no protobuf runtime dependency.

Wire format decoded::

    Example      := features(field 1: message Features)
    Features     := feature(field 1: map<string, Feature>)
    map entry    := key(field 1: string) value(field 2: message Feature)
    Feature      := one of bytes_list(1) / float_list(2) / int64_list(3)
    BytesList    := value(field 1: repeated bytes)
    FloatList    := value(field 1: repeated float, packed or not)
    Int64List    := value(field 1: repeated varint, packed or not)
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Union

import numpy as np

__all__ = ["TFRecordIterator", "parse_example", "write_tfrecord"]


def TFRecordIterator(path: str, check_crc: bool = True) -> Iterator[bytes]:
    """Yield raw records from a TFRecord file (``TFRecordIterator`` in the
    reference's ``utils/tf``)."""
    from bigdl_tpu import native

    def read_exact(f, n, what):
        buf = f.read(n)
        if len(buf) != n:
            raise IOError(f"truncated TFRecord file {path}: short read "
                          f"of {what} ({len(buf)}/{n} bytes)")
        return buf

    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) < 8:
                raise IOError(f"truncated TFRecord file {path}: short header")
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", read_exact(f, 4, "header crc"))
            if check_crc and native.masked_crc32c(header) != hcrc:
                raise IOError(f"corrupt TFRecord header in {path}")
            data = read_exact(f, length, "record data")
            (dcrc,) = struct.unpack("<I", read_exact(f, 4, "data crc"))
            if check_crc and native.masked_crc32c(data) != dcrc:
                raise IOError(f"corrupt TFRecord data in {path}")
            yield data


def write_tfrecord(path: str, records) -> None:
    """Write records with TFRecord framing (for tests/interop fixtures)."""
    from bigdl_tpu.visualization.tensorboard import RecordWriter

    with open(path, "wb") as f:
        w = RecordWriter(f)
        for rec in records:
            w.write(rec)


# ---------------------------------------------------------------------------
# proto wire-format decoding (shared helpers in bigdl_tpu.utils.protowire)
# ---------------------------------------------------------------------------

from bigdl_tpu.utils.protowire import (fields as _fields,  # noqa: E402
                                       packed_floats, packed_varints)


def _parse_feature(buf: bytes) -> Union[List[bytes], np.ndarray]:
    for field, wt, val in _fields(buf):
        if field == 1:  # BytesList
            return [v for f, _, v in _fields(val) if f == 1]
        if field == 2:  # FloatList
            floats: List[float] = []
            for f, w, v in _fields(val):
                if f == 1:
                    floats.extend(packed_floats(v, w))
            return np.asarray(floats, np.float32)
        if field == 3:  # Int64List
            ints: List[int] = []
            for f, w, v in _fields(val):
                if f == 1:
                    ints.extend(packed_varints(v, w))
            return np.asarray(ints, np.int64)
    return np.asarray([], np.float32)


def parse_example(serialized: bytes) -> Dict[str, Union[List[bytes],
                                                        np.ndarray]]:
    """Decode a serialized TF Example into {name: bytes-list or ndarray}."""
    features: Dict[str, Union[List[bytes], np.ndarray]] = {}
    for field, _, val in _fields(serialized):
        if field != 1:  # Features
            continue
        for f2, _, entry in _fields(val):
            if f2 != 1:  # map<string, Feature>
                continue
            key = None
            feat = None
            for f3, _, v3 in _fields(entry):
                if f3 == 1:
                    key = v3.decode("utf-8")
                elif f3 == 2:
                    feat = v3
            if key is not None and feat is not None:
                features[key] = _parse_feature(feat)
    return features
