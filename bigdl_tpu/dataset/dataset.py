"""DataSet abstractions (``dataset/DataSet.scala``).

The reference's split is Local (iterator on one JVM) vs Distributed (RDD,
one cached partition per node).  On TPU the split collapses: the host
pipeline produces **global batches** and the training step shards them over
the mesh's data axis (``jax.device_put`` with a NamedSharding) — the moral
equivalent of ``CachedDistriDataSet``'s one-partition-per-node caching +
per-partition shuffle (``DataSet.scala:240``), without a user-visible
cluster.

- ``LocalDataSet``: in-memory array of elements + transformer chain.
- ``DistributedDataSet``: LocalDataSet + per-host sharding metadata for
  multi-host SPMD (each process keeps ``1/num_hosts`` of the data, the
  reference's per-node partition).
- factories ``DataSet.array``, ``DataSet.image_folder``, ``DataSet.generator``.
"""

from __future__ import annotations

import math
from typing import Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils.rng import RNG

__all__ = ["AbstractDataSet", "LocalDataSet", "DistributedDataSet", "DataSet"]


class AbstractDataSet:
    """(``dataset/DataSet.scala:46``)."""

    def data(self, train: bool) -> Iterator:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self):
        raise NotImplementedError

    def transform(self, transformer: Transformer) -> "AbstractDataSet":
        raise NotImplementedError

    def __rshift__(self, transformer: Transformer) -> "AbstractDataSet":
        return self.transform(transformer)


class LocalDataSet(AbstractDataSet):
    """(``dataset/DataSet.scala:110``).

    Epoch ordering is DETERMINISTIC and seekable: epoch 0 iterates the
    base order (``_perm``, an identity permutation until ``shuffle()``),
    and every later epoch's permutation derives from a counter-based
    generator keyed by ``(shuffle seed, epoch index)`` — not from
    consuming the global RNG stream.  That makes the order a pure
    function of (seed, epoch), which is what preemption-safe resume
    rests on: ``set_position(epoch)`` re-enters any epoch's exact order
    in O(1), with no replayed or skipped records
    (docs/fault_tolerance.md)."""

    def __init__(self, data, transformers: Optional[List[Transformer]] = None):
        self._data = list(data) if not isinstance(data, np.ndarray) else data
        self._transformers = transformers or []
        self._perm = np.arange(len(self._data))
        self._epoch = 0
        self._shuffle_seed = int(RNG.get_seed()) & (2 ** 63 - 1)

    def size(self) -> int:
        return len(self._data)

    def shuffle(self):
        self._perm = RNG.permutation(len(self._data))
        return self

    def set_position(self, epoch: int) -> "LocalDataSet":
        """Start the next ``data(train=True)`` iterator at the beginning
        of 0-based ``epoch`` (checkpoint resume seeks here, then skips
        the records already consumed within the epoch)."""
        self._epoch = max(int(epoch), 0)
        return self

    def _perm_for_epoch(self, epoch: int) -> np.ndarray:
        if epoch <= 0:
            return self._perm
        gen = np.random.Generator(np.random.Philox(
            key=np.array([self._shuffle_seed, epoch], dtype=np.uint64)))
        return self._perm[gen.permutation(len(self._data))]

    def transform(self, transformer: Transformer) -> "LocalDataSet":
        ds = LocalDataSet.__new__(LocalDataSet)
        ds._data = self._data
        ds._perm = self._perm
        ds._epoch = self._epoch
        ds._shuffle_seed = self._shuffle_seed
        ds._transformers = self._transformers + [transformer]
        return ds

    def _raw_iter(self, train: bool) -> Iterator:
        if train:
            epoch = self._epoch
            while True:
                for i in self._perm_for_epoch(epoch):
                    yield self._data[i]
                epoch += 1
        else:
            for i in range(len(self._data)):
                yield self._data[i]

    def data(self, train: bool = False) -> Iterator:
        it: Iterator = self._raw_iter(train)
        for t in self._transformers:
            it = t(it)
        return it


class DistributedDataSet(LocalDataSet):
    """Multi-host SPMD dataset (``dataset/DataSet.scala:164`` capability):
    each host process feeds only its share of every global batch, so the
    batch assembled across processes covers the whole dataset — the
    reference's one-cached-partition-per-node layout.

    Epoch order is a WIDTH-INVARIANT global permutation (elastic
    recovery, docs/fault_tolerance.md): the epoch-``e`` order over
    GLOBAL record indices is a pure function of ``(shuffle seed, e,
    global size)`` — independent of ``num_shards`` — and process ``p``
    feeds the positions ``p, p+N, p+2N, ...`` of that global order.
    Any batch size divisible by ``N`` then assembles the SAME global
    batch contents at every width, so a checkpoint written by a
    4-process run resumes on 2 (or 8) processes onto the exact next
    global batch, not a resharded-differently epoch.  (The per-shard
    permutation this replaces made epoch>1 batch composition a function
    of the width — topology-portable checkpoints could restore the
    state but not the data trajectory.)  The full record list rides
    along on every host to make any position addressable; pod-scale
    datasets that cannot afford that should stream through
    ``DataSet.generator`` with their own sharding."""

    def __init__(self, data, num_shards: int = 1, shard_index: int = 0,
                 transformers: Optional[List[Transformer]] = None):
        data = list(data) if not isinstance(data, np.ndarray) else data
        self.num_shards, self.shard_index = num_shards, shard_index
        shard = data[shard_index::num_shards] if num_shards > 1 else data
        super().__init__(shard, transformers)
        self._full = data
        self._global_size = len(data)
        self._global_perm = np.arange(len(data))

    def global_size(self) -> int:
        return self._global_size

    def shuffle(self):
        # every process draws from the same shared-seed RNG stream, so
        # the global base permutation stays SPMD-consistent
        self._global_perm = RNG.permutation(self._global_size)
        return self

    def _global_perm_for_epoch(self, epoch: int) -> np.ndarray:
        if epoch <= 0:
            return self._global_perm
        gen = np.random.Generator(np.random.Philox(
            key=np.array([self._shuffle_seed, epoch], dtype=np.uint64)))
        return self._global_perm[gen.permutation(self._global_size)]

    def _raw_iter(self, train: bool) -> Iterator:
        if not train:
            yield from super()._raw_iter(train)
            return
        size = self._global_size
        if size == 0:
            return
        # stride the CONCATENATED epoch stream, not each epoch
        # separately: process p yields stream positions p, p+N, p+2N...
        # of the infinite epoch_e ++ epoch_{e+1} ++ ... sequence.  With
        # a per-epoch stride restart, a global size not divisible by N
        # gives processes unequal epoch lengths and the assembled batch
        # contents diverge by width from the first epoch boundary; the
        # continued stride keeps every batch window width-invariant
        # (and is identical to the per-epoch stride when N | size).
        n, p = self.num_shards, self.shard_index
        pos = p
        g = None
        g_epoch: Optional[int] = None
        while True:
            epoch = self._epoch + pos // size
            if g_epoch != epoch:
                g = self._global_perm_for_epoch(epoch)
                g_epoch = epoch
            yield self._full[g[pos % size]]
            pos += n

    def transform(self, transformer: Transformer) -> "DistributedDataSet":
        ds = DistributedDataSet.__new__(DistributedDataSet)
        ds._data = self._data
        ds._perm = self._perm
        ds._epoch = self._epoch
        ds._shuffle_seed = self._shuffle_seed
        ds.num_shards, ds.shard_index = self.num_shards, self.shard_index
        ds._full = self._full
        ds._global_size = self._global_size
        ds._global_perm = self._global_perm
        ds._transformers = self._transformers + [transformer]
        return ds


class _GeneratorDataSet(AbstractDataSet):
    """Wrap a callable producing fresh iterators (streaming sources)."""

    def __init__(self, gen: Callable[[bool], Iterable], size: int,
                 transformers: Optional[List[Transformer]] = None):
        self._gen = gen
        self._size = size
        self._transformers = transformers or []

    def size(self):
        return self._size

    def shuffle(self):
        return self

    def transform(self, transformer):
        return _GeneratorDataSet(self._gen, self._size,
                                 self._transformers + [transformer])

    def data(self, train: bool = False):
        it = iter(self._gen(train))
        for t in self._transformers:
            it = t(it)
        return it


class DataSet:
    """Factories (``object DataSet``, ``dataset/DataSet.scala:319``)."""

    @staticmethod
    def array(data, num_shards: int = 1, shard_index: int = 0) -> LocalDataSet:
        if num_shards > 1:
            return DistributedDataSet(data, num_shards, shard_index)
        return LocalDataSet(data)

    @staticmethod
    def generator(gen: Callable[[bool], Iterable], size: int) -> AbstractDataSet:
        return _GeneratorDataSet(gen, size)

    @staticmethod
    def image_folder(path: str, scale_to: int = 256) -> LocalDataSet:
        """ImageFolder.paths equivalent: <path>/<label>/xxx.jpg layout."""
        from bigdl_tpu.dataset.image import LocalImageFiles

        return LocalDataSet(LocalImageFiles.read_paths(path))
