"""Dataset readers (SURVEY §2.6 ``pyspark/bigdl/dataset/``: mnist.py IDX
parsing, news20; plus the Scala ImageFolder/SeqFileFolder factories).

Readers parse the standard on-disk formats when present; with no files
(this image has zero egress) they fall back to deterministic synthetic
data of the right shapes so pipelines/models/benchmarks run anywhere —
the reference's own perf harness does the same
(``models/utils/DistriOptimizerPerf.scala`` synthetic batches)."""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import List, Optional, Tuple

import numpy as np

from bigdl_tpu.dataset.image import LabeledImage

__all__ = ["load_mnist", "load_cifar10", "load_news20", "image_folder",
           "load_movielens", "movielens_id_pairs", "movielens_id_ratings",
           "TRAIN_MEAN", "TRAIN_STD"]

# MNIST normalization constants (pyspark/bigdl/dataset/mnist.py)
TRAIN_MEAN = 0.13066047740239506 * 255
TRAIN_STD = 0.3081078 * 255


def _read_idx_images(path: str) -> np.ndarray:
    """Parse an IDX3 image file (``mnist.py read_data_sets``)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad IDX image magic {magic}"
        return np.frombuffer(f.read(n * rows * cols), np.uint8).reshape(
            n, rows, cols)


def _read_idx_labels(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad IDX label magic {magic}"
        return np.frombuffer(f.read(n), np.uint8)


def _synthetic_images(n: int, h: int, w: int, c: int, classes: int,
                      seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic class-dependent synthetic images: each class gets a
    distinct mean pattern so models can actually fit them."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, n)
    base = rng.uniform(0, 255, (classes, h, w, c))
    imgs = np.clip(base[labels] + rng.normal(0, 30, (n, h, w, c)),
                   0, 255).astype(np.uint8)
    if c == 1:
        imgs = imgs[..., 0]
    return imgs, labels.astype(np.int64)


def load_mnist(data_dir: Optional[str] = None, split: str = "train",
               synthetic_size: int = 1024
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Return (images [N,28,28] uint8, labels [N] int64 0-based).

    Looks for the standard IDX files (train-images-idx3-ubyte[.gz], ...)
    under ``data_dir``; synthesizes data when absent."""
    names = {"train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
             "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")}
    if data_dir:
        img_base, lbl_base = names[split]
        for suffix in ("", ".gz"):
            ip = os.path.join(data_dir, img_base + suffix)
            lp = os.path.join(data_dir, lbl_base + suffix)
            if os.path.exists(ip) and os.path.exists(lp):
                return _read_idx_images(ip), \
                    _read_idx_labels(lp).astype(np.int64)
    return _synthetic_images(synthetic_size, 28, 28, 1, 10,
                             seed=0 if split == "train" else 1)


def load_cifar10(data_dir: Optional[str] = None, split: str = "train",
                 synthetic_size: int = 1024
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Return (images [N,32,32,3] uint8, labels [N] int64).

    Parses the python-pickle CIFAR-10 batches (cifar-10-batches-py) when
    present; synthesizes otherwise (models/vgg reads CIFAR the same way)."""
    if data_dir:
        batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
        if os.path.isdir(batch_dir):
            files = [f"data_batch_{i}" for i in range(1, 6)] \
                if split == "train" else ["test_batch"]
            imgs, labels = [], []
            for fn in files:
                with open(os.path.join(batch_dir, fn), "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                imgs.append(np.asarray(d[b"data"], np.uint8)
                            .reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
                labels.append(np.asarray(d[b"labels"], np.int64))
            return np.concatenate(imgs), np.concatenate(labels)
    return _synthetic_images(synthetic_size, 32, 32, 3, 10,
                             seed=2 if split == "train" else 3)


_NEWS_TOPICS = [
    "computer graphics rendering pixels shader display",
    "hockey team goal season player ice score win",
    "space orbit nasa launch satellite moon rocket",
    "medicine doctor disease patient treatment health",
    "politics government election vote law president",
]


def load_news20(data_dir: Optional[str] = None, synthetic_size: int = 500
                ) -> List[Tuple[str, int]]:
    """(text, label) pairs in the 20-newsgroups layout
    (``news20.py``: one dir per group, one file per post); synthesizes
    topic-worded documents when absent."""
    if data_dir and os.path.isdir(data_dir):
        out = []
        groups = [g for g in sorted(os.listdir(data_dir))
                  if os.path.isdir(os.path.join(data_dir, g))]
        for label, group in enumerate(groups):
            gdir = os.path.join(data_dir, group)
            for fn in sorted(os.listdir(gdir)):
                with open(os.path.join(gdir, fn), errors="ignore") as f:
                    out.append((f.read(), label))
        if out:
            return out
    rng = np.random.default_rng(4)
    out = []
    for i in range(synthetic_size):
        label = int(rng.integers(0, len(_NEWS_TOPICS)))
        words = _NEWS_TOPICS[label].split()
        doc = " ".join(rng.choice(words, size=30).tolist())
        out.append((doc, label))
    return out


def image_folder(path: str) -> List[LabeledImage]:
    """ImageFolder layout (``DataSet.scala:319`` ImageFolder.paths): one
    subdirectory per class, images inside. Requires PIL for decode."""
    from PIL import Image

    out = []
    classes = [c for c in sorted(os.listdir(path))
               if os.path.isdir(os.path.join(path, c))]
    for label, cls in enumerate(classes):
        cdir = os.path.join(path, cls)
        for fn in sorted(os.listdir(cdir)):
            img = np.asarray(Image.open(os.path.join(cdir, fn))
                             .convert("RGB"))
            out.append(LabeledImage(img, float(label)))
    return out


def load_movielens(data_dir: Optional[str] = None, synthetic_size: int = 2000
                   ) -> np.ndarray:
    """MovieLens ratings as an int array of (user, item, rating, timestamp)
    rows (``pyspark/bigdl/dataset/movielens.py read_data_sets``: parses
    ``ml-1m/ratings.dat``'s ``::``-separated lines).  Zero-egress here, so
    when the file is absent a seeded synthetic rating matrix with the same
    schema is generated instead of downloading."""
    if data_dir:
        for rel in ("ml-1m/ratings.dat", "ratings.dat"):
            path = os.path.join(data_dir, rel)
            if os.path.exists(path):
                with open(path) as f:
                    rows = [line.strip().split("::") for line in f
                            if line.strip()]
                return np.asarray(rows).astype(int)
    rng = np.random.default_rng(5)
    users = rng.integers(1, 201, synthetic_size)
    items = rng.integers(1, 501, synthetic_size)
    ratings = rng.integers(1, 6, synthetic_size)
    ts = rng.integers(9e8, 1e9, synthetic_size)
    return np.stack([users, items, ratings, ts], axis=1).astype(int)


def movielens_id_pairs(data_dir: Optional[str] = None) -> np.ndarray:
    """(user, item) columns (``movielens.py get_id_pairs``)."""
    return load_movielens(data_dir)[:, 0:2]


def movielens_id_ratings(data_dir: Optional[str] = None) -> np.ndarray:
    """(user, item, rating) columns (``movielens.py get_id_ratings``)."""
    return load_movielens(data_dir)[:, 0:3]
