"""bigdl_tpu.dataset — data pipeline (SURVEY §2.6)."""

from bigdl_tpu.dataset.dataset import (  # noqa: F401
    AbstractDataSet, DataSet, DistributedDataSet, LocalDataSet,
)
from bigdl_tpu.dataset.minibatch import MiniBatch  # noqa: F401
from bigdl_tpu.dataset.sample import PaddingParam, Sample  # noqa: F401
from bigdl_tpu.dataset.transformer import (  # noqa: F401
    ChainedTransformer, SampleToMiniBatch, Transformer,
)
