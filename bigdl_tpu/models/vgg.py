"""VGG (``models/vgg/VggForCifar10.scala`` and the 16/19-layer ImageNet
configs used by the reference perf harness,
``models/utils/DistriOptimizerPerf.scala``)."""

from __future__ import annotations

import bigdl_tpu.nn as nn

__all__ = ["build_vgg_for_cifar10", "build_vgg16", "build_vgg19"]


def _conv_bn_relu(model: nn.Sequential, n_in: int, n_out: int):
    model.add(nn.SpatialConvolution(n_in, n_out, 3, 3, 1, 1, 1, 1))
    model.add(nn.SpatialBatchNormalization(n_out, 1e-3))
    model.add(nn.ReLU(True))


def build_vgg_for_cifar10(class_num: int = 10, has_dropout: bool = True) -> nn.Module:
    """(``VggForCifar10.scala``)."""
    m = nn.Sequential()
    cfg = [(3, 64), (64, 64), "M", (64, 128), (128, 128), "M",
           (128, 256), (256, 256), (256, 256), "M",
           (256, 512), (512, 512), (512, 512), "M",
           (512, 512), (512, 512), (512, 512), "M"]
    for item in cfg:
        if item == "M":
            m.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
        else:
            _conv_bn_relu(m, *item)
    m.add(nn.View(512))
    classifier = nn.Sequential()
    if has_dropout:
        classifier.add(nn.Dropout(0.5))
    classifier.add(nn.Linear(512, 512))
    classifier.add(nn.BatchNormalization(512))
    classifier.add(nn.ReLU(True))
    if has_dropout:
        classifier.add(nn.Dropout(0.5))
    classifier.add(nn.Linear(512, class_num))
    classifier.add(nn.LogSoftMax())
    m.add(classifier)
    return m


def _vgg_imagenet(cfg, class_num: int) -> nn.Module:
    m = nn.Sequential()
    n_in = 3
    for item in cfg:
        if item == "M":
            m.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            m.add(nn.SpatialConvolution(n_in, item, 3, 3, 1, 1, 1, 1))
            m.add(nn.ReLU(True))
            n_in = item
    m.add(nn.View(512 * 7 * 7))
    m.add(nn.Linear(512 * 7 * 7, 4096))
    m.add(nn.Threshold(0, 1e-6))
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, 4096))
    m.add(nn.Threshold(0, 1e-6))
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, class_num))
    m.add(nn.LogSoftMax())
    return m


def build_vgg16(class_num: int = 1000) -> nn.Module:
    return _vgg_imagenet([64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
                          512, 512, 512, "M", 512, 512, 512, "M"], class_num)


def build_vgg19(class_num: int = 1000) -> nn.Module:
    return _vgg_imagenet([64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
                          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"], class_num)
