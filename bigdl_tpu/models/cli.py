"""Model-zoo command-line entry points (SURVEY §2.13: each reference
model ships scopt-based ``Train``/``Test`` mains, e.g.
``models/lenet/Train.scala``, plus the synthetic-data perf harnesses
``models/utils/{Local,Distri}OptimizerPerf.scala``).

Usage::

    python -m bigdl_tpu.models.cli train  --model lenet  -f ./mnist -b 64
    python -m bigdl_tpu.models.cli test   --model lenet  -f ./mnist \
        --checkpoint ./ckpt
    python -m bigdl_tpu.models.cli perf   --model inception_v1 -b 64 -i 10
    python -m bigdl_tpu.models.cli serve  --model lenet --port 8000 -b 32
    python -m bigdl_tpu.models.cli summary   --model lenet
    python -m bigdl_tpu.models.cli attribute --model transformer
    python -m bigdl_tpu.models.cli supervise -n 4 -- \
        python -m bigdl_tpu.models.cli train --model lenet --distributed \
        --checkpoint ./ckpt

``train`` runs the full Optimizer loop (validation every epoch, optional
checkpointing and TensorBoard summaries, resume from snapshot);
``test`` reloads a checkpoint and evaluates Top1/Top5; ``perf`` is the
LocalOptimizerPerf protocol (synthetic data, records/sec after warmup);
``summary`` prints the Torch-style per-layer table (path, output shape
via eval_shape, params); ``attribute`` prints the per-module FLOPs/bytes
cost table (docs/observability.md).  Missing dataset folders fall back
to synthetic data so every command is runnable anywhere.
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time
from typing import Optional, Tuple

import numpy as np


def _build_model(name: str, num_classes: int):
    # one shared name->builder table with the static analyzer
    # (python -m bigdl_tpu.analysis), see models/registry.py
    from bigdl_tpu.models import registry

    if name not in registry.MODELS:
        raise SystemExit(f"unknown --model {name!r}; choose from "
                         f"{registry.model_names()}")
    return registry.build_model(name, num_classes)


#: sequence models take [batch, time] int token ids, not images.
SEQ_MODELS = ("lstm", "transformer")
# shared with the analyzer's canonical input specs (models/registry.py)
from bigdl_tpu.models.registry import (  # noqa: E402
    LM_SEQ_LEN, LSTM_SEQ_LEN, LSTM_VOCAB)


@functools.lru_cache(maxsize=2)
def _news20_corpus(folder: Optional[str], vocab_size: int):
    """(dictionary, [per-doc token lists], [labels]) for news20 — cached so
    cmd_train's two _load_data calls read/tokenize the corpus once.

    The vocabulary always comes from the TRAIN split so train/test token
    ids agree.  Documents are tokenized one-by-one (a doc that tokenizes
    to nothing yields an empty list, NOT a dropped row) so tokens stay
    aligned index-for-index with labels."""
    from bigdl_tpu.dataset import datasets
    from bigdl_tpu.dataset.text import Dictionary, SentenceTokenizer

    all_pairs = datasets.load_news20(folder)
    tok = SentenceTokenizer()

    def tokens_of(text):
        out = list(tok.apply(iter([text])))
        return out[0] if out else []

    docs = [tokens_of(t) for t, _ in all_pairs]
    labels = [lab for _, lab in all_pairs]
    # Dictionary keeps vocab_size words + an UNK row, and ids are shifted
    # by 1 to reserve 0 for padding, so cap at vocab_size - 2 to keep
    # every id (UNK included) < vocab_size
    dic = Dictionary((d for i, d in enumerate(docs) if i % 5 != 4),
                     vocab_size=max(1, vocab_size - 2))
    return dic, docs, labels


def _load_token_data(model_name: str, folder: Optional[str], split: str,
                     vocab_size: int) -> Tuple[np.ndarray, np.ndarray]:
    """Token-shaped data for the sequence models: news20 text run through
    the text pipeline (tokenize -> dictionary -> fixed-length ids).

    ``lstm``  -> (tokens [N,LSTM_SEQ_LEN] int, class labels [N]);
    ``transformer`` -> (tokens [N,T] int, next-token targets [N,T])."""
    dic, docs, labels = _news20_corpus(folder, vocab_size)
    # deterministic split: every 5th doc is test, the rest train
    keep = [i for i in range(len(docs))
            if (i % 5 == 4) == (split == "test")]
    ids = [np.asarray([dic.index(w) + 1 for w in docs[i]], np.int32)
           for i in keep]  # reserve 0 for padding
    if model_name == "lstm":
        seq_len = LSTM_SEQ_LEN
        x = np.zeros((len(ids), seq_len), np.int32)
        for i, t in enumerate(ids):
            x[i, :min(len(t), seq_len)] = t[:seq_len]
        y = np.asarray([labels[i] for i in keep], np.int64)
        return x, y
    # transformer LM: one long stream chunked into next-token windows
    stream = np.concatenate(ids) if ids else np.zeros(0, np.int32)
    n = max(1, len(stream) // (LM_SEQ_LEN + 1))
    stream = np.resize(stream, n * (LM_SEQ_LEN + 1))
    chunks = stream.reshape(n, LM_SEQ_LEN + 1)
    return chunks[:, :-1].astype(np.int32), chunks[:, 1:].astype(np.int64)


def _load_data(model_name: str, folder: Optional[str], split: str,
               num_classes: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    from bigdl_tpu.dataset import datasets

    if model_name in SEQ_MODELS:
        vocab = (LSTM_VOCAB if model_name == "lstm"
                 else (num_classes or 256))
        return _load_token_data(model_name, folder, split, vocab)
    if model_name in ("lenet", "autoencoder"):
        imgs, labels = datasets.load_mnist(folder, split)
        x = ((imgs.astype(np.float32) / 255.0) - 0.1307) / 0.3081
        x = x.reshape(-1, 1, 28, 28)
    else:
        imgs, labels = datasets.load_cifar10(folder, split)
        x = imgs.astype(np.float32) / 255.0
        x = (x - x.mean((0, 1, 2))) / (x.std((0, 1, 2)) + 1e-7)
        x = x.transpose(0, 3, 1, 2)
    return x, labels


def cmd_train(args) -> None:
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.rng import RNG

    if getattr(args, "distributed", False):
        # join the cluster FIRST: jax.distributed.initialize must run
        # before any jax computation, and building the model below
        # already executes some — without this, a multi-process
        # `train --distributed` (e.g. under `supervise`) dies at
        # DistriOptimizer construction
        Engine.init()
    RNG.set_seed(args.seed)
    x, y = _load_data(args.model, args.folder, "train", args.num_classes)
    xt, yt = _load_data(args.model, args.folder, "test", args.num_classes)
    num_classes = args.num_classes
    if args.model == "lstm" and not num_classes:
        num_classes = int(max(y.max(), yt.max())) + 1
    model = _build_model(args.model, num_classes)
    if args.model_snapshot:
        from bigdl_tpu.utils import serializer

        model = serializer.load_module(args.model_snapshot)

    if args.model == "autoencoder":
        flat = x.reshape(len(x), -1)
        samples = [Sample(flat[i], flat[i]) for i in range(len(flat))]
        criterion = nn.MSECriterion()
        val_methods = [optim.Loss(nn.MSECriterion())]
        val_samples = samples[:256]
    elif args.model == "transformer":
        samples = [Sample(x[i], y[i]) for i in range(len(x))]
        criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), size_average=True)
        val_methods = [optim.Loss(
            nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), size_average=True))]
        val_samples = [Sample(xt[i], yt[i]) for i in range(len(xt))]
    else:
        samples = [Sample(x[i], y[i]) for i in range(len(x))]
        criterion = nn.ClassNLLCriterion()
        val_methods = [optim.Top1Accuracy(), optim.Top5Accuracy()]
        val_samples = [Sample(xt[i], yt[i]) for i in range(len(xt))]

    method = optim.SGD(learning_rate=args.learning_rate,
                       momentum=args.momentum,
                       weight_decay=args.weight_decay)
    if args.state_snapshot:
        from bigdl_tpu.utils import serializer

        method = serializer.load_optim_method(args.state_snapshot)

    if getattr(args, "distributed", False):
        # the reference's Train mains are the DISTRIBUTED entry points
        # (spark-submit + Engine.init); here: Engine mesh over every
        # addressable device, same loop
        o = optim.DistriOptimizer(
            model, samples, criterion, batch_size=args.batch_size,
            end_trigger=optim.Trigger.max_epoch(args.max_epoch))
    else:
        o = optim.LocalOptimizer(
            model, samples, criterion, batch_size=args.batch_size,
            end_trigger=optim.Trigger.max_epoch(args.max_epoch))
    o.set_optim_method(method)
    o.set_validation(optim.Trigger.every_epoch(), val_samples, val_methods,
                     batch_size=args.batch_size)
    if args.checkpoint:
        o.set_checkpoint(args.checkpoint, optim.Trigger.every_epoch())
    if args.summary_dir:
        from bigdl_tpu.visualization import TrainSummary, ValidationSummary

        o.set_train_summary(TrainSummary(args.summary_dir, args.app_name))
        o.set_validation_summary(
            ValidationSummary(args.summary_dir, args.app_name))
    trained = o.optimize()
    if getattr(o, "preempted", False):
        # graceful SIGTERM/SIGINT: the final checkpoint is committed;
        # exit 0 — rerunning this exact command resumes mid-epoch
        # (docs/fault_tolerance.md).  The hint names the topology the
        # checkpoint can restore onto (it is topology-PORTABLE — a
        # shrunk slice resumes on fewer chips) and the capacity-aware
        # supervise recipe, not just "re-run me".
        print(f"preempted at iteration {o.state['neval']} "
              f"(epoch {o.state['epoch']}); checkpoint committed"
              + (f" under {args.checkpoint}" if args.checkpoint else "")
              + " — rerun to resume")
        hint = o.resume_hint()
        if hint:
            print(hint)
        return
    res = optim.Evaluator(trained, batch_size=args.batch_size).evaluate(
        val_samples, val_methods)
    for r, m in res:
        print(f"final {m}: {r}")


def cmd_test(args) -> None:
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.utils import serializer

    if args.model_snapshot:
        model = serializer.load_module(args.model_snapshot)
    elif args.checkpoint:
        import glob

        cands = sorted(glob.glob(os.path.join(args.checkpoint, "**",
                                              "model.*"), recursive=True),
                       key=os.path.getmtime)
        if not cands:
            raise SystemExit(f"no model.* snapshot under {args.checkpoint}")
        model = serializer.load_module(cands[-1])
    else:
        raise SystemExit("test needs --model-snapshot or --checkpoint")
    x, y = _load_data(args.model, args.folder, "test", args.num_classes)
    samples = [Sample(x[i], y[i]) for i in range(len(x))]
    if args.model == "transformer":
        methods = [optim.Loss(
            nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), size_average=True))]
    else:
        methods = [optim.Top1Accuracy(), optim.Top5Accuracy()]
    from bigdl_tpu import telemetry

    with telemetry.maybe_run(meta={"cmd": "test",
                                   "model": args.model}) as owned_log:
        res = optim.Evaluator(model, batch_size=args.batch_size).evaluate(
            samples, methods)
    if owned_log:
        print(f"telemetry run log: {owned_log}")
    for r, m in res:
        print(f"{m}: {r}")


def cmd_perf(args) -> None:
    """LocalOptimizerPerf protocol: synthetic data, records/sec."""
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.parallel.train_step import TrainStep
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(0)
    num_classes = args.num_classes or {"lstm": 2, "transformer": 256}.get(
        args.model, 1000)
    model = _build_model(args.model, num_classes)
    rng = np.random.default_rng(0)
    criterion = nn.ClassNLLCriterion()
    if args.model in SEQ_MODELS:
        if args.model == "lstm":
            x = rng.integers(0, LSTM_VOCAB,
                             (args.batch_size, LSTM_SEQ_LEN),
                             dtype=np.int32)
            y = rng.integers(0, num_classes, args.batch_size)
        else:
            # num_classes doubles as the LM vocab, matching _build_model
            x = rng.integers(0, num_classes,
                             (args.batch_size, LM_SEQ_LEN), dtype=np.int32)
            y = rng.integers(0, num_classes, (args.batch_size, LM_SEQ_LEN))
            criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), size_average=True)
        x, y = jnp.asarray(x), jnp.asarray(y)
    else:
        shape = {"lenet": (1, 28, 28), "autoencoder": (1, 28, 28)}.get(
            args.model, (3, 224, 224))
        if args.model in ("vgg_cifar", "resnet"):
            shape = (3, 32, 32)
        x = jnp.asarray(rng.normal(size=(args.batch_size,) + shape)
                        .astype(np.float32))
        if args.model == "autoencoder":
            criterion = nn.MSECriterion()
            y = x.reshape(args.batch_size, -1)
        else:
            y = jnp.asarray(rng.integers(0, num_classes, args.batch_size))
    from bigdl_tpu import telemetry

    with telemetry.maybe_run(meta={"cmd": "perf", "model": args.model,
                                   "batch": args.batch_size}) as owned_log:
        step = TrainStep(model, criterion,
                         optim.SGD(learning_rate=0.01, momentum=0.9),
                         compute_dtype=jnp.bfloat16 if args.bf16 else None)
        with telemetry.span("perf/warmup", iters=args.warmup):
            for i in range(args.warmup):
                step.run(x, y, jax.random.key(i))
            if args.warmup:
                # drain the queue incl. the last warmup optimizer update
                float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
        with telemetry.span("perf/timed", iters=args.iteration):
            t0 = time.perf_counter()
            for i in range(args.iteration):
                step.run(x, y, jax.random.key(100 + i))
            # params-derived fetch forces the LAST iteration's optimizer
            # update inside the timed window (loss_i only depends on
            # params_{i-1})
            float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
            wall = time.perf_counter() - t0
        rate = args.batch_size * args.iteration / wall
        telemetry.counter("perf/records_per_sec", rate)
    if owned_log:
        print(f"telemetry run log: {owned_log}")
    print(f"{args.model}: {rate:.1f} records/sec "
          f"(batch {args.batch_size}, {args.iteration} iters, "
          f"{wall:.2f}s)")


def cmd_serve(args) -> None:
    """Production inference serving (docs/serving.md): HTTP frontend ->
    bounded queue -> continuous batcher -> bucketed AOT executables,
    warmed before the ready line prints.  SIGTERM drains gracefully."""
    import jax.numpy as jnp

    from bigdl_tpu import telemetry
    from bigdl_tpu.models import registry
    from bigdl_tpu.serving import serve_model
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(args.seed)  # fresh-registry weights reproducible
    if args.model_snapshot:
        from bigdl_tpu.utils import serializer

        model = serializer.load_module(args.model_snapshot)
    elif args.generate:
        # scan stacks cannot be cache-addressed; the shared build rule
        # (unrolled transformer etc.) lives beside the decode subsystem
        from bigdl_tpu.serving.generate import generation_model

        try:
            model = generation_model(args.model, args.num_classes)
        except ValueError as e:
            raise SystemExit(str(e)) from None
    else:
        model = _build_model(args.model, args.num_classes)
    spec = registry.input_spec(args.model, 1)
    if args.int8:
        from bigdl_tpu.nn.quantized import calibrate, quantize

        model = quantize(model)
        # calibrated static activation scales: the serve path must
        # never pay the dynamic per-layer amax reduce (BASELINE.md
        # round-6) — one synthetic batch at the canonical input spec
        rng = np.random.default_rng(0)
        shape = (min(8, args.batch_size),) + tuple(spec.shape[1:])
        if np.issubdtype(np.dtype(spec.dtype), np.integer):
            calib = rng.integers(0, 256, shape).astype(spec.dtype)
        else:
            calib = rng.normal(size=shape).astype(spec.dtype)
        calibrate(model, [calib])

    def _buckets(text):
        return [int(b) for b in text.split(",")] if text else None

    seq_buckets = _buckets(args.seq_buckets)
    if args.generate and not seq_buckets:
        from bigdl_tpu.serving.generate import default_seq_buckets

        seq_buckets = default_seq_buckets(spec)
    with telemetry.maybe_run(meta={"cmd": "serve", "model": args.model,
                                   "batch": args.batch_size}):
        server = serve_model(
            model, spec, name=args.model, port=args.port,
            max_batch=args.batch_size, max_wait_ms=args.max_wait_ms,
            queue_limit=args.queue_limit,
            batch_buckets=_buckets(args.buckets),
            seq_buckets=seq_buckets,
            compute_dtype=jnp.bfloat16 if args.bf16 and not args.int8
            else None,
            request_timeout_s=args.request_timeout,
            generate=args.generate,
            decode_buckets=_buckets(args.decode_buckets),
            cache_buckets=_buckets(args.cache_buckets),
            max_new_tokens_limit=args.max_new_tokens_limit,
            slo_p99_ms=args.slo_p99_ms, slo_ttft_ms=args.slo_ttft_ms)
        # readiness line AFTER warmup: every bucket is compiled once
        # this prints — tests and load balancers key off it
        gen = ""
        if args.generate:
            gen = (f", generate decode={list(server.executor.decode_buckets)}"
                   f" cache={list(server.executor.cache_buckets)}")
        print(f"serving {args.model} on port {server.port} "
              f"(buckets {list(server.executor.policy.batch_buckets)}, "
              f"warmup {server.executor.warmup_s:.1f}s{gen})", flush=True)
        server.install_signal_handlers()
        server.wait()
        server.stop(drain=True)
        st = server.batcher
        print(f"drained: {st.requests} requests, {st.rejected} rejected, "
              f"{st.batches} batches", flush=True)


def cmd_supervise(args) -> None:
    """Supervised elastic cluster launch (parallel/cluster.py): run N
    copies of a worker command as a jax.distributed cluster, let the
    collective watchdog turn peer loss into clean aborts instead of
    hung all-reduces, and restart the full cluster from the last
    cluster-consistent checkpoint when an incarnation dies."""
    import logging

    logging.basicConfig(level=logging.INFO)
    from bigdl_tpu.parallel.cluster import Supervisor

    command = list(args.command or [])
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        raise SystemExit(
            "supervise needs a worker command, e.g.:\n"
            "  python -m bigdl_tpu.models.cli supervise -n 4 -- "
            "python -m bigdl_tpu.models.cli train --model lenet "
            "--distributed --checkpoint ./ckpt")
    sup = Supervisor(nprocs=args.nprocs, command=command,
                     max_restarts=args.max_restarts,
                     cluster_dir=args.cluster_dir,
                     keep_faults=args.keep_faults,
                     log_dir=args.log_dir,
                     min_nprocs=args.min_n)
    from bigdl_tpu import telemetry

    # the supervisor's own run log is the incarnation-chain spine the
    # goodput ledger stitches against: cluster/restart (with backoff_s),
    # cluster/reshard and cluster/drain instants land here instead of
    # being dropped on the floor (BIGDL_TELEMETRY gates it, as for the
    # workers — which inherit the same dir through the environment)
    with telemetry.maybe_run(meta={"cmd": "supervise",
                                   "role": "supervisor",
                                   "declared_n": args.nprocs}):
        rc = sup.run()
    raise SystemExit(rc)


def cmd_summary(args) -> None:
    """Torch-style per-layer table over a registry model — reuses the
    module-path machinery the cost attribution is built on."""
    from bigdl_tpu.models.registry import input_spec

    model = _build_model(args.model, args.num_classes)
    print(model.summary(input_spec(args.model, args.batch_size)))


def cmd_attribute(args) -> None:
    """Per-module FLOPs/bytes table (telemetry/attribution.py), or the
    per-collective comms view (telemetry/comms.py) with ``--comms``."""
    import json

    from bigdl_tpu.telemetry import attribution

    if args.comms and args.memory:
        raise SystemExit("--comms and --memory are different views — "
                         "pass one")
    if args.comms:
        from bigdl_tpu.telemetry import comms

        result = comms.attribute_comms_model(
            args.model, batch=args.batch_size, devices=args.mesh,
            sync=args.sync, sparse=args.sparse)
        print(json.dumps(result, indent=2, default=str) if args.json
              else comms.format_comms(result))
        return
    if args.memory:
        from bigdl_tpu.telemetry import memory as tmem

        result = tmem.attribute_memory_model(
            args.model, batch=args.batch_size, devices=args.mesh,
            sync=args.sync)
        print(json.dumps(result, indent=2, default=str) if args.json
              else tmem.format_memory(result))
        return
    result = attribution.attribute_model(
        args.model, batch=args.batch_size, train=not args.forward)
    if args.json:
        print(json.dumps(result, indent=2, default=str))
    else:
        print(attribution.format_attribution(result))


def main(argv=None) -> None:
    # BEFORE any jax touch: a user-pinned JAX_PLATFORMS=cpu must win
    # over an externally-registered PJRT plugin (the axon sitecustomize
    # overrides the env var) — without this, a CPU-pinned CLI run dials
    # the device tunnel and can hang on a wedged link
    from bigdl_tpu.utils.engine import honor_platform_request

    honor_platform_request()
    p = argparse.ArgumentParser(prog="bigdl_tpu.models.cli",
                                description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("--model", default="lenet")
        sp.add_argument("-f", "--folder", default=None,
                        help="dataset folder (synthetic data when absent)")
        sp.add_argument("-b", "--batch-size", type=int, default=64)
        sp.add_argument("--num-classes", type=int, default=0)
        sp.add_argument("--telemetry", default=None, metavar="DIR",
                        help="write a JSONL telemetry run log under DIR "
                             "(same as BIGDL_TELEMETRY; inspect with "
                             "python -m bigdl_tpu.telemetry)")
        sp.add_argument("--metrics-port", type=int, default=None,
                        metavar="PORT",
                        help="serve live OpenMetrics (/metrics) + JSON "
                             "status (/status) on PORT while the run is "
                             "alive (0 = ephemeral; same as "
                             "BIGDL_METRICS_PORT; needs --telemetry or "
                             "BIGDL_TELEMETRY)")

    t = sub.add_parser("train", help="train a zoo model")
    common(t)
    t.add_argument("--learning-rate", type=float, default=0.05)
    t.add_argument("--momentum", type=float, default=0.9)
    t.add_argument("--weight-decay", type=float, default=0.0)
    t.add_argument("--max-epoch", type=int, default=2)
    t.add_argument("--checkpoint", default=None)
    t.add_argument("--summary-dir", default=None)
    t.add_argument("--app-name", default="bigdl_tpu")
    t.add_argument("--model-snapshot", default=None,
                   help="resume model from snapshot")
    t.add_argument("--state-snapshot", default=None,
                   help="resume optim method from snapshot")
    t.add_argument("--seed", type=int, default=42)
    t.add_argument("--distributed", action="store_true",
                   help="train on the Engine mesh over every addressable "
                        "device (the reference's spark-submit Train mode)")
    t.set_defaults(fn=cmd_train)

    te = sub.add_parser("test", help="evaluate a checkpointed model")
    common(te)
    te.add_argument("--checkpoint", default=None)
    te.add_argument("--model-snapshot", default=None)
    te.set_defaults(fn=cmd_test)

    pf = sub.add_parser("perf", help="synthetic-data throughput harness")
    common(pf)
    pf.add_argument("-i", "--iteration", type=int, default=10)
    pf.add_argument("--warmup", type=int, default=3)
    pf.add_argument("--bf16", action="store_true", default=True)
    pf.add_argument("--no-bf16", dest="bf16", action="store_false")
    pf.set_defaults(fn=cmd_perf)

    se = sub.add_parser("serve", help="serve a zoo model over HTTP: "
                                      "continuous batching, shape "
                                      "buckets, AOT-warmed executables "
                                      "(docs/serving.md)")
    common(se)
    se.add_argument("--port", type=int, default=8000,
                    help="HTTP port (0 = ephemeral, printed on the "
                         "ready line)")
    se.add_argument("--max-wait-ms", type=float, default=5.0,
                    help="batcher coalescing deadline from the oldest "
                         "queued request (default %(default)s)")
    se.add_argument("--queue-limit", type=int, default=256,
                    help="bounded request queue; past it requests get "
                         "429 (default %(default)s)")
    se.add_argument("--buckets", default=None, metavar="N,N,...",
                    help="batch buckets (default: powers of two up to "
                         "--batch-size)")
    se.add_argument("--seq-buckets", default=None, metavar="T,T,...",
                    help="sequence buckets for token models (default: "
                         "the model's fixed sequence length)")
    se.add_argument("--int8", action="store_true",
                    help="serve the quantized model with calibrated "
                         "static activation scales")
    se.add_argument("--bf16", action="store_true",
                    help="bf16 forward with f32 params (ignored with "
                         "--int8)")
    se.add_argument("--request-timeout", type=float, default=30.0,
                    help="per-request dispatch timeout seconds")
    se.add_argument("--model-snapshot", default=None,
                    help="serve a .btpu snapshot instead of fresh "
                         "registry weights")
    se.add_argument("--seed", type=int, default=42,
                    help="weight-init seed for fresh registry weights")
    se.add_argument("--generate", action="store_true",
                    help="causal token models: enable POST /v1/generate"
                         " — KV-cached decode, continuous batching, "
                         "token streaming (docs/serving.md)")
    se.add_argument("--decode-buckets", default=None, metavar="B,B,...",
                    help="--generate: decode batch buckets; the largest"
                         " is the max concurrent sequences (default "
                         "1,2,4,8)")
    se.add_argument("--cache-buckets", default=None, metavar="C,C,...",
                    help="--generate: KV cache-length buckets (default:"
                         " doubling from the smallest seq bucket to the"
                         " model's max_len)")
    se.add_argument("--max-new-tokens-limit", type=int, default=1024,
                    help="--generate: per-request max_new_tokens cap")
    se.add_argument("--slo-p99-ms", type=float, default=None,
                    metavar="MS",
                    help="declared request-latency p99 budget: live "
                         "burn-rate gauges on /metrics + /status.slo, "
                         "violating requests keep their trace ids "
                         "(docs/observability.md)")
    se.add_argument("--slo-ttft-ms", type=float, default=None,
                    metavar="MS",
                    help="--generate: declared time-to-first-token "
                         "p99 budget (same burn accounting)")
    se.set_defaults(fn=cmd_serve)

    sv = sub.add_parser("supervise",
                        help="launch + babysit an N-process cluster: "
                             "watchdog-clean peer-loss aborts, bounded "
                             "restarts from the last cluster-consistent "
                             "checkpoint (docs/fault_tolerance.md)")
    sv.add_argument("-n", "--nprocs", type=int, required=True,
                    help="cluster size (one jax process per slot)")
    sv.add_argument("--max-restarts", type=int, default=5,
                    help="full-cluster restarts before giving up")
    sv.add_argument("--min-n", type=int, default=None, metavar="M",
                    help="capacity-aware floor: when restart attempts "
                         "at -n keep dying on the same missing peer, "
                         "relaunch DEGRADED at M processes instead of "
                         "burning the restart budget (the topology-"
                         "portable checkpoint reshards on load; grows "
                         "back to -n on the next full-capacity restart)")
    sv.add_argument("--cluster-dir", default=None,
                    help="shared heartbeat/commit dir (default: a fresh "
                         "temp dir; must be shared storage on real "
                         "multi-host fleets)")
    sv.add_argument("--log-dir", default=None,
                    help="capture each worker's stdout+stderr to "
                         "<dir>/inc<k>.p<i>.log (a SIGKILLed worker "
                         "leaves no flight dump — this is the "
                         "supervisor-side postmortem record)")
    sv.add_argument("--keep-faults", action="store_true",
                    help="keep BIGDL_FAULTS for restart incarnations "
                         "(default: cleared — an injected fault plan "
                         "describes one scenario, not every restart)")
    sv.add_argument("command", nargs=argparse.REMAINDER, metavar="-- cmd",
                    help="worker command to run n times with the "
                         "cluster env injected")
    sv.set_defaults(fn=cmd_supervise)

    sm = sub.add_parser("summary", help="Torch-style per-layer table "
                                        "(shapes via eval_shape)")
    common(sm)
    sm.set_defaults(fn=cmd_summary)

    at = sub.add_parser("attribute", help="per-module FLOPs/bytes cost "
                                          "attribution table (--comms: "
                                          "per-collective bytes/axes)")
    common(at)
    at.add_argument("--forward", action="store_true",
                    help="attribute the inference forward instead of "
                         "the full train step")
    at.add_argument("--comms", action="store_true",
                    help="per-collective comms view: bytes moved, mesh "
                         "axes, owning modules (telemetry/comms.py)")
    at.add_argument("--memory", action="store_true",
                    help="per-module HBM view: params / optimizer "
                         "state / activations-at-peak per device "
                         "(telemetry/memory.py)")
    at.add_argument("--mesh", type=int, default=0, metavar="N",
                    help="(--comms/--memory) data-axis mesh size to "
                         "shard over (default: all local devices for "
                         "--comms, single device for --memory)")
    at.add_argument("--sync", default="allreduce",
                    choices=("allreduce", "sharded", "fsdp", "local"),
                    help="(--comms/--memory) parameter_sync mode to "
                         "compile with (local = local-SGD islands, "
                         "parallel/local_sync.py)")
    at.add_argument("--sparse", default=None,
                    choices=("off", "auto", "on"),
                    help="(--comms) override BIGDL_SPARSE for this "
                         "compile — A/B the sparse embedding sync "
                         "(docs/sparse.md)")
    at.add_argument("--json", action="store_true")
    # same default batch as `python -m bigdl_tpu.telemetry attribute`:
    # the two front-ends of one table must print the same numbers
    at.set_defaults(fn=cmd_attribute, batch_size=8)

    args = p.parse_args(argv)
    if getattr(args, "telemetry", None):
        # the env route keeps one resolution path (utils/config.py);
        # the Optimizer / perf harness start the run from config
        os.environ["BIGDL_TELEMETRY"] = args.telemetry
    if getattr(args, "metrics_port", None) is not None:
        os.environ["BIGDL_METRICS_PORT"] = str(args.metrics_port)
    args.fn(args)


if __name__ == "__main__":
    main()
