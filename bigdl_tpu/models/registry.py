"""Model-zoo registry: one table mapping model names to builders and
canonical input specs.

``models/cli.py`` (train/test/perf entry points) and the static analyzer
(``python -m bigdl_tpu.analysis <model>``) both resolve names here, so a
model added to the zoo is automatically runnable *and* checkable.  The
``input_spec`` is the abstract ``ShapeDtypeStruct`` the shape pass feeds
``jax.eval_shape`` — no data, no compile.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple

__all__ = ["ModelEntry", "MODELS", "model_names", "build_model",
           "input_spec", "train_pieces"]


class ModelEntry(NamedTuple):
    #: num_classes -> model (0/None means the builder's own default)
    build: Callable[[int], Any]
    #: batch -> (pytree of) jax.ShapeDtypeStruct
    spec: Callable[[int], Any]


def _img(c: int, h: int, w: int):
    def make(batch: int = 2):
        import jax
        import jax.numpy as jnp

        return jax.ShapeDtypeStruct((batch, c, h, w), jnp.float32)

    return make


def _flat(n: int):
    def make(batch: int = 2):
        import jax
        import jax.numpy as jnp

        return jax.ShapeDtypeStruct((batch, n), jnp.float32)

    return make


def _tokens(seq_len: int):
    def make(batch: int = 2):
        import jax
        import jax.numpy as jnp

        return jax.ShapeDtypeStruct((batch, seq_len), jnp.int32)

    return make


def _b(fn_name: str):
    def build(num_classes: int = 0):
        from bigdl_tpu import models

        fn = getattr(models, fn_name)
        return fn(num_classes) if num_classes else fn()

    return build


#: sequence lengths matching models/cli.py's data pipeline
LSTM_SEQ_LEN = 200
LM_SEQ_LEN = 128
LSTM_VOCAB = 5000
#: dlrm feature geometry (models/dlrm.py defaults): 13 count features +
#: 8 categorical ids, one per table
DLRM_FEATURES = 13 + 8
DLRM_VOCAB = 50000


def _resnet_cifar(num_classes: int = 0):
    from bigdl_tpu import models

    return models.build_resnet_cifar(20, num_classes or 10)


def _resnet50(num_classes: int = 0):
    from bigdl_tpu import models

    return models.build_resnet(50, num_classes or 1000)


def _autoencoder(num_classes: int = 0):
    from bigdl_tpu import models

    return models.build_autoencoder()


def _lstm(num_classes: int = 0):
    from bigdl_tpu import models

    return models.build_lstm_classifier(LSTM_VOCAB,
                                        class_num=num_classes or 2)


def _transformer(num_classes: int = 0):
    from bigdl_tpu import models

    return models.build_transformer_lm(vocab_size=num_classes or 256)


def _dlrm(num_classes: int = 0):
    from bigdl_tpu import models

    return models.build_dlrm(class_num=num_classes or 2)

MODELS: Dict[str, ModelEntry] = {
    "lenet": ModelEntry(_b("build_lenet5"), _flat(28 * 28)),
    "vgg16": ModelEntry(_b("build_vgg16"), _img(3, 224, 224)),
    "vgg19": ModelEntry(_b("build_vgg19"), _img(3, 224, 224)),
    "vgg_cifar": ModelEntry(_b("build_vgg_for_cifar10"),
                            _img(3, 32, 32)),
    "inception_v1": ModelEntry(_b("build_inception_v1"),
                               _img(3, 224, 224)),
    "inception_v2": ModelEntry(_b("build_inception_v2"),
                               _img(3, 224, 224)),
    "resnet": ModelEntry(_resnet_cifar, _img(3, 32, 32)),
    "resnet50": ModelEntry(_resnet50, _img(3, 224, 224)),
    "autoencoder": ModelEntry(_autoencoder, _flat(28 * 28)),
    "lstm": ModelEntry(_lstm, _tokens(LSTM_SEQ_LEN)),
    "transformer": ModelEntry(_transformer, _tokens(LM_SEQ_LEN)),
    # recsys ranking (models/dlrm.py): [batch, 13 count + 8 categorical]
    # int32 features -> click log-probs; the sparse-sync proof shape
    "dlrm": ModelEntry(_dlrm, _tokens(DLRM_FEATURES)),
}


def model_names():
    return sorted(MODELS)


def build_model(name: str, num_classes: int = 0):
    if name not in MODELS:
        raise KeyError(f"unknown model {name!r}; choose from "
                       f"{model_names()}")
    return MODELS[name].build(num_classes)


def input_spec(name: str, batch: int = 2):
    if name not in MODELS:
        raise KeyError(f"unknown model {name!r}; choose from "
                       f"{model_names()}")
    return MODELS[name].spec(batch)


#: models whose output is ClassNLL-compatible (log-probs over classes,
#: integer labels).  A model in MODELS but not here (and not special-
#: cased below) makes train_pieces return None — the attribution CLI
#: then falls back to forward-only rather than lowering a nonsense step.
_CLASSIFIERS = frozenset({
    "lenet", "vgg16", "vgg19", "vgg_cifar", "inception_v1",
    "inception_v2", "resnet", "resnet50", "lstm", "dlrm",
})


def train_pieces(name: str, batch: int = 2):
    """``(criterion, target ShapeDtypeStruct)`` for training this model
    on synthetic specs — what the cost-attribution CLI needs to lower a
    full TrainStep without data (``telemetry/attribution.py``).  Returns
    None for models the table doesn't know how to train."""
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn

    if name not in MODELS:
        raise KeyError(f"unknown model {name!r}; choose from "
                       f"{model_names()}")
    if name == "autoencoder":
        return (nn.MSECriterion(),
                jax.ShapeDtypeStruct((batch, 28 * 28), jnp.float32))
    if name == "transformer":
        return (nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                            size_average=True),
                jax.ShapeDtypeStruct((batch, LM_SEQ_LEN), jnp.int32))
    if name in _CLASSIFIERS:
        return (nn.ClassNLLCriterion(),
                jax.ShapeDtypeStruct((batch,), jnp.int32))
    return None
