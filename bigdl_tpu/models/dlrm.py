"""DLRM-shaped recommendation/ranking model (the recsys scenario,
ROADMAP item 3): per-feature embedding bags + bottom/top MLPs + pairwise
feature interaction, after *Deep Learning Recommendation Model* (Naumov
et al.) — the closest shape to real millions-of-users traffic this
framework benchmarks (every ad/feed ranking request is one of these).

Input convention (one int32 array so the registry/serving/bench plumbing
that feeds single-array models applies unchanged — Criteo-style, where
the "dense" features ARE integer counts): ``[batch, num_dense +
num_sparse]``; the first ``num_dense`` columns are count features
(log1p-transformed into the bottom MLP), the rest are categorical ids,
one per feature, each indexing its own :class:`nn.EmbeddingBag`.
Output: log-probabilities over ``class_num`` classes (click /
no-click) — ``ClassNLLCriterion``-compatible like the other registry
classifiers.

The embedding tables are the model: at the default registry shape the
tables hold ~50x the parameters of both MLPs together, and a batch
touches at most ``batch`` rows of each ``vocab_size``-row table — the
sparse-gradient sync (docs/sparse.md) is what makes training it
data-parallel-scalable, and this model is its proof shape."""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import Module

__all__ = ["build_dlrm", "DLRM"]


class DLRM(Module):
    """See module docstring.  ``bag_size > 1`` widens each categorical
    feature to a multi-hot bag (``[batch, num_sparse, bag_size]`` input
    layout flattened into the trailing columns)."""

    def __init__(self, num_dense: int = 13, num_sparse: int = 8,
                 vocab_size: int = 50000, embed_dim: int = 32,
                 bottom_dims: Sequence[int] = (64, 32),
                 top_dims: Sequence[int] = (64, 32),
                 class_num: int = 2, bag_size: int = 1,
                 bag_mode: str = "sum", sparse: Optional[bool] = None,
                 padding_idx: Optional[int] = None):
        super().__init__()
        self.num_dense = num_dense
        self.num_sparse = num_sparse
        self.vocab_size = vocab_size
        self.embed_dim = embed_dim
        self.bag_size = bag_size
        bottom = nn.Sequential()
        d = num_dense
        for h in bottom_dims:
            bottom.add(nn.Linear(d, h)).add(nn.ReLU())
            d = h
        bottom.add(nn.Linear(d, embed_dim)).add(nn.ReLU())
        self.bottom = bottom
        # one table per categorical feature (distinct cardinalities in
        # real deployments; symmetric here), registered as numbered
        # children so attribution rows and state-dict paths name them
        for i in range(num_sparse):
            setattr(self, f"embed_{i}",
                    nn.EmbeddingBag(vocab_size, embed_dim, mode=bag_mode,
                                    padding_idx=padding_idx,
                                    sparse=sparse))
        # pairwise dot-product interaction over the num_sparse embedding
        # vectors + the bottom output: F*(F-1)/2 upper-triangle terms,
        # concatenated with the bottom vector into the top MLP
        f = num_sparse + 1
        d = f * (f - 1) // 2 + embed_dim
        top = nn.Sequential()
        for h in top_dims:
            top.add(nn.Linear(d, h)).add(nn.ReLU())
            d = h
        top.add(nn.Linear(d, class_num)).add(nn.LogSoftMax())
        self.top = top

    def update_output(self, input):
        x = jnp.asarray(input)
        if x.dtype in (jnp.float32, jnp.float64, jnp.bfloat16):
            x = x.astype(jnp.int32)
        nd, ns, bs = self.num_dense, self.num_sparse, self.bag_size
        dense = jnp.log1p(jnp.maximum(x[:, :nd], 0).astype(jnp.float32))
        b = self.bottom(dense)  # [B, D]
        feats = [b]
        cat = x[:, nd:]
        for i in range(ns):
            emb = getattr(self, f"embed_{i}")
            if bs > 1:
                ids = cat[:, i * bs:(i + 1) * bs]
            else:
                ids = cat[:, i]
            feats.append(emb(ids).astype(b.dtype))  # [B, D]
        f = jnp.stack(feats, axis=1)  # [B, F, D]
        inter = jnp.einsum("bfd,bgd->bfg", f, f)
        iu, ju = jnp.triu_indices(f.shape[1], k=1)
        pairs = inter[:, iu, ju]  # [B, F*(F-1)/2]
        return self.top(jnp.concatenate([pairs, b], axis=1))


def build_dlrm(num_dense: int = 13, num_sparse: int = 8,
               vocab_size: int = 50000, embed_dim: int = 32,
               bottom_dims: Sequence[int] = (64, 32),
               top_dims: Sequence[int] = (64, 32), class_num: int = 2,
               bag_size: int = 1, bag_mode: str = "sum",
               sparse: Optional[bool] = None,
               padding_idx: Optional[int] = None) -> nn.Module:
    """Registry builder (``models/registry.py`` name ``dlrm``)."""
    return DLRM(num_dense=num_dense, num_sparse=num_sparse,
                vocab_size=vocab_size, embed_dim=embed_dim,
                bottom_dims=bottom_dims, top_dims=top_dims,
                class_num=class_num, bag_size=bag_size, bag_mode=bag_mode,
                sparse=sparse, padding_idx=padding_idx)
