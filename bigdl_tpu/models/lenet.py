"""LeNet-5 (``models/lenet/LeNet5.scala``)."""

from __future__ import annotations

import bigdl_tpu.nn as nn

__all__ = ["build_lenet5"]


def build_lenet5(class_num: int = 10) -> nn.Module:
    return nn.Sequential(
        nn.Reshape((1, 28, 28)),
        nn.SpatialConvolution(1, 6, 5, 5).set_name("conv1_5x5"),
        nn.Tanh(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.SpatialConvolution(6, 12, 5, 5).set_name("conv2_5x5"),
        nn.Tanh(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape((12 * 4 * 4,)),
        nn.Linear(12 * 4 * 4, 100).set_name("fc1"),
        nn.Tanh(),
        nn.Linear(100, class_num).set_name("fc2"),
        nn.LogSoftMax(),
    )
