"""SimpleRNN text model (``models/rnn/SimpleRNN.scala``) and an LSTM text
classifier (the reference's LSTM-text-classification benchmark config,
BASELINE.md config 4)."""

from __future__ import annotations

from typing import Optional

import bigdl_tpu.nn as nn

__all__ = ["build_simple_rnn", "build_lstm_classifier"]


def build_simple_rnn(input_size: int = 4000, hidden_size: int = 40,
                     output_size: int = 4000) -> nn.Module:
    """(``SimpleRNN.scala``): one-hot input -> RnnCell over time ->
    TimeDistributed Linear + LogSoftMax (per-timestep prediction)."""
    return nn.Sequential(
        nn.Recurrent(nn.RnnCell(input_size, hidden_size, nn.Tanh())),
        nn.TimeDistributed(nn.Sequential(nn.Linear(hidden_size, output_size),
                                         nn.LogSoftMax())),
    )


def build_lstm_classifier(vocab_size: int, embed_dim: int = 128,
                          hidden_size: int = 128, class_num: int = 2,
                          num_layers: int = 1,
                          one_based_tokens: bool = False,
                          scan: Optional[bool] = None) -> nn.Module:
    """LSTM text classification: embedding -> LSTM stack -> last step ->
    dense.  ``num_layers`` stacks LSTMs (each a scan with the fused-gate
    matmul) — the representative large-model shape for the perf harness.
    ``scan`` additionally stacks the identical LSTM layers (the 2nd
    onward when ``embed_dim != hidden_size``) into one ``nn.ScanLayers``
    body — scan over layers of scan over time, one compiled step cell
    (None = the ``BIGDL_SCAN_LAYERS`` config; docs/compile.md)."""
    m = nn.Sequential(
        nn.LookupTable(vocab_size, embed_dim, one_based=one_based_tokens))
    in_dim = embed_dim
    for _ in range(num_layers):
        m.add(nn.Recurrent(nn.LSTM(in_dim, hidden_size)))
        in_dim = hidden_size
    m.add(nn.Select(1, -1))
    m.add(nn.Linear(hidden_size, class_num))
    m.add(nn.LogSoftMax())
    from bigdl_tpu.nn.layers.scan import maybe_scan

    return maybe_scan(m, scan)
