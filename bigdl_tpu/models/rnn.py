"""SimpleRNN text model (``models/rnn/SimpleRNN.scala``) and an LSTM text
classifier (the reference's LSTM-text-classification benchmark config,
BASELINE.md config 4)."""

from __future__ import annotations

import bigdl_tpu.nn as nn

__all__ = ["build_simple_rnn", "build_lstm_classifier"]


def build_simple_rnn(input_size: int = 4000, hidden_size: int = 40,
                     output_size: int = 4000) -> nn.Module:
    """(``SimpleRNN.scala``): one-hot input -> RnnCell over time ->
    TimeDistributed Linear + LogSoftMax (per-timestep prediction)."""
    return nn.Sequential(
        nn.Recurrent(nn.RnnCell(input_size, hidden_size, nn.Tanh())),
        nn.TimeDistributed(nn.Sequential(nn.Linear(hidden_size, output_size),
                                         nn.LogSoftMax())),
    )


def build_lstm_classifier(vocab_size: int, embed_dim: int = 128,
                          hidden_size: int = 128, class_num: int = 2,
                          one_based_tokens: bool = False) -> nn.Module:
    """LSTM text classification: embedding -> LSTM -> last step -> dense."""
    return nn.Sequential(
        nn.LookupTable(vocab_size, embed_dim, one_based=one_based_tokens),
        nn.Recurrent(nn.LSTM(embed_dim, hidden_size)),
        nn.Select(1, -1),
        nn.Linear(hidden_size, class_num),
        nn.LogSoftMax(),
    )
