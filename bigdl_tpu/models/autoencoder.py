"""Autoencoder / MNIST (``models/autoencoder/Autoencoder.scala``)."""

from __future__ import annotations

import bigdl_tpu.nn as nn

__all__ = ["build_autoencoder"]


def build_autoencoder(class_num: int = 32) -> nn.Module:
    """784 -> classNum -> 784 with sigmoid output (``Autoencoder.scala``)."""
    row_n, col_n = 28, 28
    return nn.Sequential(
        nn.Reshape((row_n * col_n,)),
        nn.Linear(row_n * col_n, class_num),
        nn.ReLU(True),
        nn.Linear(class_num, row_n * col_n),
        nn.Sigmoid(),
    )
