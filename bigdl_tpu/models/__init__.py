"""bigdl_tpu.models — model zoo (SURVEY §2.13).

``models.registry`` maps zoo names to builders + canonical input specs;
it backs both the train/test/perf CLI (``models/cli.py``) and the static
analyzer (``python -m bigdl_tpu.analysis <name>``).
"""

from bigdl_tpu.models import registry  # noqa: F401

from bigdl_tpu.models.autoencoder import build_autoencoder  # noqa: F401
from bigdl_tpu.models.dlrm import build_dlrm  # noqa: F401
from bigdl_tpu.models.inception import (  # noqa: F401
    build_inception_v1, build_inception_v2, inception_layer_v1,
)
from bigdl_tpu.models.lenet import build_lenet5  # noqa: F401
from bigdl_tpu.models.resnet import build_resnet, build_resnet_cifar  # noqa: F401
from bigdl_tpu.models.rnn import build_lstm_classifier, build_simple_rnn  # noqa: F401
from bigdl_tpu.models.transformer import build_transformer_lm  # noqa: F401
from bigdl_tpu.models.vgg import (  # noqa: F401
    build_vgg16, build_vgg19, build_vgg_for_cifar10,
)
