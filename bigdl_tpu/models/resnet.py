"""ResNet (``models/resnet/ResNet.scala``): CIFAR-10 (depth 20/32/.../110,
basic blocks) and ImageNet (ResNet-18/34/50/101/152, basic or bottleneck)
variants with shortcut types A (zero-pad identity), B (1x1 conv on
dimension change), C (1x1 conv always)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import Module

__all__ = ["build_resnet", "build_resnet_cifar", "basic_block", "bottleneck"]


class _ZeroPadShortcut(Module):
    """Shortcut type A: stride then zero-pad channels (ResNet.scala
    shortcut 'A')."""

    def __init__(self, n_in: int, n_out: int, stride: int):
        super().__init__()
        self.n_in, self.n_out, self.stride = n_in, n_out, stride

    def update_output(self, input):
        x = input[:, :, ::self.stride, ::self.stride]
        pad = self.n_out - self.n_in
        if pad > 0:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
        return x


def _shortcut(n_in: int, n_out: int, stride: int, shortcut_type: str) -> Module:
    use_conv = shortcut_type == "C" or (shortcut_type == "B" and (n_in != n_out or stride != 1))
    if use_conv:
        return nn.Sequential(
            nn.SpatialConvolution(n_in, n_out, 1, 1, stride, stride),
            nn.SpatialBatchNormalization(n_out))
    if n_in != n_out or stride != 1:
        return _ZeroPadShortcut(n_in, n_out, stride)
    return nn.Identity()


def basic_block(n_in: int, n: int, stride: int, shortcut_type: str = "B") -> Module:
    s = nn.Sequential(
        nn.SpatialConvolution(n_in, n, 3, 3, stride, stride, 1, 1),
        nn.SpatialBatchNormalization(n),
        nn.ReLU(True),
        nn.SpatialConvolution(n, n, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(n))
    return nn.Sequential(
        nn.ConcatTable().add(s).add(_shortcut(n_in, n, stride, shortcut_type)),
        nn.CAddTable(True),
        nn.ReLU(True))


def bottleneck(n_in: int, n: int, stride: int, shortcut_type: str = "B") -> Module:
    n_out = n * 4
    s = nn.Sequential(
        nn.SpatialConvolution(n_in, n, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(n),
        nn.ReLU(True),
        nn.SpatialConvolution(n, n, 3, 3, stride, stride, 1, 1),
        nn.SpatialBatchNormalization(n),
        nn.ReLU(True),
        nn.SpatialConvolution(n, n_out, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(n_out))
    return nn.Sequential(
        nn.ConcatTable().add(s).add(_shortcut(n_in, n_out, stride, shortcut_type)),
        nn.CAddTable(True),
        nn.ReLU(True))


_IMAGENET_CFGS = {
    18: ([2, 2, 2, 2], basic_block, 512),
    34: ([3, 4, 6, 3], basic_block, 512),
    50: ([3, 4, 6, 3], bottleneck, 2048),
    101: ([3, 4, 23, 3], bottleneck, 2048),
    152: ([3, 8, 36, 3], bottleneck, 2048),
}


def build_resnet(depth: int = 50, class_num: int = 1000,
                 shortcut_type: str = "B",
                 scan: Optional[bool] = None) -> nn.Module:
    """ImageNet ResNet (``ResNet.scala`` apply, dataset=ImageNet).
    ``scan`` stacks each stage's run of identical blocks into one
    ``nn.ScanLayers`` body — XLA compiles one block per stage instead of
    one per layer (None = the ``BIGDL_SCAN_LAYERS`` config)."""
    counts, block, n_features = _IMAGENET_CFGS[depth]
    m = nn.Sequential(
        nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3, propagate_back=False),
        nn.SpatialBatchNormalization(64),
        nn.ReLU(True),
        nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1))
    n_in = 64
    widths = [64, 128, 256, 512]
    for stage, (w, count) in enumerate(zip(widths, counts)):
        for i in range(count):
            stride = 2 if stage > 0 and i == 0 else 1
            m.add(block(n_in, w, stride, shortcut_type))
            n_in = w * 4 if block is bottleneck else w
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    m.add(nn.View(n_features).set_num_input_dims(3))
    m.add(nn.Linear(n_features, class_num))
    m.add(nn.LogSoftMax())
    from bigdl_tpu.nn.layers.scan import maybe_scan

    return maybe_scan(m, scan)


def build_resnet_cifar(depth: int = 20, class_num: int = 10,
                       shortcut_type: str = "A",
                       scan: Optional[bool] = None) -> nn.Module:
    """CIFAR-10 ResNet (``ResNet.scala`` apply, dataset=CIFAR-10):
    depth = 6n+2 basic blocks."""
    assert (depth - 2) % 6 == 0, "CIFAR depth must be 6n+2"
    n = (depth - 2) // 6
    m = nn.Sequential(
        nn.SpatialConvolution(3, 16, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(16),
        nn.ReLU(True))
    n_in = 16
    for stage, w in enumerate([16, 32, 64]):
        for i in range(n):
            stride = 2 if stage > 0 and i == 0 else 1
            m.add(basic_block(n_in, w, stride, shortcut_type))
            n_in = w
    m.add(nn.SpatialAveragePooling(8, 8, 1, 1))
    m.add(nn.View(64).set_num_input_dims(3))
    m.add(nn.Linear(64, class_num))
    m.add(nn.LogSoftMax())
    from bigdl_tpu.nn.layers.scan import maybe_scan

    return maybe_scan(m, scan)
