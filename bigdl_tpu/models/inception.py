"""Inception v1 / v2 (``models/inception/Inception_v1.scala``,
``Inception_v2.scala``) — the reference's flagship benchmark model
(``models/utils/DistriOptimizerPerf.scala``).

Built with the Concat container exactly like the reference's
``inception`` helper; v1 includes the two auxiliary classifier heads used
during training (``Inception_v1.scala`` aux1/aux2) behind
``with_aux=True``."""

from __future__ import annotations

import bigdl_tpu.nn as nn

__all__ = ["inception_layer_v1", "build_inception_v1", "build_inception_v2"]


def inception_layer_v1(input_size: int, config, name_prefix: str = "",
                       format: str = "NCHW") -> nn.Module:
    """One inception module: 1x1 / 3x3reduce+3x3 / 5x5reduce+5x5 / pool+proj
    branches concatenated on the channel dim (``Inception_v1.scala``
    ``inception`` fn)."""
    c_dim = 3 if format == "NHWC" else 1
    concat = nn.Concat(c_dim).set_name(name_prefix + "inception")
    conv1 = nn.Sequential(
        nn.SpatialConvolution(input_size, config[0][0], 1, 1, 1, 1, format=format)
        .set_name(name_prefix + "1x1"),
        nn.ReLU(True))
    concat.add(conv1)
    conv3 = nn.Sequential(
        nn.SpatialConvolution(input_size, config[1][0], 1, 1, 1, 1, format=format)
        .set_name(name_prefix + "3x3_reduce"),
        nn.ReLU(True),
        nn.SpatialConvolution(config[1][0], config[1][1], 3, 3, 1, 1, 1, 1, format=format)
        .set_name(name_prefix + "3x3"),
        nn.ReLU(True))
    concat.add(conv3)
    conv5 = nn.Sequential(
        nn.SpatialConvolution(input_size, config[2][0], 1, 1, 1, 1, format=format)
        .set_name(name_prefix + "5x5_reduce"),
        nn.ReLU(True),
        nn.SpatialConvolution(config[2][0], config[2][1], 5, 5, 1, 1, 2, 2, format=format)
        .set_name(name_prefix + "5x5"),
        nn.ReLU(True))
    concat.add(conv5)
    pool = nn.Sequential(
        nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1, format=format).ceil(),
        nn.SpatialConvolution(input_size, config[3][0], 1, 1, 1, 1, format=format)
        .set_name(name_prefix + "pool_proj"),
        nn.ReLU(True))
    concat.add(pool)
    return concat


def build_inception_v1(class_num: int = 1000, has_dropout: bool = True,
                       with_aux: bool = False, format: str = "NCHW") -> nn.Module:
    """GoogLeNet (``Inception_v1.scala`` inception_v1_NoAuxClassifier /
    inception_v1).  ``format="NHWC"`` builds the channels-last variant
    (TPU's native conv layout; same parameters, transposed activations)."""
    f = format
    feature1 = nn.Sequential(
        nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3, format=f).set_name("conv1/7x7_s2"),
        nn.ReLU(True),
        nn.SpatialMaxPooling(3, 3, 2, 2, format=f).ceil(),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75, format=f).set_name("pool1/norm1"),
        nn.SpatialConvolution(64, 64, 1, 1, 1, 1, format=f).set_name("conv2/3x3_reduce"),
        nn.ReLU(True),
        nn.SpatialConvolution(64, 192, 3, 3, 1, 1, 1, 1, format=f).set_name("conv2/3x3"),
        nn.ReLU(True),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75, format=f).set_name("conv2/norm2"),
        nn.SpatialMaxPooling(3, 3, 2, 2, format=f).ceil(),
        inception_layer_v1(192, [[64], [96, 128], [16, 32], [32]], "inception_3a/", f),
        inception_layer_v1(256, [[128], [128, 192], [32, 96], [64]], "inception_3b/", f),
        nn.SpatialMaxPooling(3, 3, 2, 2, format=f).ceil(),
        inception_layer_v1(480, [[192], [96, 208], [16, 48], [64]], "inception_4a/", f),
    )
    feature2 = nn.Sequential(
        inception_layer_v1(512, [[160], [112, 224], [24, 64], [64]], "inception_4b/", f),
        inception_layer_v1(512, [[128], [128, 256], [24, 64], [64]], "inception_4c/", f),
        inception_layer_v1(512, [[112], [144, 288], [32, 64], [64]], "inception_4d/", f),
    )
    feature3 = nn.Sequential(
        inception_layer_v1(528, [[256], [160, 320], [32, 128], [128]], "inception_4e/", f),
        nn.SpatialMaxPooling(3, 3, 2, 2, format=f).ceil(),
        inception_layer_v1(832, [[256], [160, 320], [32, 128], [128]], "inception_5a/", f),
        inception_layer_v1(832, [[384], [192, 384], [48, 128], [128]], "inception_5b/", f),
    )
    head = nn.Sequential(
        nn.SpatialAveragePooling(7, 7, 1, 1, format=f),
        nn.View(1024).set_num_input_dims(3),
    )
    if has_dropout:
        head.add(nn.Dropout(0.4))
    head.add(nn.Linear(1024, class_num).set_name("loss3/classifier"))
    head.add(nn.LogSoftMax().set_name("loss3/loss3"))

    if not with_aux:
        return nn.Sequential(feature1, feature2, feature3, head)

    # training graph with aux classifiers: outputs (main, aux1, aux2)
    split1 = nn.ConcatTable().add(nn.Sequential(feature2,
                                                nn.ConcatTable().add(nn.Sequential(feature3, head))
                                                .add(_aux_head(528, "loss2", class_num, f))))\
                             .add(_aux_head(512, "loss1", class_num, f))
    model = nn.Sequential(feature1, split1, nn.FlattenTable())
    return model


def _aux_head(in_ch: int, name: str, class_num: int,
              format: str = "NCHW") -> nn.Module:
    """Auxiliary classifier (``Inception_v1.scala`` loss1/loss2 branches).
    The NHWC variant transposes back to channel-first before the flatten
    so the fc weights index features in the SAME order as the NCHW build
    — keeping checkpoints portable across layouts."""
    head = nn.Sequential(
        nn.SpatialAveragePooling(5, 5, 3, 3, format=format).ceil(),
        nn.SpatialConvolution(in_ch, 128, 1, 1, 1, 1, format=format)
        .set_name(name + "/conv"),
        nn.ReLU(True))
    if format == "NHWC":
        head.add(nn.Transpose([(1, 3), (2, 3)]))  # NHWC -> NCHW flatten order
    head.add(nn.View(128 * 4 * 4).set_num_input_dims(3))
    head.add(nn.Linear(128 * 4 * 4, 1024).set_name(name + "/fc"))
    head.add(nn.ReLU(True))
    head.add(nn.Dropout(0.7))
    head.add(nn.Linear(1024, class_num).set_name(name + "/classifier"))
    head.add(nn.LogSoftMax())
    return head


def _conv_bn(input_size, output_size, kw, kh, sw=1, sh=1, pw=0, ph=0, name=""):
    return [nn.SpatialConvolution(input_size, output_size, kw, kh, sw, sh, pw, ph)
            .set_name(name), nn.SpatialBatchNormalization(output_size, 1e-3), nn.ReLU(True)]


def inception_layer_v2(input_size: int, config, name_prefix: str = "") -> nn.Module:
    """Inception-BN module (``Inception_v2.scala`` inception): 3x3 double
    branch, avg/max pool selectable, optional stride-2 pass-through."""
    concat = nn.Concat(1)
    if config[0][0] != 0:
        b1 = nn.Sequential()
        for l in _conv_bn(input_size, config[0][0], 1, 1, name=name_prefix + "1x1"):
            b1.add(l)
        concat.add(b1)
    b3 = nn.Sequential()
    for l in _conv_bn(input_size, config[1][0], 1, 1, name=name_prefix + "3x3_reduce"):
        b3.add(l)
    stride = 2 if config[0][0] == 0 else 1
    for l in _conv_bn(config[1][0], config[1][1], 3, 3, stride, stride, 1, 1,
                      name=name_prefix + "3x3"):
        b3.add(l)
    concat.add(b3)
    bd = nn.Sequential()
    for l in _conv_bn(input_size, config[2][0], 1, 1, name=name_prefix + "double3x3_reduce"):
        bd.add(l)
    for l in _conv_bn(config[2][0], config[2][1], 3, 3, 1, 1, 1, 1,
                      name=name_prefix + "double3x3a"):
        bd.add(l)
    for l in _conv_bn(config[2][1], config[2][1], 3, 3, stride, stride, 1, 1,
                      name=name_prefix + "double3x3b"):
        bd.add(l)
    concat.add(bd)
    pool = nn.Sequential()
    pool_pad = 1 if stride == 1 else 0  # stride-2 downsampling pools are unpadded
    if config[3][0] == "max":
        pool.add(nn.SpatialMaxPooling(3, 3, stride, stride, pool_pad, pool_pad).ceil())
    else:
        pool.add(nn.SpatialAveragePooling(3, 3, stride, stride, pool_pad, pool_pad,
                                          ceil_mode=True))
    if config[3][1] != 0:
        for l in _conv_bn(input_size, config[3][1], 1, 1, name=name_prefix + "pool_proj"):
            pool.add(l)
    concat.add(pool)
    return concat


def build_inception_v2(class_num: int = 1000) -> nn.Module:
    """(``Inception_v2.scala``)."""
    m = nn.Sequential()
    for l in _conv_bn(3, 64, 7, 7, 2, 2, 3, 3, "conv1/7x7_s2"):
        m.add(l)
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    for l in _conv_bn(64, 64, 1, 1, name="conv2/3x3_reduce"):
        m.add(l)
    for l in _conv_bn(64, 192, 3, 3, 1, 1, 1, 1, "conv2/3x3"):
        m.add(l)
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(inception_layer_v2(192, [[64], [64, 64], [64, 96], ["avg", 32]], "inception_3a/"))
    m.add(inception_layer_v2(256, [[64], [64, 96], [64, 96], ["avg", 64]], "inception_3b/"))
    m.add(inception_layer_v2(320, [[0], [128, 160], [64, 96], ["max", 0]], "inception_3c/"))
    m.add(inception_layer_v2(576, [[224], [64, 96], [96, 128], ["avg", 128]], "inception_4a/"))
    m.add(inception_layer_v2(576, [[192], [96, 128], [96, 128], ["avg", 128]], "inception_4b/"))
    m.add(inception_layer_v2(576, [[160], [128, 160], [128, 160], ["avg", 96]], "inception_4c/"))
    m.add(inception_layer_v2(576, [[96], [128, 192], [160, 192], ["avg", 96]], "inception_4d/"))
    m.add(inception_layer_v2(576, [[0], [128, 192], [192, 256], ["max", 0]], "inception_4e/"))
    m.add(inception_layer_v2(1024, [[352], [192, 320], [160, 224], ["avg", 128]], "inception_5a/"))
    m.add(inception_layer_v2(1024, [[352], [192, 320], [192, 224], ["max", 128]], "inception_5b/"))
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    m.add(nn.View(1024).set_num_input_dims(3))
    m.add(nn.Linear(1024, class_num))
    m.add(nn.LogSoftMax())
    return m
