"""Transformer language model — the long-context flagship.

The reference has no attention or transformer models (SURVEY §5
"Long-context ... Absent"); this model exists to exercise the
capabilities the TPU build adds on top of the reference's sequence
story (RNN/TimeDistributed): the Pallas flash kernel and ring/Ulysses
sequence parallelism over a mesh ``seq`` axis.

``build_transformer_lm`` returns a causal decoder LM:
token embedding + learned positions -> N pre-norm TransformerBlocks ->
final LayerNorm -> vocab head (log-probs per position, so
``TimeDistributedCriterion(ClassNLLCriterion(), size_average=True)``
trains it — size_average averages the per-step losses; the default sums
them, scaling the loss by sequence length).

``sp_mesh``/``sp_axis``/``sp_strategy`` route every block's attention
through shard_map'd ring or Ulysses attention for sequences larger than
one chip holds.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import Module, Parameter

__all__ = ["build_transformer_lm", "PositionalEmbedding"]


class PositionalEmbedding(Module):
    """Learned absolute positions added to token embeddings.

    Under a DECODE generation trace (``serving/generate``) the input is
    one token per row and its absolute position is that row's cache
    length, not 0 — the ambient cache context supplies the per-row
    positions the same way it supplies the per-layer caches."""

    def __init__(self, max_len: int, embed_dim: int):
        super().__init__()
        self.max_len = max_len
        self.weight = Parameter(jnp.zeros((max_len, embed_dim), jnp.float32))

    def update_output(self, input):
        from bigdl_tpu.nn.layers.attention import generation_cache_context

        ctx = generation_cache_context()
        if ctx is not None and ctx.mode == "decode":
            pos = ctx.positions()  # [B] absolute position per row
            return input + self._params["weight"][pos, :][:, None, :]
        s = input.shape[1]
        return input + self._params["weight"][None, :s, :]


def build_transformer_lm(vocab_size: int, num_layers: int = 4,
                         embed_dim: int = 256, num_heads: int = 8,
                         max_len: int = 1024, mlp_ratio: int = 4,
                         dropout: float = 0.0, backend="auto",
                         sp_mesh=None, sp_axis: str = "seq",
                         sp_strategy: str = "ring",
                         sp_batch_axis=None,
                         remat: bool = False,
                         scan: Optional[bool] = None) -> nn.Module:
    """Causal decoder-only LM over [batch, seq] token ids.
    ``sp_batch_axis`` composes sequence parallelism with data
    parallelism on a 2-D (data, seq) mesh; ``remat`` wraps each block in
    ``nn.Remat`` so long-context activations are recomputed, not stored.
    ``scan`` stacks the N identical blocks into one ``nn.ScanLayers``
    body so XLA compiles ONE block instead of N (None = the
    ``BIGDL_SCAN_LAYERS`` config; docs/compile.md)."""
    if sp_mesh is not None:
        from bigdl_tpu.parallel.sequence import (
            make_sequence_parallel_attention)

        backend = make_sequence_parallel_attention(
            sp_mesh, strategy=sp_strategy, axis_name=sp_axis, causal=True,
            batch_axis=sp_batch_axis)
    model = nn.Sequential(
        nn.LookupTable(vocab_size, embed_dim),
        PositionalEmbedding(max_len, embed_dim),
    )
    for _ in range(num_layers):
        block = nn.TransformerBlock(embed_dim, num_heads,
                                    mlp_ratio=mlp_ratio, dropout=dropout,
                                    causal=True, backend=backend)
        model.add(nn.Remat(block) if remat else block)
    model.add(nn.LayerNorm(embed_dim))
    model.add(nn.TimeDistributed(nn.Sequential(
        nn.Linear(embed_dim, vocab_size), nn.LogSoftMax())))
    from bigdl_tpu.nn.layers.scan import maybe_scan

    return maybe_scan(model, scan)
