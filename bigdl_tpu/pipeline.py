"""ML-pipeline adapters: DLEstimator / DLClassifier / DLModel
(``org/apache/spark/ml/DLEstimator.scala:54``, ``DLClassifier.scala`` —
SURVEY §2.12).

The reference adapts BigDL training into Spark ML's Estimator/Transformer
contract over DataFrame feature/label columns.  The structural equivalent
here is the sklearn-style fit/transform protocol over columnar numpy
data: ``DLEstimator.fit(X, y) -> DLModel``; ``DLModel.transform(X) ->
predictions``.  ``X``/``y`` may be arrays or anything convertible; rows
are reshaped to ``feature_size``/``label_size`` like the reference's
internalFit (``DLEstimator.scala:119-136``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = ["DLEstimator", "DLClassifier", "DLModel", "DLClassifierModel"]


class DLEstimator:
    """Fit a module + criterion over columnar (X, y) data."""

    def __init__(self, model, criterion, feature_size: Sequence[int],
                 label_size: Sequence[int]):
        self.model = model
        self.criterion = criterion
        self.feature_size = tuple(feature_size)
        self.label_size = tuple(label_size)
        self.batch_size = 32
        self.max_epoch = 20
        self.learning_rate = 1e-3
        self.optim_method = None
        self.end_trigger = None
        self.mesh = None
        self.validation = None  # (trigger, X, y, methods, batch_size)
        self.train_summary = None
        self.validation_summary = None
        self.checkpoint = None  # (path, trigger)

    def set_batch_size(self, n: int) -> "DLEstimator":
        self.batch_size = n
        return self

    def set_max_epoch(self, n: int) -> "DLEstimator":
        self.max_epoch = n
        return self

    def set_learning_rate(self, lr: float) -> "DLEstimator":
        self.learning_rate = lr
        return self

    def set_optim_method(self, method) -> "DLEstimator":
        self.optim_method = method
        return self

    def set_end_trigger(self, trigger) -> "DLEstimator":
        """Override the max-epoch end condition (``DLEstimator.scala``
        endWhen param)."""
        self.end_trigger = trigger
        return self

    def set_mesh(self, mesh) -> "DLEstimator":
        """Train on a device mesh via DistriOptimizer instead of the
        single-chip LocalOptimizer."""
        self.mesh = mesh
        return self

    def set_validation(self, trigger, X, y, methods,
                       batch_size: Optional[int] = None) -> "DLEstimator":
        """Schedule validation during fit (Optimizer.setValidation
        pass-through over columnar arrays).  ``batch_size=None`` resolves
        to the training batch size AT FIT TIME, so setter order doesn't
        matter."""
        self.validation = (trigger, X, y, methods, batch_size)
        return self

    def set_train_summary(self, summary) -> "DLEstimator":
        self.train_summary = summary
        return self

    def set_validation_summary(self, summary) -> "DLEstimator":
        self.validation_summary = summary
        return self

    def set_checkpoint(self, path: str, trigger) -> "DLEstimator":
        self.checkpoint = (path, trigger)
        return self

    def _make_model(self, trained):
        return DLModel(trained, self.feature_size)

    def fit(self, X, y) -> "DLModel":
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset.sample import Sample

        X = np.asarray(X, np.float32).reshape((-1,) + self.feature_size)
        y = np.asarray(y).reshape((-1,) + self.label_size)
        samples = [Sample(X[i], y[i]) for i in range(len(X))]
        method = self.optim_method or optim.Adam(
            learning_rate=self.learning_rate)
        end = self.end_trigger or optim.Trigger.max_epoch(self.max_epoch)
        if self.mesh is not None:
            o = optim.DistriOptimizer(self.model, samples, self.criterion,
                                      batch_size=self.batch_size,
                                      end_trigger=end, mesh=self.mesh)
        else:
            o = optim.LocalOptimizer(self.model, samples, self.criterion,
                                     batch_size=self.batch_size,
                                     end_trigger=end)
        o.set_optim_method(method)
        if self.validation is not None:
            trigger, vX, vy, methods, vbatch = self.validation
            vX = np.asarray(vX, np.float32).reshape((-1,) + self.feature_size)
            vy = np.asarray(vy).reshape((-1,) + self.label_size)
            vsamples = [Sample(vX[i], vy[i]) for i in range(len(vX))]
            o.set_validation(trigger, vsamples, methods,
                             vbatch or self.batch_size)
        if self.train_summary is not None:
            o.set_train_summary(self.train_summary)
        if self.validation_summary is not None:
            o.set_validation_summary(self.validation_summary)
        if self.checkpoint is not None:
            o.set_checkpoint(self.checkpoint[0], self.checkpoint[1])
        trained = o.optimize()
        return self._make_model(trained)


class DLModel:
    """Fitted transformer (``DLEstimator.scala`` DLModel): appends
    predictions for feature rows."""

    def __init__(self, model, feature_size: Sequence[int]):
        self.model = model
        self.feature_size = tuple(feature_size)
        self.batch_size = 128

    def set_batch_size(self, n: int) -> "DLModel":
        self.batch_size = n
        return self

    def _forward_batches(self, X):
        import jax.numpy as jnp

        model = self.model.evaluate()
        outs = []
        for i in range(0, len(X), self.batch_size):
            outs.append(np.asarray(
                model.forward(jnp.asarray(X[i:i + self.batch_size]))))
        return np.concatenate(outs, axis=0)

    def transform(self, X) -> np.ndarray:
        X = np.asarray(X, np.float32).reshape((-1,) + self.feature_size)
        return self._forward_batches(X)


class DLClassifier(DLEstimator):
    """Classification specialization (``DLClassifier.scala``): labels are
    class indices; transform yields argmax class predictions."""

    def __init__(self, model, criterion, feature_size: Sequence[int]):
        super().__init__(model, criterion, feature_size, (1,))

    def _make_model(self, trained):
        return DLClassifierModel(trained, self.feature_size)

    def fit(self, X, y) -> "DLClassifierModel":
        y = np.asarray(y).reshape(-1)
        return super().fit(X, y.astype(np.int64))


class DLClassifierModel(DLModel):
    def transform(self, X) -> np.ndarray:
        X = np.asarray(X, np.float32).reshape((-1,) + self.feature_size)
        out = self._forward_batches(X)
        return out.argmax(axis=-1)
