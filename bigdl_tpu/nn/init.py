"""Initialization methods.

Parity with the reference's ``InitializationMethod`` family
(``nn/InitializationMethod.scala``: RandomUniform, RandomNormal, Xavier,
BilinearFiller, Zeros, Ones, ConstInitMethod, MsraFiller) — host-side eager
numpy draws through the global Torch-style ``RNG`` so construction is
deterministic under ``RNG.set_seed``.
"""

from __future__ import annotations

import numpy as np

from bigdl_tpu.utils.rng import RNG

__all__ = [
    "InitializationMethod",
    "Zeros",
    "Ones",
    "ConstInitMethod",
    "RandomUniform",
    "RandomNormal",
    "Xavier",
    "MsraFiller",
    "BilinearFiller",
]


class InitializationMethod:
    def init(self, shape, fan_in: int | None = None, fan_out: int | None = None) -> np.ndarray:
        raise NotImplementedError


class _Zeros(InitializationMethod):
    def init(self, shape, fan_in=None, fan_out=None):
        return np.zeros(shape, dtype=np.float32)


class _Ones(InitializationMethod):
    def init(self, shape, fan_in=None, fan_out=None):
        return np.ones(shape, dtype=np.float32)


class ConstInitMethod(InitializationMethod):
    def __init__(self, value: float):
        self.value = value

    def init(self, shape, fan_in=None, fan_out=None):
        return np.full(shape, self.value, dtype=np.float32)


class RandomUniform(InitializationMethod):
    """U(lower, upper); with no bounds, the Torch default U(-1/sqrt(fan_in), +)."""

    def __init__(self, lower: float | None = None, upper: float | None = None):
        self.lower, self.upper = lower, upper

    def init(self, shape, fan_in=None, fan_out=None):
        if self.lower is None:
            fi = fan_in if fan_in else int(np.prod(shape[1:]) or 1)
            bound = 1.0 / np.sqrt(fi)
            lo, hi = -bound, bound
        else:
            lo, hi = self.lower, self.upper
        return RNG.uniform(lo, hi, size=shape).astype(np.float32)


class RandomNormal(InitializationMethod):
    def __init__(self, mean: float = 0.0, stdv: float = 1.0):
        self.mean, self.stdv = mean, stdv

    def init(self, shape, fan_in=None, fan_out=None):
        return RNG.normal(self.mean, self.stdv, size=shape).astype(np.float32)


class _Xavier(InitializationMethod):
    """Glorot uniform: U(-sqrt(6/(fan_in+fan_out)), +)."""

    def init(self, shape, fan_in=None, fan_out=None):
        fi = fan_in if fan_in else int(np.prod(shape[1:]) or 1)
        fo = fan_out if fan_out else int(shape[0])
        bound = np.sqrt(6.0 / (fi + fo))
        return RNG.uniform(-bound, bound, size=shape).astype(np.float32)


class MsraFiller(InitializationMethod):
    """He/MSRA normal init: N(0, sqrt(2/fan))."""

    def __init__(self, variance_norm_average: bool = False):
        self.variance_norm_average = variance_norm_average

    def init(self, shape, fan_in=None, fan_out=None):
        fi = fan_in if fan_in else int(np.prod(shape[1:]) or 1)
        fo = fan_out if fan_out else int(shape[0])
        n = (fi + fo) / 2.0 if self.variance_norm_average else fi
        std = np.sqrt(2.0 / n)
        return RNG.normal(0.0, std, size=shape).astype(np.float32)


class BilinearFiller(InitializationMethod):
    """Bilinear upsampling kernel init for full (transposed) convolutions."""

    def init(self, shape, fan_in=None, fan_out=None):
        # shape (..., kH, kW)
        kh, kw = shape[-2], shape[-1]
        f_h, f_w = np.ceil(kh / 2.0), np.ceil(kw / 2.0)
        c_h, c_w = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h), (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        y = np.arange(kh).reshape(-1, 1)
        x = np.arange(kw).reshape(1, -1)
        kernel = (1 - np.abs(y / f_h - c_h)) * (1 - np.abs(x / f_w - c_w))
        out = np.zeros(shape, dtype=np.float32)
        out[...] = kernel
        return out


Zeros = _Zeros()
Ones = _Ones()
Xavier = _Xavier()
