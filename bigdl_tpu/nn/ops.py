"""TF-style forward-only operations (the reference's ``nn/ops/``
subpackage, 28 files — SURVEY §2.5): ``Operation`` base plus the op
catalog, re-expressed on jax/lax.

Ops are ``Module``s whose backward is forbidden (``ops/Operation.scala``
throws on backward); they exist for graph-import parity and for building
TF-flavored compute graphs with the ``Graph`` API.  Control flow
(``ops/ControlOps.scala``) maps to structured XLA primitives via
``bigdl_tpu.ops.control`` — under XLA both branches of a Switch/Merge
pair are traced and the result selected, rather than one branch being
skipped by a scheduler; results are identical, only the cost model
differs.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.nn.module import Module

__all__ = [
    "Operation", "ModuleToOperation",
    "Conv2D", "MaxPool", "AvgPool", "BiasAdd", "Cast",
    "Equal", "NotEqual", "Greater", "GreaterEqual", "Less", "LessEqual",
    "LogicalAnd", "LogicalOr", "LogicalNot",
    "Floor", "Ceil", "Round", "L2Loss", "OneHot", "Pad", "Prod",
    "RandomUniform", "TruncatedNormal", "Rank", "ResizeBilinearOps",
    "Slice", "Assign", "Assert", "DecodeImage", "ParseExample",
    "While", "Cond", "Switch", "Merge", "Select",
]


class Operation(Module):
    """Forward-only module (``ops/Operation.scala``): backward raises."""

    def backward(self, input, grad_output):  # noqa: D401
        raise RuntimeError(
            f"Operation {type(self).__name__} does not support backward")

    def update_grad_input(self, input, grad_output):
        raise RuntimeError(
            f"Operation {type(self).__name__} does not support backward")


class ModuleToOperation(Operation):
    """Wrap any module as a forward-only op (``ops/ModuleToOperation.scala``)."""

    def __init__(self, module: Module):
        super().__init__()
        self.module = module

    def update_output(self, input):
        return self.module.forward(input)


# ---------------------------------------------------------------------------
# compute ops
# ---------------------------------------------------------------------------

class Conv2D(Operation):
    """TF-semantics conv over (input, filter) pair (``ops/Conv2D.scala``).
    input NHWC (or NCHW), filter [kh, kw, cin, cout]."""

    def __init__(self, stride_h: int = 1, stride_w: int = 1,
                 padding: str = "SAME", format: str = "NHWC",
                 dilation_h: int = 1, dilation_w: int = 1):
        super().__init__()
        self.strides = (stride_h, stride_w)
        self.padding = padding
        self.format = format
        self.dilation = (dilation_h, dilation_w)

    def update_output(self, input):
        x, w = input
        dn = lax.conv_dimension_numbers(
            x.shape, w.shape,
            (self.format, "HWIO", self.format))
        return lax.conv_general_dilated(
            x, w, window_strides=self.strides, padding=self.padding,
            rhs_dilation=self.dilation, dimension_numbers=dn)


class _PoolOp(Operation):
    def __init__(self, ksize, strides, padding: str = "VALID",
                 format: str = "NHWC"):
        super().__init__()
        self.ksize = tuple(ksize)
        self.strides = tuple(strides)
        self.padding = padding
        self.format = format

    def _window(self):
        if self.format == "NHWC":
            return (1, *self.ksize, 1), (1, *self.strides, 1)
        return (1, 1, *self.ksize), (1, 1, *self.strides)


class MaxPool(_PoolOp):
    """``ops/MaxPool.scala``."""

    def update_output(self, input):
        win, strides = self._window()
        return lax.reduce_window(input, -jnp.inf, lax.max, win, strides,
                                 self.padding)


class AvgPool(_PoolOp):
    """TF AvgPool (``utils/tf/loaders/AvgPool.scala``)."""

    def update_output(self, input):
        win, strides = self._window()
        s = lax.reduce_window(input, 0.0, lax.add, win, strides, self.padding)
        ones = jnp.ones_like(input)
        count = lax.reduce_window(ones, 0.0, lax.add, win, strides,
                                  self.padding)
        return s / count


class BiasAdd(Operation):
    """(value, bias) -> value + bias over the channel dim
    (``ops/BiasAdd.scala``)."""

    def __init__(self, format: str = "NHWC"):
        super().__init__()
        self.format = format

    def update_output(self, input):
        x, b = input
        if self.format == "NCHW" and x.ndim > 2:
            shape = (1, -1) + (1,) * (x.ndim - 2)
            return x + b.reshape(shape)
        return x + b


class Cast(Operation):
    """``ops/Cast.scala``."""

    def __init__(self, dtype):
        super().__init__()
        self.dtype = dtype

    def update_output(self, input):
        return jnp.asarray(input).astype(self.dtype)


def _binary(name, fn, doc):
    def update_output(self, input):
        a, b = input
        return fn(jnp.asarray(a), jnp.asarray(b))

    return type(name, (Operation,), {
        "update_output": update_output, "__doc__": doc})


Equal = _binary("Equal", lambda a, b: a == b, "``ops/Equal.scala``.")
NotEqual = _binary("NotEqual", lambda a, b: a != b, "``ops/NotEqual.scala``.")
Greater = _binary("Greater", lambda a, b: a > b, "``ops/Greater.scala``.")
GreaterEqual = _binary("GreaterEqual", lambda a, b: a >= b,
                       "TF GreaterEqual.")
Less = _binary("Less", lambda a, b: a < b, "``ops/Less.scala``.")
LessEqual = _binary("LessEqual", lambda a, b: a <= b, "TF LessEqual.")
LogicalAnd = _binary("LogicalAnd", jnp.logical_and,
                     "``ops/LogicalAnd.scala``.")
LogicalOr = _binary("LogicalOr", jnp.logical_or, "``ops/LogicalOr.scala``.")


class LogicalNot(Operation):
    """``ops/LogicalNot.scala``."""

    def update_output(self, input):
        return jnp.logical_not(input)


class Floor(Operation):
    """``ops/Floor.scala``."""

    def update_output(self, input):
        return jnp.floor(input)


class Ceil(Operation):
    def update_output(self, input):
        return jnp.ceil(input)


class Round(Operation):
    def update_output(self, input):
        return jnp.round(input)


class L2Loss(Operation):
    """sum(x^2) / 2 (``ops/L2Loss.scala``)."""

    def update_output(self, input):
        x = input.astype(jnp.float32)
        return jnp.sum(x * x) / 2


class OneHot(Operation):
    """(indices, depth, on_value, off_value) -> one-hot along ``axis``
    (``ops/OneHot.scala``); depth/on/off may be fixed at construction."""

    def __init__(self, axis: int = -1, depth: Optional[int] = None,
                 on_value=1.0, off_value=0.0):
        super().__init__()
        self.axis = axis
        self.depth = depth
        self.on_value = on_value
        self.off_value = off_value

    def update_output(self, input):
        depth, on, off = self.depth, self.on_value, self.off_value
        if isinstance(input, (tuple, list)):
            indices = input[0]
            if len(input) > 1:
                depth = int(input[1])
            if len(input) > 2:
                on = input[2]
            if len(input) > 3:
                off = input[3]
        else:
            indices = input
        if depth is None:
            raise ValueError("OneHot needs a depth (constructor or input)")
        oh = jax.nn.one_hot(jnp.asarray(indices), depth, axis=self.axis)
        return oh * on + (1 - oh) * off


class Pad(Operation):
    """Constant-pad with static [n, 2] paddings (``ops/Pad.scala``)."""

    def __init__(self, paddings, constant_value=0):
        super().__init__()
        self.paddings = [tuple(int(v) for v in row) for row in
                         np.asarray(paddings)]
        self.constant_value = constant_value

    def update_output(self, input):
        return jnp.pad(input, self.paddings, mode="constant",
                       constant_values=self.constant_value)


class Prod(Operation):
    """Reduce-product along a dim (``ops/Prod.scala``)."""

    def __init__(self, axis: Optional[int] = None, keep_dims: bool = False):
        super().__init__()
        self.axis = axis
        self.keep_dims = keep_dims

    def update_output(self, input):
        return jnp.prod(input, axis=self.axis, keepdims=self.keep_dims)


class RandomUniform(Operation):
    """Uniform [min, max) of the given shape (``ops/RandomUniform.scala``).
    WithoutInput node: generates from its static shape."""

    _without_input = True

    def __init__(self, shape, min_val: float = 0.0, max_val: float = 1.0,
                 dtype=jnp.float32):
        super().__init__()
        from bigdl_tpu.utils.rng import next_rng_id

        self.shape = tuple(shape)
        self.min_val, self.max_val = min_val, max_val
        self.dtype = dtype
        self._rng_id = next_rng_id()

    def update_output(self, input):
        from bigdl_tpu.utils.rng import require_rng

        key = require_rng(self._rng_id)
        return jax.random.uniform(key, self.shape, self.dtype,
                                  self.min_val, self.max_val)


class TruncatedNormal(Operation):
    """Normal(0, std) truncated to 2 sigma (``ops/TruncatedNormal.scala``)."""

    _without_input = True

    def __init__(self, shape, mean: float = 0.0, stddev: float = 1.0,
                 dtype=jnp.float32):
        super().__init__()
        from bigdl_tpu.utils.rng import next_rng_id

        self.shape = tuple(shape)
        self.mean, self.stddev = mean, stddev
        self.dtype = dtype
        self._rng_id = next_rng_id()

    def update_output(self, input):
        from bigdl_tpu.utils.rng import require_rng

        key = require_rng(self._rng_id)
        z = jax.random.truncated_normal(key, -2.0, 2.0, self.shape,
                                        self.dtype)
        return z * self.stddev + self.mean


class Rank(Operation):
    """ndim as a scalar tensor (``ops/Rank.scala``)."""

    def update_output(self, input):
        return jnp.asarray(jnp.ndim(input), jnp.int32)


class ResizeBilinearOps(Operation):
    """(images NHWC, size) -> bilinear resize (``ops/ResizeBilinearOps.scala``)."""

    def __init__(self, align_corners: bool = False,
                 half_pixel_centers: bool = False):
        super().__init__()
        self.align_corners = align_corners
        self.half_pixel_centers = half_pixel_centers

    def update_output(self, input):
        from bigdl_tpu.nn.layers.shape import ResizeBilinear

        images, size = input
        h, w = int(size[0]), int(size[1])
        return ResizeBilinear(h, w, align_corners=self.align_corners,
                              format="NHWC",
                              half_pixel_centers=self.half_pixel_centers
                              ).forward(images)


class Slice(Operation):
    """Static begin/size slice (``ops/Slice.scala``)."""

    def __init__(self, begin: Sequence[int], size: Sequence[int]):
        super().__init__()
        self.begin = tuple(begin)
        self.size = tuple(size)

    def update_output(self, input):
        sizes = tuple(input.shape[i] - b if s == -1 else s
                      for i, (b, s) in enumerate(zip(self.begin, self.size)))
        for i, (b, s) in enumerate(zip(self.begin, sizes)):
            if b + s > input.shape[i]:  # TF errors; don't clamp silently
                raise ValueError(
                    f"Slice out of bounds on dim {i}: begin {b} + size {s} "
                    f"> {input.shape[i]}")
        return lax.dynamic_slice(input, self.begin, sizes)


class Assign(Operation):
    """Host-side variable write: stores the incoming value in a buffer and
    returns it (``ops/Assign.scala``).  Mutation happens eagerly on the
    module object; inside jit the op is a passthrough."""

    def update_output(self, input):
        ref, value = input if isinstance(input, (tuple, list)) else (None, input)
        self.__dict__["value"] = value
        return value


class Assert(Operation):
    """Eager-mode assertion (``ops/Assert.scala``): checks the predicate
    when running outside a trace; a passthrough no-op under jit."""

    def update_output(self, input):
        pred, data = input
        if not isinstance(pred, jax.core.Tracer):
            if not bool(jnp.all(jnp.asarray(pred))):
                raise AssertionError(f"Assert failed: {data}")
        return data


class DecodeImage(Operation):
    """Decode JPEG/PNG bytes to an HWC uint8 array (``ops/DecodeImage.scala``);
    host-side (not jittable)."""

    def __init__(self, channels: int = 3):
        super().__init__()
        self.channels = channels

    def update_output(self, input):
        import io

        from PIL import Image

        mode = {1: "L", 3: "RGB", 4: "RGBA"}[self.channels]
        arr = np.asarray(Image.open(io.BytesIO(bytes(input))).convert(mode))
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return jnp.asarray(arr)


class ParseExample(Operation):
    """Parse serialized TF Example protos into dense tensors
    (``ops/ParseExample.scala``); host-side, backed by the minimal proto
    reader in ``bigdl_tpu.dataset.tfrecord``."""

    def __init__(self, keys: Sequence[str], dtypes: Sequence,
                 shapes: Sequence):
        super().__init__()
        self.keys = list(keys)
        self.dtypes = list(dtypes)
        self.shapes = [tuple(s) for s in shapes]

    def update_output(self, input):
        from bigdl_tpu.dataset.tfrecord import parse_example

        records = input if isinstance(input, (tuple, list)) else [input]
        cols = {k: [] for k in self.keys}
        for rec in records:
            feats = parse_example(bytes(rec))
            for k in self.keys:
                cols[k].append(feats[k])
        outs = []
        for k, dt, shape in zip(self.keys, self.dtypes, self.shapes):
            arr = np.asarray(cols[k], dtype=dt).reshape((len(records),) + shape)
            outs.append(jnp.asarray(arr))
        return outs[0] if len(outs) == 1 else outs


# ---------------------------------------------------------------------------
# control flow
# ---------------------------------------------------------------------------

class While(Operation):
    """Structured while-loop (``ops/ControlOps.scala`` WhileOps →
    ``lax.while_loop``).  Input = initial loop vars."""

    def __init__(self, cond_module: Module, body_module: Module):
        super().__init__()
        self.cond_module = cond_module
        self.body_module = body_module

    def update_output(self, input):
        from bigdl_tpu.ops.control import while_modules

        return while_modules(self.cond_module, self.body_module, input)


class Cond(Operation):
    """Structured two-way branch: input = (pred, operand) →
    ``lax.cond`` over the two modules."""

    def __init__(self, true_module: Module, false_module: Module):
        super().__init__()
        self.true_module = true_module
        self.false_module = false_module

    def update_output(self, input):
        from bigdl_tpu.ops.control import cond_modules

        pred, operand = input
        return cond_modules(pred, self.true_module, self.false_module,
                            operand)


class Switch(Operation):
    """(data, pred) -> (false_branch, true_branch) pair.  Under XLA both
    downstream branches are traced; pair with ``Merge`` which selects by
    the same predicate (``ops/ControlOps.scala`` SwitchOps)."""

    def update_output(self, input):
        data, pred = input
        return (data, data, jnp.asarray(pred))


class Merge(Operation):
    """Select between two branch results by predicate: input =
    (false_out, true_out, pred) (``ops/ControlOps.scala`` MergeOps)."""

    def update_output(self, input):
        f_out, t_out, pred = input
        p = jnp.reshape(jnp.asarray(pred), ()).astype(bool)
        return jax.tree.map(lambda a, b: jnp.where(p, b, a), f_out, t_out)


class Select(Operation):
    """Elementwise where(condition, t, e) (``ops/Select.scala``-like)."""

    def update_output(self, input):
        c, t, e = input
        return jnp.where(c, t, e)
