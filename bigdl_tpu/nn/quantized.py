"""Post-training int8 quantization — the reference's **bigquant**
capability (`spark/dl/pom.xml:85-90` declares `bigquant-java`/`
bigquant-native`; upstream the Scala tree has no call sites, so the
semantics here follow the bigquant library itself: symmetric int8
weights with per-output-channel scales, dynamic per-tensor activation
quantization, int32 accumulation, float dequantized output) — rebuilt
TPU-native:

- the int8 x int8 -> int32 contraction runs on the MXU at TWICE the
  bf16 macs/cycle on v5e (394 int8 TOPS vs 197 bf16 TFLOP/s), so
  quantized inference is a throughput feature, not just a memory one.
  MEASURED (round 5, TPU v5e, BASELINE.md int8 table): VGG-16 inference
  2.09x bf16 end-to-end — the 2x MXU claim holds when the model is
  MXU-bound.  Inception-v1 measured 0.62x (a LOSS) with DYNAMIC
  activation scales: the per-conv global amax reduce was a full extra
  activation read and a fusion barrier (round-6 attribution hunt,
  BASELINE.md).  FIXED by the calibration pass: ``calibrate(model,
  batches)`` turns each module's activation scale into a trace
  constant, the reduce disappears, and calibrated int8 inception moves
  0.89x the bytes of bf16 at equal flops (docs/serving.md).  Guidance:
  calibrate before serving int8 — uncalibrated modules fall back to
  the dynamic path;
- weights store as int8 buffers (4x smaller than f32 in BTPU
  checkpoints and in HBM);
- `quantize(model)` mirrors `Module.quantize()` in the reference's API
  surface: walk the tree, swap eligible layers for their quantized
  twins, return the model in eval mode.

Quantized modules are inference-only (like bigquant): they carry no
trainable parameters, so `state_dict(kind="param")` is empty and the
training step refuses them naturally.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.nn.layers.conv import SpatialConvolution
from bigdl_tpu.nn.layers.linear import Linear
from bigdl_tpu.nn.module import Container, Module

__all__ = ["QuantizedLinear", "QuantizedSpatialConvolution", "quantize",
           "calibrate"]


def _quantize_weight(w: np.ndarray, reduce_axes: Tuple[int, ...]):
    """Symmetric per-output-channel int8: scale = max|w| / 127 over all
    non-output axes (bigquant's FLOAT->int8 kernel convention)."""
    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=reduce_axes, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale.reshape(-1)


def _quantize_activation(x, axes=None):
    """Dynamic per-tensor symmetric int8 for activations: returns
    (x_q int8, scale f32 scalar).  Differentiation is unsupported by
    design (inference path).

    This is the SLOW path (BASELINE.md round-6 root cause): the global
    amax reduce is a full extra read of the activation AND a fusion
    barrier — the scale feeds the very next op, so XLA cannot fuse the
    quantize into the producer, costing 2+ full-tensor passes per layer.
    Calibrated modules (``calibrate``) carry a *static* ``act_scale``
    instead and never enter here at serve time."""
    amax = jnp.max(jnp.abs(x))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _quantize_activation_static(x, scale: float):
    """Calibrated int8: ``scale`` is a Python float — a TRACE CONSTANT,
    so there is no reduce, no barrier, and the divide/round/clip/convert
    chain fuses straight into the producing op."""
    inv = np.float32(1.0 / scale)
    q = jnp.clip(jnp.round(x * inv), -127, 127).astype(jnp.int8)
    return q, np.float32(scale)


class _ActObserver:
    """Mixin: per-module activation-range observation + the static
    quantize/dynamic fallback switch shared by both quantized twins.

    ``act_scale`` (Python float, persisted by BTPU as a plain attr) is
    the calibrated per-tensor input scale; ``None`` means uncalibrated —
    the module falls back to the dynamic amax path.  Observation only
    happens on EAGER forwards (calibration passes); under jit the
    concrete ``float()`` read would be a tracer leak, so it is skipped
    by an explicit tracer check, not by trust."""

    def _quantize_input(self, x):
        d = self.__dict__
        if d.get("_observing"):
            import jax.core as _core

            if not isinstance(x, _core.Tracer):
                amax = float(jnp.max(jnp.abs(x)))
                d["_observed_amax"] = max(d.get("_observed_amax", 0.0),
                                          amax)
        scale = d.get("act_scale")
        if scale is not None and not d.get("_observing"):
            return _quantize_activation_static(x, scale)
        return _quantize_activation(x)


class QuantizedLinear(_ActObserver, Module):
    """int8 ``y = x W^T + b`` (``Linear`` twin).  The contraction is
    int8 x int8 -> int32 (``preferred_element_type``), dequantized by
    ``act_scale * w_scale[out]``."""

    def __init__(self, input_size: int, output_size: int,
                 weight_q=None, w_scale=None, bias=None):
        super().__init__()
        self.input_size, self.output_size = input_size, output_size
        self.with_bias = bias is not None
        self.act_scale = None  # calibrated static input scale (float)
        self.register_buffer("weight_q",
                             np.zeros((output_size, input_size), np.int8)
                             if weight_q is None else np.asarray(weight_q))
        self.register_buffer("w_scale",
                             np.ones(output_size, np.float32)
                             if w_scale is None else np.asarray(w_scale))
        if bias is not None:
            self.register_buffer("bias", np.asarray(bias, np.float32))

    @classmethod
    def from_float(cls, m: Linear) -> "QuantizedLinear":
        q, scale = _quantize_weight(np.asarray(m.weight), (1,))
        bias = np.asarray(m.bias) if m.with_bias else None
        out = cls(m.input_size, m.output_size, q, scale, bias)
        if m.__dict__.get("_name"):
            out.set_name(m.__dict__["_name"])
        return out

    def update_output(self, input):
        x_q, s_x = self._quantize_input(input)
        acc = lax.dot_general(
            x_q, self.weight_q,
            dimension_numbers=(((x_q.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * (s_x * self.w_scale)
        if self.with_bias:
            y = y + self.bias
        return y


class QuantizedSpatialConvolution(_ActObserver, Module):
    """int8 NCHW convolution (``SpatialConvolution`` twin); weight
    stays OIHW int8, accumulation int32 on the MXU."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int, stride_w: int = 1,
                 stride_h: int = 1, pad_w: int = 0, pad_h: int = 0,
                 n_group: int = 1, weight_q=None, w_scale=None, bias=None):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.n_group = n_group
        self.with_bias = bias is not None
        self.act_scale = None  # calibrated static input scale (float)
        wshape = (n_output_plane, n_input_plane // n_group,
                  kernel_h, kernel_w)
        self.register_buffer("weight_q",
                             np.zeros(wshape, np.int8) if weight_q is None
                             else np.asarray(weight_q))
        self.register_buffer("w_scale",
                             np.ones(n_output_plane, np.float32)
                             if w_scale is None else np.asarray(w_scale))
        if bias is not None:
            self.register_buffer("bias", np.asarray(bias, np.float32))

    @classmethod
    def from_float(cls, m: SpatialConvolution) -> "QuantizedSpatialConvolution":
        if m.format != "NCHW":
            raise ValueError("quantize supports NCHW convolutions")
        q, scale = _quantize_weight(np.asarray(m.weight), (1, 2, 3))
        bias = np.asarray(m.bias) if m.with_bias else None
        out = cls(m.n_input_plane, m.n_output_plane, m.kernel_w, m.kernel_h,
                  m.stride_w, m.stride_h, m.pad_w, m.pad_h, m.n_group,
                  q, scale, bias)
        if m.__dict__.get("_name"):
            out.set_name(m.__dict__["_name"])
        return out

    def update_output(self, input):
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        x_q, s_x = self._quantize_input(x)
        if self.pad_w == -1 or self.pad_h == -1:
            padding = "SAME"
        else:
            padding = [(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)]
        dn = lax.conv_dimension_numbers(
            x.shape, self.weight_q.shape, ("NCHW", "OIHW", "NCHW"))
        acc = lax.conv_general_dilated(
            x_q, self.weight_q, (self.stride_h, self.stride_w), padding,
            dimension_numbers=dn, feature_group_count=self.n_group,
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) \
            * (s_x * self.w_scale)[None, :, None, None]
        if self.with_bias:
            y = y + self.bias[None, :, None, None]
        return y[0] if squeeze else y


_QUANTIZABLE = {Linear: QuantizedLinear.from_float,
                SpatialConvolution: QuantizedSpatialConvolution.from_float}


def _converter_for(model):
    """Exact type first, then the MRO — so well-behaved subclasses
    (``SpatialShareConvolution``: identical math, buffer aliasing only)
    quantize as their registered base.  A subclass that OVERRIDES the
    forward math relative to that base (e.g. the space-to-depth masked
    conv) must not be silently converted with base-class semantics: it
    is skipped with a warning instead of mis-quantized or silently left
    float (ADVICE r4: exact-type dispatch dropped such layers without a
    trace)."""
    import logging

    t = type(model)
    conv = _QUANTIZABLE.get(t)
    if conv is not None:
        return conv
    mro = t.__mro__
    for i, klass in enumerate(mro[1:], start=1):
        conv = _QUANTIZABLE.get(klass)
        if conv is None:
            continue
        if any("update_output" in c.__dict__ or "forward" in c.__dict__
               for c in mro[:i]):
            logging.getLogger("bigdl_tpu").warning(
                f"quantize: {t.__name__} subclasses {klass.__name__} but "
                f"overrides its forward math — left in float")
            return None
        return conv
    return None


def calibrate(model: Module, batches, margin: float = 1.0) -> Module:
    """Calibration pass: set **static** activation scales on every
    quantized module from the observed input ranges (BASELINE.md
    round-6 fix — the serving-path answer to int8-slower-than-bf16).

    ``model`` is an already-``quantize()``d tree; ``batches`` iterates
    representative inputs (arrays shaped like inference batches).  Each
    batch runs one EAGER forward with range observers armed; afterwards
    every quantized module's ``act_scale`` becomes
    ``margin * max|input| / 127`` — a Python float, i.e. a trace
    constant: the per-call global amax reduce (a full extra activation
    read AND a fusion barrier) disappears from the compiled program,
    and the quantize chain fuses into the producing op.

    ``margin > 1`` leaves headroom for traffic hotter than the
    calibration set (out-of-range activations clip at +/-127).
    Returns the model; re-calibration overwrites the scales."""
    qmods = [m for m in model.modules() if isinstance(m, _ActObserver)]
    if not qmods:
        raise ValueError(
            "calibrate: no quantized modules found — quantize(model) "
            "first")
    for m in qmods:
        m.__dict__["_observing"] = True
        m.__dict__["_observed_amax"] = 0.0
    try:
        n = 0
        for x in batches:
            model.forward(jnp.asarray(x))
            n += 1
        if n == 0:
            raise ValueError("calibrate: empty calibration set")
    finally:
        for m in qmods:
            m.__dict__["_observing"] = False
    for m in qmods:
        amax = m.__dict__.pop("_observed_amax", 0.0)
        m.act_scale = float(margin * amax / 127.0) if amax > 0 else 1.0
    return model


def quantize(model: Module) -> Module:
    """Swap every eligible layer for its int8 twin (in place for
    containers; returns the — possibly new — root) and switch to eval
    mode: the reference API's ``quantized_model = model.quantize()``."""
    conv = _converter_for(model)
    if conv is not None:
        return conv(model)
    if isinstance(model, Container):
        mods = model.__dict__["_modules"]
        for k in list(mods):
            mods[k] = quantize(mods[k])
    else:
        for k, sub in list(model.__dict__["_modules"].items()):
            model.__dict__["_modules"][k] = quantize(sub)
    return model.evaluate()
