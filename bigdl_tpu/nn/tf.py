"""TF-support layers (the reference's ``nn/tf/`` subpackage, 7 files —
SURVEY §2.5): Const, Fill, Shape, SplitAndSelect, StrideSlice, Variable,
ControlDependency, plus the WithoutInput marker semantics used by Graph.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Module, Parameter

__all__ = ["Const", "Fill", "Shape", "SplitAndSelect", "StrideSlice",
           "Variable", "ControlDependency"]


class Const(Module):
    """Constant-emitting node (``nn/tf/Const.scala``); takes no input."""

    _without_input = True
    _is_const = True

    def __init__(self, value):
        super().__init__()
        self.value = jnp.asarray(value)

    def update_output(self, input):
        return self.value


class Fill(Module):
    """(shape, value) -> full tensor (``nn/tf/Fill.scala``); shape must be
    static (host values, not traced)."""

    def update_output(self, input):
        shape, value = input
        shape = tuple(int(s) for s in np.asarray(shape).reshape(-1))
        return jnp.full(shape, value)


class Shape(Module):
    """Tensor shape as a 1-D int32 tensor (``nn/tf/Shape.scala``)."""

    def update_output(self, input):
        return jnp.asarray(jnp.shape(input), jnp.int32)


class SplitAndSelect(Module):
    """Split along ``dim`` into ``num_splits`` and return chunk ``index``
    (``nn/tf/SplitAndSelect.scala``)."""

    def __init__(self, dim: int, index: int, num_splits: int):
        super().__init__()
        self.dim, self.index, self.num_splits = dim, index, num_splits

    def update_output(self, input):
        return jnp.split(input, self.num_splits, axis=self.dim)[self.index]


class StrideSlice(Module):
    """Python-semantics strided slice; specs = [(dim, start, stop, step)]
    (``nn/tf/StrideSlice.scala``)."""

    def __init__(self, specs: Sequence[Tuple[int, int, int, int]]):
        super().__init__()
        self.specs = [tuple(s) for s in specs]

    def update_output(self, input):
        slices = [slice(None)] * input.ndim
        for dim, start, stop, step in self.specs:
            slices[dim] = slice(start, stop, step)
        return input[tuple(slices)]


class Variable(Module):
    """Trainable tensor node (``nn/tf/Variable.scala``): emits its weight;
    gradients flow into it like any parameter."""

    _without_input = True

    def __init__(self, initial_value):
        super().__init__()
        self.weight = Parameter(initial_value)

    def update_output(self, input):
        return self._params["weight"]


class ControlDependency(Module):
    """Ordering-only edge: forwards its first input, ignores the rest
    (``nn/tf/ControlDependency.scala``).  Under XLA ordering is handled by
    data dependence, so this is a passthrough."""

    def update_output(self, input):
        if isinstance(input, (tuple, list)):
            return input[0]
        return input
