"""Core module system for bigdl_tpu.

Capability parity with the reference's ``AbstractModule``
(``nn/abstractnn/AbstractModule.scala:56``): forward/backward, parameter
access and flattening, train/eval modes, freeze/unFreeze, per-layer LR
scales (``setScaleW/B``), cloning, per-module timing, save/load and graph
node building — re-designed for JAX rather than translated:

- Modules are **host-side mutable objects** holding ``jax.Array`` parameters
  (Torch-style user API, like the reference), but every computation is
  expressed through a **pure functional core**: ``functional_call`` binds an
  explicit parameter/buffer pytree, runs ``forward`` under trace, and returns
  the updated state.  Training steps ``jit``/``pjit`` that pure function; the
  mutable API is a thin eager shell over it.
- ``backward`` is derived from ``jax.vjp`` of the pure forward instead of the
  reference's hand-written ``updateGradInput``/``accGradParameters`` chains
  (``AbstractModule.scala:260-297``).  Layers only define ``update_output``.
- Parameters are plain arrays; "shared flattened weight storage" across model
  clones (``DistriOptimizer.scala:566-571``) is unnecessary under SPMD — the
  pjit-sharded param pytree plays that role.
"""

from __future__ import annotations

import copy
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_UNSET = object()  # sentinel for __setattr__ hyper-version tracking

__all__ = [
    "Parameter",
    "Module",
    "Container",
    "Sequential",
    "Identity",
    "Echo",
    "LayerException",
    "functional_call",
    "state_dict",
    "load_state_dict",
    "stamp_scope_names",
    "capture_shapes",
    "summary",
]


class LayerException(RuntimeError):
    """Wraps errors raised inside a layer's forward/backward with the layer
    path, mirroring the reference's ``LayerException`` wrapping in
    ``AbstractModule.forward`` (``AbstractModule.scala:234``)."""

    def __init__(self, layer: str, error: BaseException):
        super().__init__(f"Layer info: {layer}\n{type(error).__name__}: {error}")
        self.layer = layer
        self.error = error


class Parameter:
    """Marker wrapper: assigning ``self.w = Parameter(arr)`` registers ``arr``
    as a trainable parameter of the module."""

    __slots__ = ("data",)

    def __init__(self, data):
        self.data = jnp.asarray(data)


def _is_array(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


class Module:
    """Base class of every layer and container."""

    def __init__(self):
        d = object.__getattribute__(self, "__dict__")
        d["_params"]: Dict[str, jax.Array] = {}
        d["_buffers"]: Dict[str, jax.Array] = {}
        d["_modules"]: Dict[str, "Module"] = {}
        d["_grads"]: Dict[str, jax.Array] = {}
        d["_frozen"] = False
        d["training"] = True
        d["_name"] = None
        d["scale_w"] = 1.0
        d["scale_b"] = 1.0
        d["forward_time"] = 0.0
        d["backward_time"] = 0.0
        d["output"] = None
        d["grad_input"] = None

    # -- attribute routing (torch-style registration) ----------------------
    def __setattr__(self, name, value):
        d = self.__dict__
        if isinstance(value, Parameter):
            d.setdefault("_params", {})[name] = value.data
            d["_modules"].pop(name, None)
            d.pop(name, None)
            return
        if "_params" in d and name in d["_params"]:
            if value is None:
                del d["_params"][name]
                d[name] = None
                return
            d["_params"][name] = jnp.asarray(value)
            return
        if "_buffers" in d and name in d["_buffers"]:
            if value is None:
                del d["_buffers"][name]
                d[name] = None
                return
            d["_buffers"][name] = jnp.asarray(value)
            return
        if isinstance(value, Module):
            d.setdefault("_modules", {})[name] = value
            d.pop(name, None)
            return
        if "_modules" in d and name in d["_modules"] and not isinstance(value, Module):
            del d["_modules"][name]
        # plain-attribute (hyperparameter) edits invalidate memoized
        # backward traces — the value may be baked into a cached jit.
        # Only SCALAR equality short-circuits the bump (container values
        # may hold arrays whose == is elementwise).
        old = d.get(name, _UNSET)
        if not (old is value or (
                isinstance(value, (int, float, str, bool, type(None)))
                and isinstance(old, type(value)) and old == value)):
            d["_hyper_version"] = d.get("_hyper_version", 0) + 1
        d[name] = value

    def __getattr__(self, name):
        # only called when normal lookup fails
        d = object.__getattribute__(self, "__dict__")
        for table in ("_params", "_buffers", "_modules"):
            t = d.get(table)
            if t is not None and name in t:
                return t[name]
        raise AttributeError(f"{type(self).__name__} has no attribute {name!r}")

    def register_buffer(self, name: str, value):
        self.__dict__["_buffers"][name] = jnp.asarray(value)

    # -- naming ------------------------------------------------------------
    def set_name(self, name: str) -> "Module":
        self.__dict__["_name"] = name
        return self

    def get_name(self) -> str:
        return self.__dict__["_name"] or f"{type(self).__name__}{abs(id(self)) % 100000}"

    def __repr__(self):
        return f"{type(self).__name__}"

    # -- forward / backward ------------------------------------------------
    def update_output(self, input):
        """Layer computation; subclasses override.  Default: identity."""
        return input

    def forward(self, input):
        from bigdl_tpu.utils.rng import RNG, current_rng_key, rng_context
        import jax as _jax

        t0 = time.perf_counter()
        # cost-attribution scope (docs/observability.md): once a model is
        # stamped (stamp_scope_names — TrainStep/EvalStep do it at build
        # time), every module runs its computation under
        # jax.named_scope(<registration key>), so compiled-HLO op metadata
        # carries the module-tree path.  Scopes are trace-time metadata
        # only: they never enter jit cache keys, so no retraces.
        scope = self.__dict__.get("_scope_name")
        run = self.update_output
        if scope:
            def run(inp, _run=self.update_output, _scope=scope):
                with _jax.named_scope(_scope):
                    return _run(inp)
        try:
            if current_rng_key() is None:
                # Eager call outside any training-step RNG context: install a
                # host-seeded key and remember it so backward() replays the
                # same random realization (dropout masks, RReLU slopes).
                key = _jax.random.key(int(RNG.randint(0, 2**31 - 1)))
                self.__dict__["_last_rng_key"] = key
                with rng_context(key):
                    out = run(input)
            else:
                out = run(input)
        except jax.errors.TracerArrayConversionError:
            raise
        except LayerException:
            raise
        except Exception as e:  # noqa: BLE001 - parity with LayerException wrap
            raise LayerException(self.get_name(), e) from e
        if _SHAPE_CAPTURE:
            # record ABSTRACT shapes only (never the tracers themselves):
            # the capture outlives the trace that produced it
            _SHAPE_CAPTURE[-1][id(self)] = jax.tree.map(
                lambda a: (tuple(jnp.shape(a)),
                           str(getattr(a, "dtype", type(a).__name__))), out)
        self.__dict__["output"] = out
        self.__dict__["forward_time"] += time.perf_counter() - t0
        return out

    __call__ = forward

    def backward(self, input, grad_output):
        """Compute ``gradInput`` and accumulate parameter gradients, via
        ``jax.vjp`` over the pure forward (replaces the reference's
        ``updateGradInput`` + ``accGradParameters``).

        The vjp is compiled and MEMOIZED per module: the trace is keyed on
        every submodule's identity, (training, frozen) flags, and
        hyperparameter version (bumped by ``__setattr__`` on plain-attr
        edits); buffers ride as traced arguments; ``jax.jit`` handles
        shape/dtype variation under each key — so a Torch-style eager loop
        pays tracing once, matching the reference's cheap repeated
        ``backward`` (``AbstractModule.scala:260-297``), while structural
        or hyperparameter edits re-trace automatically."""
        from bigdl_tpu.utils.rng import current_rng_key

        t0 = time.perf_counter()
        params = state_dict(self, kind="param")
        # Replay the key forward() used so the vjp recomputation sees the
        # same random realization the user observed.  An AMBIENT context
        # key must also ride as the traced argument — otherwise the
        # cached jit would bake the first call's key in as a constant and
        # replay stale dropout masks on every later step.
        replay_key = current_rng_key()
        if replay_key is None:
            replay_key = self.__dict__.get("_last_rng_key")

        # functional_call clears trace scratch (_last_rng_key, Recurrent
        # state, ...) — snapshot and restore so eager state survives
        # repeated backward calls and get_hidden_state() after backward
        # (only the TRACE touches python state; cached replays don't)
        scratch = []
        for m in self.modules():
            entry = {}
            if "_last_rng_key" in m.__dict__:
                entry["_last_rng_key"] = m.__dict__["_last_rng_key"]
            for attr in m.__dict__.get("_trace_attrs", ()):
                entry[attr] = m.__dict__.get(attr)
            scratch.append(entry)

        cache = self.__dict__.setdefault("_bwd_cache", {})
        # key: identity + mode + frozen + hyperparameter version of every
        # submodule (attr edits bump _hyper_version via __setattr__), so
        # stale traces cannot be replayed; buffers are traced ARGUMENTS so
        # e.g. BN running stats are always current
        flags = tuple((id(m), m.training, m.__dict__["_frozen"],
                       m.__dict__.get("_hyper_version", 0))
                      for m in self.modules())
        ckey = (replay_key is not None, flags)
        buffers = state_dict(self, kind="buffer")
        if ckey not in cache:
            def bwd_fn(p, bufs, inp, gout, key):
                def fn(p2, i2):
                    out, _ = functional_call(self, {**p2, **bufs}, i2,
                                             rng=key)
                    return out

                out, vjp = jax.vjp(fn, p, inp)
                tangent = jax.tree.map(
                    lambda o, g: jnp.asarray(g, o.dtype) if g is not None
                    else jnp.zeros_like(o), out, gout)
                return vjp(tangent)

            cache.clear()  # one live trace per module keeps memory bounded
            cache[ckey] = jax.jit(bwd_fn)
        p_grads, grad_input = cache[ckey](params, buffers, input,
                                          grad_output, replay_key)
        for m, entry in zip(self.modules(), scratch):
            for attr, val in entry.items():
                m.__dict__[attr] = val
        if not self.__dict__["_frozen"]:
            self._accumulate_grads(p_grads)
        self.__dict__["grad_input"] = grad_input
        self.__dict__["backward_time"] += time.perf_counter() - t0
        return grad_input

    def update_grad_input(self, input, grad_output):
        return self.backward(input, grad_output)

    def _accumulate_grads(self, path_grads: Dict[str, jax.Array]):
        for path, g in path_grads.items():
            mod, leaf = _resolve(self, path)
            if mod.__dict__["_frozen"]:
                continue
            scale = mod.scale_b if leaf == "bias" else mod.scale_w
            prev = mod.__dict__["_grads"].get(leaf)
            g = g * scale if scale != 1.0 else g
            mod.__dict__["_grads"][leaf] = g if prev is None else prev + g

    # -- parameters --------------------------------------------------------
    def named_parameters(self, prefix: str = "") -> Iterator[Tuple[str, jax.Array]]:
        for k, v in self.__dict__["_params"].items():
            yield prefix + k, v
        for name, m in self.__dict__["_modules"].items():
            yield from m.named_parameters(prefix + name + ".")

    def parameters(self) -> Tuple[List[jax.Array], List[jax.Array]]:
        """(weights, gradients) — mirrors ``AbstractModule.parameters``."""
        ws, gs = [], []
        for path, w in self.named_parameters():
            mod, leaf = _resolve(self, path)
            g = mod.__dict__["_grads"].get(leaf)
            ws.append(w)
            gs.append(g if g is not None else jnp.zeros_like(w))
        return ws, gs

    def get_parameters(self) -> Tuple[jax.Array, jax.Array]:
        """Flattened (weights, grads) 1-D views, mirroring
        ``AbstractModule.getParameters`` (``AbstractModule.scala:313``)."""
        ws, gs = self.parameters()
        if not ws:
            return jnp.zeros((0,)), jnp.zeros((0,))
        flat_w = jnp.concatenate([jnp.ravel(w) for w in ws])
        flat_g = jnp.concatenate([jnp.ravel(g) for g in gs])
        return flat_w, flat_g

    def set_flat_parameters(self, flat: jax.Array):
        offset = 0
        for path, w in list(self.named_parameters()):
            n = int(np.prod(w.shape)) if w.ndim else 1
            mod, leaf = _resolve(self, path)
            mod.__dict__["_params"][leaf] = flat[offset : offset + n].reshape(w.shape).astype(w.dtype)
            offset += n

    def zero_grad_parameters(self):
        for m in self.modules():
            m.__dict__["_grads"].clear()

    def update_parameters(self, lr: float):
        for path, w in list(self.named_parameters()):
            mod, leaf = _resolve(self, path)
            g = mod.__dict__["_grads"].get(leaf)
            if g is not None:
                mod.__dict__["_params"][leaf] = w - lr * g

    # -- modes / traversal -------------------------------------------------
    def modules(self) -> Iterator["Module"]:
        yield self
        for m in self.__dict__["_modules"].values():
            yield from m.modules()

    def named_modules(self, prefix: str = "") -> Iterator[Tuple[str, "Module"]]:
        yield prefix.rstrip("."), self
        for name, m in self.__dict__["_modules"].items():
            yield from m.named_modules(prefix + name + ".")

    def training_mode(self) -> "Module":
        for m in self.modules():
            m.__dict__["training"] = True
        return self

    # reference naming: model.training() / model.evaluate()
    def train(self) -> "Module":
        return self.training_mode()

    def evaluate(self) -> "Module":
        for m in self.modules():
            m.__dict__["training"] = False
        return self

    def is_training(self) -> bool:
        return self.__dict__["training"]

    def freeze(self) -> "Module":
        for m in self.modules():
            m.__dict__["_frozen"] = True
        return self

    def unfreeze(self) -> "Module":
        for m in self.modules():
            m.__dict__["_frozen"] = False
        return self

    def is_frozen(self) -> bool:
        return self.__dict__["_frozen"]

    def set_scale_w(self, s: float) -> "Module":
        self.__dict__["scale_w"] = s
        return self

    def set_scale_b(self, s: float) -> "Module":
        self.__dict__["scale_b"] = s
        return self

    # -- init --------------------------------------------------------------
    def reset(self):
        """Re-initialise parameters; layers with weights override."""
        for m in self.__dict__["_modules"].values():
            m.reset()

    def set_init_method(self, weight_init=None, bias_init=None) -> "Module":
        if weight_init is not None:
            self.__dict__["weight_init"] = weight_init
        if bias_init is not None:
            self.__dict__["bias_init"] = bias_init
        self.reset()
        return self

    # -- timing (getTimes parity) -----------------------------------------
    def get_times(self) -> List[Tuple["Module", float, float]]:
        return [(m, m.__dict__["forward_time"], m.__dict__["backward_time"]) for m in self.modules()]

    def reset_times(self):
        for m in self.modules():
            m.__dict__["forward_time"] = 0.0
            m.__dict__["backward_time"] = 0.0

    # -- cloning / persistence --------------------------------------------
    def clone_module(self) -> "Module":
        return copy.deepcopy(self)

    def save(self, path: str, overwrite: bool = False):
        from bigdl_tpu.utils.serializer import save_module

        save_module(self, path, overwrite=overwrite)
        return self

    # -- graph building ----------------------------------------------------
    def inputs(self, *nodes):
        """Build a graph ``Node`` from predecessor nodes — the functional-API
        builder mirroring ``AbstractModule.inputs`` (``AbstractModule.scala:607``)."""
        from bigdl_tpu.nn.graph import node_from_module

        return node_from_module(self, nodes)

    def __getitem__(self, name):
        for n, m in self.named_modules():
            if m.__dict__["_name"] == name or n == name:
                return m
        raise KeyError(name)

    def summary(self, input_spec=None) -> str:
        """Torch-style per-layer table (path, class, output shape via
        ``jax.eval_shape``, param count/bytes) — see
        :func:`bigdl_tpu.nn.module.summary`."""
        return summary(self, input_spec)

    # -- prediction / evaluation (single-process convenience) -------------
    def predict(self, dataset, batch_size: int = 32):
        from bigdl_tpu.optim.predictor import LocalPredictor

        return LocalPredictor(self, batch_size=batch_size).predict(dataset)

    def predict_class(self, dataset, batch_size: int = 32):
        from bigdl_tpu.optim.predictor import LocalPredictor

        return LocalPredictor(self, batch_size=batch_size).predict_class(dataset)

    def evaluate_on(self, dataset, methods, batch_size: int = 32):
        from bigdl_tpu.optim.evaluator import Evaluator

        return Evaluator(self, batch_size=batch_size).evaluate(dataset, methods)


# --------------------------------------------------------------------------
# Module paths: cost-attribution scopes + shape capture + summary
# --------------------------------------------------------------------------

#: stack of active shape-capture dicts (id(module) -> output shape pytree);
#: a plain module global so Module.forward pays one falsy check when off.
_SHAPE_CAPTURE: List[Dict[int, Any]] = []


def stamp_scope_names(root: Module, enabled: bool = True) -> Module:
    """Stamp every submodule with its registration key so
    :meth:`Module.forward` wraps its computation in
    ``jax.named_scope(<key>)`` — nesting reproduces the full module path
    (``features/0/conv1``) in compiled-HLO op metadata, the substrate of
    per-module cost attribution (``telemetry/attribution.py``).

    Labels are the ``_modules`` registration keys, so a scope path joined
    with ``.`` equals the ``named_parameters`` path of the same module.
    The root carries no scope (its children are the first frame).  A
    weight-shared module registered under several paths keeps the first
    label — its usages aggregate under one row.  ``enabled=False`` clears
    the stamps (``BIGDL_SCOPES=off``)."""
    seen = {id(root)}
    for name, m in root.named_modules():
        if not name:
            continue
        if not enabled:
            m.__dict__.pop("_scope_name", None)
            continue
        if id(m) in seen:  # weight sharing: first path wins
            continue
        seen.add(id(m))
        # __dict__ write, NOT __setattr__: stamping must not bump
        # _hyper_version (that would invalidate memoized backward traces)
        m.__dict__["_scope_name"] = name.rsplit(".", 1)[-1]
    return root


@contextmanager
def capture_shapes():
    """Collect each module's output shapes during the forwards run inside
    the block — yields ``{id(module): pytree of (shape, dtype)}``.  Safe
    under ``jax.eval_shape``: only abstract shapes are stored."""
    cap: Dict[int, Any] = {}
    _SHAPE_CAPTURE.append(cap)
    try:
        yield cap
    finally:
        # remove by IDENTITY: list.remove uses ==, and two empty capture
        # dicts compare equal — equality removal could strip another
        # active capture's dict under concurrency/nesting
        for i in range(len(_SHAPE_CAPTURE) - 1, -1, -1):
            if _SHAPE_CAPTURE[i] is cap:
                del _SHAPE_CAPTURE[i]
                break


def summary(module: Module, input_spec=None) -> str:
    """Torch-style per-layer table: module path, class, output shape,
    own-parameter count/bytes, trainable flag.

    ``input_spec``: a (pytree of) ``jax.ShapeDtypeStruct`` (or concrete
    arrays) fed through ``jax.eval_shape`` — no data, no compile.  When
    omitted the output-shape column is skipped (parameters only).

    The table needs no scope stamping (shape capture keys on module
    identity), so a ``BIGDL_SCOPES=off`` choice is left untouched."""
    shapes: Dict[int, Any] = {}
    if input_spec is not None:
        state = state_dict(module)

        def fwd(x):
            return functional_call(module, state, x, training=False)[0]

        with capture_shapes() as shapes:
            jax.eval_shape(fwd, input_spec)

    def _fmt_shape(tree) -> str:
        leaves = jax.tree_util.tree_leaves(
            tree, is_leaf=lambda x: isinstance(x, tuple) and len(x) == 2
            and isinstance(x[0], tuple) and isinstance(x[1], str))
        return ", ".join(f"{list(s)} {d}" for s, d in leaves) or "?"

    rows = []
    total_params = total_bytes = 0
    for name, m in module.named_modules():
        own = m.__dict__["_params"]
        n_params = sum(int(np.prod(p.shape)) if p.ndim else 1
                       for p in own.values())
        n_bytes = sum(int(getattr(p, "nbytes", 0)) for p in own.values())
        total_params += n_params
        total_bytes += n_bytes
        rows.append((name or "(root)", type(m).__name__,
                     _fmt_shape(shapes.get(id(m))) if shapes else "-",
                     n_params, n_bytes,
                     "frozen" if m.__dict__["_frozen"] else "train"))
    widths = [max(len(str(r[i])) for r in rows) for i in range(3)]
    lines = [f"{'module':<{widths[0]}}  {'class':<{widths[1]}}  "
             f"{'output shape':<{widths[2]}}  {'params':>10}  "
             f"{'bytes':>12}  mode"]
    lines.append("-" * len(lines[0]))
    for path, cls, shape, n, b, mode in rows:
        lines.append(f"{path:<{widths[0]}}  {cls:<{widths[1]}}  "
                     f"{shape:<{widths[2]}}  {n:>10}  {b:>12}  {mode}")
    lines.append("-" * len(lines[0]))
    lines.append(f"total parameters: {total_params:,}  "
                 f"({total_bytes:,} bytes)")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# Functional core
# --------------------------------------------------------------------------

def _resolve(root: Module, path: str) -> Tuple[Module, str]:
    parts = path.split(".")
    mod = root
    for p in parts[:-1]:
        mod = mod.__dict__["_modules"][p]
    return mod, parts[-1]


def state_dict(module: Module, kind: str = "all", prefix: str = "") -> Dict[str, jax.Array]:
    """Collect ``{path: array}`` for params and/or buffers."""
    out: Dict[str, jax.Array] = {}
    if kind in ("all", "param"):
        for k, v in module.__dict__["_params"].items():
            out[prefix + k] = v
    if kind in ("all", "buffer"):
        for k, v in module.__dict__["_buffers"].items():
            out[prefix + k] = v
    for name, m in module.__dict__["_modules"].items():
        out.update(state_dict(m, kind, prefix + name + "."))
    return out


def load_state_dict(module: Module, state: Dict[str, Any], strict: bool = True):
    """Load ``{path: array}`` into the module tree.

    Under ``strict=True`` ALL missing and unexpected keys are collected
    and reported in ONE ``KeyError`` (instead of failing on the first),
    so a checkpoint/analyzer mismatch is actionable in one shot."""
    own = state_dict(module)
    unexpected = [path for path in state if path not in own]
    for path, v in state.items():
        if path not in own:
            continue
        mod, leaf = _resolve(module, path)
        if leaf in mod.__dict__["_params"]:
            mod.__dict__["_params"][leaf] = v if isinstance(v, jax.Array) else jnp.asarray(v)
        elif leaf in mod.__dict__["_buffers"]:
            mod.__dict__["_buffers"][leaf] = v if isinstance(v, jax.Array) else jnp.asarray(v)
    if strict:
        missing = sorted(set(own) - set(state))
        if missing or unexpected:
            parts = []
            if missing:
                parts.append(f"missing keys in state: {missing}")
            if unexpected:
                parts.append(
                    f"no parameter/buffer in {type(module).__name__} for "
                    f"unexpected keys: {sorted(unexpected)}")
            raise KeyError("; ".join(parts))


def _clear_outputs(module: Module):
    for m in module.modules():
        m.__dict__["output"] = None
        m.__dict__["grad_input"] = None
        # forward() may have stored a replay key; under trace it is a tracer
        # (jax.random.key stages to the ambient trace) and must not survive
        m.__dict__.pop("_last_rng_key", None)
        # clear any module-specific trace-time scratch (e.g. Recurrent's
        # final scan state) so tracers never leak out of functional_call
        for attr in m.__dict__.get("_trace_attrs", ()):
            m.__dict__[attr] = None


def functional_call(
    module: Module,
    state: Dict[str, jax.Array],
    input,
    training: Optional[bool] = None,
    rng=None,
) -> Tuple[Any, Dict[str, jax.Array]]:
    """Pure-function view of ``module.forward``.

    Binds ``state`` (params and, optionally, buffers) onto the module tree,
    runs forward, collects the (possibly updated) buffer state, then restores
    the module's original concrete arrays.  Safe to trace under
    ``jit``/``pjit``/``grad``; this is the bridge from the Torch-style
    mutable API to the functional JAX core.

    Returns ``(output, new_state)`` where ``new_state`` covers the same keys
    as ``state`` with post-forward values (buffers may have advanced).
    """
    from bigdl_tpu.utils.rng import rng_context

    original = state_dict(module)
    unknown = set(state) - set(original)
    if unknown:
        raise KeyError(
            f"functional_call: state contains keys not present in "
            f"{type(module).__name__}: {sorted(unknown)}")
    modes = None
    if training is not None:
        modes = [m.__dict__["training"] for m in module.modules()]
        for m in module.modules():
            m.__dict__["training"] = training
    try:
        load_state_dict(module, state, strict=False)
        if rng is not None:
            with rng_context(rng):
                out = module.forward(input)
        else:
            out = module.forward(input)
        full = state_dict(module)
        new_state = {k: full[k] for k in state}
        return out, new_state
    finally:
        load_state_dict(module, original, strict=False)
        _clear_outputs(module)
        if modes is not None:
            for m, t in zip(module.modules(), modes):
                m.__dict__["training"] = t


# --------------------------------------------------------------------------
# Containers
# --------------------------------------------------------------------------

class Container(Module):
    """Base of composite modules (``nn/Container.scala:40``)."""

    def __init__(self, *modules: Module):
        super().__init__()
        for m in modules:
            self.add(m)

    def add(self, module: Module) -> "Container":
        idx = len(self.__dict__["_modules"])
        self.__dict__["_modules"][str(idx)] = module
        return self

    @property
    def layers(self) -> List[Module]:
        return list(self.__dict__["_modules"].values())

    def __len__(self):
        return len(self.__dict__["_modules"])

    def get(self, i: int) -> Module:
        return self.layers[i]


class Sequential(Container):
    """Chain container (``nn/Sequential.scala:30``)."""

    def update_output(self, input):
        out = input
        for m in self.layers:
            out = m.forward(out)
        return out


class Identity(Module):
    """Pass-through (``nn/Identity.scala``)."""


class Echo(Module):
    """Identity that prints its input's shape when eager (``nn/Echo.scala``)."""

    def update_output(self, input):
        try:
            print(f"Echo[{self.get_name()}]: shape={jnp.shape(input)}")
        except Exception:  # noqa: BLE001
            pass
        return input
