"""Graph-level TPU optimization passes over module trees.

The reference optimizes its execution graph at the Scala level (e.g. the
``ir`` package's conversions and fusions feeding MKL-DNN,
``utils/intermediate/IRGraph.scala``); here the hot structural rewrite is
**sibling-convolution merging**: a ``Concat`` whose branches all start
with a 1x1/kxk convolution *of the same signature over the same input*
(the Inception pattern, ``models/inception/Inception_v1.scala``) computes
several small GEMMs whose output-channel counts (16..128) each pad up to
the MXU's 128-lane tile.  Merging them into ONE convolution with the
concatenated output channels runs one well-tiled GEMM instead, and the
branch remainders read channel slices (``Narrow``) that XLA fuses into
their consumers.  The rewrite preserves the math and the parameter
values exactly (only the packing changes); outputs agree with the
unfused graph up to GEMM-regrouping float reassociation.

Apply via ``optimize_for_tpu(model)`` BEFORE building a train step or
checkpointing: the merged model's state_dict packs the sibling weights
into one tensor, so it is not parameter-compatible with the unfused
layout.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp

from bigdl_tpu.nn.layers.container_ext import Concat
from bigdl_tpu.nn.layers.conv import SpatialConvolution
from bigdl_tpu.nn.layers.normalization import SpatialBatchNormalization
from bigdl_tpu.nn.layers.shape import Narrow
from bigdl_tpu.nn.module import Container, Module, Sequential

__all__ = ["optimize_for_tpu", "merge_sibling_convs", "fold_batchnorm"]


def optimize_for_tpu(model: Module) -> Module:
    """Run the training-safe graph passes in place; returns the model for
    chaining.  (``fold_batchnorm`` is inference-only and therefore NOT
    included here.)"""
    return merge_sibling_convs(model)


def merge_sibling_convs(model: Module) -> Module:
    """Merge runs of adjacent ``Concat`` branches that start with
    same-signature convolutions (see module docstring).  In-place."""
    _walk(model)
    return model


def _walk(m: Module) -> None:
    if isinstance(m, Container):
        for child in m.layers:
            _walk(child)
        if isinstance(m, Concat):
            _merge_concat(m)


def _leading_conv(branch: Module) -> Optional[Tuple[SpatialConvolution, List[Module]]]:
    """(conv, rest-of-branch) when the branch starts with a plain conv."""
    if type(branch) in (SpatialConvolution,):
        conv, rest = branch, []
    elif type(branch) is Sequential and len(branch) > 0 \
            and type(branch.get(0)) is SpatialConvolution:
        conv, rest = branch.get(0), branch.layers[1:]
    else:
        return None
    # merging repacks weights: bail out when per-layer training metadata
    # (freeze/scale/regularizers) would have to be split back apart
    d = conv.__dict__
    if conv.n_group != 1 or d.get("_frozen") \
            or d.get("scale_w", 1.0) != 1.0 or d.get("scale_b", 1.0) != 1.0 \
            or d.get("w_regularizer") is not None \
            or d.get("b_regularizer") is not None:
        return None
    return conv, rest


def _signature(conv: SpatialConvolution):
    return (conv.n_input_plane, conv.kernel_w, conv.kernel_h,
            conv.stride_w, conv.stride_h, conv.pad_w, conv.pad_h,
            conv.with_bias, conv.format, conv.propagate_back)


def _merge_run(dim: int, entries) -> Module:
    """One branch replacing a run of (conv, rest) branches: the merged
    conv followed by an inner Concat of Narrow-sliced remainders."""
    convs = [c for c, _ in entries]
    c0 = convs[0]
    w = jnp.concatenate([c.weight for c in convs], axis=0)
    b = jnp.concatenate([c.bias for c in convs], axis=0) \
        if c0.with_bias else None
    total = sum(c.n_output_plane for c in convs)
    merged = SpatialConvolution(
        c0.n_input_plane, total, c0.kernel_w, c0.kernel_h,
        c0.stride_w, c0.stride_h, c0.pad_w, c0.pad_h,
        propagate_back=c0.propagate_back, init_weight=w, init_bias=b,
        with_bias=c0.with_bias, format=c0.format)
    merged.set_name("+".join(c.get_name() for c in convs))
    inner = Concat(dim)
    offset = 0
    for conv, rest in entries:
        inner.add(Sequential(Narrow(dim, offset, conv.n_output_plane), *rest))
        offset += conv.n_output_plane
    return Sequential(merged, inner)


def _merge_concat(m: Concat) -> None:
    c_axis = {"NCHW": 1, "NHWC": 3}
    parsed = []
    for branch in m.layers:
        entry = _leading_conv(branch)
        if entry is not None and c_axis.get(entry[0].format) != m.dim:
            entry = None  # concat must run along the conv channel axis
        parsed.append((branch, entry))

    out: List[Module] = []
    run: List[Tuple[Module, Tuple[SpatialConvolution, List[Module]]]] = []

    def flush():
        nonlocal run
        if len(run) >= 2:
            out.append(_merge_run(m.dim, [e for _, e in run]))
        else:
            out.extend(branch for branch, _ in run)
        run = []

    for branch, entry in parsed:
        if entry is None:
            flush()
            out.append(branch)
        elif run and _signature(entry[0]) != _signature(run[0][1][0]):
            flush()
            run.append((branch, entry))
        else:
            run.append((branch, entry))
    flush()

    if len(out) != len(m.layers):
        m.__dict__["_modules"].clear()
        for branch in out:
            m.add(branch)


def fold_batchnorm(model: Module) -> Module:
    """INFERENCE-ONLY pass: fold each ``SpatialBatchNormalization`` that
    directly follows a ``SpatialConvolution`` inside a ``Sequential`` into
    the conv's weights (the standard conv-BN algebra over the RUNNING
    statistics):

        w' = w * gamma / sqrt(var + eps)      (per output channel)
        b' = (b - mean) * gamma / sqrt(var + eps) + beta

    After folding, the BN layer disappears — one conv per block at serving
    time (the inference-graph fusion the reference performs when lowering
    to its MKL-DNN ``ir`` graph, ``utils/intermediate/IRGraph.scala``).
    Training a folded model would be WRONG (no batch statistics), so this
    is never part of :func:`optimize_for_tpu`; call it on a model about to
    be served/exported.  In place."""

    def walk(m: Module) -> None:
        if not isinstance(m, Container):
            return
        for child in m.layers:
            walk(child)
        if type(m) is not Sequential:
            return
        mods = m.__dict__["_modules"]
        layers = list(mods.values())
        out: List[Module] = []
        i = 0
        while i < len(layers):
            cur, nxt = layers[i], layers[i + 1] if i + 1 < len(layers) else None
            if type(cur) is SpatialConvolution \
                    and type(nxt) is SpatialBatchNormalization \
                    and nxt.affine and cur.n_output_plane == nxt.n_output \
                    and cur.format == nxt.format:
                scale = nxt.weight / jnp.sqrt(nxt.running_var + nxt.eps)
                w = cur.weight * scale.reshape(-1, 1, 1, 1)
                b0 = cur.bias if cur.with_bias \
                    else jnp.zeros((cur.n_output_plane,), jnp.float32)
                b = (b0 - nxt.running_mean) * scale + nxt.bias
                if cur.with_bias:
                    cur.weight, cur.bias = w, b
                    folded = cur
                else:
                    # the usual conv(bias=False)+BN pairing: the fold
                    # materializes the bias, so rebuild the conv with one
                    folded = SpatialConvolution(
                        cur.n_input_plane, cur.n_output_plane,
                        cur.kernel_w, cur.kernel_h, cur.stride_w,
                        cur.stride_h, cur.pad_w, cur.pad_h,
                        n_group=cur.n_group,
                        propagate_back=cur.propagate_back,
                        init_weight=w, init_bias=b, format=cur.format)
                    folded.set_name(cur.get_name())
                out.append(folded)
                i += 2
            else:
                out.append(cur)
                i += 1
        if len(out) != len(layers):
            mods.clear()
            for l in out:
                m.add(l)

    walk(model)
    return model
