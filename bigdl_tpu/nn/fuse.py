"""Graph-level TPU optimization passes over module trees.

The reference optimizes its execution graph at the Scala level (e.g. the
``ir`` package's conversions and fusions feeding MKL-DNN,
``utils/intermediate/IRGraph.scala``); here the hot structural rewrite is
**sibling-convolution merging**: a ``Concat`` whose branches all start
with a 1x1/kxk convolution *of the same signature over the same input*
(the Inception pattern, ``models/inception/Inception_v1.scala``) computes
several small GEMMs whose output-channel counts (16..128) each pad up to
the MXU's 128-lane tile.  Merging them into ONE convolution with the
concatenated output channels runs one well-tiled GEMM instead, and the
branch remainders read channel slices (``Narrow``) that XLA fuses into
their consumers.  The rewrite preserves the math and the parameter
values exactly (only the packing changes); outputs agree with the
unfused graph up to GEMM-regrouping float reassociation.

Apply via ``optimize_for_tpu(model)`` BEFORE building a train step or
checkpointing: the merged model's state_dict packs the sibling weights
into one tensor, so it is not parameter-compatible with the unfused
layout.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.nn.graph import Graph, Node
from bigdl_tpu.nn.layers.container_ext import Concat
from bigdl_tpu.nn.layers.conv import SpatialConvolution
from bigdl_tpu.nn.layers.normalization import SpatialBatchNormalization
from bigdl_tpu.nn.layers.shape import Narrow
from bigdl_tpu.nn.module import Container, Module, Sequential

__all__ = ["optimize_for_tpu", "merge_sibling_convs", "fold_batchnorm",
           "space_to_depth_input", "ShapeInvariantError"]


class ShapeInvariantError(RuntimeError):
    """A fusion pass changed the model's output shapes/dtypes — the
    rewrite is wrong, refuse to hand back the broken model."""


def optimize_for_tpu(model: Module, example_input=None,
                     check: bool = True) -> Module:
    """Run the training-safe graph passes; ALWAYS rebind the result
    (``model = optimize_for_tpu(model)``): most rewrites mutate in place,
    but when the model root itself is an eligible input conv,
    ``space_to_depth_input`` must return a new root.  (``fold_batchnorm``
    is inference-only and therefore NOT included here.)

    By default every run proves the SHAPE INVARIANT: the model's output
    ``ShapeDtypeStruct``s (via ``jax.eval_shape`` — abstract, no compile)
    are captured before the passes and re-checked after; a mismatch
    raises :class:`ShapeInvariantError` instead of handing back a
    silently-broken model.  ``example_input`` pins the input spec; when
    omitted it is inferred from the model's first layer
    (``analysis.infer_input_spec``), and models whose input cannot be
    inferred skip the check.  ``check=False`` disables it."""
    in_spec = before = None
    if check:
        from bigdl_tpu.analysis.shape_pass import (format_spec,
                                                   infer_input_output,
                                                   output_spec, specs_equal)

        if example_input is not None:
            in_spec = example_input
            before = output_spec(model, in_spec)
            if before is None:
                # the caller PINNED this spec — a model that cannot even
                # evaluate for it is already broken; skipping silently
                # would break the "every run proves the invariant" contract
                raise ShapeInvariantError(
                    f"model fails abstract evaluation for the given "
                    f"example_input {format_spec(in_spec)} — nothing to "
                    f"prove; run analysis.check_shapes for the per-layer "
                    f"diagnosis")
        else:
            found = infer_input_output(model)  # one walk proves the fit
            if found is not None:
                in_spec, before = found
    model = merge_sibling_convs(model)  # may REBUILD a Graph root
    model = space_to_depth_input(model)
    if before is not None:
        after = output_spec(model, in_spec)
        if not specs_equal(before, after):
            raise ShapeInvariantError(
                f"optimize_for_tpu changed the model's output spec: "
                f"{format_spec(before)} -> "
                f"{'<eval failed>' if after is None else format_spec(after)}"
                f" (input {format_spec(in_spec)})")
    return model


def merge_sibling_convs(model: Module) -> Module:
    """Merge same-signature sibling convolutions over a shared input —
    both forms of the Inception pattern: adjacent ``Concat`` branches
    (container models) and same-predecessor fan-out nodes (``Graph``
    DAGs, i.e. Caffe/TF-imported models).  Mostly in place, but a Graph
    root is rebuilt — ALWAYS rebind the result."""
    return _walk(model)


def _walk(m: Module) -> Module:
    if isinstance(m, Graph):
        return _merge_graph_siblings(m)
    if isinstance(m, Container):
        mods = m.__dict__["_modules"]
        for k in list(mods):
            mods[k] = _walk(mods[k])
        if isinstance(m, Concat):
            _merge_concat(m)
    return m


def _leading_conv(branch: Module) -> Optional[Tuple[SpatialConvolution, List[Module]]]:
    """(conv, rest-of-branch) when the branch starts with a plain conv."""
    if type(branch) in (SpatialConvolution,):
        conv, rest = branch, []
    elif type(branch) is Sequential and len(branch) > 0 \
            and type(branch.get(0)) is SpatialConvolution:
        conv, rest = branch.get(0), branch.layers[1:]
    else:
        return None
    # merging repacks weights: bail out when per-layer training metadata
    # (freeze/scale/regularizers) would have to be split back apart
    d = conv.__dict__
    if conv.n_group != 1 or d.get("_frozen") \
            or d.get("scale_w", 1.0) != 1.0 or d.get("scale_b", 1.0) != 1.0 \
            or d.get("w_regularizer") is not None \
            or d.get("b_regularizer") is not None:
        return None
    return conv, rest


def _signature(conv: SpatialConvolution):
    return (conv.n_input_plane, conv.kernel_w, conv.kernel_h,
            conv.stride_w, conv.stride_h, conv.pad_w, conv.pad_h,
            conv.with_bias, conv.format, conv.propagate_back,
            str(conv.weight.dtype))


def _merged_conv_of(convs) -> SpatialConvolution:
    """One conv whose output channels are the concatenation of the
    siblings' (identical signatures assumed)."""
    c0 = convs[0]
    w = jnp.concatenate([c.weight for c in convs], axis=0)
    b = jnp.concatenate([c.bias for c in convs], axis=0) \
        if c0.with_bias else None
    merged = SpatialConvolution(
        c0.n_input_plane, sum(c.n_output_plane for c in convs),
        c0.kernel_w, c0.kernel_h, c0.stride_w, c0.stride_h,
        c0.pad_w, c0.pad_h, propagate_back=c0.propagate_back,
        init_weight=w, init_bias=b, with_bias=c0.with_bias,
        format=c0.format)
    merged.set_name("+".join(c.get_name() for c in convs))
    return merged


def _element_use_counts(g: Graph) -> dict:
    """id(element) -> number of nodes wrapping it (weight sharing)."""
    uses: dict = {}
    for n in g._sorted:
        uses[id(n.element)] = uses.get(id(n.element), 0) + 1
    return uses


def _rebuild_graph(g: Graph) -> Graph:
    """Fresh Graph over the (surgically modified) node structure,
    preserving graph-level state: stop-gradient set, name, train flag."""
    rebuilt = Graph(g.input_nodes, g.output_nodes)
    rebuilt._stop_gradient = set(g._stop_gradient)
    if g.__dict__.get("_name"):
        rebuilt.set_name(g.__dict__["_name"])
    if not g.training:
        rebuilt.evaluate()
    return rebuilt


def _merge_graph_siblings(g: Graph) -> Graph:
    """Graph form of the sibling merge: nodes wrapping same-signature
    convs that consume the SAME predecessor output fan out into one
    merged conv node, and each original node's element becomes a
    ``Narrow`` channel slice — downstream edges stay untouched, so the
    rewrite composes with arbitrary imported DAGs (Caffe GoogLeNet, TF
    GraphDefs)."""
    # negative axes so slices work for batched (NCHW) AND the conv's
    # supported unbatched (CHW) inputs alike
    c_axis = {"NCHW": -3, "NHWC": -1}
    changed = False
    # recurse into node elements first (a node may wrap a Sequential
    # containing Concats — or a whole inner Graph that gets REBUILT).
    # Each DISTINCT element is walked once: a shared (Siamese) inner
    # graph must map to ONE rebuilt object, not a rebuilt copy for the
    # first node and a stale mutated original for the rest.
    walked: dict = {}
    for n in g._sorted:
        key = id(n.element)
        if key not in walked:
            walked[key] = _walk(n.element)
        if walked[key] is not n.element:
            n.element = walked[key]
            changed = True  # _modules must re-register the new object

    # a module object wrapped by MORE than one node is weight-shared
    # (Siamese); repacking any of its uses would fork the tied weights
    uses = _element_use_counts(g)

    groups: dict = {}
    for n in g._sorted:
        el = n.element
        if type(el) is not SpatialConvolution or len(n.prev) != 1:
            continue
        if uses[id(el)] > 1:
            continue
        name = el.__dict__["_name"]
        if name and name in g._stop_gradient:
            continue
        if _leading_conv(el) is None:
            continue
        p, idx = n.prev[0]
        groups.setdefault((p.id, idx, _signature(el)), (p, idx, []))[2] \
            .append(n)

    for (pid, _i, _sig), (pnode, idx, nodes) in groups.items():
        if len(nodes) < 2:
            continue
        convs = [n.element for n in nodes]
        merged = _merged_conv_of(convs)
        mnode = Node(merged)
        mnode.add_prev(pnode, idx)
        dim = c_axis[convs[0].format]
        offset = 0
        for n in nodes:
            pnode.next.remove(n)
            n.prev = []
            narrow = Narrow(dim, offset, n.element.n_output_plane)
            narrow.set_name((n.element.get_name() or "conv") + "/slice")
            offset += n.element.n_output_plane
            n.element = narrow
            n.add_prev(mnode)
        changed = True

    changed = _merge_tf_conv_siblings(g, uses) or changed

    if not changed:
        return g
    return _rebuild_graph(g)


def _merge_tf_conv_siblings(g: Graph, uses: dict) -> bool:
    """TF-op form (``ops.Conv2D`` takes its HWIO weight as a SECOND graph
    input from a Const/Variable node): same-attr sibling convs over one
    data input merge by concatenating their weight nodes on the O axis.
    BiasAdd consumers are untouched — they read the Narrow slices.
    Orphaned weight nodes fall out of the rebuilt topo order."""
    from bigdl_tpu.nn import ops as nnops
    from bigdl_tpu.nn import tf as nntf

    def weight_of(wnode) -> Optional[jnp.ndarray]:
        el = wnode.element
        name = el.__dict__.get("_name")
        if name and name in g._stop_gradient:
            return None  # frozen-by-name weight must not be repacked
        if type(el) is nntf.Const:
            return el.value
        d = el.__dict__
        if type(el) is nntf.Variable and not d.get("_frozen") \
                and d.get("scale_w", 1.0) == 1.0 \
                and d.get("w_regularizer") is None:
            return el.weight
        return None

    groups: dict = {}
    for n in g._sorted:
        el = n.element
        if type(el) is not nnops.Conv2D or len(n.prev) != 2:
            continue
        if uses[id(el)] > 1:
            continue
        name = el.__dict__["_name"]
        if name and name in g._stop_gradient:
            continue
        (dnode, didx), (wnode, widx) = n.prev
        if widx is not None or len(wnode.next) != 1 \
                or uses.get(id(wnode.element), 1) > 1:
            continue
        w = weight_of(wnode)
        if w is None or w.ndim != 4:
            continue
        sig = (el.strides, el.padding, el.format, el.dilation,
               tuple(w.shape[:3]), str(w.dtype),
               type(wnode.element).__name__)
        groups.setdefault((dnode.id, didx, sig), (dnode, didx, []))[2] \
            .append((n, wnode, w))

    changed = False
    for (_pid, _i, sig), (dnode, didx, members) in groups.items():
        if len(members) < 2:
            continue
        w_merged = jnp.concatenate([w for _, _, w in members], axis=3)
        wcls = type(members[0][1].element)
        merged_w = wcls(w_merged)
        merged_w.set_name("+".join(
            wn.element.get_name() or "w" for _, wn, _ in members))
        wnode_m = Node(merged_w)
        conv0 = members[0][0].element
        merged_conv = nnops.Conv2D(
            conv0.strides[0], conv0.strides[1], conv0.padding,
            conv0.format, conv0.dilation[0], conv0.dilation[1])
        merged_conv.set_name("+".join(
            n.element.get_name() or "conv" for n, _, _ in members))
        mnode = Node(merged_conv)
        mnode.add_prev(dnode, didx)
        mnode.add_prev(wnode_m)
        dim = -1 if conv0.format == "NHWC" else -3
        offset = 0
        for n, wnode, w in members:
            dnode.next.remove(n)
            wnode.next.remove(n)
            n.prev = []
            cout = int(w.shape[3])
            narrow = Narrow(dim, offset, cout)
            narrow.set_name((n.element.get_name() or "conv") + "/slice")
            offset += cout
            n.element = narrow
            n.add_prev(mnode)
        changed = True
    return changed


def _merge_run(dim: int, entries) -> Module:
    """One branch replacing a run of (conv, rest) branches: the merged
    conv followed by an inner Concat of Narrow-sliced remainders."""
    convs = [c for c, _ in entries]
    merged = _merged_conv_of(convs)
    inner = Concat(dim)
    offset = 0
    for conv, rest in entries:
        inner.add(Sequential(Narrow(dim, offset, conv.n_output_plane), *rest))
        offset += conv.n_output_plane
    return Sequential(merged, inner)


def _merge_concat(m: Concat) -> None:
    c_axis = {"NCHW": 1, "NHWC": 3}
    parsed = []
    for branch in m.layers:
        entry = _leading_conv(branch)
        if entry is not None and c_axis.get(entry[0].format) != m.dim:
            entry = None  # concat must run along the conv channel axis
        parsed.append((branch, entry))

    out: List[Module] = []
    run: List[Tuple[Module, Tuple[SpatialConvolution, List[Module]]]] = []

    def flush():
        nonlocal run
        if len(run) >= 2:
            out.append(_merge_run(m.dim, [e for _, e in run]))
        else:
            out.extend(branch for branch, _ in run)
        run = []

    for branch, entry in parsed:
        if entry is None:
            flush()
            out.append(branch)
        elif run and _signature(entry[0]) != _signature(run[0][1][0]):
            flush()
            run.append((branch, entry))
        else:
            run.append((branch, entry))
    flush()

    if len(out) != len(m.layers):
        m.__dict__["_modules"].clear()
        for branch in out:
            m.add(branch)


class _SpaceToDepthPad(Module):
    """Fold a strided conv's zero padding into an explicit pad, then
    rearrange ``stride x stride`` spatial blocks into channels (NCHW).
    Produced only by :func:`space_to_depth_input`, which pairs it with a
    repacked stride-1 convolution.

    Derivation: with ``xp = pad(x, p)`` the original conv reads
    ``out[i] = sum_dy w[dy] * xp[s*i + dy]``.  Writing ``dy = s*j + a``
    (``a = dy mod s``) and block-decomposing ``xp[s*u + a] = xp'[a][u]``
    gives ``out[i] = sum_{a,j} w[s*j + a] * xp'[a][i + j]`` — a stride-1
    conv over ``C*s*s`` channels with kernel ``ceil(k/s)``.  The MLPerf
    ResNet TPU submissions use the same transform for conv0 (public
    technique; no code consulted)."""

    def __init__(self, s_h: int, s_w: int, pad_h: int, pad_w: int,
                 k_h: int, k_w: int):
        super().__init__()
        self.s_h, self.s_w = s_h, s_w
        self.pad_h, self.pad_w = pad_h, pad_w
        self.k_h, self.k_w = k_h, k_w  # the ORIGINAL kernel extents

    @staticmethod
    def _extents(size: int, s: int, p: int, k: int) -> Tuple[int, int, int]:
        """(U, lo, hi): block count and lax.pad config (hi may be a crop)
        such that U*s == lo + size + hi and the stride-1 conv over U
        blocks emits exactly the original output count."""
        out = (size + 2 * p - k) // s + 1
        kp = -(-k // s)
        u = out - 1 + kp
        return u, p, u * s - size - p

    def update_output(self, input):
        squeeze = input.ndim == 3  # SpatialConvolution's unbatched path
        x = input[None] if squeeze else input
        n, c, h, w = x.shape
        u_h, lo_h, hi_h = self._extents(h, self.s_h, self.pad_h, self.k_h)
        u_w, lo_w, hi_w = self._extents(w, self.s_w, self.pad_w, self.k_w)
        zero = jnp.zeros((), x.dtype)
        xp = jax.lax.pad(x, zero, ((0, 0, 0), (0, 0, 0),
                                   (lo_h, hi_h, 0), (lo_w, hi_w, 0)))
        xp = xp.reshape(n, c, u_h, self.s_h, u_w, self.s_w)
        xp = xp.transpose(0, 1, 3, 5, 2, 4)  # (c, a_h, a_w) channel order
        out = xp.reshape(n, c * self.s_h * self.s_w, u_h, u_w)
        return out[0] if squeeze else out


class _MaskedStride1Conv(SpatialConvolution):
    """Stride-1/pad-0 NCHW conv whose weight is multiplied by a constant
    0/1 buffer before use — keeps the dead (never-present-in-the-original)
    kernel slots of a space-to-depth repack at zero through training."""

    def __init__(self, n_in: int, n_out: int, kw: int, kh: int, **kwargs):
        super().__init__(n_in, n_out, kw, kh, 1, 1, 0, 0, **kwargs)

    def update_output(self, input):
        squeeze = input.ndim == 3
        x = input[None] if squeeze else input
        w = self.weight * self.weight_mask
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCHW", "OIHW", "NCHW"))
        y = lax.conv_general_dilated(
            x, w.astype(x.dtype), (1, 1), ((0, 0), (0, 0)),
            dimension_numbers=dn)
        if self.with_bias:
            y = y + self.bias.reshape(1, -1, 1, 1).astype(y.dtype)
        return y[0] if squeeze else y


def space_to_depth_input(model: Module) -> Module:
    """Rewrite the model's INPUT convolution (stride > 1, few input
    channels — the ImageNet conv1 pattern) as space-to-depth + a stride-1
    conv with repacked weights.  A 7x7/s2 conv over 3 channels becomes a
    4x4/s1 conv over 12 channels: the contraction depth rises from
    3 (padded to 8 MXU sublanes) to 12, which matters most for the
    backprop-filter GEMM (profiled at 18 TFLOP/s on TPU v5e in the
    original form).  The repacked kernel has dead slots (window taps the
    original kernel never had, e.g. row 7 of the 8-row covered window);
    a constant mask keeps them at zero through training, so the rewrite
    is exact — forward, gradients, and the whole SGD trajectory — up to
    float reassociation.  In place where possible; call as
    ``model = space_to_depth_input(model)``."""
    if isinstance(model, Graph):
        return _s2d_graph_inputs(model)

    if _s2d_eligible(model):
        return _s2d_repack(model)
    m = model
    while type(m) is Sequential and len(m) > 0:
        first = m.get(0)
        if _s2d_eligible(first):
            m.__dict__["_modules"]["0"] = _s2d_repack(first)
            return model
        m = first
    return model


def _s2d_repack(conv: SpatialConvolution) -> Sequential:
    s_h, s_w = conv.stride_h, conv.stride_w
    k_h, k_w = conv.kernel_h, conv.kernel_w
    kp_h, kp_w = -(-k_h // s_h), -(-k_w // s_w)
    c_in, c_out = conv.n_input_plane, conv.n_output_plane
    w = np.asarray(conv.weight)
    wp = np.zeros((c_out, c_in * s_h * s_w, kp_h, kp_w), w.dtype)
    mask = np.zeros((1, c_in * s_h * s_w, kp_h, kp_w), np.float32)
    for a_h in range(s_h):
        for a_w in range(s_w):
            for j_h in range(kp_h):
                dy = s_h * j_h + a_h
                if dy >= k_h:
                    continue
                for j_w in range(kp_w):
                    dx = s_w * j_w + a_w
                    if dx >= k_w:
                        continue
                    ch = (np.arange(c_in) * s_h + a_h) * s_w + a_w
                    wp[:, ch, j_h, j_w] = w[:, :, dy, dx]
                    mask[:, ch, j_h, j_w] = 1.0
    new_conv = _MaskedStride1Conv(
        c_in * s_h * s_w, c_out, kp_w, kp_h,
        propagate_back=conv.propagate_back,
        init_weight=jnp.asarray(wp),
        init_bias=conv.bias if conv.with_bias else None,
        with_bias=conv.with_bias)
    new_conv.register_buffer("weight_mask", jnp.asarray(mask))
    new_conv.set_name(conv.get_name() + "/s2d")
    return Sequential(
        _SpaceToDepthPad(s_h, s_w, conv.pad_h, conv.pad_w, k_h, k_w),
        new_conv)

def _s2d_eligible(m: Module) -> bool:
    return (type(m) is SpatialConvolution and m.format == "NCHW"
            and m.n_group == 1 and m.n_input_plane <= 4
            and (m.stride_h > 1 or m.stride_w > 1)
            and m.pad_h >= 0 and m.pad_w >= 0  # -1 = SAME: different math
            and _leading_conv(m) is not None)


def _s2d_graph_inputs(g: Graph) -> Graph:
    """Graph form: repack eligible conv nodes fed DIRECTLY by an input
    node (the imported-model conv1 pattern).  The node's element becomes
    the pad+conv Sequential; edges stay untouched, but a NEW Graph root
    is returned when anything changed so the module table re-registers
    the swapped elements — rebind the result."""
    input_ids = {n.id for n in g.input_nodes}
    changed = False
    uses = _element_use_counts(g)
    for n in g._sorted:
        el = n.element
        if not _s2d_eligible(el) or uses[id(el)] > 1:
            continue
        if len(n.prev) != 1 or n.prev[0][0].id not in input_ids:
            continue
        name = el.__dict__["_name"]
        if name and name in g._stop_gradient:
            continue
        n.element = _s2d_repack(el)
        changed = True
    if not changed:
        return g
    return _rebuild_graph(g)


def fold_batchnorm(model: Module) -> Module:
    """INFERENCE-ONLY pass: fold each ``SpatialBatchNormalization`` that
    directly follows a ``SpatialConvolution`` inside a ``Sequential`` into
    the conv's weights (the standard conv-BN algebra over the RUNNING
    statistics):

        w' = w * gamma / sqrt(var + eps)      (per output channel)
        b' = (b - mean) * gamma / sqrt(var + eps) + beta

    After folding, the BN layer disappears — one conv per block at serving
    time (the inference-graph fusion the reference performs when lowering
    to its MKL-DNN ``ir`` graph, ``utils/intermediate/IRGraph.scala``).
    Training a folded model would be WRONG (no batch statistics), so this
    is never part of :func:`optimize_for_tpu`; call it on a model about to
    be served/exported.  In place."""

    def walk(m: Module) -> None:
        if not isinstance(m, Container):
            return
        for child in m.layers:
            walk(child)
        if type(m) is not Sequential:
            return
        mods = m.__dict__["_modules"]
        layers = list(mods.values())
        out: List[Module] = []
        i = 0
        while i < len(layers):
            cur, nxt = layers[i], layers[i + 1] if i + 1 < len(layers) else None
            if type(cur) is SpatialConvolution \
                    and type(nxt) is SpatialBatchNormalization \
                    and nxt.affine and cur.n_output_plane == nxt.n_output \
                    and cur.format == nxt.format:
                scale = nxt.weight / jnp.sqrt(nxt.running_var + nxt.eps)
                w = cur.weight * scale.reshape(-1, 1, 1, 1)
                b0 = cur.bias if cur.with_bias \
                    else jnp.zeros((cur.n_output_plane,), jnp.float32)
                b = (b0 - nxt.running_mean) * scale + nxt.bias
                if cur.with_bias:
                    cur.weight, cur.bias = w, b
                    folded = cur
                else:
                    # the usual conv(bias=False)+BN pairing: the fold
                    # materializes the bias, so rebuild the conv with one
                    folded = SpatialConvolution(
                        cur.n_input_plane, cur.n_output_plane,
                        cur.kernel_w, cur.kernel_h, cur.stride_w,
                        cur.stride_h, cur.pad_w, cur.pad_h,
                        n_group=cur.n_group,
                        propagate_back=cur.propagate_back,
                        init_weight=w, init_bias=b, format=cur.format)
                    folded.set_name(cur.get_name())
                out.append(folded)
                i += 2
            else:
                out.append(cur)
                i += 1
        if len(out) != len(layers):
            mods.clear()
            for l in out:
                m.add(l)

    walk(model)
    return model
