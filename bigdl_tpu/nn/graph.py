"""Functional/DAG model API (``nn/Graph.scala:72``, ``utils/DirectedGraph.scala``).

Users build graphs exactly like the reference's functional API::

    inp = Input()
    fc1 = Linear(10, 20).inputs(inp)
    act = ReLU().inputs(fc1)
    out = Linear(20, 2).inputs(act)
    model = Graph(inp, out)

Execution is a host-side topological walk during tracing — under ``jit``
the whole DAG flattens into one XLA computation, so the reference's
ready-queue ``Scheduler`` (``nn/Scheduler.scala``) is unnecessary for
acyclic graphs; its control-flow cycles (while-loops) map to
``jax.lax.while_loop`` via ``bigdl_tpu.ops.control`` instead.

``stop_gradient(names)`` reproduces ``Graph.stopGradient`` with
``jax.lax.stop_gradient`` on the named nodes' outputs.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Container, Identity, Module

__all__ = ["Node", "Input", "Graph", "GraphBuildError", "node_from_module"]


class GraphBuildError(ValueError):
    """Graph construction rejected the DAG.  The message carries the
    analyzer rule id (``graph/cycle`` or ``graph/duplicate-name``) and
    the offending node names, Diagnostic-style, so the error is
    actionable without re-running under the checker."""

    def __init__(self, rule: str, message: str, hint: str = ""):
        text = f"[{rule}] {message}"
        if hint:
            text += f"\n    hint: {hint}"
        super().__init__(text)
        self.rule = rule
        self.hint = hint


class Node:
    """DAG node wrapping a module (``utils/DirectedGraph.scala:175``)."""

    _counter = [0]

    def __init__(self, element: Module):
        self.element = element
        self.prev: List[Tuple["Node", Optional[int]]] = []  # (node, from_index)
        self.next: List["Node"] = []
        Node._counter[0] += 1
        self.id = Node._counter[0]

    def add_prev(self, node: "Node", from_index: Optional[int] = None):
        self.prev.append((node, from_index))
        node.next.append(self)

    # allow chaining: Linear(...)(node) style via module.inputs
    def __repr__(self):
        return f"Node({self.element.get_name()})"


def node_from_module(module: Module, nodes: Sequence) -> Node:
    n = Node(module)
    for item in nodes:
        if isinstance(item, tuple) and not isinstance(item, Node):
            src, idx = item
            n.add_prev(src, idx)
        else:
            n.add_prev(item)
    return n


def Input(name: Optional[str] = None) -> Node:
    """Create an input placeholder node (``nn/Input.scala``)."""
    m = Identity()
    if name:
        m.set_name(name)
    return Node(m)


def _topo_sort(outputs: List[Node]) -> List[Node]:
    order: List[Node] = []
    seen: Dict[int, int] = {}  # id -> 0 visiting, 1 done
    path: List[Node] = []  # current DFS stack, for the cycle message

    def visit(n: Node):
        state = seen.get(n.id)
        if state == 1:
            return
        if state == 0:
            # report the actual cycle: the path suffix from n back to n
            ids = [p.id for p in path]
            start = ids.index(n.id) if n.id in ids else 0
            names = [p.element.get_name() for p in path[start:]] + \
                [n.element.get_name()]
            raise GraphBuildError(
                "graph/cycle",
                "Graph contains a cycle: " + " -> ".join(names),
                hint="XLA graphs are acyclic; use ops.control "
                     "while_modules/cond_modules for loops")
        seen[n.id] = 0
        path.append(n)
        for p, _ in n.prev:
            visit(p)
        path.pop()
        seen[n.id] = 1
        order.append(n)

    for o in outputs:
        visit(o)
    return order


class Graph(Container):
    """DAG container (``nn/Graph.scala``)."""

    def __init__(self, inputs, outputs, variables=None):
        super().__init__()
        self.input_nodes: List[Node] = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
        self.output_nodes: List[Node] = list(outputs) if isinstance(outputs, (list, tuple)) else [outputs]
        self._sorted = _topo_sort(self.output_nodes)
        input_ids = {n.id for n in self.input_nodes}
        missing = [n for n in self._sorted if not n.prev and n.id not in input_ids
                   and not getattr(n.element, "_is_const", False)]
        for n in missing:
            if not _is_without_input(n.element):
                raise ValueError(f"node {n} has no inputs and is not an Input node")
        self._stop_gradient: set = set()
        # two DISTINCT modules sharing an explicit name would make name
        # lookups (__getitem__, stop_gradient) silently pick one — reject
        # with every collision listed (one round-trip, analyzer-style)
        by_name: Dict[str, set] = {}
        for n in self._sorted:
            name = n.element.__dict__["_name"]
            if name:
                by_name.setdefault(name, set()).add(id(n.element))
        dupes = sorted(k for k, ids in by_name.items() if len(ids) > 1)
        if dupes:
            raise GraphBuildError(
                "graph/duplicate-name",
                f"distinct modules share explicit names: {dupes}",
                hint="set_name() each module uniquely (re-using one "
                     "module object for weight sharing is fine)")
        # register the modules so parameters are discoverable; keys must be
        # unique even when names repeat via weight sharing (same element
        # wrapped by several nodes), or params silently vanish
        used = set()
        for i, n in enumerate(self._sorted):
            if n.id in input_ids:
                continue
            key = n.element.__dict__["_name"] or f"node{i}"
            if key in used:
                key = f"{key}__{i}"
            used.add(key)
            self.__dict__["_modules"][key] = n.element

    def stop_gradient(self, names: Sequence[str]) -> "Graph":
        """Block gradients flowing through the named nodes
        (``nn/Graph.scala`` stopGradient)."""
        self._stop_gradient |= set(names)
        return self

    def update_output(self, input):
        values: Dict[int, object] = {}
        inputs = input if isinstance(input, (list, tuple)) else [input]
        if len(inputs) != len(self.input_nodes):
            raise ValueError(
                f"graph expects {len(self.input_nodes)} inputs, got {len(inputs)}")
        for n, v in zip(self.input_nodes, inputs):
            values[n.id] = v
        for n in self._sorted:
            if n.id in values:
                continue
            if not n.prev:
                node_in = None
            else:
                gathered = []
                for p, idx in n.prev:
                    v = values[p.id]
                    if idx is not None:
                        v = v[idx]
                    gathered.append(v)
                node_in = gathered[0] if len(gathered) == 1 else gathered
            out = n.element.forward(node_in)
            name = n.element.__dict__["_name"]
            if name and name in self._stop_gradient:
                out = jax.tree.map(jax.lax.stop_gradient, out)
            values[n.id] = out
        outs = [values[o.id] for o in self.output_nodes]
        return outs[0] if len(outs) == 1 else outs


def _is_without_input(m: Module) -> bool:
    return getattr(m, "_without_input", False)
