"""bigdl_tpu.nn — module/criterion layer (the reference's ``nn`` package,
SURVEY §2.4-§2.5), re-designed for JAX."""

from bigdl_tpu.nn.module import (  # noqa: F401
    Module, Parameter, Container, Sequential, Identity, Echo,
    LayerException, functional_call, state_dict, load_state_dict,
    stamp_scope_names, capture_shapes, summary,
)
from bigdl_tpu.nn import init  # noqa: F401
from bigdl_tpu.nn.criterion import *  # noqa: F401,F403
from bigdl_tpu.nn.layers.activation import *  # noqa: F401,F403
from bigdl_tpu.nn.layers.linear import *  # noqa: F401,F403
from bigdl_tpu.nn.layers.embedding import *  # noqa: F401,F403
from bigdl_tpu.nn.layers.conv import *  # noqa: F401,F403
from bigdl_tpu.nn.layers.pooling import *  # noqa: F401,F403
from bigdl_tpu.nn.layers.normalization import *  # noqa: F401,F403
from bigdl_tpu.nn.layers.shape import *  # noqa: F401,F403
from bigdl_tpu.nn.layers.container_ext import *  # noqa: F401,F403
from bigdl_tpu.nn.layers.rnn import *  # noqa: F401,F403
from bigdl_tpu.nn.layers.attention import *  # noqa: F401,F403
from bigdl_tpu.nn.layers.tree import *  # noqa: F401,F403
from bigdl_tpu.nn.layers.moe import *  # noqa: F401,F403
from bigdl_tpu.nn.layers.scan import *  # noqa: F401,F403
from bigdl_tpu.nn.quantized import *  # noqa: F401,F403
from bigdl_tpu.nn.graph import Graph, Input, Node  # noqa: F401
# TF-style op subpackages stay namespaced (ops.Select vs the Select layer)
from bigdl_tpu.nn import ops  # noqa: F401
from bigdl_tpu.nn import tf  # noqa: F401
