"""Normalization layers (SURVEY §2.5: BatchNormalization,
SpatialBatchNormalization, SpatialCrossMapLRN, SpatialWithinChannelLRN,
SpatialContrastiveNormalization, SpatialDivisiveNormalization,
SpatialSubtractiveNormalization, Normalize) plus Dropout and L1Penalty
(grouped with the reference's "Regularization" rows).

BatchNorm running statistics are module *buffers*: the functional training
step carries them in the state pytree and they advance under jit
(``functional_call`` returns the updated state) — the JAX re-design of the
reference's in-place ``runningMean``/``runningVar`` updates.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.nn.module import Module, Parameter
from bigdl_tpu.utils.rng import next_rng_id, require_rng

__all__ = [
    "BatchNormalization", "SpatialBatchNormalization", "SpatialCrossMapLRN",
    "SpatialWithinChannelLRN", "SpatialContrastiveNormalization",
    "SpatialDivisiveNormalization", "SpatialSubtractiveNormalization",
    "Normalize", "Dropout", "L1Penalty",
]


def _bn_reduce_count(x, axes):
    n = 1
    for a in axes:
        n *= x.shape[a]
    return n


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _bn_train_apply(axes, eps, x, weight, bias):
    """Fused training batch-norm with hand-written VJP.

    TPU profile finding (round 5, ResNet-50): the autodiff of the naive
    ``mean``/``var`` two-pass form lowered to a pile of per-channel
    reduce fusions with bf16 accumulators at ~25% of the train step.
    This version makes the minimum number of passes — ONE fused
    sum/sum-of-squares read forward (f32 accumulation), ONE fused
    dbeta/dgamma read backward, and the standard fused dx formula — and
    keeps every reduction in f32.  Semantics follow
    ``nn/BatchNormalization.scala:269`` (biased var for normalization).

    Returns ``(out, mean, var)``; mean/var are f32 for the caller's
    running-stat buffers (stop-gradient them — their cotangents are
    ignored by the VJP, which is correct only for buffer use)."""
    out, mean, var, _ = _bn_train_fwd_impl(axes, eps, x, weight, bias)
    return out, mean, var


def _bn_train_fwd_impl(axes, eps, x, weight, bias):
    n = _bn_reduce_count(x, axes)
    s1 = jnp.sum(x, axis=axes, dtype=jnp.float32)
    # the f32 convert fuses into the reduce read (no materialized copy);
    # squaring in bf16 would cost ~3 mantissa bits on the stats
    s2 = jnp.sum(lax.square(x.astype(jnp.float32)), axis=axes,
                 dtype=jnp.float32)
    mean = s1 / n
    var = jnp.maximum(s2 / n - lax.square(mean), 0.0)
    inv = lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    for a in range(x.ndim):
        if a not in axes:
            shape[a] = x.shape[a]
    scale = (inv * weight).reshape(shape).astype(x.dtype)
    shift = (bias - mean * inv * weight).reshape(shape).astype(x.dtype)
    out = x * scale + shift
    return out, mean, var, inv


def _bn_train_vjp_fwd(axes, eps, x, weight, bias):
    out, mean, var, inv = _bn_train_fwd_impl(axes, eps, x, weight, bias)
    return (out, mean, var), (x, weight, mean, inv)


def _bn_train_vjp_bwd(axes, eps, res, cotangents):
    gy, _gmean, _gvar = cotangents  # stat cotangents: buffer-only outputs
    x, weight, mean, inv = res
    n = _bn_reduce_count(x, axes)
    shape = [1] * x.ndim
    for a in range(x.ndim):
        if a not in axes:
            shape[a] = x.shape[a]
    gy32 = gy.astype(jnp.float32)
    xhat32 = (x.astype(jnp.float32) - mean.reshape(shape)) \
        * inv.reshape(shape)
    dbeta = jnp.sum(gy32, axis=axes, dtype=jnp.float32)
    dgamma = jnp.sum(gy32 * xhat32, axis=axes, dtype=jnp.float32)
    k = (weight * inv).reshape(shape)
    dx = (k * (gy32 - (dbeta / n).reshape(shape)
               - xhat32 * (dgamma / n).reshape(shape))).astype(x.dtype)
    return dx, dgamma.astype(weight.dtype), dbeta.astype(weight.dtype)


_bn_train_apply.defvjp(_bn_train_vjp_fwd, _bn_train_vjp_bwd)


class BatchNormalization(Module):
    """Batch norm over [batch, feature] (``nn/BatchNormalization.scala``)."""

    _feature_axis = 1

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True, init_weight=None, init_bias=None):
        super().__init__()
        self.n_output, self.eps, self.momentum, self.affine = n_output, eps, momentum, affine
        if affine:
            self.weight = Parameter(init_weight if init_weight is not None
                                    else jnp.ones((n_output,), jnp.float32))
            self.bias = Parameter(init_bias if init_bias is not None
                                  else jnp.zeros((n_output,), jnp.float32))
        self.register_buffer("running_mean", jnp.zeros((n_output,), jnp.float32))
        self.register_buffer("running_var", jnp.ones((n_output,), jnp.float32))

    def reset(self):
        if self.affine:
            self.weight = jnp.ones((self.n_output,), jnp.float32)
            self.bias = jnp.zeros((self.n_output,), jnp.float32)
        self.running_mean = jnp.zeros((self.n_output,), jnp.float32)
        self.running_var = jnp.ones((self.n_output,), jnp.float32)

    def _stat_axes(self, ndim):
        return tuple(a for a in range(ndim) if a != self._feature_axis)

    def update_output(self, input):
        axes = self._stat_axes(input.ndim)
        shape = [1] * input.ndim
        shape[self._feature_axis] = self.n_output
        if self.training:
            w = self.weight if self.affine \
                else jnp.ones((self.n_output,), jnp.float32)
            b = self.bias if self.affine \
                else jnp.zeros((self.n_output,), jnp.float32)
            out, mean, var = _bn_train_apply(axes, self.eps, input, w, b)
            mean = lax.stop_gradient(mean)
            var = lax.stop_gradient(var)
            n = input.size // self.n_output
            unbiased = var * n / max(n - 1, 1)
            self.running_mean = (1 - self.momentum) * self.running_mean + self.momentum * mean
            self.running_var = (1 - self.momentum) * self.running_var + self.momentum * unbiased
            return out
        mean, var = self.running_mean, self.running_var
        inv = lax.rsqrt(var + self.eps).reshape(shape)
        out = (input - mean.reshape(shape)) * inv
        if self.affine:
            out = out * self.weight.reshape(shape) + self.bias.reshape(shape)
        return out.astype(input.dtype)


class SpatialBatchNormalization(BatchNormalization):
    """Batch norm over [batch, C, H, W] / [batch, H, W, C]
    (``nn/SpatialBatchNormalization.scala``)."""

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True, init_weight=None, init_bias=None,
                 format: str = "NCHW"):
        super().__init__(n_output, eps, momentum, affine, init_weight, init_bias)
        self.format = format

    @property
    def _feature_axis(self):  # type: ignore[override]
        return 3 if self.format == "NHWC" else 1


class SpatialCrossMapLRN(Module):
    """AlexNet-style local response normalization across channels
    (``nn/SpatialCrossMapLRN.scala``)."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0, format: str = "NCHW"):
        super().__init__()
        # the reference only defines odd windows (SpatialCrossMapLRN.scala:59);
        # even sizes would also diverge from torch's window anchoring
        assert size % 2 == 1, f"LRN only supports odd size, got {size}"
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.format = format

    def update_output(self, input):
        # fused kernel-library path (ops/lrn_pallas.py): Pallas or the
        # XLA banded-conv reference per BIGDL_KERNELS, exact custom VJP
        # on either leg; NHWC runs the reference natively in its layout
        from bigdl_tpu.ops.lrn_pallas import cross_map_lrn

        squeeze = input.ndim == 3
        x = input[None] if squeeze else input
        if x.ndim == 4:
            out = cross_map_lrn(x, self.size, self.alpha, self.beta,
                                self.k, self.format)
            return out[0] if squeeze else out
        # rank > 4: generic channel-window reference (no fused kernel)
        c_ax = x.ndim - 1 if self.format == "NHWC" else 1
        half = (self.size - 1) // 2
        dims, strides, pads = [1] * x.ndim, [1] * x.ndim, [(0, 0)] * x.ndim
        dims[c_ax] = self.size
        pads[c_ax] = (half, self.size - 1 - half)
        window_sum = lax.reduce_window(x * x, 0.0, lax.add, tuple(dims),
                                       tuple(strides), pads)
        scale = self.k + window_sum * (self.alpha / self.size)
        out = x * jnp.power(scale, -self.beta)
        return out[0] if squeeze else out


def _gaussian_kernel(size: int) -> np.ndarray:
    sigma = 0.25 * size
    xs = np.arange(size) - (size - 1) / 2.0
    k = np.exp(-(xs**2) / (2 * sigma * sigma))
    k2 = np.outer(k, k)
    return (k2 / k2.sum()).astype(np.float32)


class SpatialWithinChannelLRN(Module):
    """LRN within each channel over a spatial window
    (``nn/SpatialWithinChannelLRN.scala``)."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75):
        super().__init__()
        self.size, self.alpha, self.beta = size, alpha, beta

    def update_output(self, input):
        from bigdl_tpu.ops.lrn_pallas import within_channel_lrn

        if input.ndim == 3:
            return within_channel_lrn(input[None], self.size, self.alpha,
                                      self.beta)[0]
        if input.ndim == 4:
            return within_channel_lrn(input, self.size, self.alpha,
                                      self.beta)
        # rank > 4: reference path (no fused kernel / exact VJP)
        half = (self.size - 1) // 2
        dims, strides, pads = [1] * input.ndim, [1] * input.ndim, [(0, 0)] * input.ndim
        for ax in (input.ndim - 2, input.ndim - 1):
            dims[ax] = self.size
            pads[ax] = (half, self.size - 1 - half)
        window_mean = lax.reduce_window(input * input, 0.0, lax.add,
                                        tuple(dims), tuple(strides), pads) / (self.size * self.size)
        scale = 1.0 + window_mean * self.alpha
        return input * jnp.power(scale, -self.beta)


class SpatialSubtractiveNormalization(Module):
    """Subtract a kernel-weighted local mean (``nn/SpatialSubtractiveNormalization.scala``)."""

    def __init__(self, n_input_plane: int = 1, kernel: Optional[np.ndarray] = None):
        super().__init__()
        self.n_input_plane = n_input_plane
        k = np.asarray(kernel, np.float32) if kernel is not None else _gaussian_kernel(9)
        if k.ndim == 1:
            k = np.outer(k, k)
        self.register_buffer("kernel", k / k.sum())

    def update_output(self, input):
        from bigdl_tpu.ops.norm_pallas import subtractive_norm

        squeeze = input.ndim == 3
        x = input[None] if squeeze else input
        # the smoothing kernel is a buffer, never trained: stop_gradient
        # documents what the op's zero kernel-cotangent already enforces
        out = subtractive_norm(x, lax.stop_gradient(self.kernel))
        return out[0] if squeeze else out


class SpatialDivisiveNormalization(Module):
    """Divide by the local standard deviation (``nn/SpatialDivisiveNormalization.scala``)."""

    def __init__(self, n_input_plane: int = 1, kernel: Optional[np.ndarray] = None,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__()
        self.n_input_plane = n_input_plane
        k = np.asarray(kernel, np.float32) if kernel is not None else _gaussian_kernel(9)
        if k.ndim == 1:
            k = np.outer(k, k)
        self.register_buffer("kernel", k / k.sum())
        self.threshold, self.thresval = threshold, thresval

    def update_output(self, input):
        from bigdl_tpu.ops.norm_pallas import divisive_norm

        squeeze = input.ndim == 3
        x = input[None] if squeeze else input
        out = divisive_norm(x, lax.stop_gradient(self.kernel),
                            self.threshold, self.thresval)
        return out[0] if squeeze else out


class SpatialContrastiveNormalization(Module):
    """Subtractive then divisive normalization
    (``nn/SpatialContrastiveNormalization.scala``)."""

    def __init__(self, n_input_plane: int = 1, kernel: Optional[np.ndarray] = None,
                 threshold: float = 1e-4, thresval: float = 1e-4):
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel, threshold, thresval)

    def update_output(self, input):
        return self.div.forward(self.sub.forward(input))


class Normalize(Module):
    """Lp-normalize along the feature dim (``nn/Normalize.scala``)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10):
        super().__init__()
        self.p, self.eps = p, eps

    def update_output(self, input):
        if self.p == float("inf"):
            norm = jnp.max(jnp.abs(input), axis=-1, keepdims=True)
        else:
            norm = jnp.sum(jnp.abs(input) ** self.p, axis=-1, keepdims=True) ** (1.0 / self.p)
        return input / (norm + self.eps)


class Dropout(Module):
    """Inverted dropout (``nn/Dropout.scala``: scales by 1/(1-p) in train
    when ``scale``)."""

    def __init__(self, init_p: float = 0.5, inplace: bool = False, scale: bool = True):
        super().__init__()
        self.p = init_p
        self.scale = scale
        self._rng_id = next_rng_id()

    def set_p(self, p: float):
        self.p = p
        return self

    def update_output(self, input):
        if not self.training or self.p <= 0.0:
            return input
        key = require_rng(self._rng_id)
        keep = jax.random.bernoulli(key, 1.0 - self.p, jnp.shape(input))
        out = jnp.where(keep, input, 0.0)
        if self.scale:
            out = out / (1.0 - self.p)
        return out.astype(input.dtype)


class L1Penalty(Module):
    """Identity forward that adds an L1 sparsity gradient in backward
    (``nn/L1Penalty.scala``) — expressed as a custom VJP."""

    def __init__(self, l1weight: float, size_average: bool = False,
                 provide_output: bool = True):
        super().__init__()
        self.l1weight = l1weight
        self.size_average = size_average

    def update_output(self, input):
        w = self.l1weight
        if self.size_average:
            w = w / input.size

        @jax.custom_vjp
        def penalty(x):
            return x

        def fwd(x):
            return x, jnp.sign(x)

        def bwd(sign, g):
            return (g + w * sign,)

        penalty.defvjp(fwd, bwd)
        return penalty(input)
