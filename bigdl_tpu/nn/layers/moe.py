"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh
axis.

The reference's nearest relative is the local gating container
``MixtureTable`` (``nn/MixtureTable.scala``): gate weights blend expert
outputs on one machine.  This layer is the scaled TPU-first design: a
learned router dispatches tokens to E feed-forward experts whose stacked
parameters shard over the ``expert`` axis — the Mesh-TensorFlow /
GShard-style DENSE dispatch (one-hot capacity-bucketed einsums) that XLA
lowers to all-to-all collectives when tokens are data-sharded and experts
expert-sharded.  No sparse scatter: static shapes keep the MXU fed.

Routing: top-k gating with a per-expert capacity
``C = ceil(top_k * tokens / E * capacity_factor)``; tokens over capacity
are dropped (their combine weight is zero), the standard GShard policy.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Module, Parameter
from bigdl_tpu.nn.init import Xavier
from bigdl_tpu.utils.rng import next_rng_id, require_rng

__all__ = ["MixtureOfExperts", "expert_sharding_rules"]


def expert_sharding_rules(axis: str = "expert"):
    """``extra_sharding_rules`` hook for TrainStep: shards every
    parameter whose path contains ``experts`` on its leading (expert)
    dimension."""
    from jax.sharding import PartitionSpec as P

    def rule(path: str, arr):
        if "expert" in path and getattr(arr, "ndim", 0) >= 1:
            return P(axis, *([None] * (arr.ndim - 1)))
        return None

    return rule


class MixtureOfExperts(Module):
    """Token-routed MoE FFN block.

    Input [tokens, d_model] (or [batch, seq, d_model], flattened for
    routing); output the same shape.  Experts are two-layer FFNs with
    stacked parameters ``experts_w1 [E, D, H]`` etc.; under a mesh with
    an ``expert`` axis, pass ``expert_sharding_rules()`` to TrainStep so
    the stacks shard and dispatch/combine einsums become all-to-alls."""

    def __init__(self, d_model: int, d_hidden: int, n_experts: int,
                 top_k: int = 2, capacity_factor: float = 1.25,
                 noise_std: float = 0.0):
        super().__init__()
        self.d_model, self.d_hidden, self.n_experts = \
            d_model, d_hidden, n_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.noise_std = noise_std
        self._rng_id = next_rng_id()
        init = Xavier
        self.gate_weight = Parameter(
            init.init((d_model, n_experts), fan_in=d_model,
                      fan_out=n_experts))
        self.experts_w1 = Parameter(init.init(
            (n_experts, d_model, d_hidden), fan_in=d_model,
            fan_out=d_hidden))
        self.experts_b1 = Parameter(
            jnp.zeros((n_experts, d_hidden), jnp.float32))
        self.experts_w2 = Parameter(init.init(
            (n_experts, d_hidden, d_model), fan_in=d_hidden,
            fan_out=d_model))
        self.experts_b2 = Parameter(
            jnp.zeros((n_experts, d_model), jnp.float32))

    def capacity(self, n_tokens: int) -> int:
        return max(1, int(math.ceil(
            self.top_k * n_tokens / self.n_experts * self.capacity_factor)))

    def _route(self, x):
        """x [T, D] -> (dispatch [T, E, C] one-hot, combine [T, E, C])."""
        t = x.shape[0]
        e = self.n_experts
        c = self.capacity(t)
        logits = x @ self.gate_weight.astype(x.dtype)
        if self.training and self.noise_std > 0.0:
            # noisy top-k gating: exploration noise on the router logits
            key = require_rng(self._rng_id)
            logits = logits + self.noise_std * jax.random.normal(
                key, logits.shape, logits.dtype)
        gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        # top-k per token, processed one choice at a time so capacity
        # counters accumulate across choices (GShard's sequential greedy)
        _, topk_idx = jax.lax.top_k(gates, self.top_k)
        dispatch = jnp.zeros((t, e, c), jnp.float32)
        combine = jnp.zeros((t, e, c), jnp.float32)
        counts = jnp.zeros((e,), jnp.int32)
        for k in range(self.top_k):
            idx = topk_idx[:, k]                     # [T]
            onehot = jax.nn.one_hot(idx, e)          # [T, E]
            # position of each token within its expert's bucket:
            # running count over the token dim, offset by prior choices
            pos_in_e = (jnp.cumsum(onehot, axis=0) - 1.0) \
                + counts[None, :].astype(jnp.float32)
            pos = jnp.sum(pos_in_e * onehot, axis=1).astype(jnp.int32)
            keep = pos < c                            # capacity drop
            pos_oh = jax.nn.one_hot(jnp.where(keep, pos, 0), c)
            slot = onehot[:, :, None] * pos_oh[:, None, :] \
                * keep[:, None, None]
            dispatch = dispatch + slot
            gate_k = jnp.sum(gates * onehot, axis=1)
            combine = combine + slot * gate_k[:, None, None]
            counts = counts + jnp.sum(
                onehot * keep[:, None], axis=0).astype(jnp.int32)
        return dispatch, combine

    def update_output(self, input):
        x = input
        lead = x.shape[:-1]
        x2 = x.reshape(-1, self.d_model)
        dispatch, combine = self._route(x2)
        xd = x2.astype(jnp.float32)
        # [T,E,C],[T,D] -> [E,C,D]: the all-to-all dispatch einsum
        expert_in = jnp.einsum("tec,td->ecd", dispatch, xd)
        h = jnp.einsum("ecd,edh->ech", expert_in,
                       self.experts_w1.astype(jnp.float32))
        h = jax.nn.relu(h + self.experts_b1[:, None, :])
        out = jnp.einsum("ech,ehd->ecd", h,
                         self.experts_w2.astype(jnp.float32))
        out = out + self.experts_b2[:, None, :]
        y = jnp.einsum("tec,ecd->td", combine, out)
        return y.reshape(lead + (self.d_model,)).astype(x.dtype)

    def aux_load_balancing_loss(self, input) -> jax.Array:
        """GShard/Switch auxiliary loss: E * dot(mean gate fraction,
        mean dispatch fraction) — add to the criterion to keep experts
        balanced."""
        x2 = input.reshape(-1, self.d_model)
        logits = x2 @ self.gate_weight.astype(x2.dtype)
        gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        top1 = jnp.argmax(gates, axis=-1)
        frac_tokens = jnp.mean(jax.nn.one_hot(top1, self.n_experts), axis=0)
        frac_gates = jnp.mean(gates, axis=0)
        return self.n_experts * jnp.sum(frac_tokens * frac_gates)
