"""Convolution layers (SURVEY §2.5 "Convolutions": SpatialConvolution,
SpatialShareConvolution, SpatialFullConvolution, SpatialDilatedConvolution,
SpatialConvolutionMap, TemporalConvolution, VolumetricConvolution,
VolumetricFullConvolution).

The reference lowers convs to hand-written im2col + MKL gemm
(``nn/SpatialConvolution.scala:224+``, ``nn/NNPrimitive.scala:24-592``).
On TPU that entire machinery is one ``lax.conv_general_dilated`` — XLA
tiles it onto the MXU directly; im2col would only waste HBM bandwidth.

Conventions kept from the reference: Torch weight layout
(out, in/group, kH, kW), NCHW or NHWC data formats, ``pad = -1`` meaning
SAME padding, ``n_group`` for grouped convolution.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.nn.init import InitializationMethod, RandomUniform, Zeros
from bigdl_tpu.nn.module import Module, Parameter

__all__ = [
    "SpatialConvolution", "SpatialShareConvolution", "SpatialFullConvolution",
    "SpatialDilatedConvolution", "SpatialConvolutionMap",
    "TemporalConvolution", "VolumetricConvolution", "VolumetricFullConvolution",
]


def _pair_padding(pad: int, k: int, stride: int, size: Optional[int] = None) -> Tuple[int, int]:
    """Explicit (lo, hi) padding; pad == -1 is SAME (TF convention)."""
    if pad == -1:
        if size is None:
            # SAME with unknown size: symmetric k-based padding (stride-1 exact)
            total = k - 1
        else:
            out = -(-size // stride)
            total = max(0, (out - 1) * stride + k - size)
        return total // 2, total - total // 2
    return pad, pad


class _ConvBase(Module):
    def _init_params(self, w_shape, fan_in, fan_out, with_bias, bias_shape,
                     init_weight=None, init_bias=None):
        self.weight_init: InitializationMethod = RandomUniform()
        self.bias_init: InitializationMethod = RandomUniform()
        self._w_shape, self._fan_in, self._fan_out = w_shape, fan_in, fan_out
        self._bias_shape = bias_shape
        if init_weight is not None:
            self.weight = Parameter(init_weight)
        else:
            self.weight = Parameter(self.weight_init.init(w_shape, fan_in=fan_in, fan_out=fan_out))
        if with_bias:
            if init_bias is not None:
                self.bias = Parameter(init_bias)
            else:
                self.bias = Parameter(self.bias_init.init(bias_shape, fan_in=fan_in, fan_out=fan_out))

    def reset(self):
        self.weight = self.weight_init.init(self._w_shape, fan_in=self._fan_in, fan_out=self._fan_out)
        if getattr(self, "with_bias", True) and "bias" in self.__dict__["_params"]:
            self.bias = self.bias_init.init(self._bias_shape, fan_in=self._fan_in, fan_out=self._fan_out)


class SpatialConvolution(_ConvBase):
    """2-D convolution (``nn/SpatialConvolution.scala``)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int, stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, n_group: int = 1,
                 propagate_back: bool = True, w_regularizer=None, b_regularizer=None,
                 init_weight=None, init_bias=None, with_bias: bool = True,
                 format: str = "NCHW"):
        super().__init__()
        assert n_input_plane % n_group == 0 and n_output_plane % n_group == 0
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.n_group = n_group
        self.propagate_back = propagate_back
        self.with_bias = with_bias
        self.format = format
        self.w_regularizer, self.b_regularizer = w_regularizer, b_regularizer
        fan_in = n_input_plane // n_group * kernel_h * kernel_w
        fan_out = n_output_plane // n_group * kernel_h * kernel_w
        self._init_params((n_output_plane, n_input_plane // n_group, kernel_h, kernel_w),
                          fan_in, fan_out, with_bias, (n_output_plane,),
                          init_weight, init_bias)

    def update_output(self, input):
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        if self.format == "NHWC":
            dn = lax.conv_dimension_numbers(x.shape, self.weight.shape[2:] + (1, 1), ("NHWC", "HWIO", "NHWC"))
            w = jnp.transpose(self.weight, (2, 3, 1, 0))  # OIHW -> HWIO
            h_ax, w_ax, c_ax = 1, 2, 3
        else:
            dn = lax.conv_dimension_numbers(x.shape, self.weight.shape, ("NCHW", "OIHW", "NCHW"))
            w = self.weight
            h_ax, w_ax, c_ax = 2, 3, 1
        pad_h = _pair_padding(self.pad_h, self.kernel_h, self.stride_h, x.shape[h_ax])
        pad_w = _pair_padding(self.pad_w, self.kernel_w, self.stride_w, x.shape[w_ax])
        y = lax.conv_general_dilated(
            x, w.astype(x.dtype), (self.stride_h, self.stride_w), (pad_h, pad_w),
            dimension_numbers=dn, feature_group_count=self.n_group)
        if self.with_bias:
            bshape = [1, 1, 1, 1]
            bshape[c_ax] = self.n_output_plane
            y = y + self.bias.reshape(bshape).astype(y.dtype)
        return y[0] if squeeze else y


class SpatialShareConvolution(SpatialConvolution):
    """Buffer-sharing variant in the reference
    (``nn/SpatialShareConvolution.scala``); identical math — XLA owns
    memory reuse here, so it is an alias."""


class SpatialDilatedConvolution(_ConvBase):
    """Atrous conv (``nn/SpatialDilatedConvolution.scala``)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 dilation_w: int = 1, dilation_h: int = 1,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kw, self.kh, self.dw, self.dh = kw, kh, dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.dilation_w, self.dilation_h = dilation_w, dilation_h
        self.w_regularizer, self.b_regularizer = w_regularizer, b_regularizer
        fan_in = n_input_plane * kh * kw
        self._init_params((n_output_plane, n_input_plane, kh, kw), fan_in,
                          n_output_plane * kh * kw, True, (n_output_plane,))

    def update_output(self, input):
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        dn = lax.conv_dimension_numbers(x.shape, self.weight.shape, ("NCHW", "OIHW", "NCHW"))
        eff_kh = (self.kh - 1) * self.dilation_h + 1
        eff_kw = (self.kw - 1) * self.dilation_w + 1
        pad_h = _pair_padding(self.pad_h, eff_kh, self.dh, x.shape[2])
        pad_w = _pair_padding(self.pad_w, eff_kw, self.dw, x.shape[3])
        y = lax.conv_general_dilated(
            x, self.weight.astype(x.dtype), (self.dh, self.dw), (pad_h, pad_w),
            rhs_dilation=(self.dilation_h, self.dilation_w), dimension_numbers=dn)
        y = y + self.bias.reshape(1, -1, 1, 1).astype(y.dtype)
        return y[0] if squeeze else y


class SpatialFullConvolution(_ConvBase):
    """Transposed ("de")convolution (``nn/SpatialFullConvolution.scala``).
    Weight layout (in, out/group, kH, kW) as in Torch; implemented as an
    input-dilated conv so XLA emits the standard transposed-conv kernel."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0, adj_w: int = 0, adj_h: int = 0,
                 n_group: int = 1, no_bias: bool = False,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kw, self.kh, self.dw, self.dh = kw, kh, dw, dh
        self.pad_w, self.pad_h, self.adj_w, self.adj_h = pad_w, pad_h, adj_w, adj_h
        self.n_group = n_group
        self.with_bias = not no_bias
        self.w_regularizer, self.b_regularizer = w_regularizer, b_regularizer
        fan_in = n_output_plane // n_group * kh * kw  # note: transposed fans
        self._init_params((n_input_plane, n_output_plane // n_group, kh, kw),
                          fan_in, n_input_plane * kh * kw,
                          self.with_bias, (n_output_plane,))

    def update_output(self, input):
        x = input
        if isinstance(x, (list, tuple)):  # (input, size-reference) table form
            x = x[0]
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        # weight (I, O/g, kh, kw); conv_general with lhs_dilation implements
        # the transpose: flip spatial dims and swap I/O per group.
        w = self.weight
        if self.n_group > 1:
            w = w.reshape(self.n_group, self.n_input_plane // self.n_group,
                          self.n_output_plane // self.n_group, self.kh, self.kw)
            w = jnp.swapaxes(w, 1, 2).reshape(
                self.n_output_plane, self.n_input_plane // self.n_group, self.kh, self.kw)
        else:
            w = jnp.swapaxes(w, 0, 1)
        w = jnp.flip(w, axis=(2, 3))
        dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))
        pad_h = (self.kh - 1 - self.pad_h, self.kh - 1 - self.pad_h + self.adj_h)
        pad_w = (self.kw - 1 - self.pad_w, self.kw - 1 - self.pad_w + self.adj_w)
        y = lax.conv_general_dilated(
            x, w.astype(x.dtype), (1, 1), (pad_h, pad_w),
            lhs_dilation=(self.dh, self.dw), dimension_numbers=dn,
            feature_group_count=self.n_group)
        if self.with_bias:
            y = y + self.bias.reshape(1, -1, 1, 1).astype(y.dtype)
        return y[0] if squeeze else y


class SpatialConvolutionMap(Module):
    """Convolution with an explicit input→output connection table
    (``nn/SpatialConvolutionMap.scala``).  Expressed as a masked dense conv:
    the sparse table becomes a 0/1 mask on a full OIHW kernel — dense MXU
    matmuls beat gather-scatter on TPU for the tiny maps this layer is used
    with (LeNet-era models)."""

    def __init__(self, conn_table: np.ndarray, kw: int, kh: int,
                 dw: int = 1, dh: int = 1, pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        table = np.asarray(conn_table, np.int64)  # rows of (in, out), 0-based
        self.kw, self.kh, self.dw, self.dh = kw, kh, dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.n_input_plane = int(table[:, 0].max()) + 1
        self.n_output_plane = int(table[:, 1].max()) + 1
        mask = np.zeros((self.n_output_plane, self.n_input_plane, 1, 1), np.float32)
        fan_ins = np.zeros((self.n_output_plane,), np.int64)
        for i, o in table:
            mask[o, i, 0, 0] = 1.0
            fan_ins[o] += 1
        self.register_buffer("mask", mask)
        fan_in = int(fan_ins.max()) * kh * kw
        self.weight_init = RandomUniform()
        self.bias_init = RandomUniform()
        self.weight = Parameter(self.weight_init.init(
            (self.n_output_plane, self.n_input_plane, kh, kw), fan_in=fan_in))
        self.bias = Parameter(self.bias_init.init((self.n_output_plane,), fan_in=fan_in))

    def update_output(self, input):
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        w = self.weight * self.mask
        dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))
        y = lax.conv_general_dilated(
            x, w.astype(x.dtype), (self.dh, self.dw),
            ((self.pad_h, self.pad_h), (self.pad_w, self.pad_w)),
            dimension_numbers=dn)
        y = y + self.bias.reshape(1, -1, 1, 1).astype(y.dtype)
        return y[0] if squeeze else y


class TemporalConvolution(_ConvBase):
    """1-D convolution over [batch, nInputFrame, inputFrameSize]
    (``nn/TemporalConvolution.scala``)."""

    def __init__(self, input_frame_size: int, output_frame_size: int,
                 kernel_w: int, stride_w: int = 1,
                 propagate_back: bool = True, w_regularizer=None, b_regularizer=None,
                 init_weight=None, init_bias=None):
        super().__init__()
        self.input_frame_size, self.output_frame_size = input_frame_size, output_frame_size
        self.kernel_w, self.stride_w = kernel_w, stride_w
        self.w_regularizer, self.b_regularizer = w_regularizer, b_regularizer
        fan_in = input_frame_size * kernel_w
        self._init_params((output_frame_size, input_frame_size, kernel_w), fan_in,
                          output_frame_size * kernel_w, True, (output_frame_size,),
                          init_weight, init_bias)

    def update_output(self, input):
        x = input
        squeeze = x.ndim == 2
        if squeeze:
            x = x[None]
        # [B, T, C] -> conv over T with NWC layout
        dn = lax.conv_dimension_numbers(x.shape, (self.kernel_w, 1, 1), ("NWC", "WIO", "NWC"))
        w = jnp.transpose(self.weight, (2, 1, 0))  # OIW -> WIO
        y = lax.conv_general_dilated(
            x, w.astype(x.dtype), (self.stride_w,), ((0, 0),), dimension_numbers=dn)
        y = y + self.bias.astype(y.dtype)
        return y[0] if squeeze else y


class VolumetricConvolution(_ConvBase):
    """3-D convolution over [batch, C, T, H, W]
    (``nn/VolumetricConvolution.scala``)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int, d_t: int = 1, d_w: int = 1, d_h: int = 1,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0, with_bias: bool = True,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.k_t, self.k_w, self.k_h = k_t, k_w, k_h
        self.d_t, self.d_w, self.d_h = d_t, d_w, d_h
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.with_bias = with_bias
        self.w_regularizer, self.b_regularizer = w_regularizer, b_regularizer
        fan_in = n_input_plane * k_t * k_h * k_w
        self._init_params((n_output_plane, n_input_plane, k_t, k_h, k_w), fan_in,
                          n_output_plane * k_t * k_h * k_w, with_bias, (n_output_plane,))

    def update_output(self, input):
        x = input
        squeeze = x.ndim == 4
        if squeeze:
            x = x[None]
        dn = lax.conv_dimension_numbers(x.shape, self.weight.shape, ("NCDHW", "OIDHW", "NCDHW"))
        pads = [_pair_padding(self.pad_t, self.k_t, self.d_t, x.shape[2]),
                _pair_padding(self.pad_h, self.k_h, self.d_h, x.shape[3]),
                _pair_padding(self.pad_w, self.k_w, self.d_w, x.shape[4])]
        y = lax.conv_general_dilated(
            x, self.weight.astype(x.dtype), (self.d_t, self.d_h, self.d_w), pads,
            dimension_numbers=dn)
        if self.with_bias:
            y = y + self.bias.reshape(1, -1, 1, 1, 1).astype(y.dtype)
        return y[0] if squeeze else y


class VolumetricFullConvolution(_ConvBase):
    """3-D transposed convolution (``nn/VolumetricFullConvolution.scala``)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int, d_t: int = 1, d_w: int = 1, d_h: int = 1,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 adj_t: int = 0, adj_w: int = 0, adj_h: int = 0,
                 n_group: int = 1, no_bias: bool = False,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.k_t, self.k_w, self.k_h = k_t, k_w, k_h
        self.d_t, self.d_w, self.d_h = d_t, d_w, d_h
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.adj_t, self.adj_w, self.adj_h = adj_t, adj_w, adj_h
        self.n_group = n_group
        self.with_bias = not no_bias
        self.w_regularizer, self.b_regularizer = w_regularizer, b_regularizer
        fan_in = n_output_plane // n_group * k_t * k_h * k_w
        self._init_params((n_input_plane, n_output_plane // n_group, k_t, k_h, k_w),
                          fan_in, n_input_plane * k_t * k_h * k_w,
                          self.with_bias, (n_output_plane,))

    def update_output(self, input):
        x = input
        if isinstance(x, (list, tuple)):
            x = x[0]
        squeeze = x.ndim == 4
        if squeeze:
            x = x[None]
        w = self.weight
        if self.n_group > 1:
            w = w.reshape(self.n_group, self.n_input_plane // self.n_group,
                          self.n_output_plane // self.n_group, self.k_t, self.k_h, self.k_w)
            w = jnp.swapaxes(w, 1, 2).reshape(
                self.n_output_plane, self.n_input_plane // self.n_group,
                self.k_t, self.k_h, self.k_w)
        else:
            w = jnp.swapaxes(w, 0, 1)
        w = jnp.flip(w, axis=(2, 3, 4))
        dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NCDHW", "OIDHW", "NCDHW"))
        pads = [(self.k_t - 1 - self.pad_t, self.k_t - 1 - self.pad_t + self.adj_t),
                (self.k_h - 1 - self.pad_h, self.k_h - 1 - self.pad_h + self.adj_h),
                (self.k_w - 1 - self.pad_w, self.k_w - 1 - self.pad_w + self.adj_w)]
        y = lax.conv_general_dilated(
            x, w.astype(x.dtype), (1, 1, 1), pads,
            lhs_dilation=(self.d_t, self.d_h, self.d_w), dimension_numbers=dn,
            feature_group_count=self.n_group)
        if self.with_bias:
            y = y + self.bias.reshape(1, -1, 1, 1, 1).astype(y.dtype)
        return y[0] if squeeze else y
