"""Linear-algebra layers (SURVEY §2.5: Linear, Bilinear, CMul, CAdd, Mul,
Add, MulConstant, AddConstant, MM, MV, DotProduct, Cosine, CosineDistance,
Euclidean, PairwiseDistance, LookupTable, MixtureTable).

Matmuls map straight onto the TPU MXU via ``jnp.dot``/``einsum``; the
reference's MKL gemm dispatch (``tensor/DenseTensorBLAS.scala``) has no
analogue here — XLA owns the tiling.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.init import InitializationMethod, RandomUniform, Zeros
from bigdl_tpu.nn.module import Module, Parameter

__all__ = [
    "Linear", "Bilinear", "CMul", "CAdd", "Mul", "Add", "MulConstant",
    "AddConstant", "MM", "MV", "DotProduct", "Cosine", "CosineDistance",
    "Euclidean", "PairwiseDistance", "LookupTable", "MixtureTable",
]


class Linear(Module):
    """y = x W^T + b (``nn/Linear.scala``).  Weight layout (out, in) as in
    the reference; regularizers applied by the training step."""

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 w_regularizer=None, b_regularizer=None,
                 init_weight=None, init_bias=None):
        super().__init__()
        self.input_size, self.output_size = input_size, output_size
        self.with_bias = with_bias
        self.w_regularizer, self.b_regularizer = w_regularizer, b_regularizer
        self.weight_init: InitializationMethod = RandomUniform()
        self.bias_init: InitializationMethod = RandomUniform()
        if init_weight is not None:
            self.weight = Parameter(init_weight)
        else:
            self.weight = Parameter(self.weight_init.init(
                (output_size, input_size), fan_in=input_size, fan_out=output_size))
        if with_bias:
            if init_bias is not None:
                self.bias = Parameter(init_bias)
            else:
                self.bias = Parameter(self.bias_init.init(
                    (output_size,), fan_in=input_size, fan_out=output_size))

    def reset(self):
        self.weight = self.weight_init.init(
            (self.output_size, self.input_size),
            fan_in=self.input_size, fan_out=self.output_size)
        if self.with_bias:
            self.bias = self.bias_init.init(
                (self.output_size,), fan_in=self.input_size, fan_out=self.output_size)

    def update_output(self, input):
        squeeze = input.ndim == 1
        x = input[None, :] if squeeze else input
        # Cast weights to the activation dtype (bf16 compute keeps bf16 out;
        # the MXU still accumulates bf16 contractions in f32 internally).
        # No preferred_element_type: the f32-preferred + downcast sandwich
        # breaks the dot/conv transpose dtypes under mixed precision.
        y = jnp.dot(x, self.weight.T.astype(x.dtype))
        if self.with_bias:
            y = y + self.bias.astype(y.dtype)
        return y[0] if squeeze else y


class Bilinear(Module):
    """y_k = x1^T W_k x2 + b_k over a table input (x1, x2)
    (``nn/Bilinear.scala``)."""

    def __init__(self, input_size1: int, input_size2: int, output_size: int,
                 bias_res: bool = True, w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.input_size1, self.input_size2 = input_size1, input_size2
        self.output_size, self.bias_res = output_size, bias_res
        self.w_regularizer, self.b_regularizer = w_regularizer, b_regularizer
        self.weight_init = RandomUniform()
        self.bias_init = RandomUniform()
        self.reset()

    def reset(self):
        fan = self.input_size1 * self.input_size2
        self.weight = Parameter(self.weight_init.init(
            (self.output_size, self.input_size1, self.input_size2), fan_in=fan))
        if self.bias_res:
            self.bias = Parameter(self.bias_init.init((self.output_size,), fan_in=fan))

    def update_output(self, input):
        x1, x2 = input
        y = jnp.einsum("bi,oij,bj->bo", x1, self.weight, x2)
        if self.bias_res:
            y = y + self.bias
        return y


def _left_align(w, input):
    """Reference CMul/CAdd expand semantics (``CMul.scala:68-77``): a
    lower-rank weight gets ONE leading batch dim prepended then expands
    dim-by-dim; a higher-rank weight (caffe Scale reloads as (1,n,1,1))
    sheds trailing singletons.  numpy's silent right-alignment — which
    would scale the WRONG axis with the same output shape — is never
    allowed: rank mismatches that the reference rejects raise here."""
    if w.ndim > input.ndim:
        while w.ndim > input.ndim and w.shape[-1] == 1:
            w = w.reshape(w.shape[:-1])
    elif w.ndim < input.ndim:
        w = w.reshape((1,) + w.shape)  # CMul.scala:71
    if w.ndim != input.ndim:
        raise ValueError(
            f"CMul/CAdd parameter of shape {tuple(w.shape)} cannot "
            f"expand to a rank-{input.ndim} input (reference expand "
            f"prepends exactly one batch dim)")
    return w


class CMul(Module):
    """Learnable per-element scale, broadcast over the batch
    (``nn/CMul.scala``)."""

    def __init__(self, size):
        super().__init__()
        self.size = tuple(size)
        self.weight = Parameter(jnp.ones(self.size, jnp.float32))

    def reset(self):
        import numpy as np

        std = 1.0 / np.sqrt(np.prod(self.size))
        self.weight = RandomUniform(-std, std).init(self.size)

    def update_output(self, input):
        return input * _left_align(self.weight, input)


class CAdd(Module):
    """Learnable per-element bias (``nn/CAdd.scala``)."""

    def __init__(self, size):
        super().__init__()
        self.size = tuple(size)
        self.bias = Parameter(jnp.zeros(self.size, jnp.float32))

    def reset(self):
        import numpy as np

        std = 1.0 / np.sqrt(np.prod(self.size))
        self.bias = RandomUniform(-std, std).init(self.size)

    def update_output(self, input):
        return input + _left_align(self.bias, input)


class Mul(Module):
    """Single learnable scalar multiplier (``nn/Mul.scala``)."""

    def __init__(self):
        super().__init__()
        self.weight = Parameter(jnp.ones((1,), jnp.float32))

    def reset(self):
        self.weight = RandomUniform(-1.0, 1.0).init((1,))

    def update_output(self, input):
        return input * self.weight[0]


class Add(Module):
    """Learnable bias vector over the feature dim (``nn/Add.scala``)."""

    def __init__(self, input_size: int):
        super().__init__()
        self.input_size = input_size
        self.bias = Parameter(jnp.zeros((input_size,), jnp.float32))

    def reset(self):
        import numpy as np

        std = 1.0 / np.sqrt(self.input_size)
        self.bias = RandomUniform(-std, std).init((self.input_size,))

    def update_output(self, input):
        return input + self.bias


class MulConstant(Module):
    def __init__(self, scalar: float, ip: bool = False):
        super().__init__()
        self.scalar = scalar

    def update_output(self, input):
        return input * self.scalar


class AddConstant(Module):
    def __init__(self, constant: float, ip: bool = False):
        super().__init__()
        self.constant = constant

    def update_output(self, input):
        return input + self.constant


class MM(Module):
    """Batch/plain matmul over a table (a, b) with optional transposes
    (``nn/MM.scala``)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False):
        super().__init__()
        self.trans_a, self.trans_b = trans_a, trans_b

    def update_output(self, input):
        a, b = input
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)


class MV(Module):
    """Matrix-vector product over a table (mat, vec) (``nn/MV.scala``)."""

    def __init__(self, trans: bool = False):
        super().__init__()
        self.trans = trans

    def update_output(self, input):
        m, v = input
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v)


class DotProduct(Module):
    def update_output(self, input):
        a, b = input
        if a.ndim == 1:
            return jnp.sum(a * b)[None]
        return jnp.sum(a * b, axis=-1)


class Cosine(Module):
    """Cosine similarity of the input against each row of a learnable weight
    (``nn/Cosine.scala``)."""

    def __init__(self, input_size: int, output_size: int):
        super().__init__()
        self.input_size, self.output_size = input_size, output_size
        self.weight_init = RandomUniform()
        self.weight = Parameter(self.weight_init.init(
            (output_size, input_size), fan_in=input_size))

    def reset(self):
        self.weight = self.weight_init.init(
            (self.output_size, self.input_size), fan_in=self.input_size)

    def update_output(self, input):
        squeeze = input.ndim == 1
        x = input[None, :] if squeeze else input
        xn = x / jnp.clip(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-12)
        wn = self.weight / jnp.clip(jnp.linalg.norm(self.weight, axis=1, keepdims=True), 1e-12)
        y = xn @ wn.T
        return y[0] if squeeze else y


class CosineDistance(Module):
    """Cosine similarity over a table (a, b) (``nn/CosineDistance.scala``)."""

    def update_output(self, input):
        a, b = input
        squeeze = a.ndim == 1
        if squeeze:
            a, b = a[None, :], b[None, :]
        cos = jnp.sum(a * b, axis=1) / jnp.clip(
            jnp.linalg.norm(a, axis=1) * jnp.linalg.norm(b, axis=1), 1e-12)
        return cos[0] if squeeze else cos


class Euclidean(Module):
    """Distance from the input to each learnable center
    (``nn/Euclidean.scala``)."""

    def __init__(self, input_size: int, output_size: int, fast_backward: bool = True):
        super().__init__()
        self.input_size, self.output_size = input_size, output_size
        self.weight_init = RandomUniform()
        self.weight = Parameter(self.weight_init.init(
            (output_size, input_size), fan_in=input_size))

    def reset(self):
        self.weight = self.weight_init.init(
            (self.output_size, self.input_size), fan_in=self.input_size)

    def update_output(self, input):
        squeeze = input.ndim == 1
        x = input[None, :] if squeeze else input
        d = jnp.linalg.norm(x[:, None, :] - self.weight[None, :, :], axis=-1)
        return d[0] if squeeze else d


class PairwiseDistance(Module):
    """L-p distance over a table (a, b) (``nn/PairwiseDistance.scala``)."""

    def __init__(self, norm: int = 2):
        super().__init__()
        self.norm = norm

    def update_output(self, input):
        a, b = input
        squeeze = a.ndim == 1
        if squeeze:
            a, b = a[None, :], b[None, :]
        d = jnp.sum(jnp.abs(a - b) ** self.norm, axis=1) ** (1.0 / self.norm)
        return d[0] if squeeze else d


# LookupTable moved to nn/layers/embedding.py (the sparse-gradient
# fast-path family, ISSUE 15); re-exported here so `from ...linear
# import LookupTable` keeps working
from bigdl_tpu.nn.layers.embedding import LookupTable  # noqa: E402,F401


class MixtureTable(Module):
    """Mixture-of-experts combiner: input = (gates, experts)
    (``nn/MixtureTable.scala``).  Experts either a stacked tensor
    [batch, n_experts, ...] or a table of per-expert tensors."""

    def __init__(self, dim: Optional[int] = None):
        super().__init__()
        self.dim = dim

    def update_output(self, input):
        gates, experts = input
        if isinstance(experts, (list, tuple)):
            experts = jnp.stack(list(experts), axis=1)
        g = gates
        while g.ndim < experts.ndim:
            g = g[..., None]
        return jnp.sum(g * experts, axis=1)
