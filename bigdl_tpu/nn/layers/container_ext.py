"""Composite containers beyond Sequential (SURVEY §2.4: Concat,
ConcatTable, ParallelTable, MapTable, TimeDistributed, and the
table-routing helpers).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.nn.module import Container, Module

__all__ = [
    "Concat", "ConcatTable", "ParallelTable", "MapTable", "TimeDistributed",
    "Remat",
]


class Concat(Container):
    """Apply every member to the same input, concatenate outputs along
    ``dim`` (``nn/Concat.scala``; reference dim 1 of [batch, ...] — here an
    explicit 0-based axis, default 1)."""

    def __init__(self, dim: int = 1):
        super().__init__()
        self.dim = dim

    def update_output(self, input):
        return jnp.concatenate([m.forward(input) for m in self.layers], axis=self.dim)


class ConcatTable(Container):
    """Apply every member to the same input, output a table
    (``nn/ConcatTable.scala``)."""

    def update_output(self, input):
        return [m.forward(input) for m in self.layers]


class ParallelTable(Container):
    """Member i applied to input[i] (``nn/ParallelTable.scala``)."""

    def update_output(self, input):
        return [m.forward(x) for m, x in zip(self.layers, input)]


class MapTable(Container):
    """One module applied to every table element (``nn/MapTable.scala``).
    The reference clones the module per element with shared weights; under
    the functional core the SAME module instance is simply reused — weight
    sharing is the default."""

    def __init__(self, module: Optional[Module] = None):
        super().__init__()
        if module is not None:
            self.add(module)

    def update_output(self, input):
        m = self.layers[0]
        return [m.forward(x) for x in input]


class Remat(Container):
    """Gradient checkpointing / rematerialization boundary: activations
    inside the wrapped module are NOT saved for the backward pass —
    ``jax.checkpoint`` recomputes them during the gradient, trading
    recompute FLOPs for HBM (the standard TPU memory lever; no reference
    analogue — BigDL materializes every layer's output by design).

    Wrap repeated blocks of a deep model::

        nn.Sequential(*[nn.Remat(block()) for _ in range(depth)])

    Exact: forward values and gradients are bit-identical to the
    unwrapped module (dropout keys derive from the same fold_in chain on
    recompute), only the memory/compute schedule changes.
    """

    def __init__(self, module: Module, policy=None):
        super().__init__()
        self.add(module)
        self._policy = policy

    def update_output(self, input):
        import jax

        inner = self.layers[0]
        fn = jax.checkpoint(lambda v: inner.forward(v), policy=self._policy)
        return fn(input)


class TimeDistributed(Container):
    """Apply the inner module to every timestep of [batch, time, ...]
    (``nn/TimeDistributed.scala``) by folding time into the batch — one big
    MXU-friendly batched op instead of a per-step loop."""

    def __init__(self, module: Module):
        super().__init__()
        self.add(module)

    def update_output(self, input):
        b, t = input.shape[0], input.shape[1]
        flat = input.reshape((b * t,) + input.shape[2:])
        out = self.layers[0].forward(flat)
        return out.reshape((b, t) + out.shape[1:])
