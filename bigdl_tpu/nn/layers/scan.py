"""Scan-over-layers: N structurally identical blocks as ONE compiled body.

An unrolled ``Sequential`` of N identical blocks makes XLA lower, optimize
and codegen the block N times — compile time scales with depth while the
computed program doesn't (docs/compile.md).  :class:`ScanLayers` holds the
N blocks as one **stacked-param pytree** (every leaf gains a leading
``[n_layers]`` axis) and runs them with ``jax.lax.scan``, so XLA compiles
the block once and loops it.  Forward values, gradients and buffer
updates are exact matches of the unrolled container (rtol ~1e-6 fp32 —
same ops, same order, per layer).

The stacked layout is also the parameter layout ZeRO-style sharded
weight updates want (*Automatic Cross-Replica Sharding of Weight
Update*, arXiv 2004.13336): one ``[n_layers, ...]`` leaf per block
parameter shards over a mesh axis without per-layer bookkeeping.

Contract (see docs/compile.md):

- **structural identity**: every block must have the same module-class
  tree, the same param/buffer paths with equal shapes/dtypes, and equal
  scalar hyperparameters (:func:`layer_signature`).  Construction fails
  loudly otherwise.
- **numerics**: the constructor stacks the blocks' EXISTING arrays, so
  replacing an unrolled run with ``ScanLayers(blocks)`` preserves the
  model's parameters exactly.
- **state-dict mapping, both directions**: the stacked tree round-trips
  through ``state_dict``/BTPU as ``body.<path> -> [n_layers, ...]``;
  :meth:`ScanLayers.layer_state_dict` / :meth:`load_layer_state_dict`
  map to/from the per-layer keys (``"<i>.<path>"``) an unrolled
  ``Sequential`` of the same blocks would use, and :meth:`to_layers`
  reconstructs the unrolled blocks.
- **RNG**: stochastic layers (dropout) get an independent stream per
  scanned layer — the layer index is folded into the step key before
  the block's own ``_rng_id`` fold, mirroring the unrolled case where
  every clone owns a distinct id.
- **attribution**: the body is a real registered submodule (``body``),
  so PR-4 scope stamping and per-module cost attribution see the
  scanned block under ``...<scan>.body.<child>`` — once, which is also
  how often XLA compiles it.
- **limits**: per-layer differing ``scale_w/scale_b``/freeze masks or
  hyperparameters cannot be expressed on a stacked run (the signature
  check rejects them); convert such layers unrolled.
"""

from __future__ import annotations

import copy
from typing import List, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import (Container, Module, Sequential,
                                 functional_call, load_state_dict,
                                 state_dict)

__all__ = ["ScanLayers", "layer_signature", "auto_scan", "maybe_scan"]


#: per-module __dict__ entries excluded from the behavioral fingerprint:
#: identity/bookkeeping that legitimately differs between clones of one
#: block (names, rng ids, scope stamps, timing, trace scratch)
_SIG_SKIP = frozenset({
    "_name", "_hyper_version", "_rng_id", "_scope_name", "_bwd_cache",
    "forward_time", "backward_time", "output", "grad_input",
    "_last_rng_key", "_last_state", "_init_state_override", "_spatial",
    "_tele_dispatched", "_dispatch_observed",
})


def _hyper_value(v):
    """Fingerprint one hyperparameter value: scalars as-is, tuples/
    lists/sets recursively (shape specs like ``View.sizes`` and
    ``Transpose.permutations`` MUST participate — two same-class layers
    differing only in a tuple hyper compute different functions), and
    anything non-simple (arrays, modules, callables) as an opaque
    marker so it neither crashes hashing nor falsely distinguishes."""
    if isinstance(v, (int, float, str, bool, type(None))):
        return v
    if isinstance(v, (tuple, list)):
        return (type(v).__name__,) + tuple(_hyper_value(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return (type(v).__name__,) + tuple(
            sorted(map(repr, (_hyper_value(x) for x in v))))
    return f"<{type(v).__name__}>"


def layer_signature(module: Module) -> Tuple:
    """Structural + behavioral fingerprint of a block: the module-class
    tree, every param/buffer path with shape and dtype, and every
    simple hyperparameter (scalar or tuple/list-of-scalar ``__dict__``
    entries outside :data:`_SIG_SKIP`, plus training/frozen flags).
    Two blocks with equal signatures compute the same function of
    (params, input) — the precondition for stacking them onto one
    scanned body."""
    rows: List[Tuple] = []
    for name, m in module.named_modules():
        hyper = tuple(sorted(
            (k, repr(_hyper_value(v))) for k, v in m.__dict__.items()
            if k not in _SIG_SKIP
            and isinstance(v, (int, float, str, bool, type(None),
                               tuple, list, set, frozenset))))
        rows.append((name, type(m).__name__, m.__dict__["training"],
                     m.__dict__["_frozen"], hyper))
    arrays = tuple(sorted(
        (path, tuple(jnp.shape(v)), str(getattr(v, "dtype", "?")))
        for path, v in state_dict(module).items()))
    return (tuple(rows), arrays)


class ScanLayers(Container):
    """N structurally identical blocks compiled as ONE ``lax.scan`` body.

    ``ScanLayers(b0, b1, ..., bN)`` (or one iterable) takes ownership of
    ``b0`` as the scan **body** and stacks every block's params/buffers
    onto it with a leading ``[n_layers]`` axis; the remaining block
    objects are discarded after their arrays are captured.  Drop-in for
    the ``Sequential`` run it replaces: same outputs, same grads, same
    buffer advance (BN running stats update per layer, in order).
    """

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and not isinstance(layers[0], Module):
            layers = tuple(layers[0])
        blocks = list(layers)
        if not blocks:
            raise ValueError("ScanLayers needs at least one block")
        for b in blocks:
            if not isinstance(b, Module):
                raise TypeError(f"ScanLayers blocks must be Modules, got "
                                f"{type(b).__name__}")
        sig0 = layer_signature(blocks[0])
        for i, b in enumerate(blocks[1:], 1):
            if layer_signature(b) != sig0:
                raise ValueError(
                    f"ScanLayers block {i} is not structurally identical "
                    f"to block 0 — stacked scan needs equal module trees, "
                    f"param shapes/dtypes and scalar hyperparameters")
        # registration order: the paths exist before stacking mutates them
        self.n_layers = len(blocks)
        self.buffer_paths = tuple(sorted(
            state_dict(blocks[0], kind="buffer")))
        self.body = blocks[0]
        states = [state_dict(b) for b in blocks]
        stacked = {path: jnp.stack([s[path] for s in states])
                   for path in states[0]}
        load_state_dict(blocks[0], stacked, strict=False)

    def add(self, module: Module) -> "Container":
        raise TypeError("ScanLayers is fixed at construction — build a "
                        "new one from to_layers() + the extra blocks")

    # -- forward -----------------------------------------------------------
    def update_output(self, input):
        from bigdl_tpu.utils.rng import current_rng_key

        body = self.__dict__["_modules"]["body"]
        stacked = state_dict(body)
        buf_paths = self.buffer_paths
        key = current_rng_key()
        if key is not None:
            # one independent stream per layer: fold the layer index in
            # BEFORE each stochastic module folds its own _rng_id — the
            # scanned analogue of every unrolled clone owning its own id
            keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
                jnp.arange(self.n_layers))
        else:
            keys = None  # an empty pytree node: scan carries no leaf

        def step(carry, xs_t):
            layer_state, k = xs_t
            out, new_state = functional_call(body, layer_state, carry,
                                             rng=k)
            updated = {p: new_state[p] for p in buf_paths} or None
            return out, updated

        # explicit length: a param-less body (e.g. stacked stateless
        # layers) scans over an empty pytree in eval mode
        out, new_buffers = lax.scan(step, input, (stacked, keys),
                                    length=self.n_layers)
        if new_buffers is not None:
            # buffer advance (BN running stats): the scan's stacked ys
            # ARE the per-layer updated buffers; bind them so the outer
            # functional_call collects them as the new state
            load_state_dict(body, new_buffers, strict=False)
        return out

    # -- per-layer state mapping (both directions) -------------------------
    def layer_state_dict(self):
        """``{"<i>.<path>": array}`` — the keys an unrolled
        ``Sequential`` of the same blocks produces from ``state_dict``
        (the export direction of checkpoint compatibility)."""
        out = {}
        stacked = state_dict(self.__dict__["_modules"]["body"])
        for path, v in stacked.items():
            for i in range(self.n_layers):
                out[f"{i}.{path}"] = v[i]
        return out

    def load_layer_state_dict(self, state, strict: bool = True):
        """Load per-layer keys (``"<i>.<path>"``, the unrolled
        ``Sequential`` layout) onto the stacked axis — the import
        direction.  ``strict`` aggregates all missing/unexpected keys in
        one ``KeyError``, mirroring ``load_state_dict``."""
        body = self.__dict__["_modules"]["body"]
        own = state_dict(body)
        stacked, missing = {}, []
        for path in own:
            rows = []
            for i in range(self.n_layers):
                k = f"{i}.{path}"
                if k in state:
                    rows.append(jnp.asarray(state[k]))
                else:
                    missing.append(k)
            if len(rows) == self.n_layers:
                stacked[path] = jnp.stack(rows)

        def _known(k: str) -> bool:
            head, _, rest = k.partition(".")
            return head.isdigit() and int(head) < self.n_layers \
                and rest in own

        unexpected = sorted(k for k in state if not _known(k))
        if strict and (missing or unexpected):
            parts = []
            if missing:
                parts.append(f"missing per-layer keys: {sorted(missing)}")
            if unexpected:
                parts.append(f"unexpected keys: {unexpected}")
            raise KeyError("; ".join(parts))
        load_state_dict(body, stacked, strict=False)
        return self

    def to_layers(self) -> List[Module]:
        """Reconstruct the N unrolled blocks (fresh modules, slice-``i``
        arrays) — the inverse of construction."""
        body = self.__dict__["_modules"]["body"]
        stacked = state_dict(body)
        out = []
        for i in range(self.n_layers):
            blk = copy.deepcopy(body)
            load_state_dict(blk, {p: v[i] for p, v in stacked.items()},
                            strict=False)
            out.append(blk)
        return out

    def __repr__(self):
        return (f"ScanLayers(n_layers={self.__dict__.get('n_layers')}, "
                f"body={type(self.__dict__['_modules']['body']).__name__})")


def auto_scan(model: Module, min_run: int = 2) -> Module:
    """Rewrite every maximal run of >= ``min_run`` consecutive,
    structurally identical children of each (exact) ``Sequential``
    container into one :class:`ScanLayers` — in place, preserving the
    model's parameter VALUES exactly (the blocks' arrays are stacked,
    not re-initialized).  Registration indices of later children shift
    (N blocks collapse to one slot), so convert before checkpointing, or
    map old checkpoints through ``load_layer_state_dict``.

    Children are processed innermost-first so nested identical runs
    collapse before the outer comparison sees them.  Only exact
    ``Sequential`` containers are rewritten: subclasses and table
    containers (Concat/ConcatTable/...) don't compose children
    sequentially, so a "run" there is not a chain."""
    mods = list(model.modules())
    for m in reversed(mods):  # pre-order reversed ~= innermost first
        if type(m) is not Sequential:
            continue
        children = list(m.__dict__["_modules"].values())
        new: List[Module] = []
        i = 0
        while i < len(children):
            if isinstance(children[i], ScanLayers):
                new.append(children[i])
                i += 1
                continue
            sig = layer_signature(children[i])
            j = i + 1
            while j < len(children) \
                    and not isinstance(children[j], ScanLayers) \
                    and layer_signature(children[j]) == sig:
                j += 1
            if j - i >= min_run:
                new.append(ScanLayers(children[i:j]))
            else:
                new.extend(children[i:j])
            i = j
        m.__dict__["_modules"] = {str(k): c for k, c in enumerate(new)}
    return model


def maybe_scan(model: Module, scan=None, min_run: int = 2) -> Module:
    """The registry-flag gate the model builders call: ``scan=None``
    defers to the ``BIGDL_SCAN_LAYERS`` config (default off — the
    unrolled build stays byte-identical for existing checkpoints);
    ``True``/``False`` force."""
    if scan is None:
        from bigdl_tpu.utils.config import get_config

        scan = get_config().scan_layers
    return auto_scan(model, min_run=min_run) if scan else model
