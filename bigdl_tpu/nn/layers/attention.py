"""Attention layers: MultiHeadAttention, LayerNorm, TransformerBlock.

The reference has no attention (SURVEY §5); these extend the module
catalog so long-context transformer models are first-class citizens of
the framework.  The compute core routes to ``bigdl_tpu.ops``: dense
XLA-fused attention, the Pallas flash kernel, or a sequence-parallel
strategy (ring / Ulysses over a mesh ``seq`` axis).
"""

from __future__ import annotations

import math
import sys
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.layers.linear import Linear
from bigdl_tpu.nn.module import Module, Parameter

__all__ = ["LayerNorm", "MultiHeadAttention", "TransformerBlock"]


def generation_cache_context():
    """The ambient KV-cache context bound by a generation trace
    (``serving/generate/kv_cache.py``), or None.  Resolved through
    ``sys.modules`` so the nn layer never imports the serving stack:
    a process that never generated cannot have bound a context, and a
    process that did has the module loaded already."""
    mod = sys.modules.get("bigdl_tpu.serving.generate.kv_cache")
    return mod.current() if mod is not None else None


class LayerNorm(Module):
    """Layer normalization over the last dimension (extension beyond the
    reference catalog; required by the transformer stack)."""

    def __init__(self, normalized_size: int, eps: float = 1e-5,
                 affine: bool = True):
        super().__init__()
        self.normalized_size = normalized_size
        self.eps = eps
        self.affine = affine
        if affine:
            self.weight = Parameter(jnp.ones((normalized_size,)))
            self.bias = Parameter(jnp.zeros((normalized_size,)))

    def update_output(self, input):
        mu = jnp.mean(input, axis=-1, keepdims=True)
        var = jnp.var(input, axis=-1, keepdims=True)
        out = (input - mu) * jax.lax.rsqrt(var + self.eps)
        if self.affine:
            out = out * self._params["weight"] + self._params["bias"]
        return out

    def __repr__(self):
        return f"LayerNorm({self.normalized_size})"


class MultiHeadAttention(Module):
    """Multi-head attention over [batch, seq, embed] inputs.

    ``backend``: 'auto' (on TPU: flash when ``max(Sq, Sk)`` reaches
    ``bigdl_tpu.ops.attention.flash_min_seq()`` — default 512, env
    ``BIGDL_FLASH_MIN_SEQ`` — else dense, which below one k-block is one
    batched MXU matmul; always dense off-TPU), 'dense',
    'flash', or a callable ``f(q, k, v) -> out`` over [B, H, S, D] arrays
    with causal/scale baked in — e.g. a shard_map-wrapped ring/ulysses
    attention from
    ``bigdl_tpu.parallel.sequence.make_sequence_parallel_attention``.
    Custom callables do not receive masks; pass masking via the callable's
    own construction.  ``dropout`` is applied to the attention context
    (before the output projection) in training mode.

    Input: a single tensor (self-attention), ``(x, mask)``,
    ``(query, key, value)``, or ``(query, key, value, mask)``, where
    tensors are [B, S, E] and mask broadcasts to [B, H, Sq, Sk]
    (True = attend).
    """

    def __init__(self, embed_dim: int, num_heads: int,
                 dropout: float = 0.0, with_bias: bool = True,
                 causal: bool = False, backend="auto"):
        super().__init__()
        assert embed_dim % num_heads == 0, \
            "embed_dim must be divisible by num_heads"
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.causal = causal
        self.dropout_p = dropout
        self.backend = backend
        self.q_proj = Linear(embed_dim, embed_dim, with_bias=with_bias)
        self.k_proj = Linear(embed_dim, embed_dim, with_bias=with_bias)
        self.v_proj = Linear(embed_dim, embed_dim, with_bias=with_bias)
        self.out_proj = Linear(embed_dim, embed_dim, with_bias=with_bias)
        if dropout > 0.0:
            from bigdl_tpu.nn.layers.normalization import Dropout

            self.drop = Dropout(dropout)

    def _split(self, x):
        b, s, _ = x.shape
        return x.reshape(b, s, self.num_heads, self.head_dim).transpose(
            0, 2, 1, 3)

    def _attend(self, q, k, v, mask):
        from bigdl_tpu.ops import dot_product_attention, flash_attention

        backend = self.backend
        if callable(backend):
            if mask is not None:
                raise ValueError(
                    "custom attention backends do not accept masks; bake "
                    "masking into the callable")
            return backend(q, k, v)
        if backend == "flash" and mask is not None:
            raise ValueError(
                "backend='flash' does not support masks (only causal=True); "
                "use backend='dense' or 'auto' for masked attention")
        if backend == "auto":
            from bigdl_tpu.ops.attention import select_attention_backend
            from bigdl_tpu.ops.dispatch import note

            # dense below the threshold, flash at/above it.  With the
            # round-5 block defaults (1024/512) flash BEATS dense from
            # seq 512 up (exp_attention_backend: 734 vs 562 seq/s — the
            # earlier "flash was 53% of the seq-512 step" profile was an
            # artifact of the old 128x128 blocks).  The routing rule
            # itself lives in ops.attention (shared with bench.py's MFU
            # correction) and honors the BIGDL_KERNELS kill switch.
            backend, reason = select_attention_backend(
                q.shape[2], k.shape[2], mask is not None)
            note("attention",
                 "pallas" if backend == "flash" else "xla", reason)
        if backend == "flash":
            return flash_attention(q, k, v, causal=self.causal)
        return dot_product_attention(q, k, v, mask=mask, causal=self.causal)

    def update_output(self, input):
        mask = None
        if isinstance(input, (tuple, list)):
            if len(input) == 2:
                x, mask = input
                xq = xk = xv = x
            elif len(input) == 3:
                xq, xk, xv = input
            elif len(input) == 4:
                xq, xk, xv, mask = input
            else:
                raise ValueError("input must be x, (x, mask), (q, k, v) or "
                                 "(q, k, v, mask)")
        else:
            xq = xk = xv = input
        if xq is xk and xk is xv:
            # self-attention: ONE [*, E] @ [E, 3E] GEMM instead of three
            # [*, E] @ [E, E] with the same left operand — better MXU
            # tiling. The weight concat is tiny next to the activation
            # matmul; gradients flow through it back to the separate
            # q/k/v parameters, so state_dict layout is unchanged.
            # Deliberate tradeoff: this bypasses Linear.forward, so
            # get_times() attributes the fused GEMM to THIS module, not
            # per-projection.
            w = jnp.concatenate([self.q_proj.weight, self.k_proj.weight,
                                 self.v_proj.weight], axis=0)
            qkv = jnp.dot(xq, w.T.astype(xq.dtype))
            if self.q_proj.with_bias:
                b_all = jnp.concatenate([self.q_proj.bias, self.k_proj.bias,
                                         self.v_proj.bias])
                qkv = qkv + b_all.astype(qkv.dtype)
            q, k, v = (self._split(t)
                       for t in jnp.split(qkv, 3, axis=-1))
        else:
            q = self._split(self.q_proj.forward(xq))
            k = self._split(self.k_proj.forward(xk))
            v = self._split(self.v_proj.forward(xv))
        ctx = generation_cache_context()
        out = None
        if ctx is not None and self.causal and xq is xk:
            # generation trace: prefill RECORDS the fresh k/v (and falls
            # through to the normal backend below — long prompts keep
            # the flash path); decode scatters the single new k/v row
            # into this layer's cache and returns q-against-cache
            # attention (dense by the q_len=1 routing rule)
            out = ctx.attend(q, k, v, causal=self.causal)
        if out is None:
            out = self._attend(q, k, v, mask)
        b, h, s, d = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        if self.dropout_p > 0.0:
            out = self.drop.forward(out)
        return self.out_proj.forward(out)

    def __repr__(self):
        return (f"MultiHeadAttention({self.embed_dim}, heads="
                f"{self.num_heads}, causal={self.causal})")


class TransformerBlock(Module):
    """Pre-norm transformer block: LN -> MHA -> residual, LN -> MLP ->
    residual.  The building block of the long-context flagship model."""

    def __init__(self, embed_dim: int, num_heads: int, mlp_ratio: int = 4,
                 dropout: float = 0.0, causal: bool = True, backend="auto"):
        super().__init__()
        self.ln1 = LayerNorm(embed_dim)
        self.attn = MultiHeadAttention(embed_dim, num_heads, dropout=dropout,
                                       causal=causal, backend=backend)
        self.ln2 = LayerNorm(embed_dim)
        self.fc1 = Linear(embed_dim, embed_dim * mlp_ratio)
        self.fc2 = Linear(embed_dim * mlp_ratio, embed_dim)

    def update_output(self, input):
        x = input + self.attn.forward(self.ln1.forward(input))
        h = self.fc1.forward(self.ln2.forward(x))
        h = jax.nn.gelu(h)
        return x + self.fc2.forward(h)
