"""Tree-structured LSTMs (``nn/TreeLSTM.scala``, ``nn/BinaryTreeLSTM.scala``)
and the Nms detection helper (``nn/Nms.scala``).

The reference walks each sample's parse tree with host-side recursion and
per-node cloned cell modules.  TPU-first redesign: all nodes are processed
**vectorized per round** — each round gathers both children's (c, h) for
every node and updates the nodes whose children are ready, so the whole
forward is one ``lax.scan`` of depth ``node_count`` over MXU-batched gate
matmuls, jit-able and reverse-differentiable (scan, not while_loop).

Tree encoding matches the reference's ``TensorTree``: input =
``(embeddings [B, leafNum, inputSize], trees [B, nodeNum, 3])`` where
``trees[b, i] = (leftChild, rightChild, leafIndex)`` with 1-based node
indices, 0 = no child; output = hidden states ``[B, nodeNum, hidden]``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.layers.linear import Linear
from bigdl_tpu.nn.module import Container, Module

__all__ = ["TreeLSTM", "BinaryTreeLSTM", "Nms"]


class TreeLSTM(Container):
    """Abstract tree LSTM (``nn/TreeLSTM.scala``)."""

    def __init__(self, input_size: int, hidden_size: int):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size


class BinaryTreeLSTM(TreeLSTM):
    """Constituency (binary) tree LSTM (``nn/BinaryTreeLSTM.scala:40``).

    Leaf cell: c = W_c x; h = sigmoid(W_o x) * tanh(c) (when
    ``gate_output``) — ``createLeafModuleWithGraph``.
    Composer: gates i/lf/rf/update/o each = Linear(lh) + Linear(rh);
    c = i*update + lf*lc + rf*rc; h = o * tanh(c) —
    ``createComposerWithGraph``.  One shared parameter set for all leaves
    and one for all composers (the reference shares via shareParams).
    """

    def __init__(self, input_size: int, hidden_size: int,
                 gate_output: bool = True):
        super().__init__(input_size, hidden_size)
        self.gate_output = gate_output
        self.leaf_c = Linear(input_size, hidden_size)
        if gate_output:
            self.leaf_o = Linear(input_size, hidden_size)
        for gate in ("i", "lf", "rf", "u", "o"):
            setattr(self, f"comp_{gate}_l", Linear(hidden_size, hidden_size))
            setattr(self, f"comp_{gate}_r", Linear(hidden_size, hidden_size))

    def _leaf(self, x):
        c = self.leaf_c.forward(x)
        if self.gate_output:
            h = jax.nn.sigmoid(self.leaf_o.forward(x)) * jnp.tanh(c)
        else:
            h = jnp.tanh(c)
        return c, h

    def _compose(self, lc, lh, rc, rh):
        def gate(name):
            return (getattr(self, f"comp_{name}_l").forward(lh) +
                    getattr(self, f"comp_{name}_r").forward(rh))

        i = jax.nn.sigmoid(gate("i"))
        lf = jax.nn.sigmoid(gate("lf"))
        rf = jax.nn.sigmoid(gate("rf"))
        u = jnp.tanh(gate("u"))
        c = i * u + lf * lc + rf * rc
        if self.gate_output:
            h = jax.nn.sigmoid(gate("o")) * jnp.tanh(c)
        else:
            h = jnp.tanh(c)
        return c, h

    def update_output(self, input):
        embeddings, trees = input
        trees = jnp.asarray(trees).astype(jnp.int32)
        b, node_num = trees.shape[0], trees.shape[1]
        hid = self.hidden_size

        left = trees[:, :, 0]      # [B, N], 1-based; 0 = none
        right = trees[:, :, 1]
        leaf_idx = trees[:, :, 2]  # 1-based index into embeddings
        is_leaf = (left == 0) & (right == 0)
        is_node = jnp.any(trees != 0, axis=-1)  # padding rows are all-zero

        # leaf candidates for every slot (gather with clamped indices)
        gath = jnp.take_along_axis(
            embeddings, jnp.maximum(leaf_idx - 1, 0)[:, :, None], axis=1)
        leaf_c, leaf_h = self._leaf(gath)  # [B, N, hid]

        # state slot 0 is the "absent child" zero state
        c0 = jnp.zeros((b, node_num + 1, hid), leaf_c.dtype)
        h0 = jnp.zeros_like(c0)
        ready0 = jnp.concatenate(
            [jnp.ones((b, 1), bool), jnp.zeros((b, node_num), bool)], axis=1)

        leaf_mask = is_leaf & is_node
        c0 = c0.at[:, 1:].set(jnp.where(leaf_mask[:, :, None], leaf_c, 0.0))
        h0 = h0.at[:, 1:].set(jnp.where(leaf_mask[:, :, None], leaf_h, 0.0))
        ready0 = ready0.at[:, 1:].set(leaf_mask)

        def round_fn(carry, _):
            c, h, ready = carry
            lc = jnp.take_along_axis(c, left[:, :, None], axis=1)
            lh = jnp.take_along_axis(h, left[:, :, None], axis=1)
            rc = jnp.take_along_axis(c, right[:, :, None], axis=1)
            rh = jnp.take_along_axis(h, right[:, :, None], axis=1)
            cand_c, cand_h = self._compose(lc, lh, rc, rh)  # [B, N, hid]
            l_ready = jnp.take_along_axis(ready, left, axis=1)
            r_ready = jnp.take_along_axis(ready, right, axis=1)
            newly = (~is_leaf) & is_node & l_ready & r_ready \
                & ~ready[:, 1:]
            c = c.at[:, 1:].set(jnp.where(newly[:, :, None], cand_c,
                                          c[:, 1:]))
            h = h.at[:, 1:].set(jnp.where(newly[:, :, None], cand_h,
                                          h[:, 1:]))
            ready = ready.at[:, 1:].set(ready[:, 1:] | newly)
            return (c, h, ready), None

        # depth <= node_num rounds; scan keeps it reverse-differentiable
        (c, h, ready), _ = lax.scan(round_fn, (c0, h0, ready0), None,
                                    length=node_num)
        return h[:, 1:, :]


class Nms(Module):
    """Greedy IoU non-max suppression (``nn/Nms.scala``): input =
    (boxes [N, 4] xyxy, scores [N]); returns (keep_indices [max_out],
    valid_count) with -1 padding.  Forward-only; O(N^2) masked, expressed
    as a fori_loop so it lowers to one XLA computation."""

    def __init__(self, threshold: float = 0.3, max_output: int = 100):
        super().__init__()
        self.threshold = threshold
        self.max_output = max_output

    def update_output(self, input):
        boxes, scores = input
        n = boxes.shape[0]
        x1, y1, x2, y2 = (boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3])
        areas = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)

        ix1 = jnp.maximum(x1[:, None], x1[None, :])
        iy1 = jnp.maximum(y1[:, None], y1[None, :])
        ix2 = jnp.minimum(x2[:, None], x2[None, :])
        iy2 = jnp.minimum(y2[:, None], y2[None, :])
        inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
        iou = inter / jnp.maximum(areas[:, None] + areas[None, :] - inter,
                                  1e-10)

        max_out = min(self.max_output, n)

        def body(i, carry):
            alive, keep, count = carry
            masked = jnp.where(alive, scores, -jnp.inf)
            best = jnp.argmax(masked)
            valid = masked[best] > -jnp.inf
            keep = keep.at[i].set(jnp.where(valid, best, -1))
            count = count + valid.astype(jnp.int32)
            suppress = iou[best] > self.threshold
            alive = alive & ~suppress & ~(jnp.arange(n) == best)
            alive = alive & valid  # once empty, stay empty
            return alive, keep, count

        alive0 = jnp.ones((n,), bool)
        keep0 = jnp.full((max_out,), -1, jnp.int32)
        _, keep, count = lax.fori_loop(0, max_out, body,
                                       (alive0, keep0, jnp.int32(0)))
        return keep, count
