"""Activation layers (SURVEY §2.5 "Activations" — one class per reference
file under ``nn/``: ReLU, ReLU6, PReLU, RReLU, LeakyReLU, ELU, Tanh,
TanhShrink, Sigmoid, LogSigmoid, SoftMax, SoftMin, LogSoftMax, SoftPlus,
SoftShrink, HardShrink, HardTanh, Clamp, Threshold, Power, Square, Sqrt,
Log, Exp, Abs, GradientReversal).

All are stateless elementwise maps — XLA fuses them into adjacent matmuls,
so no hand kernels are needed (the reference's MKL VML dispatch in
``tensor/DenseTensorMath.scala:313-401`` is subsumed by the compiler).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.init import Zeros, ConstInitMethod
from bigdl_tpu.nn.module import Module, Parameter
from bigdl_tpu.utils.rng import next_rng_id, require_rng

__all__ = [
    "ReLU", "ReLU6", "PReLU", "RReLU", "LeakyReLU", "ELU", "Tanh",
    "TanhShrink", "Sigmoid", "LogSigmoid", "SoftSign", "SoftMax", "SoftMin",
    "LogSoftMax", "SoftPlus", "SoftShrink", "HardShrink", "HardTanh",
    "Clamp", "Threshold", "Power", "Square", "Sqrt", "Log", "Exp", "Abs",
    "GradientReversal",
]


@jax.custom_vjp
def _relu_outgrad(x):
    return jnp.maximum(x, 0)


def _relu_outgrad_fwd(x):
    y = jnp.maximum(x, 0)
    return y, y


def _relu_outgrad_bwd(y, gy):
    return (jnp.where(y > 0, gy, jnp.zeros((), gy.dtype)),)


_relu_outgrad.defvjp(_relu_outgrad_fwd, _relu_outgrad_bwd)


@jax.custom_vjp
def _relu6_outgrad(x):
    return jnp.clip(x, 0.0, 6.0)


def _relu6_outgrad_fwd(x):
    y = jnp.clip(x, 0.0, 6.0)
    return y, y


def _relu6_outgrad_bwd(y, gy):
    keep = (y > 0) & (y < 6.0)
    return (jnp.where(keep, gy, jnp.zeros((), gy.dtype)),)


_relu6_outgrad.defvjp(_relu6_outgrad_fwd, _relu6_outgrad_bwd)


class ReLU(Module):
    """The backward is expressed in terms of the OUTPUT (``gy * (y>0)``,
    same zero-at-origin convention as ``jax.nn.relu``) so autodiff never
    keeps the pre-activation tensor alive — XLA then fuses conv+bias+relu
    into one kernel and materializes each activation map once instead of
    twice (measured ~10% of the Inception-v1 train step on TPU v5e)."""

    def __init__(self, ip: bool = False):
        super().__init__()

    def update_output(self, input):
        if jnp.issubdtype(jnp.asarray(input).dtype, jnp.floating):
            return _relu_outgrad(input)
        return jax.nn.relu(input)


class ReLU6(Module):
    def update_output(self, input):
        if jnp.issubdtype(jnp.asarray(input).dtype, jnp.floating):
            return _relu6_outgrad(input)
        return jnp.clip(input, 0.0, 6.0)


class PReLU(Module):
    """Learnable leaky slope; n_output_plane=0 shares one slope
    (``nn/PReLU.scala``)."""

    def __init__(self, n_output_plane: int = 0):
        super().__init__()
        self.n_output_plane = n_output_plane
        n = max(1, n_output_plane)
        self.weight = Parameter(jnp.full((n,), 0.25, jnp.float32))

    def reset(self):
        n = max(1, self.n_output_plane)
        self.weight = jnp.full((n,), 0.25, jnp.float32)

    def update_output(self, input):
        w = self.weight
        if self.n_output_plane > 0:
            # channel axis is 1 for batched NCHW-style input, 0 otherwise
            shape = [1] * input.ndim
            ch_axis = 1 if input.ndim > 1 else 0
            shape[ch_axis] = self.n_output_plane
            w = w.reshape(shape)
        return jnp.where(input > 0, input, w * input)


class RReLU(Module):
    """Randomized leaky ReLU: slope ~ U(lower, upper) in training, the mean
    slope in eval (``nn/RReLU.scala``)."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3, ip: bool = False):
        super().__init__()
        self.lower, self.upper = lower, upper
        self._rng_id = next_rng_id()

    def update_output(self, input):
        if self.training:
            key = require_rng(self._rng_id)
            a = jax.random.uniform(key, jnp.shape(input), input.dtype, self.lower, self.upper)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.where(input >= 0, input, a * input)


class LeakyReLU(Module):
    def __init__(self, negval: float = 0.01, ip: bool = False):
        super().__init__()
        self.negval = negval

    def update_output(self, input):
        return jnp.where(input >= 0, input, self.negval * input)


class ELU(Module):
    def __init__(self, alpha: float = 1.0, ip: bool = False):
        super().__init__()
        self.alpha = alpha

    def update_output(self, input):
        return jnp.where(input > 0, input, self.alpha * jnp.expm1(input))


class Tanh(Module):
    def update_output(self, input):
        return jnp.tanh(input)


class TanhShrink(Module):
    def update_output(self, input):
        return input - jnp.tanh(input)


class Sigmoid(Module):
    def update_output(self, input):
        return jax.nn.sigmoid(input)


class LogSigmoid(Module):
    def update_output(self, input):
        return jax.nn.log_sigmoid(input)


class SoftSign(Module):
    """x / (1 + |x|) (``nn/SoftSign.scala:31``)."""

    def update_output(self, input):
        return input / (1.0 + jnp.abs(input))


class SoftMax(Module):
    """Softmax over the feature axis (``nn/SoftMax.scala``: dim 1 of
    [batch, n] or the only dim of [n]); ``axis`` overrides (extension,
    used by torch interop for dim=-1 semantics)."""

    def __init__(self, axis=None):
        super().__init__()
        self.axis = axis

    def update_output(self, input):
        axis = self.axis if self.axis is not None \
            else (1 if input.ndim >= 2 else 0)
        return jax.nn.softmax(input, axis=axis)


class SoftMin(Module):
    def update_output(self, input):
        axis = 1 if input.ndim >= 2 else 0
        return jax.nn.softmax(-input, axis=axis)


class LogSoftMax(Module):
    """(``nn/LogSoftMax.scala:21`` — MKL-accelerated there; XLA-fused
    here); ``axis`` overrides the feature-axis default (extension)."""

    def __init__(self, axis=None):
        super().__init__()
        self.axis = axis

    def update_output(self, input):
        axis = self.axis if self.axis is not None \
            else (1 if input.ndim >= 2 else 0)
        return jax.nn.log_softmax(input, axis=axis)


class SoftPlus(Module):
    def __init__(self, beta: float = 1.0):
        super().__init__()
        self.beta = beta

    def update_output(self, input):
        return jax.nn.softplus(self.beta * input) / self.beta


class SoftShrink(Module):
    def __init__(self, lam: float = 0.5):
        super().__init__()
        self.lam = lam

    def update_output(self, input):
        return jnp.where(input > self.lam, input - self.lam,
                         jnp.where(input < -self.lam, input + self.lam, 0.0))


class HardShrink(Module):
    def __init__(self, lam: float = 0.5):
        super().__init__()
        self.lam = lam

    def update_output(self, input):
        return jnp.where(jnp.abs(input) > self.lam, input, 0.0)


class HardTanh(Module):
    def __init__(self, min_value: float = -1.0, max_value: float = 1.0, ip: bool = False):
        super().__init__()
        self.min_value, self.max_value = min_value, max_value

    def update_output(self, input):
        return jnp.clip(input, self.min_value, self.max_value)


class Clamp(HardTanh):
    def __init__(self, min_value: float, max_value: float):
        super().__init__(min_value, max_value)


class Threshold(Module):
    def __init__(self, th: float = 1e-6, v: float = 0.0, ip: bool = False):
        super().__init__()
        self.th, self.v = th, v

    def update_output(self, input):
        return jnp.where(input > self.th, input, self.v)


class Power(Module):
    """(shift + scale * x) ** power (``nn/Power.scala``)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0):
        super().__init__()
        self.power, self.scale, self.shift = power, scale, shift

    def update_output(self, input):
        return jnp.power(self.shift + self.scale * input, self.power)


class Square(Module):
    def update_output(self, input):
        return input * input


class Sqrt(Module):
    def update_output(self, input):
        return jnp.sqrt(input)


class Log(Module):
    def update_output(self, input):
        return jnp.log(input)


class Exp(Module):
    def update_output(self, input):
        return jnp.exp(input)


class Abs(Module):
    def update_output(self, input):
        return jnp.abs(input)


class GradientReversal(Module):
    """Identity forward, negated+scaled gradient (``nn/GradientReversal.scala``)."""

    def __init__(self, lam: float = 1.0):
        super().__init__()
        self.lam = lam

    def update_output(self, input):
        lam = self.lam

        @jax.custom_vjp
        def rev(x):
            return x

        def fwd(x):
            return x, None

        def bwd(_, g):
            return (-lam * g,)

        rev.defvjp(fwd, bwd)
        return rev(input)

    def set_lambda(self, lam: float):
        self.lam = lam
        return self
