"""Recurrent layers (SURVEY §2.4-§2.5: Cell, Recurrent, RecurrentDecoder,
BiRecurrent, RnnCell, LSTM, LSTMPeephole, GRU, ConvLSTMPeephole,
ConvLSTMPeephole3D).

TPU-first redesign of the reference's time loop: ``Recurrent`` lowers to
``jax.lax.scan`` (one compiled step body, no per-timestep Python), and each
cell's input projection (the reference's ``preTopology`` hoisting,
``nn/Cell.scala:46`` / ``nn/Recurrent.scala:121+``) is applied to the whole
[batch*time] block as a single large MXU matmul before the scan.

Layout: [batch, time, ...] like the reference.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.init import RandomUniform
from bigdl_tpu.nn.layers.conv import SpatialConvolution, VolumetricConvolution
from bigdl_tpu.nn.layers.linear import Linear
from bigdl_tpu.nn.module import Container, Module, Parameter

__all__ = [
    "Cell", "RnnCell", "LSTM", "LSTMPeephole", "GRU",
    "ConvLSTMPeephole", "ConvLSTMPeephole3D",
    "Recurrent", "RecurrentDecoder", "BiRecurrent",
]


class Cell(Container):
    """RNN cell contract (``nn/Cell.scala:46``): ``initial_state`` sizes the
    carry, ``pre_topology`` is hoisted out of the time loop, ``step``
    advances one timestep."""

    hidden_size: int

    def initial_state(self, batch_size: int, dtype=jnp.float32):
        raise NotImplementedError

    def pre_topology(self) -> Optional[Module]:
        return None

    def step(self, x_t, state):
        """(pre-projected x_t, state) -> (output_t, new_state)."""
        raise NotImplementedError

    def update_output(self, input):
        """Single-step eager use: input = (x_t, state)."""
        x_t, state = input
        return self.step(x_t, state)


class RnnCell(Cell):
    """Elman RNN cell (``nn/RNN.scala``): h' = act(W_ih x + b_ih + W_hh h + b_hh)."""

    def __init__(self, input_size: int, hidden_size: int, activation: Optional[Module] = None,
                 isInputWithBias: bool = True, w_regularizer=None, u_regularizer=None,
                 b_regularizer=None):
        super().__init__()
        from bigdl_tpu.nn.layers.activation import Tanh

        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation if activation is not None else Tanh()
        self.i2h = Linear(input_size, hidden_size, with_bias=isInputWithBias,
                          w_regularizer=w_regularizer, b_regularizer=b_regularizer)
        self.h2h = Linear(hidden_size, hidden_size, w_regularizer=u_regularizer)

    def initial_state(self, batch_size, dtype=jnp.float32):
        return jnp.zeros((batch_size, self.hidden_size), dtype)

    def pre_topology(self):
        return self.i2h

    def step(self, x_t, state):
        h = self.activation.forward(x_t + self.h2h.forward(state))
        return h, h


def _make_dropouts(cell: Cell, p: float):
    """Gate-input dropout parity with the reference cells (``nn/LSTM.scala``
    applies Dropout(p) on the x and h projections).  The x-side mask is drawn
    per timestep (applied in the hoisted pre-projection over [B*T]); the
    h-side mask is drawn once per sequence inside the scan body — i.e.
    variational dropout, the deterministic-under-scan choice."""
    if p > 0:
        from bigdl_tpu.nn.layers.normalization import Dropout

        cell.dropout_x = Dropout(p)
        cell.dropout_h = Dropout(p)


def _pre_with_dropout(cell: Cell, proj: Module) -> Module:
    if cell.p > 0:
        from bigdl_tpu.nn.module import Sequential

        return Sequential(cell.dropout_x, proj)
    return proj


def _drop_h(cell: Cell, h):
    return cell.dropout_h.forward(h) if cell.p > 0 else h


class LSTM(Cell):
    """Standard LSTM (``nn/LSTM.scala``).  Gate order (i, f, g, o) packed in
    one 4*hidden projection so the scan body is two matmuls."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.p = p
        self.i2g = Linear(input_size, 4 * hidden_size,
                          w_regularizer=w_regularizer, b_regularizer=b_regularizer)
        self.h2g = Linear(hidden_size, 4 * hidden_size, with_bias=False,
                          w_regularizer=u_regularizer)
        _make_dropouts(self, p)

    def initial_state(self, batch_size, dtype=jnp.float32):
        z = jnp.zeros((batch_size, self.hidden_size), dtype)
        return (z, z)

    def pre_topology(self):
        return _pre_with_dropout(self, self.i2g)

    def step(self, x_t, state):
        h, c = state
        gates = x_t + self.h2g.forward(_drop_h(self, h))
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class LSTMPeephole(Cell):
    """LSTM with peephole connections from the cell state to the gates
    (``nn/LSTMPeephole.scala``)."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.p = p
        self.i2g = Linear(input_size, 4 * hidden_size,
                          w_regularizer=w_regularizer, b_regularizer=b_regularizer)
        self.h2g = Linear(hidden_size, 4 * hidden_size, with_bias=False,
                          w_regularizer=u_regularizer)
        self.peep_i = Parameter(jnp.zeros((hidden_size,), jnp.float32))
        self.peep_f = Parameter(jnp.zeros((hidden_size,), jnp.float32))
        self.peep_o = Parameter(jnp.zeros((hidden_size,), jnp.float32))
        _make_dropouts(self, p)

    def initial_state(self, batch_size, dtype=jnp.float32):
        z = jnp.zeros((batch_size, self.hidden_size), dtype)
        return (z, z)

    def pre_topology(self):
        return _pre_with_dropout(self, self.i2g)

    def step(self, x_t, state):
        h, c = state
        gates = x_t + self.h2g.forward(_drop_h(self, h))
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i + self.peep_i * c)
        f = jax.nn.sigmoid(f + self.peep_f * c)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        o = jax.nn.sigmoid(o + self.peep_o * c_new)
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class GRU(Cell):
    """GRU (``nn/GRU.scala``): r/z from packed projections, candidate uses
    the reset-gated hidden state."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.p = p
        self.i2g = Linear(input_size, 3 * hidden_size,
                          w_regularizer=w_regularizer, b_regularizer=b_regularizer)
        self.h2rz = Linear(hidden_size, 2 * hidden_size, with_bias=False,
                           w_regularizer=u_regularizer)
        self.h2n = Linear(hidden_size, hidden_size, with_bias=False,
                          w_regularizer=u_regularizer)
        _make_dropouts(self, p)

    def initial_state(self, batch_size, dtype=jnp.float32):
        return jnp.zeros((batch_size, self.hidden_size), dtype)

    def pre_topology(self):
        return _pre_with_dropout(self, self.i2g)

    def step(self, x_t, state):
        x_r, x_z, x_n = jnp.split(x_t, 3, axis=-1)
        h_in = _drop_h(self, state)
        h_r, h_z = jnp.split(self.h2rz.forward(h_in), 2, axis=-1)
        r = jax.nn.sigmoid(x_r + h_r)
        z = jax.nn.sigmoid(x_z + h_z)
        n = jnp.tanh(x_n + r * self.h2n.forward(h_in))
        h_new = (1.0 - z) * n + z * state
        return h_new, h_new


class ConvLSTMPeephole(Cell):
    """Convolutional LSTM over [batch, time, C, H, W]
    (``nn/ConvLSTMPeephole.scala``); gates are SAME-padded convolutions."""

    def __init__(self, input_size: int, output_size: int, kernel_i: int, kernel_c: int,
                 stride: int = 1, with_peephole: bool = True,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, output_size
        self.output_size = output_size
        self.with_peephole = with_peephole
        self.i2g = SpatialConvolution(input_size, 4 * output_size, kernel_i, kernel_i,
                                      stride, stride, -1, -1,
                                      w_regularizer=w_regularizer, b_regularizer=b_regularizer)
        self.h2g = SpatialConvolution(output_size, 4 * output_size, kernel_c, kernel_c,
                                      1, 1, -1, -1, with_bias=False,
                                      w_regularizer=u_regularizer)
        if with_peephole:
            self.peep_i = Parameter(jnp.zeros((output_size, 1, 1), jnp.float32))
            self.peep_f = Parameter(jnp.zeros((output_size, 1, 1), jnp.float32))
            self.peep_o = Parameter(jnp.zeros((output_size, 1, 1), jnp.float32))
        self._spatial = None  # set lazily from input

    def initial_state(self, batch_size, dtype=jnp.float32, spatial=None):
        if spatial is None:
            spatial = self._spatial
        h, w = spatial
        z = jnp.zeros((batch_size, self.output_size, h, w), dtype)
        return (z, z)

    def pre_topology(self):
        return self.i2g

    def step(self, x_t, state):
        h, c = state
        gates = x_t + self.h2g.forward(h)
        i, f, g, o = jnp.split(gates, 4, axis=1)
        if self.with_peephole:
            i = jax.nn.sigmoid(i + self.peep_i * c)
            f = jax.nn.sigmoid(f + self.peep_f * c)
        else:
            i, f = jax.nn.sigmoid(i), jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        if self.with_peephole:
            o = jax.nn.sigmoid(o + self.peep_o * c_new)
        else:
            o = jax.nn.sigmoid(o)
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class ConvLSTMPeephole3D(ConvLSTMPeephole):
    """3-D ConvLSTM over [batch, time, C, T, H, W]
    (``nn/ConvLSTMPeephole3D.scala``)."""

    def __init__(self, input_size: int, output_size: int, kernel_i: int, kernel_c: int,
                 stride: int = 1, with_peephole: bool = True,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None):
        Cell.__init__(self)
        self.input_size, self.hidden_size = input_size, output_size
        self.output_size = output_size
        self.with_peephole = with_peephole
        pad = (kernel_i - 1) // 2
        pad_c = (kernel_c - 1) // 2
        self.i2g = VolumetricConvolution(input_size, 4 * output_size,
                                         kernel_i, kernel_i, kernel_i, stride, stride, stride,
                                         pad, pad, pad,
                                         w_regularizer=w_regularizer, b_regularizer=b_regularizer)
        self.h2g = VolumetricConvolution(output_size, 4 * output_size,
                                         kernel_c, kernel_c, kernel_c, 1, 1, 1,
                                         pad_c, pad_c, pad_c, with_bias=False,
                                         w_regularizer=u_regularizer)
        if with_peephole:
            self.peep_i = Parameter(jnp.zeros((output_size, 1, 1, 1), jnp.float32))
            self.peep_f = Parameter(jnp.zeros((output_size, 1, 1, 1), jnp.float32))
            self.peep_o = Parameter(jnp.zeros((output_size, 1, 1, 1), jnp.float32))
        self._spatial = None

    def initial_state(self, batch_size, dtype=jnp.float32, spatial=None):
        if spatial is None:
            spatial = self._spatial
        t, h, w = spatial
        z = jnp.zeros((batch_size, self.output_size, t, h, w), dtype)
        return (z, z)


class Recurrent(Container):
    """Time-loop container over [batch, time, ...] (``nn/Recurrent.scala:36``):
    hoists the cell's pre-projection over all timesteps, then ``lax.scan``s
    the step body."""

    def __init__(self, cell: Optional[Cell] = None):
        super().__init__()
        if cell is not None:
            self.add(cell)
        self._last_state = None
        self._init_state_override = None
        self._remat_cell = False
        self._trace_attrs = ("_last_state",)

    def remat_cell(self):
        """Recompute the cell body in the backward pass instead of
        saving its intermediates.  The round-5 TPU profile of the large
        LSTM config put ~21% of the step in residual stacking (the
        [T, B, 4H] gate pre-activation buffer's init broadcast +
        dynamic-update-slice writes); rematerialization trades that HBM
        traffic for one extra fused-gate matmul per step in the
        backward.  Opt-in — measure per shape
        (``tools/experiments/exp_lstm_remat.py``)."""
        self._remat_cell = True
        return self

    @property
    def cell(self) -> Cell:
        return self.layers[0]

    def get_hidden_state(self):
        return self._last_state

    def set_hidden_state(self, state):
        self._init_state_override = state
        return self

    def _pre_apply(self, input):
        pre = self.cell.pre_topology()
        if pre is None:
            return input
        b, t = input.shape[0], input.shape[1]
        flat = input.reshape((b * t,) + input.shape[2:])
        out = pre.forward(flat)
        return out.reshape((b, t) + out.shape[1:])

    def _initial_state(self, pre_x):
        """Size the carry from the PRE-PROJECTED input so strided ConvLSTM
        gate convolutions see matching spatial dims."""
        if self._init_state_override is not None:
            return self._init_state_override
        cell = self.cell
        if isinstance(cell, ConvLSTMPeephole):
            cell._spatial = pre_x.shape[3:]
        return cell.initial_state(pre_x.shape[0], pre_x.dtype)

    def update_output(self, input):
        cell = self.cell
        x = self._pre_apply(input)
        state0 = self._initial_state(x)
        xs = jnp.moveaxis(x, 1, 0)  # [T, B, ...]

        def body(state, x_t):
            out_t, new_state = cell.step(x_t, state)
            return new_state, out_t

        if self._remat_cell:
            body = jax.checkpoint(body)
        final_state, outs = lax.scan(body, state0, xs)
        self._last_state = final_state
        return jnp.moveaxis(outs, 0, 1)


class RecurrentDecoder(Recurrent):
    """Decoder loop feeding the output back as the next input for
    ``output_length`` steps (``nn/RecurrentDecoder.scala``).  Input is the
    first-step input [batch, ...]."""

    def __init__(self, output_length: int, cell: Optional[Cell] = None):
        super().__init__(cell)
        self.output_length = output_length

    def update_output(self, input):
        cell = self.cell
        if isinstance(cell, ConvLSTMPeephole):
            cell._spatial = input.shape[2:]
        state0 = self._init_state_override if self._init_state_override is not None \
            else cell.initial_state(input.shape[0], input.dtype)
        pre = cell.pre_topology()

        def body(carry, _):
            x, state = carry
            x_proj = pre.forward(x) if pre is not None else x
            out_t, new_state = cell.step(x_proj, state)
            return (out_t, new_state), out_t

        (_, final_state), outs = lax.scan(
            body, (input, state0), None, length=self.output_length)
        self._last_state = final_state
        return jnp.moveaxis(outs, 0, 1)


class BiRecurrent(Container):
    """Bidirectional wrapper (``nn/BiRecurrent.scala``): forward pass +
    time-reversed pass, merged (default JoinTable on the feature dim)."""

    def __init__(self, merge: Optional[Module] = None, cell: Optional[Cell] = None):
        super().__init__()
        if cell is not None:
            self.fwd = Recurrent(cell)
            self.bwd = Recurrent(cell.clone_module())
        self.merge = merge

    def with_cell(self, cell: Cell) -> "BiRecurrent":
        self.fwd = Recurrent(cell)
        self.bwd = Recurrent(cell.clone_module())
        return self

    def update_output(self, input):
        out_f = self.fwd.forward(input)
        out_b = jnp.flip(self.bwd.forward(jnp.flip(input, 1)), 1)
        if self.merge is not None:
            return self.merge.forward([out_f, out_b])
        return jnp.concatenate([out_f, out_b], axis=-1)
