"""Embedding layers with a sparse-gradient fast path (ROADMAP item 3,
per *Parallax: Sparsity-aware Data Parallel Training*, arXiv 1808.02621).

The problem: an embedding forward is a gather, so its parameter gradient
is a **scatter-add into a mostly-zero ``[vocab, dim]`` table** — and the
data-parallel sync then all-reduces the whole mostly-zero table every
step (lstm_text at MFU 0.02 is this bill).  Sparse and dense parameters
deserve different sync paths: a batch touches at most
``min(n_lookups, vocab)`` rows, so the gradient IS ``(indices, rows)``
pairs, and only those should cross the interconnect.

How the row-sparse cotangent works (the "custom VJP" is structural, not
a ``jax.custom_vjp`` — the cotangent of a *parameter* must match its
aval, so the table is routed around differentiation instead):

- ``TrainStep`` opens a :class:`SparseCapture` around the traced
  forward.  A sparse-active embedding then **unique-coalesces** its flat
  index vector (``jnp.unique(size=min(L, V), fill_value=V)`` — static
  shape, duplicate indices mapped onto one slot), gathers the touched
  rows from the **stop-gradiented** table, and adds a zeros **proxy**
  array fetched from the capture.  The proxy is a differentiated input
  of the step's loss function, so its cotangent is exactly the coalesced
  per-row gradient ``[slots, dim]`` — duplicates summed by the gather's
  own VJP, padding-index rows masked to zero — and the dense
  ``[vocab, dim]`` scatter never exists in the backward.
- The capture also records each call's unique-index vector ``u`` (as a
  loss-function aux output), so the update step can scatter-add the
  synced rows once into the table — see
  ``OptimMethod.update_mixed``/``_apply_sparse`` for the lazy row-wise
  Adagrad/SGD applies.
- Outside a capture (eager use, ``EvalStep``, serving) the layers run
  the plain dense gather — inference never pays the coalesce.

When dense wins (docs/sparse.md): the coalesce cap is
``min(n_lookups, vocab)``, so once a batch's lookup count approaches the
vocab (long-sequence LMs over small vocabs) the "sparse" rows are the
table and the sync saves nothing.  ``sparse=None`` (auto) therefore
activates only when ``2 * n_lookups <= vocab``; ``sparse=True`` forces
the sparse path, ``sparse=False``/``BIGDL_SPARSE=off`` force dense.
Exactness guardrails: ``max_norm`` renorm is differentiated through on
the dense path, so a renormed table always syncs dense; a regularized
or value-clipped-outside-zero table does too (``TrainStep`` owns those
checks).
"""

from __future__ import annotations

import contextvars
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module, Parameter

__all__ = ["LookupTable", "EmbeddingBag", "SparseCapture", "sparse_tables",
           "sparse_enabled", "discover_proxies", "sparse_sync_stats",
           "row_sharding_rules"]

#: the active capture (None = dense everywhere).  A ContextVar so nested
#: traces and threaded servers can never see another trace's capture.
_CAPTURE: contextvars.ContextVar[Optional["SparseCapture"]] = \
    contextvars.ContextVar("bigdl_sparse_capture", default=None)


def sparse_enabled() -> bool:
    """Global sparse-sync switch (``BIGDL_SPARSE`` off/auto/on; default
    auto).  ``off`` kills the path process-wide — the dense-baseline leg
    of every A/B."""
    from bigdl_tpu.utils.config import get_config

    mode = (get_config().sparse_sync or "auto").strip().lower()
    return mode not in ("0", "off", "false", "no")


def _sparse_forced() -> bool:
    from bigdl_tpu.utils.config import get_config

    return (get_config().sparse_sync or "auto").strip().lower() \
        in ("1", "on", "true", "yes")


class SparseCapture:
    """Trace-scoped registry connecting sparse embedding layers to the
    training step.

    ``mode='discover'``: an abstract (``jax.eval_shape``) forward runs
    under it; each sparse-active call *requests* a proxy shape, which is
    recorded and answered with zeros.  ``mode='bind'``: the real traced
    forward runs under it; each call *fetches* its proxy (a
    differentiated input of the loss) by the same deterministic key
    ``<param_path>#<call_index>`` and records its unique-index vector.
    The forward runs once per jit trace, so call indices line up between
    the two passes by construction."""

    def __init__(self, paths: Dict[int, str],
                 proxies: Optional[Dict[str, jax.Array]] = None):
        #: id(module) -> param path ("features.0.weight")
        self.paths = paths
        self.mode = "bind" if proxies is not None else "discover"
        self.proxies = proxies or {}
        self.shapes: Dict[str, jax.ShapeDtypeStruct] = {}
        #: key -> {"path", "u", "slots", "vocab", "dim"} (bind mode: the
        #: aux the loss function returns to the update step)
        self.aux: Dict[str, Dict[str, Any]] = {}
        self._calls: Dict[int, int] = {}
        self._token = None

    # -- context management ------------------------------------------------
    def __enter__(self):
        self._token = _CAPTURE.set(self)
        return self

    def __exit__(self, *exc):
        _CAPTURE.reset(self._token)
        return False

    # -- layer-side API ----------------------------------------------------
    def wants(self, module) -> bool:
        return id(module) in self.paths

    def next_key(self, module) -> str:
        n = self._calls.get(id(module), 0)
        self._calls[id(module)] = n + 1
        return f"{self.paths[id(module)]}#{n}"

    def proxy(self, key: str, shape: Tuple[int, ...], dtype) -> jax.Array:
        if self.mode == "discover":
            self.shapes[key] = jax.ShapeDtypeStruct(shape, dtype)
            return jnp.zeros(shape, dtype)
        if key not in self.proxies:
            # a forward that takes a different path between discovery
            # and the real trace would silently drop this table's
            # gradient — fail the trace loudly instead
            raise RuntimeError(
                f"sparse capture has no proxy for {key!r} — the traced "
                f"forward requested a slot discovery did not see")
        return self.proxies[key]

    def record(self, key: str, u: jax.Array, vocab: int, dim: int) -> None:
        self.aux[key] = {"path": key.split("#", 1)[0], "u": u,
                         "slots": int(u.shape[0]), "vocab": vocab,
                         "dim": dim}


def current_capture() -> Optional[SparseCapture]:
    return _CAPTURE.get()


def sparse_tables(model: Module) -> Dict[str, Module]:
    """``{param_path: module}`` for every sparse-capable embedding table
    of ``model``.  A module registered under several paths (weight
    tying) is excluded — its calls would need per-path cotangent
    routing the proxy keying deliberately does not attempt."""
    found: Dict[str, Module] = {}
    owners: Dict[int, str] = {}
    shared = set()
    for name, m in model.named_modules():
        if not getattr(m, "_sparse_capable", False):
            continue
        if getattr(m, "sparse", None) is False:
            continue
        if id(m) in owners:
            shared.add(id(m))
            continue
        owners[id(m)] = name
        path = f"{name}.weight" if name else "weight"
        found[path] = m
    return {p: m for p, m in found.items() if id(m) not in shared}


def discover_proxies(call, paths: Dict[int, str]
                     ) -> Tuple[Dict[str, jax.ShapeDtypeStruct],
                                Dict[str, Dict[str, Any]]]:
    """Abstractly evaluate ``call()`` (a thunk running the traced
    forward; it may close over outer-trace tracers) under a discovery
    capture to learn which proxies the real trace will request and
    their shapes — one ``jax.eval_shape`` pass, no FLOPs.  Returns
    ``(shapes, metas)``: proxy ShapeDtypeStructs and the static per-key
    facts (path/slots/vocab/dim) by the same keys the bind-mode capture
    will use."""
    cap = SparseCapture(paths, proxies=None)

    def absfn():
        with cap:
            call()
        return jnp.zeros(())

    jax.eval_shape(absfn)
    metas = {k: {kk: vv for kk, vv in v.items() if kk != "u"}
             for k, v in cap.aux.items()}
    return cap.shapes, metas


def _gather_rows(module, w, idx, padding_idx: Optional[int]):
    """``w[idx]`` with the row-sparse cotangent capture when active.

    ``idx`` is integer, any shape; returns ``idx.shape + (dim,)``.
    Padding-index semantics here are *gradient-only* (the row's value is
    still gathered; LookupTable keeps it, EmbeddingBag masks the value
    separately): the padding row's cotangent is zeroed on both paths so
    sparse and dense stay numerics-equal."""
    V, D = int(w.shape[0]), int(w.shape[1])
    cap = current_capture()
    if cap is not None and cap.wants(module):
        key = cap.next_key(module)
        if module._sparse_active(idx.size, V):
            return _sparse_gather(module, cap, key, w, idx, V, D,
                                  padding_idx)
    # dense path: block the padding row's gradient without touching its
    # value — the select routes padding POSITIONS' cotangents into the
    # stopped branch, so the table grad at the padding row is zero.
    # O(output) and fusable with the gather (a `.at[padding_idx].set`
    # on the table would copy the whole [vocab, dim] array per forward,
    # a real bill for serving-sized tables).
    rows = w[idx]
    if padding_idx is not None:
        rows = jnp.where((idx != padding_idx)[..., None], rows,
                         jax.lax.stop_gradient(rows))
    return rows


def _sparse_gather(module, cap: SparseCapture, key: str, w, idx,
                   V: int, D: int, padding_idx: Optional[int]):
    flat = idx.reshape(-1)
    slots = min(int(flat.size), V)
    # fill_value=V: unused slots scatter out-of-bounds at update time
    # (mode='drop'), so padding the unique set can never touch row 0
    u, inv = jnp.unique(flat, size=slots, fill_value=V,
                        return_inverse=True)
    rows = jax.lax.stop_gradient(w)[jnp.clip(u, 0, V - 1)]
    proxy = cap.proxy(key, (slots, D), rows.dtype)
    if padding_idx is not None:
        # zero the padding slot's cotangent inside the VJP itself —
        # the row's VALUE (from the stop-gradiented gather) is kept
        proxy = proxy * (u != padding_idx)[:, None].astype(proxy.dtype)
    rows = rows + proxy
    cap.record(key, u, V, D)
    return rows[inv.reshape(-1)].reshape(idx.shape + (D,))


class _EmbeddingBase(Module):
    """Shared machinery: the table parameter, index normalization, and
    the sparse-activation rule."""

    _sparse_capable = True

    def __init__(self, n_index: int, n_output: int,
                 padding_idx: Optional[int] = None,
                 sparse: Optional[bool] = None,
                 one_based: bool = False):
        super().__init__()
        self.n_index, self.n_output = n_index, n_output
        self.padding_idx = padding_idx
        self.sparse = sparse
        self.one_based = one_based
        from bigdl_tpu.nn.init import RandomNormal

        self.weight_init = RandomNormal(0.0, 1.0)
        self.weight = Parameter(self.weight_init.init((n_index, n_output)))

    def reset(self):
        self.weight = self.weight_init.init((self.n_index, self.n_output))

    def _indices(self, input):
        idx = jnp.asarray(input)
        if idx.dtype in (jnp.float32, jnp.float64, jnp.bfloat16):
            idx = idx.astype(jnp.int32)
        if self.one_based:
            idx = idx - 1
        return idx

    def _sparse_active(self, n_lookup: int, vocab: int) -> bool:
        """Trace-time (static-shape) decision.  ``sparse=True`` or
        ``BIGDL_SPARSE=on`` force it; auto requires the worst-case
        coalesced row count to be at most half the table — past that
        the "sparse" sync approaches a dense one and the coalesce is
        pure overhead (docs/sparse.md "when dense wins")."""
        if not sparse_enabled():
            return False
        if self.sparse is True or _sparse_forced():
            return True
        return 2 * n_lookup <= vocab


class LookupTable(_EmbeddingBase):
    """Embedding lookup with optional max-norm renorm and padding row
    (``nn/LookupTable.scala``).  Index gather is TPU-friendly (no scatter
    in forward); the backward scatter is either XLA's dense ``[vocab,
    dim]`` problem or — under a TrainStep sparse capture — the row-sparse
    ``(indices, rows)`` cotangent this module's family exists for.

    ``padding_idx``: that row receives zero gradient (torch semantics;
    its value is still gathered).  ``sparse``: None = auto (on when the
    batch's worst-case touched rows are at most half the vocab), True =
    force, False = never.  ``max_norm`` renorm keeps the table on the
    dense path — the renorm Jacobian is part of the dense cotangent and
    the sparse path will not silently drop it."""

    def __init__(self, n_index: int, n_output: int, padding_value: float = 0.0,
                 max_norm: float = float("inf"), norm_type: float = 2.0,
                 should_scale_grad_by_freq: bool = False, w_regularizer=None,
                 one_based: bool = False, padding_idx: Optional[int] = None,
                 sparse: Optional[bool] = None):
        super().__init__(n_index, n_output, padding_idx=padding_idx,
                         sparse=sparse, one_based=one_based)
        self.padding_value = padding_value
        self.max_norm, self.norm_type = max_norm, norm_type
        self.w_regularizer = w_regularizer

    def _sparse_active(self, n_lookup: int, vocab: int) -> bool:
        if self.max_norm != float("inf"):
            return False  # renorm Jacobian lives on the dense path only
        return super()._sparse_active(n_lookup, vocab)

    def update_output(self, input):
        idx = self._indices(input)
        w = self.weight
        if self.max_norm != float("inf"):
            norms = jnp.linalg.norm(w, ord=self.norm_type, axis=1,
                                    keepdims=True)
            w = w * jnp.minimum(1.0, self.max_norm / jnp.clip(norms, 1e-12))
        return _gather_rows(self, w, idx, self.padding_idx)


class EmbeddingBag(_EmbeddingBase):
    """Per-sample bag of lookups reduced to one vector — the recsys
    feature shape (a user's N clicked categories -> one embedding):
    ``[batch, bag]`` indices -> gather -> sum/mean over the bag ->
    ``[batch, dim]``.  ``padding_idx`` entries contribute nothing: their
    value is masked out of the reduction and (mean mode) excluded from
    the denominator, so ragged bags ride fixed shapes.

    The fused form never materializes per-position gradients the way a
    LookupTable + Sum stack would at ``[batch, bag, dim]`` cotangent
    granularity — under a sparse capture the cotangent is the coalesced
    ``(indices, rows)`` of the whole bag batch."""

    MODES = ("sum", "mean")

    def __init__(self, n_index: int, n_output: int, mode: str = "sum",
                 padding_idx: Optional[int] = None,
                 sparse: Optional[bool] = None, one_based: bool = False):
        if mode not in self.MODES:
            raise ValueError(f"unknown EmbeddingBag mode {mode!r} "
                             f"(sum | mean)")
        super().__init__(n_index, n_output, padding_idx=padding_idx,
                         sparse=sparse, one_based=one_based)
        self.mode = mode

    def update_output(self, input):
        idx = self._indices(input)
        if idx.ndim == 1:
            idx = idx[:, None]
        emb = _gather_rows(self, self.weight, idx, self.padding_idx)
        if self.padding_idx is not None:
            valid = (idx != self.padding_idx)
            emb = emb * valid[..., None].astype(emb.dtype)
            out = jnp.sum(emb, axis=-2)
            if self.mode == "mean":
                n = jnp.maximum(jnp.sum(valid, axis=-1, keepdims=True), 1)
                out = out / n.astype(out.dtype)
            return out
        out = jnp.sum(emb, axis=-2)
        if self.mode == "mean":
            out = out / jnp.asarray(idx.shape[-1], out.dtype)
        return out


def row_sharding_rules(model: Module, axis: str = "data",
                       chain=None):
    """``TrainStep extra_sharding_rules`` mapping every sparse-capable
    table of ``model`` onto a row-sharded ``PartitionSpec((axis,
    None))`` — each device holds ``vocab/N`` rows, the forward gather
    partitions into masked-local lookups, and the sparse update's row
    scatter lands only on the owning shard (docs/sparse.md
    "Row-sharded tables").  ``chain``: an existing rules callable
    consulted first (explicit TP rules win)."""
    paths = frozenset(sparse_tables(model))

    def rules(path, arr):
        if chain is not None:
            spec = chain(path, arr)
            if spec is not None:
                return spec
        if path in paths and getattr(arr, "ndim", 0) == 2:
            from jax.sharding import PartitionSpec as P

            return P(axis, None)
        return None

    return rules


def sparse_sync_stats(metas: Dict[str, Dict[str, Any]],
                      itemsize: int = 4) -> Dict[str, Any]:
    """Static per-step sync accounting from a trace's capture metas: per
    table, the bytes a dense all-reduce would move (the full ``[vocab,
    dim]`` gradient) vs what the sparse path syncs (the coalesced rows +
    their int32 indices).  These are static caps — the per-batch unique
    count is at most ``slots`` — and the numbers the ``train/sparse``
    instant and ``tpu_watch`` print."""
    tables: Dict[str, Dict[str, Any]] = {}
    for meta in metas.values():
        row = tables.setdefault(meta["path"], {
            "path": meta["path"], "vocab": meta["vocab"],
            "dim": meta["dim"], "touched_rows": 0, "calls": 0,
            "dense_bytes": meta["vocab"] * meta["dim"] * itemsize})
        row["touched_rows"] += meta["slots"]
        row["calls"] += 1
    for row in tables.values():
        row["sync_bytes"] = row["touched_rows"] * (row["dim"] * itemsize + 4)
        row["saved_bytes"] = max(0, row["dense_bytes"] - row["sync_bytes"])
    rows = sorted(tables.values(), key=lambda r: -r["saved_bytes"])
    return {"tables": len(rows),
            "touched_rows": sum(r["touched_rows"] for r in rows),
            "sync_bytes": sum(r["sync_bytes"] for r in rows),
            "dense_bytes": sum(r["dense_bytes"] for r in rows),
            "saved_bytes": sum(r["saved_bytes"] for r in rows),
            "rows": rows}
