"""Shape/structure layers (SURVEY §2.5 "Shape/structure": Reshape,
InferReshape, View, Transpose, Replicate, Padding, SpatialZeroPadding,
Narrow, NarrowTable, Select, SelectTable, Index, MaskedSelect, Squeeze,
Unsqueeze, Contiguous, Reverse, Pack, BifurcateSplitTable, SplitTable,
JoinTable, FlattenTable, Max, Min, Mean, Sum, ResizeBilinear, Scale,
Bottle) and the elementwise table ops (CAddTable, CSubTable, CMulTable,
CDivTable, CMaxTable, CMinTable).

Dim convention: 0-based Python axes (negative allowed), not the
reference's 1-based Torch dims — idiomatic for a new JAX API.  Layers that
batch-shift dims in the reference take an explicit axis instead.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module, Parameter

__all__ = [
    "Reshape", "InferReshape", "View", "Transpose", "Replicate", "Padding",
    "SpatialZeroPadding", "Narrow", "NarrowTable", "Select", "SelectTable",
    "Index", "MaskedSelect", "Squeeze", "Unsqueeze", "Contiguous", "Reverse",
    "Pack", "SplitTable", "BifurcateSplitTable", "JoinTable", "FlattenTable",
    "Max", "Min", "Mean", "Sum", "ResizeBilinear", "Scale", "Bottle",
    "CAddTable", "CSubTable", "CMulTable", "CDivTable", "CMaxTable", "CMinTable",
]


class Reshape(Module):
    """Reshape the non-batch dims (``nn/Reshape.scala``); ``batch_mode=None``
    auto-detects a leading batch dim like the reference."""

    def __init__(self, size: Sequence[int], batch_mode: Optional[bool] = None):
        super().__init__()
        self.size = tuple(int(s) for s in size)
        self.batch_mode = batch_mode
        self._n_elem = 1
        for s in self.size:
            self._n_elem *= s

    def update_output(self, input):
        batch = self.batch_mode
        if batch is None:
            # auto-detect (Reshape.scala:61-63): treat as batched when the
            # leading dim looks like a batch; batch-size-1 inputs keep their
            # batch dim when they carry one extra dim over the target size
            if input.size == self._n_elem * input.shape[0] and (
                    input.shape[0] != 1 or input.ndim == len(self.size) + 1):
                batch = input.size != self._n_elem or input.shape[0] == 1
            else:
                batch = False
        if batch:
            return jnp.reshape(input, (input.shape[0],) + self.size)
        return jnp.reshape(input, self.size)


class InferReshape(Module):
    """Reshape with -1 (inferred) and 0 (copy input dim) entries
    (``nn/InferReshape.scala``)."""

    def __init__(self, size: Sequence[int], batch_mode: bool = False):
        super().__init__()
        self.size = tuple(int(s) for s in size)
        self.batch_mode = batch_mode

    def update_output(self, input):
        in_shape = input.shape[1:] if self.batch_mode else input.shape
        out: List[int] = []
        for i, s in enumerate(self.size):
            out.append(in_shape[i] if s == 0 else s)
        total = 1
        for d in in_shape:
            total *= d
        if -1 in out:
            known = 1
            for d in out:
                if d != -1:
                    known *= d
            out[out.index(-1)] = total // known
        if self.batch_mode:
            return jnp.reshape(input, (input.shape[0],) + tuple(out))
        return jnp.reshape(input, tuple(out))


class View(Module):
    """(``nn/View.scala``) — reshape allowing one -1."""

    def __init__(self, *sizes: int):
        super().__init__()
        if len(sizes) == 1 and isinstance(sizes[0], (list, tuple)):
            sizes = tuple(sizes[0])
        self.sizes = tuple(int(s) for s in sizes)
        self.num_input_dims = 0

    def set_num_input_dims(self, n: int):
        self.num_input_dims = n
        return self

    def update_output(self, input):
        if self.num_input_dims and input.ndim > self.num_input_dims:
            # batch-shift: keep the leading (ndim - num_input_dims) dims
            lead = input.shape[: input.ndim - self.num_input_dims]
            return jnp.reshape(input, lead + self.sizes)
        n_elem = 1
        for s in self.sizes:
            if s != -1:
                n_elem *= s
        if -1 not in self.sizes and input.size != n_elem:
            # leading batch dim preserved
            return jnp.reshape(input, (-1,) + self.sizes)
        return jnp.reshape(input, self.sizes)


class Transpose(Module):
    """Swap listed axis pairs in order (``nn/Transpose.scala``)."""

    def __init__(self, permutations: Sequence[Sequence[int]]):
        super().__init__()
        self.permutations = tuple((int(a), int(b)) for a, b in permutations)

    def update_output(self, input):
        out = input
        for a, b in self.permutations:
            out = jnp.swapaxes(out, a, b)
        return out


class Replicate(Module):
    """Insert a new axis of size ``n_features`` at ``dim`` by replication
    (``nn/Replicate.scala``)."""

    def __init__(self, n_features: int, dim: int = 0):
        super().__init__()
        self.n_features, self.dim = n_features, dim

    def update_output(self, input):
        out = jnp.expand_dims(input, self.dim)
        reps = [1] * out.ndim
        reps[self.dim] = self.n_features
        return jnp.tile(out, reps)


class Padding(Module):
    """Pad ``pad`` entries (sign = side) along ``dim`` with ``value``
    (``nn/Padding.scala``)."""

    def __init__(self, dim: int, pad: int, n_input_dim: int = 0,
                 value: float = 0.0, n_index: int = 1):
        super().__init__()
        self.dim, self.pad, self.value = dim, pad, value
        self.n_input_dim = n_input_dim

    def update_output(self, input):
        dim = self.dim
        if self.n_input_dim and input.ndim > self.n_input_dim:
            dim += input.ndim - self.n_input_dim  # batch shift
        pads = [(0, 0)] * input.ndim
        pads[dim] = (-self.pad, 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(input, pads, constant_values=self.value)


class SpatialZeroPadding(Module):
    """(``nn/SpatialZeroPadding.scala``); negative pads crop."""

    def __init__(self, pad_left: int, pad_right: int, pad_top: int, pad_bottom: int):
        super().__init__()
        self.l, self.r, self.t, self.b = pad_left, pad_right, pad_top, pad_bottom

    def update_output(self, input):
        h_ax, w_ax = input.ndim - 2, input.ndim - 1
        out = input
        # crops first (negative pads)
        sl = [slice(None)] * input.ndim
        sl[h_ax] = slice(max(0, -self.t), input.shape[h_ax] - max(0, -self.b))
        sl[w_ax] = slice(max(0, -self.l), input.shape[w_ax] - max(0, -self.r))
        out = out[tuple(sl)]
        pads = [(0, 0)] * input.ndim
        pads[h_ax] = (max(0, self.t), max(0, self.b))
        pads[w_ax] = (max(0, self.l), max(0, self.r))
        return jnp.pad(out, pads)


class Narrow(Module):
    """Slice ``length`` entries from ``offset`` along ``dim``
    (``nn/Narrow.scala``); length -1 = to the end."""

    def __init__(self, dim: int, offset: int, length: int = 1):
        super().__init__()
        self.dim, self.offset, self.length = dim, offset, length

    def update_output(self, input):
        length = self.length
        if length < 0:
            length = input.shape[self.dim] - self.offset + (length + 1)
        sl = [slice(None)] * input.ndim
        sl[self.dim] = slice(self.offset, self.offset + length)
        return input[tuple(sl)]


class NarrowTable(Module):
    """Slice a table (``nn/NarrowTable.scala``)."""

    def __init__(self, offset: int, length: int = 1):
        super().__init__()
        self.offset, self.length = offset, length

    def update_output(self, input):
        length = self.length
        if length < 0:
            length = len(input) - self.offset + (length + 1)
        return list(input)[self.offset : self.offset + length]


class Select(Module):
    """Select index along dim, dropping the dim (``nn/Select.scala``);
    negative index counts from the end."""

    def __init__(self, dim: int, index: int):
        super().__init__()
        self.dim, self.index = dim, index

    def update_output(self, input):
        return jnp.take(input, self.index % input.shape[self.dim], axis=self.dim)


class SelectTable(Module):
    """Select a table element (``nn/SelectTable.scala``)."""

    def __init__(self, index: int):
        super().__init__()
        self.index = index

    def update_output(self, input):
        return list(input)[self.index]


class Index(Module):
    """index_select along ``dim``: input = (tensor, indices)
    (``nn/Index.scala``)."""

    def __init__(self, dim: int):
        super().__init__()
        self.dim = dim

    def update_output(self, input):
        t, idx = input
        return jnp.take(t, jnp.asarray(idx).astype(jnp.int32), axis=self.dim)


class MaskedSelect(Module):
    """input = (tensor, mask) -> 1-D of selected entries
    (``nn/MaskedSelect.scala``).  Output size is data-dependent, so this
    layer is **eager-only**; inside jit use ``jnp.where`` masking instead."""

    def update_output(self, input):
        t, mask = input
        if isinstance(t, jax.core.Tracer):
            raise RuntimeError(
                "MaskedSelect has a data-dependent output shape and cannot be "
                "jit-traced on TPU; restructure with jnp.where or run eagerly.")
        return t[jnp.asarray(mask, bool)]


class Squeeze(Module):
    """(``nn/Squeeze.scala``)."""

    def __init__(self, dim: Optional[int] = None, num_input_dims: int = 0):
        super().__init__()
        self.dim = dim
        self.num_input_dims = num_input_dims

    def update_output(self, input):
        if self.dim is None:
            return jnp.squeeze(input)
        dim = self.dim
        if self.num_input_dims and input.ndim > self.num_input_dims:
            dim += input.ndim - self.num_input_dims
        if input.shape[dim] == 1:
            return jnp.squeeze(input, dim)
        return input


class Unsqueeze(Module):
    """(``nn/Unsqueeze.scala``)."""

    def __init__(self, pos: int, num_input_dims: int = 0):
        super().__init__()
        self.pos = pos
        self.num_input_dims = num_input_dims

    def update_output(self, input):
        pos = self.pos
        if self.num_input_dims and input.ndim > self.num_input_dims:
            pos += input.ndim - self.num_input_dims
        return jnp.expand_dims(input, pos)


class Contiguous(Module):
    """No-op on XLA (arrays are always dense) (``nn/Contiguous.scala``)."""

    def update_output(self, input):
        return input


class Reverse(Module):
    """Flip along ``dim`` (``nn/Reverse.scala``)."""

    def __init__(self, dim: int = 0):
        super().__init__()
        self.dim = dim

    def update_output(self, input):
        return jnp.flip(input, self.dim)


class Pack(Module):
    """Stack a table of tensors along a new ``dim`` (``nn/Pack.scala``)."""

    def __init__(self, dim: int = 0):
        super().__init__()
        self.dim = dim

    def update_output(self, input):
        if not isinstance(input, (list, tuple)):
            input = [input]
        return jnp.stack(list(input), axis=self.dim)


class SplitTable(Module):
    """Split a tensor along ``dim`` into a table (``nn/SplitTable.scala``)."""

    def __init__(self, dim: int, num_input_dims: int = 0):
        super().__init__()
        self.dim = dim
        self.num_input_dims = num_input_dims

    def update_output(self, input):
        dim = self.dim
        if self.num_input_dims and input.ndim > self.num_input_dims:
            dim += input.ndim - self.num_input_dims
        return [jnp.squeeze(s, dim) for s in jnp.split(input, input.shape[dim], axis=dim)]


class BifurcateSplitTable(Module):
    """Split into two halves along ``dim`` (``nn/BifurcateSplitTable.scala``)."""

    def __init__(self, dim: int):
        super().__init__()
        self.dim = dim

    def update_output(self, input):
        half = input.shape[self.dim] // 2
        a, b = jnp.split(input, [half], axis=self.dim)
        return [a, b]


class JoinTable(Module):
    """Concatenate a table along ``dim`` (``nn/JoinTable.scala``)."""

    def __init__(self, dim: int, n_input_dims: int = 0):
        super().__init__()
        self.dim = dim
        self.n_input_dims = n_input_dims

    def update_output(self, input):
        dim = self.dim
        first = input[0]
        if self.n_input_dims and first.ndim > self.n_input_dims:
            dim += first.ndim - self.n_input_dims
        return jnp.concatenate(list(input), axis=dim)


class FlattenTable(Module):
    """Flatten nested tables (``nn/FlattenTable.scala``)."""

    def update_output(self, input):
        out: List = []

        def walk(x):
            if isinstance(x, (list, tuple)):
                for e in x:
                    walk(e)
            else:
                out.append(x)

        walk(input)
        return out


class _Reduce(Module):
    def __init__(self, dim: int = 0, num_input_dims: int = 0, keepdims: bool = False,
                 squeeze: bool = True):
        super().__init__()
        self.dim = dim
        self.num_input_dims = num_input_dims
        # keepdims=True and squeeze=False both mean "retain the reduced dim"
        self.squeeze = squeeze and not keepdims

    def _axis(self, input):
        dim = self.dim
        if self.num_input_dims and input.ndim > self.num_input_dims:
            dim += input.ndim - self.num_input_dims
        return dim


class Max(_Reduce):
    def update_output(self, input):
        return jnp.max(input, axis=self._axis(input), keepdims=not self.squeeze)


class Min(_Reduce):
    def update_output(self, input):
        return jnp.min(input, axis=self._axis(input), keepdims=not self.squeeze)


class Mean(_Reduce):
    def update_output(self, input):
        return jnp.mean(input, axis=self._axis(input), keepdims=not self.squeeze)


class Sum(_Reduce):
    def __init__(self, dim: int = 0, num_input_dims: int = 0, size_average: bool = False,
                 squeeze: bool = True):
        super().__init__(dim, num_input_dims, squeeze=squeeze)
        self.size_average = size_average

    def update_output(self, input):
        ax = self._axis(input)
        out = jnp.sum(input, axis=ax, keepdims=not self.squeeze)
        if self.size_average:
            out = out / input.shape[ax]
        return out


class ResizeBilinear(Module):
    """Bilinear resize of NCHW/NHWC maps (``nn/ResizeBilinear.scala``)."""

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False, format: str = "NCHW",
                 half_pixel_centers: bool = False):
        super().__init__()
        assert not (align_corners and half_pixel_centers)
        self.output_height, self.output_width = output_height, output_width
        self.align_corners = align_corners
        self.half_pixel_centers = half_pixel_centers
        self.format = format

    def update_output(self, input):
        h_ax = input.ndim - 3 if self.format == "NHWC" else input.ndim - 2
        w_ax = h_ax + 1
        ih, iw = input.shape[h_ax], input.shape[w_ax]
        if self.align_corners:
            # linear sample grid including both endpoints
            ys = jnp.linspace(0, ih - 1, self.output_height)
            xs = jnp.linspace(0, iw - 1, self.output_width)
        elif self.half_pixel_centers:
            # TF2 convention: src = (dst + 0.5) * scale - 0.5, clamped
            ys = (jnp.arange(self.output_height) + 0.5) \
                * (ih / self.output_height) - 0.5
            xs = (jnp.arange(self.output_width) + 0.5) \
                * (iw / self.output_width) - 0.5
            ys = jnp.clip(ys, 0, ih - 1)
            xs = jnp.clip(xs, 0, iw - 1)
        else:
            # the reference (and TF v1's legacy kernel it mirrors) uses the
            # asymmetric src = dst * scale convention — NOT half-pixel
            # centers (``nn/ResizeBilinear.scala`` computeInterpolationWeights)
            ys = jnp.arange(self.output_height) * (ih / self.output_height)
            xs = jnp.arange(self.output_width) * (iw / self.output_width)
            ys = jnp.minimum(ys, ih - 1)
            xs = jnp.minimum(xs, iw - 1)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, ih - 1)
        y1 = jnp.clip(y0 + 1, 0, ih - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, iw - 1)
        x1 = jnp.clip(x0 + 1, 0, iw - 1)
        wy = (ys - y0).reshape((-1, 1))
        wx = (xs - x0).reshape((1, -1))

        def gather(h_idx, w_idx):
            g = jnp.take(input, h_idx, axis=h_ax)
            return jnp.take(g, w_idx, axis=w_ax)

        # broadcast weights to the spatial axes
        wshape = [1] * input.ndim
        wshape[h_ax], wshape[w_ax] = self.output_height, self.output_width
        wy_b = jnp.broadcast_to(wy, (self.output_height, self.output_width)).reshape(wshape)
        wx_b = jnp.broadcast_to(wx, (self.output_height, self.output_width)).reshape(wshape)
        top = gather(y0, x0) * (1 - wx_b) + gather(y0, x1) * wx_b
        bot = gather(y1, x0) * (1 - wx_b) + gather(y1, x1) * wx_b
        return top * (1 - wy_b) + bot * wy_b


class Scale(Module):
    """Channel-wise affine y = w*x + b with learnable w, b of ``size``
    (``nn/Scale.scala``: CMul + CAdd fused)."""

    def __init__(self, size: Sequence[int]):
        super().__init__()
        self.size = tuple(size)
        self.weight = Parameter(jnp.ones(self.size, jnp.float32))
        self.bias = Parameter(jnp.zeros(self.size, jnp.float32))

    def update_output(self, input):
        return input * self.weight + self.bias


class Bottle(Module):
    """Flatten leading dims, apply inner module, restore
    (``nn/Bottle.scala``)."""

    def __init__(self, module: Module, n_input_dim: int = 2, n_output_dim: int = 2):
        super().__init__()
        self.inner = module
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim

    def update_output(self, input):
        if input.ndim <= self.n_input_dim:
            return self.inner.forward(input)
        lead = input.shape[: input.ndim - self.n_input_dim + 1]
        flat = input.reshape((-1,) + input.shape[input.ndim - self.n_input_dim + 1 :])
        out = self.inner.forward(flat)
        return out.reshape(lead + out.shape[1:])


# ---------------------------- table elementwise ---------------------------

class CAddTable(Module):
    """(``nn/CAddTable.scala``)."""

    def __init__(self, inplace: bool = False):
        super().__init__()

    def update_output(self, input):
        out = input[0]
        for t in input[1:]:
            out = out + t
        return out


class CSubTable(Module):
    def update_output(self, input):
        return input[0] - input[1]


class CMulTable(Module):
    def update_output(self, input):
        out = input[0]
        for t in input[1:]:
            out = out * t
        return out


class CDivTable(Module):
    def update_output(self, input):
        return input[0] / input[1]


class CMaxTable(Module):
    def update_output(self, input):
        out = input[0]
        for t in input[1:]:
            out = jnp.maximum(out, t)
        return out


class CMinTable(Module):
    def update_output(self, input):
        out = input[0]
        for t in input[1:]:
            out = jnp.minimum(out, t)
        return out
