"""Pooling layers (SURVEY §2.5: SpatialMaxPooling, SpatialAveragePooling,
TemporalMaxPooling, VolumetricMaxPooling, RoiPooling).

The reference's hand-written pooling loops (``nn/NNPrimitive.scala:594-972``)
become ``lax.reduce_window`` — XLA lowers these to fused VPU reductions.
Ceil-mode semantics (Torch) are reproduced with explicit asymmetric padding.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module

__all__ = [
    "SpatialMaxPooling", "SpatialAveragePooling", "TemporalMaxPooling",
    "VolumetricMaxPooling", "VolumetricAveragePooling", "RoiPooling",
]


def _pool_out_size(size: int, k: int, stride: int, pad: int, ceil_mode: bool) -> int:
    if ceil_mode:
        out = int(math.ceil(float(size - k + 2 * pad) / stride)) + 1
    else:
        out = int(math.floor(float(size - k + 2 * pad) / stride)) + 1
    if pad > 0 and (out - 1) * stride >= size + pad:
        out -= 1  # Torch: last window must start inside the (left-)padded input
    return out


def _pool_padding(size: int, k: int, stride: int, pad: int, ceil_mode: bool):
    out = _pool_out_size(size, k, stride, pad, ceil_mode)
    needed = (out - 1) * stride + k
    hi = max(0, needed - size - pad)
    return (pad, hi), out


class SpatialMaxPooling(Module):
    """(``nn/SpatialMaxPooling.scala``); pad == -1 means SAME."""

    def __init__(self, kw: int, kh: int, dw: Optional[int] = None, dh: Optional[int] = None,
                 pad_w: int = 0, pad_h: int = 0, format: str = "NCHW"):
        super().__init__()
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw or kw, dh or kh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.format = format
        self.ceil_mode = False

    def ceil(self):
        self.ceil_mode = True
        return self

    def floor(self):
        self.ceil_mode = False
        return self

    def _spatial_axes(self, ndim):
        if self.format == "NHWC":
            return (ndim - 3, ndim - 2)
        return (ndim - 2, ndim - 1)

    def _reduce(self, x, init, op):
        h_ax, w_ax = self._spatial_axes(x.ndim)
        dims = [1] * x.ndim
        strides = [1] * x.ndim
        pads = [(0, 0)] * x.ndim
        dims[h_ax], dims[w_ax] = self.kh, self.kw
        strides[h_ax], strides[w_ax] = self.dh, self.dw
        if self.pad_h == -1 or self.pad_w == -1:  # SAME
            for ax, k, s in ((h_ax, self.kh, self.dh), (w_ax, self.kw, self.dw)):
                out = -(-x.shape[ax] // s)
                total = max(0, (out - 1) * s + k - x.shape[ax])
                pads[ax] = (total // 2, total - total // 2)
        else:
            pads[h_ax], _ = _pool_padding(x.shape[h_ax], self.kh, self.dh, self.pad_h, self.ceil_mode)
            pads[w_ax], _ = _pool_padding(x.shape[w_ax], self.kw, self.dw, self.pad_w, self.ceil_mode)
        return lax.reduce_window(x, init, op, tuple(dims), tuple(strides), tuple(pads))

    def update_output(self, input):
        return self._reduce(input, -jnp.inf if jnp.issubdtype(input.dtype, jnp.floating)
                            else jnp.iinfo(input.dtype).min, lax.max)


class SpatialAveragePooling(SpatialMaxPooling):
    """(``nn/SpatialAveragePooling.scala``)."""

    def __init__(self, kw: int, kh: int, dw: Optional[int] = None, dh: Optional[int] = None,
                 pad_w: int = 0, pad_h: int = 0, global_pooling: bool = False,
                 ceil_mode: bool = False, count_include_pad: bool = True,
                 divide: bool = True, format: str = "NCHW"):
        super().__init__(kw, kh, dw, dh, pad_w, pad_h, format)
        self.ceil_mode = ceil_mode
        self.global_pooling = global_pooling
        self.count_include_pad = count_include_pad
        self.divide = divide

    def update_output(self, input):
        if self.global_pooling:
            h_ax, w_ax = self._spatial_axes(input.ndim)
            self.kh, self.kw = input.shape[h_ax], input.shape[w_ax]
            self.dh, self.dw = self.kh, self.kw
        s = self._reduce(input, 0.0, lax.add)
        if not self.divide:
            return s
        if self.count_include_pad:
            return s / (self.kh * self.kw)
        ones = jnp.ones_like(input)
        counts = self._reduce(ones, 0.0, lax.add)
        return s / counts


class TemporalMaxPooling(Module):
    """1-D max pooling over [batch, time, feature]
    (``nn/TemporalMaxPooling.scala``)."""

    def __init__(self, k_w: int, d_w: Optional[int] = None):
        super().__init__()
        self.k_w, self.d_w = k_w, d_w or k_w

    def update_output(self, input):
        t_ax = input.ndim - 2
        dims = [1] * input.ndim
        strides = [1] * input.ndim
        dims[t_ax], strides[t_ax] = self.k_w, self.d_w
        return lax.reduce_window(input, -jnp.inf, lax.max, tuple(dims), tuple(strides),
                                 [(0, 0)] * input.ndim)


class VolumetricMaxPooling(Module):
    """3-D max pooling over [batch, C, T, H, W]
    (``nn/VolumetricMaxPooling.scala``)."""

    def __init__(self, k_t: int, k_w: int, k_h: int,
                 d_t: Optional[int] = None, d_w: Optional[int] = None, d_h: Optional[int] = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        self.k_t, self.k_w, self.k_h = k_t, k_w, k_h
        self.d_t, self.d_w, self.d_h = d_t or k_t, d_w or k_w, d_h or k_h
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.ceil_mode = False

    def update_output(self, input):
        ndim = input.ndim
        t_ax, h_ax, w_ax = ndim - 3, ndim - 2, ndim - 1
        dims, strides, pads = [1] * ndim, [1] * ndim, [(0, 0)] * ndim
        for ax, k, d, p in ((t_ax, self.k_t, self.d_t, self.pad_t),
                            (h_ax, self.k_h, self.d_h, self.pad_h),
                            (w_ax, self.k_w, self.d_w, self.pad_w)):
            dims[ax], strides[ax] = k, d
            pads[ax], _ = _pool_padding(input.shape[ax], k, d, p, self.ceil_mode)
        return lax.reduce_window(input, -jnp.inf, lax.max, tuple(dims), tuple(strides), pads)


class VolumetricAveragePooling(VolumetricMaxPooling):
    def update_output(self, input):
        ndim = input.ndim
        t_ax, h_ax, w_ax = ndim - 3, ndim - 2, ndim - 1
        dims, strides, pads = [1] * ndim, [1] * ndim, [(0, 0)] * ndim
        for ax, k, d, p in ((t_ax, self.k_t, self.d_t, self.pad_t),
                            (h_ax, self.k_h, self.d_h, self.pad_h),
                            (w_ax, self.k_w, self.d_w, self.pad_w)):
            dims[ax], strides[ax] = k, d
            pads[ax], _ = _pool_padding(input.shape[ax], k, d, p, self.ceil_mode)
        s = lax.reduce_window(input, 0.0, lax.add, tuple(dims), tuple(strides), pads)
        return s / (self.k_t * self.k_h * self.k_w)


class RoiPooling(Module):
    """Region-of-interest max pooling (``nn/RoiPooling.scala``).  Input is a
    table (features [N,C,H,W], rois [R,5] of (batch_idx, x1, y1, x2, y2)).
    Implemented with a dense one-hot projection per output cell so shapes
    stay static under jit (no data-dependent slicing on TPU)."""

    def __init__(self, pooled_w: int, pooled_h: int, spatial_scale: float = 1.0):
        super().__init__()
        self.pooled_w, self.pooled_h = pooled_w, pooled_h
        self.spatial_scale = spatial_scale

    def update_output(self, input):
        data, rois = input
        n, c, h, w = data.shape

        def pool_one(roi):
            b = roi[0].astype(jnp.int32)
            x1 = jnp.round(roi[1] * self.spatial_scale).astype(jnp.int32)
            y1 = jnp.round(roi[2] * self.spatial_scale).astype(jnp.int32)
            x2 = jnp.round(roi[3] * self.spatial_scale).astype(jnp.int32)
            y2 = jnp.round(roi[4] * self.spatial_scale).astype(jnp.int32)
            roi_w = jnp.maximum(x2 - x1 + 1, 1)
            roi_h = jnp.maximum(y2 - y1 + 1, 1)
            bin_w = roi_w.astype(jnp.float32) / self.pooled_w
            bin_h = roi_h.astype(jnp.float32) / self.pooled_h
            feat = data[b]  # (C, H, W)

            ys = jnp.arange(h)
            xs = jnp.arange(w)

            def cell(py, px):
                hstart = jnp.floor(py * bin_h).astype(jnp.int32) + y1
                hend = jnp.ceil((py + 1) * bin_h).astype(jnp.int32) + y1
                wstart = jnp.floor(px * bin_w).astype(jnp.int32) + x1
                wend = jnp.ceil((px + 1) * bin_w).astype(jnp.int32) + x1
                hstart, hend = jnp.clip(hstart, 0, h), jnp.clip(hend, 0, h)
                wstart, wend = jnp.clip(wstart, 0, w), jnp.clip(wend, 0, w)
                mask = ((ys[:, None] >= hstart) & (ys[:, None] < hend)
                        & (xs[None, :] >= wstart) & (xs[None, :] < wend))
                empty = (hend <= hstart) | (wend <= wstart)
                masked = jnp.where(mask[None, :, :], feat, -jnp.inf)
                val = jnp.max(masked, axis=(1, 2))
                return jnp.where(empty, 0.0, val)

            py = jnp.arange(self.pooled_h)
            px = jnp.arange(self.pooled_w)
            return jax.vmap(lambda y: jax.vmap(lambda x: cell(y, x))(px))(py).transpose(2, 0, 1)

        return jax.vmap(pool_one)(rois.astype(jnp.float32))
