"""Pooling layers (SURVEY §2.5: SpatialMaxPooling, SpatialAveragePooling,
TemporalMaxPooling, VolumetricMaxPooling, RoiPooling).

The reference's hand-written pooling loops (``nn/NNPrimitive.scala:594-972``)
become ``lax.reduce_window`` — XLA lowers these to fused VPU reductions.
Ceil-mode semantics (Torch) are reproduced with explicit asymmetric padding;
average-pooling divisors follow the reference exactly: declared padding
counts when ``count_include_pad`` but ceil-overflow padding never does
(``SpatialAveragePooling.scala:133-135`` clips the pool size at the
declared pad).
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module

__all__ = [
    "SpatialMaxPooling", "SpatialAveragePooling", "TemporalMaxPooling",
    "VolumetricMaxPooling", "VolumetricAveragePooling", "RoiPooling",
]


def _max_init(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return -jnp.inf
    return jnp.iinfo(dtype).min


def _pool_out_size(size: int, k: int, stride: int, pad: int, ceil_mode: bool) -> int:
    if ceil_mode:
        out = int(math.ceil(float(size - k + 2 * pad) / stride)) + 1
    else:
        out = int(math.floor(float(size - k + 2 * pad) / stride)) + 1
    if pad > 0 and (out - 1) * stride >= size + pad:
        out -= 1  # Torch: last window must start inside the (left-)padded input
    return out


def _axis_padding(size: int, k: int, stride: int, pad: int, ceil_mode: bool
                  ) -> Tuple[int, int, int]:
    """(lo, hi, declared_hi): hi includes ceil-overflow; declared_hi is the
    part of hi within the user-declared padding (counts toward the
    count_include_pad divisor)."""
    if pad == -1:  # SAME
        out = -(-size // stride)
        total = max(0, (out - 1) * stride + k - size)
        lo, hi = total // 2, total - total // 2
        return lo, hi, hi
    out = _pool_out_size(size, k, stride, pad, ceil_mode)
    needed = (out - 1) * stride + k
    hi = max(0, needed - size - pad)
    return pad, hi, min(hi, pad)


class _PoolBase(Module):
    """Shared window plumbing over the trailing spatial axes."""

    ceil_mode = False
    #: XLA's select-and-scatter backward (first-argmax ties, bit-parity
    #: with the reference) benches FASTER on TPU v5e than the unrolled
    #: tie-split VJP (4,853 vs 3,494 img/s on the Inception-v1 train
    #: step) — the claim that select-and-scatter dominated the step was
    #: an attribution error in the round-2 profile.  tie_split() opts
    #: into the equal-split gradient (residue-class gather backward).
    tie_split = False

    def torch_ties(self):
        """First-argmax tie gradient (the reference's semantics) via
        XLA's native select-and-scatter lowering — the default."""
        self.tie_split = False
        return self

    def split_ties(self):
        """Equal-split tie gradient via the residue-class gather VJP
        (conserves gradient mass across tied maxima)."""
        self.tie_split = True
        return self

    def _axes_spec(self, ndim) -> List[Tuple[int, int, int, int]]:
        """[(axis, k, stride, pad), ...] — subclasses define."""
        raise NotImplementedError

    def _window(self, x):
        dims = [1] * x.ndim
        strides = [1] * x.ndim
        pads = [(0, 0)] * x.ndim
        declared = [(0, 0)] * x.ndim
        for ax, k, d, p in self._axes_spec(x.ndim):
            dims[ax], strides[ax] = k, d
            lo, hi, dh = _axis_padding(x.shape[ax], k, d, p, self.ceil_mode)
            pads[ax] = (lo, hi)
            declared[ax] = (lo, dh)
        return tuple(dims), tuple(strides), pads, declared

    #: largest window (taps per element) the unrolled tie-split backward
    #: may handle — beyond this (e.g. global pooling over a 56x56 map)
    #: the per-tap unroll would blow up compile time, and XLA's
    #: select-and-scatter is used instead
    _TIE_SPLIT_MAX_TAPS = 64

    def _max(self, x):
        dims, strides, pads, _ = self._window(x)
        taps = 1
        for d in dims:
            taps *= d
        if self.tie_split and taps <= self._TIE_SPLIT_MAX_TAPS \
                and jnp.issubdtype(x.dtype, jnp.floating):
            # ops/pool_pallas.py: exact equal-tie-split custom VJP,
            # fused Pallas backward on supported 4-D planes
            from bigdl_tpu.ops.pool_pallas import maxpool_tie_split
            return maxpool_tie_split(x, dims, strides, tuple(pads))
        if not self.tie_split:
            from bigdl_tpu.ops.pooling_pallas import (
                maxpool_argmax, pallas_pool_supported)
            if pallas_pool_supported(x, dims, strides, pads):
                # Pallas argmax-index kernel: same first-argmax tie
                # semantics as select-and-scatter, but the backward
                # scatters from a saved int8 tap index instead of
                # re-reading x and y (round-5 profile: the re-read was
                # ~28% of the Inception-v1 step)
                return maxpool_argmax(x, dims, strides, tuple(pads))
        return lax.reduce_window(x, _max_init(x.dtype), lax.max, dims, strides, pads)

    def _avg(self, x, count_include_pad: bool, divide: bool = True):
        dims, strides, pads, declared = self._window(x)
        # ops/pool_pallas.py: the Torch divisor map (declared padding
        # counts, ceil-overflow never does) is a trace-time numpy
        # constant there, the window sum a fused kernel, and the
        # backward the exact linear transpose
        from bigdl_tpu.ops.pool_pallas import avg_pool
        return avg_pool(x, dims, strides, tuple(pads), tuple(declared),
                        count_include_pad, divide)


class SpatialMaxPooling(_PoolBase):
    """(``nn/SpatialMaxPooling.scala``); pad == -1 means SAME (per axis)."""

    def __init__(self, kw: int, kh: int, dw: Optional[int] = None, dh: Optional[int] = None,
                 pad_w: int = 0, pad_h: int = 0, format: str = "NCHW",
                 global_pooling: bool = False):
        super().__init__()
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw or kw, dh or kh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.format = format
        self.ceil_mode = False
        self.global_pooling = global_pooling

    def ceil(self):
        self.ceil_mode = True
        return self

    def floor(self):
        self.ceil_mode = False
        return self

    def _axes_spec(self, ndim):
        if self.format == "NHWC":
            h_ax, w_ax = ndim - 3, ndim - 2
        else:
            h_ax, w_ax = ndim - 2, ndim - 1
        return [(h_ax, self.kh, self.dh, self.pad_h),
                (w_ax, self.kw, self.dw, self.pad_w)]

    def _apply_global(self, input):
        if self.global_pooling:
            spec = self._axes_spec(input.ndim)
            (h_ax, *_), (w_ax, *_) = spec
            self.kh, self.kw = input.shape[h_ax], input.shape[w_ax]
            self.dh, self.dw = self.kh, self.kw

    def update_output(self, input):
        self._apply_global(input)
        return self._max(input)


class SpatialAveragePooling(SpatialMaxPooling):
    """(``nn/SpatialAveragePooling.scala``)."""

    def __init__(self, kw: int, kh: int, dw: Optional[int] = None, dh: Optional[int] = None,
                 pad_w: int = 0, pad_h: int = 0, global_pooling: bool = False,
                 ceil_mode: bool = False, count_include_pad: bool = True,
                 divide: bool = True, format: str = "NCHW"):
        super().__init__(kw, kh, dw, dh, pad_w, pad_h, format)
        self.ceil_mode = ceil_mode
        self.global_pooling = global_pooling
        self.count_include_pad = count_include_pad
        self.divide = divide

    def update_output(self, input):
        self._apply_global(input)
        return self._avg(input, self.count_include_pad, self.divide)


class TemporalMaxPooling(_PoolBase):
    """1-D max pooling over [batch, time, feature]
    (``nn/TemporalMaxPooling.scala``)."""

    def __init__(self, k_w: int, d_w: Optional[int] = None):
        super().__init__()
        self.k_w, self.d_w = k_w, d_w or k_w

    def _axes_spec(self, ndim):
        return [(ndim - 2, self.k_w, self.d_w, 0)]

    def update_output(self, input):
        return self._max(input)


class VolumetricMaxPooling(_PoolBase):
    """3-D max pooling over [batch, C, T, H, W]
    (``nn/VolumetricMaxPooling.scala``)."""

    def __init__(self, k_t: int, k_w: int, k_h: int,
                 d_t: Optional[int] = None, d_w: Optional[int] = None, d_h: Optional[int] = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        self.k_t, self.k_w, self.k_h = k_t, k_w, k_h
        self.d_t, self.d_w, self.d_h = d_t or k_t, d_w or k_w, d_h or k_h
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.ceil_mode = False

    def ceil(self):
        self.ceil_mode = True
        return self

    def _axes_spec(self, ndim):
        return [(ndim - 3, self.k_t, self.d_t, self.pad_t),
                (ndim - 2, self.k_h, self.d_h, self.pad_h),
                (ndim - 1, self.k_w, self.d_w, self.pad_w)]

    def update_output(self, input):
        return self._max(input)


class VolumetricAveragePooling(VolumetricMaxPooling):
    """(``nn/VolumetricAveragePooling.scala``)."""

    def __init__(self, k_t: int, k_w: int, k_h: int,
                 d_t: Optional[int] = None, d_w: Optional[int] = None, d_h: Optional[int] = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 count_include_pad: bool = True):
        super().__init__(k_t, k_w, k_h, d_t, d_w, d_h, pad_t, pad_w, pad_h)
        self.count_include_pad = count_include_pad

    def update_output(self, input):
        return self._avg(input, self.count_include_pad)


class RoiPooling(Module):
    """Region-of-interest max pooling (``nn/RoiPooling.scala``).  Input is a
    table (features [N,C,H,W], rois [R,5] of (batch_idx, x1, y1, x2, y2)).
    Implemented with dense masks per output cell so shapes stay static under
    jit (no data-dependent slicing on TPU)."""

    def __init__(self, pooled_w: int, pooled_h: int, spatial_scale: float = 1.0):
        super().__init__()
        self.pooled_w, self.pooled_h = pooled_w, pooled_h
        self.spatial_scale = spatial_scale

    def update_output(self, input):
        data, rois = input
        n, c, h, w = data.shape

        def pool_one(roi):
            b = roi[0].astype(jnp.int32)
            x1 = jnp.round(roi[1] * self.spatial_scale).astype(jnp.int32)
            y1 = jnp.round(roi[2] * self.spatial_scale).astype(jnp.int32)
            x2 = jnp.round(roi[3] * self.spatial_scale).astype(jnp.int32)
            y2 = jnp.round(roi[4] * self.spatial_scale).astype(jnp.int32)
            roi_w = jnp.maximum(x2 - x1 + 1, 1)
            roi_h = jnp.maximum(y2 - y1 + 1, 1)
            bin_w = roi_w.astype(jnp.float32) / self.pooled_w
            bin_h = roi_h.astype(jnp.float32) / self.pooled_h
            feat = data[b]  # (C, H, W)

            ys = jnp.arange(h)
            xs = jnp.arange(w)

            def cell(py, px):
                hstart = jnp.floor(py * bin_h).astype(jnp.int32) + y1
                hend = jnp.ceil((py + 1) * bin_h).astype(jnp.int32) + y1
                wstart = jnp.floor(px * bin_w).astype(jnp.int32) + x1
                wend = jnp.ceil((px + 1) * bin_w).astype(jnp.int32) + x1
                hstart, hend = jnp.clip(hstart, 0, h), jnp.clip(hend, 0, h)
                wstart, wend = jnp.clip(wstart, 0, w), jnp.clip(wend, 0, w)
                mask = ((ys[:, None] >= hstart) & (ys[:, None] < hend)
                        & (xs[None, :] >= wstart) & (xs[None, :] < wend))
                empty = (hend <= hstart) | (wend <= wstart)
                masked = jnp.where(mask[None, :, :], feat, -jnp.inf)
                val = jnp.max(masked, axis=(1, 2))
                return jnp.where(empty, 0.0, val)

            py = jnp.arange(self.pooled_h)
            px = jnp.arange(self.pooled_w)
            return jax.vmap(lambda y: jax.vmap(lambda x: cell(y, x))(px))(py).transpose(2, 0, 1)

        return jax.vmap(pool_one)(rois.astype(jnp.float32))
