"""Pooling layers (SURVEY §2.5: SpatialMaxPooling, SpatialAveragePooling,
TemporalMaxPooling, VolumetricMaxPooling, RoiPooling).

The reference's hand-written pooling loops (``nn/NNPrimitive.scala:594-972``)
become ``lax.reduce_window`` — XLA lowers these to fused VPU reductions.
Ceil-mode semantics (Torch) are reproduced with explicit asymmetric padding;
average-pooling divisors follow the reference exactly: declared padding
counts when ``count_include_pad`` but ceil-overflow padding never does
(``SpatialAveragePooling.scala:133-135`` clips the pool size at the
declared pad).
"""

from __future__ import annotations

import itertools
import math
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module

__all__ = [
    "SpatialMaxPooling", "SpatialAveragePooling", "TemporalMaxPooling",
    "VolumetricMaxPooling", "VolumetricAveragePooling", "RoiPooling",
]


def _max_init(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return -jnp.inf
    return jnp.iinfo(dtype).min


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _maxpool_tie_split(x, dims, strides, pads):
    """Max pooling whose backward avoids XLA's ``select-and-scatter`` —
    profiled at ~20% of the whole Inception-v1 train step on TPU v5e (the
    op has no efficient TPU lowering).  The custom VJP re-derives the
    argmax by comparing each window tap against the pooled max and spreads
    the cotangent through ``lax.pad`` (interior padding = stride), which
    XLA fuses into plain VPU loops.

    Tie semantics: the gradient is split EQUALLY among tied maxima
    (gradient mass is conserved), where the reference's CPU loop sends it
    to the first argmax (``nn/NNPrimitive.scala:594-972``).  Ties have
    measure zero for continuous activations; tests that need bit-parity
    with Torch use ``torch_ties()`` to fall back to the lowering XLA
    autodiff picks."""
    return lax.reduce_window(x, _max_init(x.dtype), lax.max, dims, strides, pads)


def _maxpool_fwd(x, dims, strides, pads):
    y = _maxpool_tie_split(x, dims, strides, pads)
    return y, (x, y)


def _maxpool_taps(xp, off, out_shape, strides):
    """Strided window tap: element ``off`` of every pooling window."""
    limits = [o + (n - 1) * s + 1 for o, n, s in zip(off, out_shape, strides)]
    return lax.slice(xp, off, limits, strides)


def _maxpool_bwd(dims, strides, pads, res, gy):
    x, y = res
    xp = jnp.pad(x, pads, constant_values=_max_init(x.dtype))
    offsets = list(itertools.product(*[range(d) for d in dims]))
    # tie count per window (on the output grid)
    eqs = [_maxpool_taps(xp, off, y.shape, strides) == y for off in offsets]
    cnt = sum(e.astype(gy.dtype) for e in eqs)
    wgt = gy / cnt
    # transpose of the tap extraction: interior-pad back onto the padded
    # input grid, accumulate over window offsets, then crop the padding
    gxp = None
    for off, e in zip(offsets, eqs):
        contrib = jnp.where(e, wgt, jnp.zeros((), gy.dtype))
        cfg = [(o, xp.shape[ax] - (o + (y.shape[ax] - 1) * s + 1), s - 1)
               for ax, (o, s) in enumerate(zip(off, strides))]
        spread = lax.pad(contrib, jnp.zeros((), gy.dtype), cfg)
        gxp = spread if gxp is None else gxp + spread
    gx = lax.slice(gxp, [lo for lo, _ in pads],
                   [lo + n for (lo, _), n in zip(pads, x.shape)])
    return (gx,)


_maxpool_tie_split.defvjp(_maxpool_fwd, _maxpool_bwd)


def _pool_out_size(size: int, k: int, stride: int, pad: int, ceil_mode: bool) -> int:
    if ceil_mode:
        out = int(math.ceil(float(size - k + 2 * pad) / stride)) + 1
    else:
        out = int(math.floor(float(size - k + 2 * pad) / stride)) + 1
    if pad > 0 and (out - 1) * stride >= size + pad:
        out -= 1  # Torch: last window must start inside the (left-)padded input
    return out


def _axis_padding(size: int, k: int, stride: int, pad: int, ceil_mode: bool
                  ) -> Tuple[int, int, int]:
    """(lo, hi, declared_hi): hi includes ceil-overflow; declared_hi is the
    part of hi within the user-declared padding (counts toward the
    count_include_pad divisor)."""
    if pad == -1:  # SAME
        out = -(-size // stride)
        total = max(0, (out - 1) * stride + k - size)
        lo, hi = total // 2, total - total // 2
        return lo, hi, hi
    out = _pool_out_size(size, k, stride, pad, ceil_mode)
    needed = (out - 1) * stride + k
    hi = max(0, needed - size - pad)
    return pad, hi, min(hi, pad)


class _PoolBase(Module):
    """Shared window plumbing over the trailing spatial axes."""

    ceil_mode = False
    tie_split = True  # fast TPU backward (see _maxpool_tie_split)

    def torch_ties(self):
        """Bit-parity with the reference's first-argmax gradient (slow on
        TPU: XLA autodiff emits select-and-scatter)."""
        self.tie_split = False
        return self

    def _axes_spec(self, ndim) -> List[Tuple[int, int, int, int]]:
        """[(axis, k, stride, pad), ...] — subclasses define."""
        raise NotImplementedError

    def _window(self, x):
        dims = [1] * x.ndim
        strides = [1] * x.ndim
        pads = [(0, 0)] * x.ndim
        declared = [(0, 0)] * x.ndim
        for ax, k, d, p in self._axes_spec(x.ndim):
            dims[ax], strides[ax] = k, d
            lo, hi, dh = _axis_padding(x.shape[ax], k, d, p, self.ceil_mode)
            pads[ax] = (lo, hi)
            declared[ax] = (lo, dh)
        return tuple(dims), tuple(strides), pads, declared

    #: largest window (taps per element) the unrolled tie-split backward
    #: may handle — beyond this (e.g. global pooling over a 56x56 map)
    #: the per-tap unroll would blow up compile time, and XLA's
    #: select-and-scatter is used instead
    _TIE_SPLIT_MAX_TAPS = 64

    def _max(self, x):
        dims, strides, pads, _ = self._window(x)
        taps = 1
        for d in dims:
            taps *= d
        if self.tie_split and taps <= self._TIE_SPLIT_MAX_TAPS \
                and jnp.issubdtype(x.dtype, jnp.floating):
            return _maxpool_tie_split(x, dims, strides, tuple(pads))
        return lax.reduce_window(x, _max_init(x.dtype), lax.max, dims, strides, pads)

    def _avg(self, x, count_include_pad: bool, divide: bool = True):
        dims, strides, pads, declared = self._window(x)
        s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
        if not divide:
            return s
        if count_include_pad:
            # ones over data + declared padding; ceil-overflow region is zero
            ones = jnp.ones(x.shape, x.dtype)
            ones = jnp.pad(ones, declared, constant_values=1.0)
            extra = [(p[0] - d[0], p[1] - d[1]) for p, d in zip(pads, declared)]
            ones = jnp.pad(ones, extra, constant_values=0.0)
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides,
                                       [(0, 0)] * x.ndim)
        else:
            ones = jnp.ones(x.shape, x.dtype)
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
        return s / counts


class SpatialMaxPooling(_PoolBase):
    """(``nn/SpatialMaxPooling.scala``); pad == -1 means SAME (per axis)."""

    def __init__(self, kw: int, kh: int, dw: Optional[int] = None, dh: Optional[int] = None,
                 pad_w: int = 0, pad_h: int = 0, format: str = "NCHW",
                 global_pooling: bool = False):
        super().__init__()
        self.kw, self.kh = kw, kh
        self.dw, self.dh = dw or kw, dh or kh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.format = format
        self.ceil_mode = False
        self.global_pooling = global_pooling

    def ceil(self):
        self.ceil_mode = True
        return self

    def floor(self):
        self.ceil_mode = False
        return self

    def _axes_spec(self, ndim):
        if self.format == "NHWC":
            h_ax, w_ax = ndim - 3, ndim - 2
        else:
            h_ax, w_ax = ndim - 2, ndim - 1
        return [(h_ax, self.kh, self.dh, self.pad_h),
                (w_ax, self.kw, self.dw, self.pad_w)]

    def _apply_global(self, input):
        if self.global_pooling:
            spec = self._axes_spec(input.ndim)
            (h_ax, *_), (w_ax, *_) = spec
            self.kh, self.kw = input.shape[h_ax], input.shape[w_ax]
            self.dh, self.dw = self.kh, self.kw

    def update_output(self, input):
        self._apply_global(input)
        return self._max(input)


class SpatialAveragePooling(SpatialMaxPooling):
    """(``nn/SpatialAveragePooling.scala``)."""

    def __init__(self, kw: int, kh: int, dw: Optional[int] = None, dh: Optional[int] = None,
                 pad_w: int = 0, pad_h: int = 0, global_pooling: bool = False,
                 ceil_mode: bool = False, count_include_pad: bool = True,
                 divide: bool = True, format: str = "NCHW"):
        super().__init__(kw, kh, dw, dh, pad_w, pad_h, format)
        self.ceil_mode = ceil_mode
        self.global_pooling = global_pooling
        self.count_include_pad = count_include_pad
        self.divide = divide

    def update_output(self, input):
        self._apply_global(input)
        return self._avg(input, self.count_include_pad, self.divide)


class TemporalMaxPooling(_PoolBase):
    """1-D max pooling over [batch, time, feature]
    (``nn/TemporalMaxPooling.scala``)."""

    def __init__(self, k_w: int, d_w: Optional[int] = None):
        super().__init__()
        self.k_w, self.d_w = k_w, d_w or k_w

    def _axes_spec(self, ndim):
        return [(ndim - 2, self.k_w, self.d_w, 0)]

    def update_output(self, input):
        return self._max(input)


class VolumetricMaxPooling(_PoolBase):
    """3-D max pooling over [batch, C, T, H, W]
    (``nn/VolumetricMaxPooling.scala``)."""

    def __init__(self, k_t: int, k_w: int, k_h: int,
                 d_t: Optional[int] = None, d_w: Optional[int] = None, d_h: Optional[int] = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        self.k_t, self.k_w, self.k_h = k_t, k_w, k_h
        self.d_t, self.d_w, self.d_h = d_t or k_t, d_w or k_w, d_h or k_h
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.ceil_mode = False

    def ceil(self):
        self.ceil_mode = True
        return self

    def _axes_spec(self, ndim):
        return [(ndim - 3, self.k_t, self.d_t, self.pad_t),
                (ndim - 2, self.k_h, self.d_h, self.pad_h),
                (ndim - 1, self.k_w, self.d_w, self.pad_w)]

    def update_output(self, input):
        return self._max(input)


class VolumetricAveragePooling(VolumetricMaxPooling):
    """(``nn/VolumetricAveragePooling.scala``)."""

    def __init__(self, k_t: int, k_w: int, k_h: int,
                 d_t: Optional[int] = None, d_w: Optional[int] = None, d_h: Optional[int] = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 count_include_pad: bool = True):
        super().__init__(k_t, k_w, k_h, d_t, d_w, d_h, pad_t, pad_w, pad_h)
        self.count_include_pad = count_include_pad

    def update_output(self, input):
        return self._avg(input, self.count_include_pad)


class RoiPooling(Module):
    """Region-of-interest max pooling (``nn/RoiPooling.scala``).  Input is a
    table (features [N,C,H,W], rois [R,5] of (batch_idx, x1, y1, x2, y2)).
    Implemented with dense masks per output cell so shapes stay static under
    jit (no data-dependent slicing on TPU)."""

    def __init__(self, pooled_w: int, pooled_h: int, spatial_scale: float = 1.0):
        super().__init__()
        self.pooled_w, self.pooled_h = pooled_w, pooled_h
        self.spatial_scale = spatial_scale

    def update_output(self, input):
        data, rois = input
        n, c, h, w = data.shape

        def pool_one(roi):
            b = roi[0].astype(jnp.int32)
            x1 = jnp.round(roi[1] * self.spatial_scale).astype(jnp.int32)
            y1 = jnp.round(roi[2] * self.spatial_scale).astype(jnp.int32)
            x2 = jnp.round(roi[3] * self.spatial_scale).astype(jnp.int32)
            y2 = jnp.round(roi[4] * self.spatial_scale).astype(jnp.int32)
            roi_w = jnp.maximum(x2 - x1 + 1, 1)
            roi_h = jnp.maximum(y2 - y1 + 1, 1)
            bin_w = roi_w.astype(jnp.float32) / self.pooled_w
            bin_h = roi_h.astype(jnp.float32) / self.pooled_h
            feat = data[b]  # (C, H, W)

            ys = jnp.arange(h)
            xs = jnp.arange(w)

            def cell(py, px):
                hstart = jnp.floor(py * bin_h).astype(jnp.int32) + y1
                hend = jnp.ceil((py + 1) * bin_h).astype(jnp.int32) + y1
                wstart = jnp.floor(px * bin_w).astype(jnp.int32) + x1
                wend = jnp.ceil((px + 1) * bin_w).astype(jnp.int32) + x1
                hstart, hend = jnp.clip(hstart, 0, h), jnp.clip(hend, 0, h)
                wstart, wend = jnp.clip(wstart, 0, w), jnp.clip(wend, 0, w)
                mask = ((ys[:, None] >= hstart) & (ys[:, None] < hend)
                        & (xs[None, :] >= wstart) & (xs[None, :] < wend))
                empty = (hend <= hstart) | (wend <= wstart)
                masked = jnp.where(mask[None, :, :], feat, -jnp.inf)
                val = jnp.max(masked, axis=(1, 2))
                return jnp.where(empty, 0.0, val)

            py = jnp.arange(self.pooled_h)
            px = jnp.arange(self.pooled_w)
            return jax.vmap(lambda y: jax.vmap(lambda x: cell(y, x))(px))(py).transpose(2, 0, 1)

        return jax.vmap(pool_one)(rois.astype(jnp.float32))
