"""Criterions (losses).

Parity with the reference's criterion catalog (SURVEY §2.5; base class
``nn/abstractnn/AbstractCriterion.scala``): ``forward(input, target)``
computes the loss, ``backward(input, target)`` the input gradient.  Unlike
the reference's hand-written ``updateGradInput`` per loss, backward here is
``jax.grad`` of the pure forward — one definition, exact gradients.

Label convention: the reference (Torch lineage) uses 1-based class labels;
this framework is 0-based by default (idiomatic for a new Python/JAX API),
with ``one_based=True`` available on classification losses for users porting
reference pipelines.
"""

from __future__ import annotations

import copy
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "Criterion",
    "AbstractCriterion",
    "ClassNLLCriterion",
    "CrossEntropyCriterion",
    "BCECriterion",
    "MSECriterion",
    "AbsCriterion",
    "SmoothL1Criterion",
    "SmoothL1CriterionWithWeights",
    "DistKLDivCriterion",
    "HingeEmbeddingCriterion",
    "L1HingeEmbeddingCriterion",
    "MarginCriterion",
    "MarginRankingCriterion",
    "MultiCriterion",
    "ParallelCriterion",
    "MultiLabelMarginCriterion",
    "MultiLabelSoftMarginCriterion",
    "MultiMarginCriterion",
    "SoftMarginCriterion",
    "L1Cost",
    "CosineEmbeddingCriterion",
    "CosineDistanceCriterion",
    "ClassSimplexCriterion",
    "DiceCoefficientCriterion",
    "TimeDistributedCriterion",
    "SoftmaxWithCriterion",
]


class Criterion:
    """Loss base (``nn/abstractnn/AbstractCriterion.scala``)."""

    def __init__(self):
        self.output = None
        self.grad_input = None
        self.forward_time = 0.0
        self.backward_time = 0.0

    def update_output(self, input, target):
        raise NotImplementedError

    def forward(self, input, target):
        t0 = time.perf_counter()
        self.output = self.update_output(input, target)
        self.forward_time += time.perf_counter() - t0
        return self.output

    __call__ = forward

    def backward(self, input, target):
        t0 = time.perf_counter()
        self.grad_input = jax.grad(lambda x: jnp.sum(self.update_output(x, target)))(input)
        self.backward_time += time.perf_counter() - t0
        return self.grad_input

    def clone_criterion(self):
        return copy.deepcopy(self)


AbstractCriterion = Criterion


def _reduce(x, size_average: bool):
    return jnp.mean(x) if size_average else jnp.sum(x)


def _to_index(target, one_based: bool):
    t = jnp.asarray(target)
    if t.dtype in (jnp.float32, jnp.float64, jnp.bfloat16):
        t = t.astype(jnp.int32)
    if one_based:
        t = t - 1
    return t


class ClassNLLCriterion(Criterion):
    """Negative log-likelihood over log-probability input
    (``nn/ClassNLLCriterion.scala``)."""

    def __init__(self, weights=None, size_average: bool = True,
                 log_prob_as_input: bool = True, one_based: bool = False):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average
        self.log_prob_as_input = log_prob_as_input
        self.one_based = one_based

    def update_output(self, input, target):
        t = _to_index(target, self.one_based)
        logp = input if self.log_prob_as_input else jnp.log(jnp.clip(input, 1e-8))
        if logp.ndim == 1:
            logp = logp[None, :]
            t = jnp.reshape(t, (1,))
        t = jnp.reshape(t, (logp.shape[0],))
        picked = jnp.take_along_axis(logp, t[:, None], axis=1)[:, 0]
        if self.weights is not None:
            w = self.weights[t]
            total = -jnp.sum(w * picked)
            return total / jnp.sum(w) if self.size_average else total
        return _reduce(-picked, self.size_average)


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused (``nn/CrossEntropyCriterion.scala``)."""

    def __init__(self, weights=None, size_average: bool = True, one_based: bool = False):
        super().__init__()
        self.nll = ClassNLLCriterion(weights, size_average, True, one_based)

    def update_output(self, input, target):
        return self.nll.update_output(jax.nn.log_softmax(input, axis=-1), target)


class BCECriterion(Criterion):
    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def update_output(self, input, target):
        eps = 1e-12
        x = jnp.clip(input, eps, 1.0 - eps)
        t = jnp.asarray(target, x.dtype)
        loss = -(t * jnp.log(x) + (1.0 - t) * jnp.log(1.0 - x))
        if self.weights is not None:
            loss = loss * self.weights
        return _reduce(loss, self.size_average)


class MSECriterion(Criterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def update_output(self, input, target):
        return _reduce((input - jnp.asarray(target, input.dtype)) ** 2, self.size_average)


class AbsCriterion(Criterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def update_output(self, input, target):
        return _reduce(jnp.abs(input - jnp.asarray(target, input.dtype)), self.size_average)


class SmoothL1Criterion(Criterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def update_output(self, input, target):
        d = jnp.abs(input - jnp.asarray(target, input.dtype))
        loss = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return _reduce(loss, self.size_average)


class SmoothL1CriterionWithWeights(Criterion):
    """Smooth-L1 with inside/outside weights (Fast-RCNN bbox loss,
    ``nn/SmoothL1CriterionWithWeights.scala``). Target is a table
    (target, inside_w, outside_w)."""

    def __init__(self, sigma: float = 1.0, num: int = 0):
        super().__init__()
        self.sigma2 = sigma * sigma
        self.num = num

    def update_output(self, input, target):
        t, w_in, w_out = target
        d = w_in * (input - t)
        ad = jnp.abs(d)
        loss = jnp.where(ad < 1.0 / self.sigma2,
                         0.5 * self.sigma2 * d * d,
                         ad - 0.5 / self.sigma2)
        total = jnp.sum(w_out * loss)
        return total / self.num if self.num > 0 else total


class DistKLDivCriterion(Criterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def update_output(self, input, target):
        t = jnp.asarray(target, input.dtype)
        loss = jnp.where(t > 0, t * (jnp.log(jnp.clip(t, 1e-12)) - input), 0.0)
        return _reduce(loss, self.size_average)


class HingeEmbeddingCriterion(Criterion):
    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def update_output(self, input, target):
        t = jnp.asarray(target, input.dtype)
        loss = jnp.where(t == 1, input, jnp.maximum(0.0, self.margin - input))
        return _reduce(loss, self.size_average)


class L1HingeEmbeddingCriterion(Criterion):
    """Pairwise L1-distance hinge; input is a table (x1, x2)."""

    def __init__(self, margin: float = 1.0):
        super().__init__()
        self.margin = margin

    def update_output(self, input, target):
        x1, x2 = input
        d = jnp.sum(jnp.abs(x1 - x2))
        t = jnp.reshape(jnp.asarray(target), ())
        return jnp.where(t == 1, d, jnp.maximum(0.0, self.margin - d))


class MarginCriterion(Criterion):
    def __init__(self, margin: float = 1.0, size_average: bool = True, squared: bool = False):
        super().__init__()
        self.margin = margin
        self.size_average = size_average
        self.squared = squared

    def update_output(self, input, target):
        t = jnp.asarray(target, input.dtype)
        h = jnp.maximum(0.0, self.margin - input * t)
        if self.squared:
            h = h * h
        return _reduce(h, self.size_average)


class MarginRankingCriterion(Criterion):
    """input = (x1, x2); loss = max(0, -y*(x1-x2) + margin)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def update_output(self, input, target):
        x1, x2 = input
        y = jnp.asarray(target, x1.dtype)
        loss = jnp.maximum(0.0, -y * (x1 - x2) + self.margin)
        return _reduce(loss, self.size_average)


class MultiCriterion(Criterion):
    """Weighted sum of criterions over the SAME (input, target)."""

    def __init__(self):
        super().__init__()
        self.criterions: list[Criterion] = []
        self.weights: list[float] = []

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def update_output(self, input, target):
        return sum(w * c.update_output(input, target)
                   for c, w in zip(self.criterions, self.weights))


class ParallelCriterion(Criterion):
    """Each criterion applied to its own (input[i], target[i]) pair."""

    def __init__(self, repeat_target: bool = False):
        super().__init__()
        self.repeat_target = repeat_target
        self.criterions: list[Criterion] = []
        self.weights: list[float] = []

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.criterions.append(criterion)
        self.weights.append(weight)
        return self

    def update_output(self, input, target):
        total = 0.0
        for i, (c, w) in enumerate(zip(self.criterions, self.weights)):
            t = target if self.repeat_target else target[i]
            total = total + w * c.update_output(input[i], t)
        return total


class MultiLabelMarginCriterion(Criterion):
    """Multi-class multi-label hinge (torch ``MultiLabelMarginCriterion``).
    Target rows list positive class indices, padded with -1 (0-based) or 0
    (1-based)."""

    def __init__(self, size_average: bool = True, one_based: bool = False):
        super().__init__()
        self.size_average = size_average
        self.one_based = one_based

    def update_output(self, input, target):
        x = input if input.ndim == 2 else input[None, :]
        t = jnp.asarray(target)
        t = t if t.ndim == 2 else t[None, :]
        pad = 0 if self.one_based else -1
        valid = t != pad
        idx = (t - 1 if self.one_based else t)
        idx = jnp.where(valid, idx, 0).astype(jnp.int32)
        n, c = x.shape

        def per_sample(xi, idxi, validi):
            pos = xi[idxi]  # (K,)
            # padding entries scatter to index c (out of bounds → dropped)
            is_target = jnp.zeros((c,), bool).at[jnp.where(validi, idxi, c)].set(
                True, mode="drop")
            # hinge between every valid positive and every non-target class
            margins = jnp.maximum(0.0, 1.0 - (pos[:, None] - xi[None, :]))
            margins = margins * validi[:, None] * (~is_target)[None, :]
            return jnp.sum(margins) / c

        losses = jax.vmap(per_sample)(x, idx, valid)
        return _reduce(losses, self.size_average)


class MultiLabelSoftMarginCriterion(Criterion):
    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def update_output(self, input, target):
        t = jnp.asarray(target, input.dtype)
        # numerically stable log-sigmoid formulation
        loss = jnp.maximum(input, 0) - input * t + jnp.log1p(jnp.exp(-jnp.abs(input)))
        if self.weights is not None:
            loss = loss * self.weights
        n_class = input.shape[-1]
        if self.size_average:
            return jnp.mean(jnp.sum(loss, axis=-1) / n_class)
        return jnp.sum(loss) / n_class


class MultiMarginCriterion(Criterion):
    def __init__(self, p: int = 1, weights=None, margin: float = 1.0,
                 size_average: bool = True, one_based: bool = False):
        super().__init__()
        self.p = p
        self.weights = None if weights is None else jnp.asarray(weights)
        self.margin = margin
        self.size_average = size_average
        self.one_based = one_based

    def update_output(self, input, target):
        x = input if input.ndim == 2 else input[None, :]
        t = _to_index(target, self.one_based).reshape((x.shape[0],))
        n, c = x.shape
        correct = jnp.take_along_axis(x, t[:, None], axis=1)
        m = jnp.maximum(0.0, self.margin - correct + x)
        if self.p == 2:
            m = m * m
        if self.weights is not None:
            m = m * self.weights[t][:, None]
        mask = jax.nn.one_hot(t, c, dtype=x.dtype)
        loss = jnp.sum(m * (1.0 - mask), axis=1) / c
        return _reduce(loss, self.size_average)


class SoftMarginCriterion(Criterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def update_output(self, input, target):
        t = jnp.asarray(target, input.dtype)
        return _reduce(jax.nn.softplus(-input * t), self.size_average)


class L1Cost(Criterion):
    def update_output(self, input, target=None):
        return jnp.sum(jnp.abs(input))


class CosineEmbeddingCriterion(Criterion):
    """input = (x1, x2), target ±1 (``nn/CosineEmbeddingCriterion.scala``)."""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        super().__init__()
        self.margin = margin
        self.size_average = size_average

    def update_output(self, input, target):
        x1, x2 = input
        if x1.ndim == 1:
            x1, x2 = x1[None, :], x2[None, :]
        y = jnp.reshape(jnp.asarray(target, x1.dtype), (-1,))
        cos = jnp.sum(x1 * x2, axis=1) / jnp.clip(
            jnp.linalg.norm(x1, axis=1) * jnp.linalg.norm(x2, axis=1), 1e-12)
        loss = jnp.where(y > 0, 1.0 - cos, jnp.maximum(0.0, cos - self.margin))
        return _reduce(loss, self.size_average)


class CosineDistanceCriterion(Criterion):
    """loss = 1 - cos(input, target) (``nn/CosineDistanceCriterion.scala``)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def update_output(self, input, target):
        x, t = input, jnp.asarray(target, input.dtype)
        if x.ndim == 1:
            x, t = x[None, :], t[None, :]
        cos = jnp.sum(x * t, axis=1) / jnp.clip(
            jnp.linalg.norm(x, axis=1) * jnp.linalg.norm(t, axis=1), 1e-12)
        return _reduce(1.0 - cos, self.size_average)


class ClassSimplexCriterion(Criterion):
    """MSE against a regular-simplex embedding of the class label
    (``nn/ClassSimplexCriterion.scala``)."""

    def __init__(self, n_classes: int, size_average: bool = True, one_based: bool = False):
        super().__init__()
        self.n_classes = n_classes
        self.size_average = size_average
        self.one_based = one_based
        self.simplex = jnp.asarray(self._build_simplex(n_classes))

    @staticmethod
    def _build_simplex(n):
        import numpy as np

        a = np.zeros((n, n), dtype=np.float32)
        a[0, 0] = 1.0
        for k in range(1, n):
            for c in range(k):
                a[k, c] = (-1.0 / n - np.dot(a[k, :c], a[c, :c])) / a[c, c]
            a[k, k] = np.sqrt(max(0.0, 1.0 - np.sum(a[k, :k] ** 2)))
        return a

    def update_output(self, input, target):
        t = _to_index(target, self.one_based).reshape((-1,))
        goal = self.simplex[t]
        return _reduce((input - goal) ** 2, self.size_average)


class DiceCoefficientCriterion(Criterion):
    def __init__(self, size_average: bool = True, epsilon: float = 1.0):
        super().__init__()
        self.size_average = size_average
        self.epsilon = epsilon

    def update_output(self, input, target):
        t = jnp.asarray(target, input.dtype)
        x = input.reshape((input.shape[0], -1)) if input.ndim > 1 else input[None, :]
        t = t.reshape((x.shape[0], -1))
        inter = jnp.sum(x * t, axis=1)
        union = jnp.sum(x, axis=1) + jnp.sum(t, axis=1)
        # epsilon offsets BOTH terms (DiceCoefficientCriterion.scala:69-81)
        dice = 1.0 - (2.0 * inter + self.epsilon) / (union + self.epsilon)
        return _reduce(dice, self.size_average)


class TimeDistributedCriterion(Criterion):
    """Apply an inner criterion at every timestep of [batch, time, ...]
    (``nn/TimeDistributedCriterion.scala``)."""

    def __init__(self, criterion: Criterion, size_average: bool = False):
        super().__init__()
        self.criterion = criterion
        self.size_average = size_average

    def update_output(self, input, target):
        # reference semantics: the inner criterion runs PER TIMESTEP and
        # the step losses are summed (averaged when size_average).
        # vmap over the time axis keeps that exact for ANY inner
        # criterion — including weighted ones whose per-step
        # normalization differs from a flattened [B*T] pass — without
        # unrolling the sequence.
        t = input.shape[1]
        losses = jax.vmap(self.criterion.update_output, in_axes=(1, 1))(
            input, jnp.asarray(target))
        total = jnp.sum(losses)
        return total / t if self.size_average else total


class SoftmaxWithCriterion(Criterion):
    """Caffe-style SoftmaxWithLoss over spatial maps [N,C,H,W]
    (``nn/SoftmaxWithCriterion.scala``)."""

    def __init__(self, ignore_label: Optional[int] = None,
                 normalize_mode: str = "VALID", one_based: bool = False):
        super().__init__()
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode
        self.one_based = one_based

    def update_output(self, input, target):
        logp = jax.nn.log_softmax(input, axis=1)
        t_raw = jnp.asarray(target)
        if t_raw.dtype in (jnp.float32, jnp.float64, jnp.bfloat16):
            t_raw = t_raw.astype(jnp.int32)
        t_raw = t_raw.reshape((input.shape[0],) + input.shape[2:])
        t = t_raw - 1 if self.one_based else t_raw
        if self.ignore_label is not None:
            # ignore_label is in the user's raw convention; clamp ignored
            # pixels to a valid row before the gather
            mask = (t_raw != self.ignore_label)
            t = jnp.where(mask, t, 0)
        t = jnp.clip(t, 0, input.shape[1] - 1)
        picked = jnp.take_along_axis(logp, t[:, None, ...], axis=1)[:, 0]
        if self.ignore_label is not None:
            picked = picked * mask
            valid = jnp.sum(mask)
        else:
            valid = picked.size
        total = -jnp.sum(picked)
        if self.normalize_mode == "VALID":
            return total / jnp.maximum(valid, 1)
        if self.normalize_mode == "FULL":
            return total / picked.size
        if self.normalize_mode == "BATCH_SIZE":
            return total / input.shape[0]
        return total
