"""Device-mesh utilities (the TPU-native replacement for the reference's
Engine node/core topology, ``utils/Engine.scala:313-418``).

Axes convention (each axis has working machinery behind it):
- ``data``  — data parallelism (the reference's only axis;
  ``parallel/train_step.py`` batch sharding + ZeRO-1)
- ``model`` — tensor parallelism (``TrainStep.extra_sharding_rules``
  megatron-style weight shardings; see ``__graft_entry__.dryrun_multichip``)
- ``seq``   — sequence/context parallelism for long sequences
  (``parallel/sequence.py`` ring attention / Ulysses all-to-all)
- ``pipe``  — pipeline stages (``parallel/pipeline.py`` GPipe/ppermute
  schedule)
- ``expert``— expert parallelism for MoE layers
  (``nn/layers/moe.py`` GShard-style dense dispatch)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["make_mesh", "data_sharding", "replicated", "mesh_process_count",
           "shard_local_batch", "DATA_AXIS", "MODEL_AXIS", "SEQ_AXIS",
           "PIPE_AXIS", "EXPERT_AXIS"]

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


def make_mesh(shape: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = (DATA_AXIS,),
              devices=None):
    """Build a ``jax.sharding.Mesh``.  ``shape=None`` puts all devices on
    the first axis."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    arr = np.array(devs).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def data_sharding(mesh, ndim: int, batch_axes: Sequence[str] = (DATA_AXIS,)):
    """NamedSharding that splits the leading axis over the data axes."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = [None] * ndim
    spec[0] = batch_axes[0] if len(batch_axes) == 1 else tuple(batch_axes)
    return NamedSharding(mesh, P(*spec))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def mesh_process_count(mesh) -> int:
    """Number of host processes the mesh spans (1 = single-host)."""
    if mesh is None:
        return 1
    return len({d.process_index for d in mesh.devices.flat})


def _batch_scale(mesh, batch_axes: Sequence[str]) -> int:
    """global_rows // local_rows for THIS process: how many times larger
    the global batch dim is than the rows this process feeds.

    The batch dim is split K ways (K = prod of the batch axes' mesh
    sizes); this process addresses K_p distinct batch-shard positions, so
    it feeds K_p/K of the global rows.  On a mesh whose batch axes do NOT
    span processes (e.g. multi-host model/seq parallelism with data=1)
    K_p == K and every process feeds the full global batch."""
    import jax

    axes = [mesh.axis_names.index(a) for a in batch_axes]
    k = 1
    for a in batch_axes:
        k *= mesh.shape[a]
    pid = jax.process_index()
    coords = {tuple(idx[i] for i in axes)
              for idx in np.ndindex(mesh.devices.shape)
              if mesh.devices[idx].process_index == pid}
    if k % len(coords) != 0:
        raise ValueError(
            f"batch axes {batch_axes} split {k} ways but this process "
            f"addresses {len(coords)} positions — uneven process layout")
    return k // len(coords)


def shard_local_batch(mesh, local, batch_axes: Sequence[str] = (DATA_AXIS,)):
    """Place one process's shard of the global batch onto the mesh.

    Single-host: plain ``device_put`` of the (already global) batch.
    Multi-host: each process passes its LOCAL rows and the global array is
    assembled with ``jax.make_array_from_process_local_data`` — the
    TPU-native analogue of the reference's one-cached-partition-per-node
    feeding (``dataset/DataSet.scala:164-240``)."""
    import jax
    import jax.numpy as jnp

    sharding = data_sharding(mesh, np.ndim(local), batch_axes)
    if mesh_process_count(mesh) == 1:
        return jax.device_put(jnp.asarray(local), sharding)
    local = np.asarray(local)
    scale = _batch_scale(mesh, batch_axes)
    global_shape = (local.shape[0] * scale,) + local.shape[1:]
    return jax.make_array_from_process_local_data(sharding, local,
                                                  global_shape)
