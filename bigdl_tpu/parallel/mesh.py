"""Device-mesh utilities (the TPU-native replacement for the reference's
Engine node/core topology, ``utils/Engine.scala:313-418``).

Axes convention:
- ``data``  — data parallelism (the reference's only axis)
- ``model`` — tensor parallelism (new capability, TPU-first)
- ``seq``   — sequence/context parallelism for long sequences (ring
  attention / all-to-all; new capability)
- ``pipe``  — pipeline stages
- ``expert``— expert parallelism for MoE layers
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["make_mesh", "data_sharding", "replicated", "DATA_AXIS",
           "MODEL_AXIS", "SEQ_AXIS", "PIPE_AXIS", "EXPERT_AXIS"]

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


def make_mesh(shape: Optional[Sequence[int]] = None,
              axis_names: Sequence[str] = (DATA_AXIS,),
              devices=None):
    """Build a ``jax.sharding.Mesh``.  ``shape=None`` puts all devices on
    the first axis."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    arr = np.array(devs).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def data_sharding(mesh, ndim: int, batch_axes: Sequence[str] = (DATA_AXIS,)):
    """NamedSharding that splits the leading axis over the data axes."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = [None] * ndim
    spec[0] = batch_axes[0] if len(batch_axes) == 1 else tuple(batch_axes)
    return NamedSharding(mesh, P(*spec))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())
