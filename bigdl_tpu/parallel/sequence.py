"""Sequence / context parallelism: ring attention and Ulysses all-to-all.

The reference has NO long-context machinery — sequences are bounded by
single-node memory and iterated locally (SURVEY §5 "Long-context ...
Absent"); this subsystem is the TPU-first design the capability demands.
Two strategies, both SPMD over a ``seq`` mesh axis:

- **Ring attention** (`ring_attention`): q stays put; k/v chunks rotate
  around the ring via ``lax.ppermute`` (XLA lowers to ICI neighbor
  transfers that overlap with the blockwise compute), partial softmax
  states merged with the online-softmax algebra from
  ``bigdl_tpu.ops.attention``.  Memory per chip: O(S_local), supports
  sequences N_devices x longer than one chip holds.  Differentiable for
  free (ppermute's transpose is the reverse permute).

- **Ulysses** (`ulysses_attention`): ``lax.all_to_all`` re-shards
  [seq-sharded, all heads] -> [head-sharded, full seq], runs ordinary
  (flash) attention per local head group, and re-shards back.  Cheaper
  collectives for moderate S; requires heads % n_devices == 0.

Both are meant to be called INSIDE ``shard_map``/pjit with q,k,v already
sharded on the sequence axis; ``make_sequence_parallel_attention`` builds
the shard_map wrapper over a mesh for direct use on global arrays.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.ops.attention import (attention_partial, combine_partials,
                                     flash_attention, _NEG_INF)

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "make_sequence_parallel_attention",
    "SEQ_AXIS",
]

SEQ_AXIS = "seq"


def ring_attention(q, k, v, axis_name: str = SEQ_AXIS,
                   causal: bool = False, scale: Optional[float] = None,
                   axis_size: Optional[int] = None):
    """Ring attention over local shards [B, H, S_local, D].

    Call inside shard_map with q/k/v sharded along seq.  Each of the
    ``n`` steps computes a blockwise partial against the currently-held
    k/v chunk, then rotates k/v to the next ring neighbor.
    """
    d = q.shape[-1]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    n = axis_size if axis_size is not None else int(lax.psum(1, axis_name))
    idx = lax.axis_index(axis_name)
    s_local = q.shape[2]

    q_pos = idx * s_local + jnp.arange(s_local)
    b, h, sq, _ = q.shape
    state = (jnp.zeros((b, h, sq, d), jnp.float32),
             jnp.full((b, h, sq), _NEG_INF, jnp.float32),
             jnp.zeros((b, h, sq), jnp.float32))

    perm = [(i, (i + 1) % n) for i in range(n)]
    neutral = state
    for step in range(n):
        src = (idx - step) % n
        if causal:
            # branch by chunk position so fully-future chunks cost nothing
            # and fully-past chunks skip the mask: 0 = skip (src > idx),
            # 1 = diagonal triangle (src == idx), 2 = unmasked (src < idx)
            k_pos = src * s_local + jnp.arange(s_local)
            tri_mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
            case = jnp.where(src < idx, 2, jnp.where(src == idx, 1, 0))
            part = lax.switch(case, [
                lambda kv: neutral,
                lambda kv: attention_partial(q, kv[0], kv[1], scale,
                                             mask=tri_mask),
                lambda kv: attention_partial(q, kv[0], kv[1], scale),
            ], (k, v))
        else:
            part = attention_partial(q, k, v, scale)
        state = combine_partials(state, part)
        if step != n - 1:
            k, v = lax.ppermute((k, v), axis_name, perm)
    acc, _, l = state
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = SEQ_AXIS,
                      causal: bool = False, scale: Optional[float] = None,
                      use_flash: bool = False):
    """Ulysses sequence parallelism over local shards [B, H, S_local, D].

    all_to_all to [B, H/n, S_global, D], local full-sequence attention
    (optionally the Pallas flash kernel), all_to_all back.
    """
    # [B, H, S_local, D] -> [B, H/n, S_global, D]
    qg = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    kg = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    vg = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    if use_flash:
        out = flash_attention(qg, kg, vg, causal=causal, scale=scale)
    else:
        from bigdl_tpu.ops.attention import dot_product_attention

        out = dot_product_attention(qg, kg, vg, causal=causal, scale=scale)
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def make_sequence_parallel_attention(mesh, strategy: str = "ring",
                                     axis_name: str = SEQ_AXIS,
                                     causal: bool = False,
                                     scale: Optional[float] = None,
                                     use_flash: bool = False,
                                     batch_axis: Optional[str] = None):
    """shard_map-wrap ring/ulysses attention for global [B, H, S, D] arrays
    sharded on ``axis_name`` over ``mesh``.  Pass ``batch_axis`` to
    compose with data parallelism on a 2-D ``(data, seq)`` mesh: the
    batch dim shards over ``batch_axis`` while each data-row runs its own
    k/v ring over ``axis_name`` (ppermute is scoped per axis, so the
    rings never cross data rows)."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis_name]
    spec = P(batch_axis, None, axis_name, None)

    if strategy == "ring":
        fn = partial(ring_attention, axis_name=axis_name, causal=causal,
                     scale=scale, axis_size=n)
    elif strategy == "ulysses":
        fn = partial(ulysses_attention, axis_name=axis_name, causal=causal,
                     scale=scale, use_flash=use_flash)
    else:
        raise ValueError(f"unknown strategy {strategy!r}")

    try:
        return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)
    except TypeError:  # older shard_map API
        return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_rep=False)
