"""Pipeline parallelism over the ``pipe`` mesh axis.

The reference has no pipeline parallelism (synchronous data parallelism
only, SURVEY §2.7) — this is new TPU-first capability, like the ``seq``
machinery in ``parallel/sequence.py``.  The design is the SPMD/GPipe
collective-permute schedule (the standard TPU formulation — all chips run
the SAME program; no per-stage programs or send/recv graphs):

- the model is a stack of S structurally-identical blocks whose
  parameters carry a leading stage dimension sharded over ``pipe``;
- the global batch splits into M microbatches; the schedule runs
  ``M + S - 1`` ticks of ``lax.scan``.  Each tick every stage applies its
  block to its in-flight microbatch, then activations rotate one stage
  forward via ``lax.ppermute`` (ICI neighbor transfer, overlapped by XLA
  with the next tick's compute);
- stage 0 injects microbatch ``t`` at tick ``t``; the last stage emits
  microbatch ``t - (S-1)``; a bubble of ``S-1`` ticks is the usual GPipe
  cost, amortized by M;
- the whole schedule is differentiable (``ppermute``'s transpose is the
  reverse rotation), so ``jax.grad`` of the pipelined loss IS pipelined
  backprop — no hand-written backward schedule.

Heterogeneous stage stacks are out of scope by design: scan-over-stacked
blocks is the XLA-idiomatic form (one compiled block body), and a stack
of identical blocks is what pipeline parallelism is used for in practice
(transformer/MLP blocks).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.parallel.mesh import PIPE_AXIS

__all__ = ["pipeline_apply", "make_pipeline_fn", "PIPE_AXIS"]


def _stage_apply(block_fn, stage_params, h):
    """Apply the LOCAL stage's block (stage_params has a leading 1 dim
    inside shard_map)."""
    local = jax.tree.map(lambda a: a[0], stage_params)
    return block_fn(local, h)


def pipeline_apply(block_fn: Callable, stage_params, x_microbatches,
                   axis_name: str = PIPE_AXIS):
    """Run the pipelined stack INSIDE shard_map.

    ``block_fn(params, h) -> h``: one stage's computation.
    ``stage_params``: this stage's parameter shard, leading dim 1.
    ``x_microbatches``: [M, mb, ...] microbatches, replicated.
    Returns [M, mb, ...] outputs (valid on the LAST stage; other stages
    hold zeros — combine with ``lax.psum`` or mask outside if needed).
    """
    s = int(lax.psum(1, axis_name))
    stage = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    ticks = m + s - 1

    h0 = jnp.zeros_like(x_microbatches[0])
    out0 = jnp.zeros((m,) + x_microbatches.shape[1:],
                     x_microbatches.dtype)

    def tick(carry, t):
        h, outs = carry
        # stage 0 swallows microbatch t (clamped; masked later)
        inject = x_microbatches[jnp.minimum(t, m - 1)]
        h = jnp.where(stage == 0, inject, h)
        h = _stage_apply(block_fn, stage_params, h)
        # the last stage emits microbatch t-(s-1) once the fill ends
        emit_idx = t - (s - 1)
        valid = (stage == s - 1) & (emit_idx >= 0)
        outs = lax.cond(
            valid,
            lambda o: lax.dynamic_update_index_in_dim(
                o, h, jnp.maximum(emit_idx, 0), 0),
            lambda o: o, outs)
        # rotate activations one stage forward (ring; stage0's incoming
        # value is overwritten by the next inject)
        h = lax.ppermute(h, axis_name,
                         [(i, (i + 1) % s) for i in range(s)])
        return (h, outs), None

    (_, outs), _ = lax.scan(tick, (h0, out0), jnp.arange(ticks))
    return outs


def make_pipeline_fn(block_fn: Callable, mesh, n_microbatches: int,
                     axis_name: str = PIPE_AXIS):
    """Build ``fn(stacked_params, x) -> y`` running the S-stage stack
    pipelined over ``mesh``'s ``axis_name``.

    ``stacked_params``: pytree with leading stage dim S (sharded over the
    pipe axis by the returned fn's shard_map specs).
    ``x``: the [B, ...] global batch; B must divide by n_microbatches.
    Returns the [B, ...] outputs, replicated (psum of the last stage's
    emissions).
    """
    try:  # jax >= 0.6 exports shard_map at top level (check_vma kwarg)
        from jax import shard_map
        _sm_checked = partial(shard_map, check_vma=False)
    except ImportError:  # this jaxlib (0.4.x): experimental, check_rep
        from jax.experimental.shard_map import shard_map
        _sm_checked = partial(shard_map, check_rep=False)
    from jax.sharding import PartitionSpec as P

    s = mesh.shape[axis_name]

    def fn(stacked_params, x):
        b = x.shape[0]
        if b % n_microbatches:
            raise ValueError(
                f"batch {b} must divide into {n_microbatches} microbatches")
        x_mb = x.reshape((n_microbatches, b // n_microbatches) + x.shape[1:])

        p_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)

        @partial(_sm_checked, mesh=mesh,
                 in_specs=(p_specs, P()), out_specs=P())
        def run(params, xmb):
            outs = pipeline_apply(block_fn, params, xmb, axis_name)
            # only the last stage holds real outputs; psum replicates
            stage = lax.axis_index(axis_name)
            outs = jnp.where(stage == s - 1, outs, jnp.zeros_like(outs))
            return lax.psum(outs, axis_name)

        y_mb = run(stacked_params, x_mb)
        return y_mb.reshape((b,) + y_mb.shape[2:])

    return fn
