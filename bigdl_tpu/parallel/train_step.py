"""The compiled training step — the TPU-native collapse of the reference's
entire L4+L5 distributed machinery (SURVEY §2.7, §3.1).

Where the reference runs TWO Spark jobs per iteration (forward/backward +
putGradients, then aggregateGradientPartition + sharded update +
sendWeightPartition, ``optim/DistriOptimizer.scala:175-315``) with gradients
bounced through the BlockManager as bf16-truncated chunks, here ONE
jit/pjit-compiled function does it all inside XLA:

- batch sharded over the mesh ``data`` axis (the per-node minibatch split,
  ``DistriOptimizer.scala:184-202``),
- gradient averaging via the collective XLA inserts for the sharded batch
  (the getWeights/putGradients/aggregate round-trips,
  ``parameters/AllReduceParameter.scala:181-305``),
- optional **ZeRO-1 layout** (`parameter_sync='sharded'`): optimizer state
  sharded over ``data`` via sharding constraints so XLA lowers the gradient
  collective to reduce-scatter + all-gather around a 1/N-sized update —
  structurally identical to the reference's owner-node update
  (``DistriOptimizer.scala:294-315``),
- optional bf16 gradient compression matching the reference's
  top-16-bit truncation exactly (``parameters/FP16CompressedTensor.scala:272``),
- per-layer regularizers, gradient scales (setScaleW/B), and freeze masks
  applied functionally,
- BN running stats carried through the state pytree.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import telemetry as _telemetry
from bigdl_tpu.analysis import hooks as _hooks
from bigdl_tpu.nn.module import Module, functional_call, state_dict, _resolve
from bigdl_tpu.parallel.mesh import (DATA_AXIS, data_sharding,
                                     mesh_process_count, replicated,
                                     shard_local_batch)


def _jit_cache_size(compiled) -> Optional[int]:
    """Executable-cache entry count of a jit-wrapped callable (None when
    the jit internals don't expose it)."""
    try:
        return int(compiled._cache_size())
    except Exception:  # noqa: BLE001 - observability only, never fail
        return None


def _note_compile(tracer, owner, kind: str, before, t0: float,
                  compiled) -> bool:
    """Post-dispatch compile detection for the telemetry stream: the jit
    executable cache grew (or this is the owner's first dispatch and the
    cache size is unreadable) means the call just paid trace+compile —
    emit it with the wall time of the dispatch that carried it.  Returns
    whether a compile was recorded (the caller keys one-time facts off
    the first)."""
    after = _jit_cache_size(compiled)
    first = not getattr(owner, "_tele_dispatched", False)
    owner._tele_dispatched = True
    if before is not None and after is not None:
        grew = after > before
    else:
        grew = first
    if grew:
        fields = {"dur": time.perf_counter() - t0}
        if after is not None:
            fields["cache_size"] = after
        tracer.emit("compile", name=kind, **fields)
    return first

__all__ = ["TrainStep", "bf16_truncate", "EvalStep"]


def bf16_truncate(x: jax.Array) -> jax.Array:
    """Exact parity with the reference's FP16CompressedTensor: keep the top
    16 bits of the IEEE float32 (== bfloat16 round-toward-zero),
    ``FP16CompressedTensor.scala:272``."""
    if x.dtype != jnp.float32:
        return x
    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    return jax.lax.bitcast_convert_type(bits & jnp.uint32(0xFFFF0000), jnp.float32)


def _param_meta(model: Module):
    """Per-parameter (scale, frozen, regularizer) from the module tree."""
    meta = {}
    for path, _ in model.named_parameters():
        mod, leaf = _resolve(model, path)
        scale = mod.__dict__.get("scale_b", 1.0) if leaf == "bias" \
            else mod.__dict__.get("scale_w", 1.0)
        reg = mod.__dict__.get("b_regularizer") if leaf == "bias" \
            else mod.__dict__.get("w_regularizer")
        if reg is not None and not getattr(reg, "is_enabled", True):
            reg = None
        meta[path] = (scale, mod.__dict__["_frozen"], reg)
    return meta


class TrainStep:
    """Build and run the compiled train step.

    ``parameter_sync``: 'allreduce' (plain DP), 'sharded' (ZeRO-1: shard
    optimizer state over the data axis), 'fsdp' (ZeRO-3: shard the
    PARAMETERS themselves over the data axis too — no device holds a
    whole replica; XLA all-gathers each weight at use and lowers the
    gradient collective to reduce-scatter.  Pure GSPMD: the sharding
    annotations change, the step math doesn't), or 'local' (local SGD,
    docs/fault_tolerance.md "Straggler tolerance": every device along
    the data axis trains its OWN island — params/opt-state/buffers gain
    a leading island axis sharded over ``data`` and the step runs under
    ``vmap``, so the compiled program carries ZERO cross-island
    collectives; islands re-converge only when the driver calls
    :meth:`average_islands` every H steps, parallel/local_sync.py).
    ``gradient_compression``: None or 'bf16' (reference truncation
    semantics).
    ``compute_dtype``: e.g. jnp.bfloat16 to run fwd/bwd in bf16 with f32
    master params.
    ``health_probe``: compute the fused numeric-health reduction per
    step (global grad/param/update norms + nonfinite counts,
    ``telemetry/health.py PROBE_FIELDS``) as an extra step output,
    stored on ``self.last_health`` — an async device array whose values
    are ready once the loss fetch the driver already performs has
    synced, so reading it is a d2h copy, not another device sync.
    ``skip_nonfinite``: additionally KEEP the previous
    params/opt-state/buffers (in-graph select) whenever the step's
    gradients, updated params, or loss are nonfinite — the poisoned
    update never lands (donation-safe: the select is part of the same
    compiled program).
    """

    def __init__(self, model: Module, criterion, optim_method, mesh=None,
                 parameter_sync: str = "allreduce",
                 gradient_compression: Optional[str] = None,
                 compute_dtype=None,
                 batch_axes=(DATA_AXIS,),
                 extra_sharding_rules: Optional[Callable] = None,
                 gradient_clipping: Optional[Tuple[float, float]] = None,
                 max_norm: Optional[float] = None,
                 remat: bool = False,
                 health_probe: bool = False,
                 skip_nonfinite: bool = False,
                 grad_fault: bool = False):
        self.model = model
        self.criterion = criterion
        self.optim = optim_method
        self.mesh = mesh
        if parameter_sync not in ("allreduce", "sharded", "fsdp", "local"):
            # validate where the mode is CONSUMED: a typo must not
            # silently degrade to replicated allreduce
            raise ValueError(f"unknown parameter_sync {parameter_sync!r} "
                             f"(allreduce | sharded | fsdp | local)")
        self.parameter_sync = parameter_sync
        self.gradient_compression = gradient_compression
        self.compute_dtype = compute_dtype
        self.batch_axes = tuple(batch_axes)
        self.extra_sharding_rules = extra_sharding_rules
        self.gradient_clipping = gradient_clipping
        self.max_norm = max_norm
        self.remat = remat
        self.health_probe = health_probe
        self.skip_nonfinite = skip_nonfinite
        # fault injection (bigdl_tpu/faults.py): the compiled step takes
        # one extra traced scalar multiplied into the RAW gradients —
        # 1.0 in healthy steps, NaN when a nan_grads fault fires, so the
        # poison enters through the same path a real divergence would
        # and the in-graph health probe judges it
        self.grad_fault = grad_fault
        self.last_health = None  # device [5] vector, see PROBE_FIELDS

        # module-path scopes (docs/observability.md): stamped before the
        # first trace so compiled-HLO op metadata carries the module tree
        # — the substrate of per-module cost attribution.  Trace-time
        # metadata only; jit cache keys are unchanged (zero retraces).
        from bigdl_tpu.nn.module import stamp_scope_names
        from bigdl_tpu.utils.config import get_config

        stamp_scope_names(model, enabled=get_config().module_scopes)
        self.params = state_dict(model, kind="param")
        self.buffers = state_dict(model, kind="buffer")
        self.opt_state = optim_method.init_state(self.params)
        self._meta = _param_meta(model)
        # sparse embedding-gradient sync (docs/sparse.md): the tables
        # whose gradient may arrive as unique-coalesced (indices, rows)
        # pairs instead of a dense [vocab, dim] scatter + all-reduce.
        # Exactness guardrails applied HERE (the layer owns the
        # per-trace density decision): a regularized table's reg
        # gradient is dense by definition, and value-clipping with a
        # bound that moves zeros (lo > 0 or hi < 0) would update every
        # untouched row on the dense path — both stay dense.
        from bigdl_tpu.nn.layers import embedding as _embed

        self._sparse_tables = {
            p: m for p, m in _embed.sparse_tables(model).items()
            if self._meta.get(p, (1.0, False, None))[2] is None}
        if self.gradient_clipping is not None and self._sparse_tables:
            lo, hi = self.gradient_clipping
            if not (lo <= 0.0 <= hi):
                self._sparse_tables = {}
        self._sparse_stats = None
        if parameter_sync == "local":
            # local-SGD islands (parallel/local_sync.py): every state
            # leaf gains a leading island axis and the step runs under
            # vmap with NO cross-island comms, so sharding rules and the
            # sparse row sync (both collective machinery) cannot apply
            if extra_sharding_rules is not None:
                raise ValueError("parameter_sync='local' does not "
                                 "compose with extra_sharding_rules")
            if len(self.batch_axes) != 1:
                raise ValueError("parameter_sync='local' needs exactly "
                                 "one batch axis")
            self._sparse_tables = {}
        self._avg_cache = None
        self._compiled = None
        self._scan_cache = None
        self._place_initial()

    # -- sharding ----------------------------------------------------------
    def _param_sharding(self, path: str, arr):
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self.mesh is None:
            return None
        if self.extra_sharding_rules is not None:
            spec = self.extra_sharding_rules(path, arr)
            if spec is not None:
                return NamedSharding(self.mesh, spec)
        if self.parameter_sync == "fsdp" and hasattr(arr, "ndim") \
                and arr.ndim >= 1:
            # ZeRO-3: each weight lives sharded over the batch axis
            # (axis 0 when divisible); XLA inserts the per-use
            # all-gather and the reduce-scatter on its gradient.
            # Explicit TP rules above take precedence; indivisible
            # leaves stay replicated.
            ax = self._zero_axis()
            n = self.mesh.shape.get(ax, 1)
            if n > 1 and arr.shape[0] % n == 0 and arr.shape[0] >= n:
                return NamedSharding(
                    self.mesh, P(*((ax,) + (None,) * (arr.ndim - 1))))
        return replicated(self.mesh)

    def _zero_axis(self):
        """The mesh axis ZeRO state shards over — the leading batch
        axis, not a hard-coded 'data' (a mesh may name it differently)."""
        return self.batch_axes[0] if self.batch_axes else DATA_AXIS

    def _opt_leaf_sharding(self, arr):
        """ZeRO-1/3: shard large optimizer-state leaves over the batch
        axis."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self.mesh is None:
            return None
        if self.parameter_sync in ("sharded", "fsdp") \
                and hasattr(arr, "ndim") and arr.ndim >= 1:
            ax = self._zero_axis()
            n = self.mesh.shape.get(ax, 1)
            if n > 1 and arr.shape[0] % n == 0 and arr.shape[0] >= n:
                return NamedSharding(self.mesh, P(ax))
        return replicated(self.mesh)

    def _opt_state_shardings(self, opt_state):
        """Per-leaf opt-state shardings ALIGNED with the owning param's
        layout: a TP-ruled param's moment buffers follow the TP sharding
        (constraining them onto the ZeRO axis would force a per-step
        resharding collective); everything else gets the ZeRO layout."""
        rules = self.extra_sharding_rules

        def leaf(path, arr):
            if rules is not None and hasattr(arr, "ndim"):
                # the innermost dict key is the param name for the
                # per-param moment trees (velocity/m/v/...)
                key = None
                for part in reversed(path):
                    if hasattr(part, "key"):
                        key = part.key
                        break
                if key is not None:
                    spec = rules(str(key), arr)
                    if spec is not None:
                        from jax.sharding import NamedSharding

                        return NamedSharding(self.mesh, spec)
            return self._opt_leaf_sharding(arr)

        return jax.tree_util.tree_map_with_path(leaf, opt_state)

    def _place_initial(self):
        if self.parameter_sync == "local":
            self.params = {k: self._stack_island(v)
                           for k, v in self.params.items()}
            self.buffers = {k: self._stack_island(v)
                            for k, v in self.buffers.items()}
            self.opt_state = jax.tree.map(self._stack_island,
                                          self.opt_state)
            return
        if self.mesh is None:
            return
        self.params = {k: jax.device_put(v, self._param_sharding(k, v))
                       for k, v in self.params.items()}
        self.buffers = {k: jax.device_put(v, replicated(self.mesh))
                        for k, v in self.buffers.items()}
        self.opt_state = jax.tree.map(
            jax.device_put, self.opt_state,
            self._opt_state_shardings(self.opt_state))

    # -- local-SGD islands (parameter_sync='local') ------------------------
    def island_count(self) -> int:
        """Islands = devices along the batch axis (1 off-mesh)."""
        if self.mesh is None:
            return 1
        return int(self.mesh.shape.get(self._zero_axis(), 1))

    def _island_sharding(self, ndim: int):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(
            self.mesh, P(self._zero_axis(), *([None] * (ndim - 1))))

    def _stack_island(self, v):
        """Replicate one (unstacked) leaf into the stacked island layout:
        leading axis = island count, sharded over the batch axis so each
        device owns its own island's copy.  Multi-process: built from
        process-local rows — no collective, which is what lets the
        survivors rebuild state after a peer is shed."""
        a = np.asarray(v)
        n = self.island_count()
        if self.mesh is None:
            return jnp.broadcast_to(jnp.asarray(a), (n,) + a.shape)
        sharding = self._island_sharding(a.ndim + 1)
        nproc = mesh_process_count(self.mesh)
        if nproc > 1:
            local = np.ascontiguousarray(
                np.broadcast_to(a, (max(1, n // nproc),) + a.shape))
            return jax.make_array_from_process_local_data(
                sharding, local, (n,) + a.shape)
        return jax.device_put(
            np.ascontiguousarray(np.broadcast_to(a, (n,) + a.shape)),
            sharding)

    def _island_rows(self, stacked) -> np.ndarray:
        """This process's islands of one stacked leaf, as a host array
        with the island axis leading (all islands on a single host)."""
        shards = getattr(stacked, "addressable_shards", None)
        if not shards:
            return np.asarray(stacked)
        return np.concatenate([np.asarray(s.data) for s in shards],
                              axis=0)

    def island_mean_host(self, tree) -> Dict[str, np.ndarray]:
        """Host-side mean over this process's ADDRESSABLE islands — no
        collective, so it stays safe after peers desynchronize or are
        shed (the multi-process averaging path and the local-mode
        ``sync_to_model`` both build on it)."""
        out = {}
        for k, v in tree.items():
            rows = self._island_rows(v)
            if np.issubdtype(rows.dtype, np.floating):
                out[k] = rows.mean(axis=0).astype(rows.dtype)
            else:
                out[k] = rows[0]  # counters: islands agree by design
        return out

    def load_island_state(self, params: Dict[str, np.ndarray],
                          buffers: Optional[Dict[str, np.ndarray]] = None
                          ) -> None:
        """Overwrite every LOCAL island with the given (unstacked)
        state — the write-back half of a cross-process averaging round.
        Optimizer state intentionally stays per-island (local SGD
        averages parameters, not moments)."""
        self.params = {
            k: self._stack_island(np.asarray(params[k]).astype(
                self._island_rows(v).dtype))
            if k in params else v
            for k, v in self.params.items()}
        if buffers:
            self.buffers = {
                k: self._stack_island(np.asarray(buffers[k]).astype(
                    self._island_rows(v).dtype))
                if k in buffers else v
                for k, v in self.buffers.items()}

    def _fold_island_health(self, health) -> np.ndarray:
        """Aggregate the stacked (islands, 5) health probe into the one
        5-vector the policy reads: norms combine as sqrt-of-sum-of-
        squares, nonfinite counts sum.  Host-side over addressable
        islands — each process judges its own islands."""
        rows = self._island_rows(health).astype(np.float64)
        norms = np.sqrt(np.sum(rows[:, :3] ** 2, axis=0))
        bads = np.sum(rows[:, 3:], axis=0)
        return np.concatenate([norms, bads]).astype(np.float32)

    def _avg_fn(self):
        """The in-graph island averaging program (single-process path):
        mean over the island axis + broadcast back — the ONE collective
        local mode retains, paid every H steps instead of every step
        (its measured bytes are the ``sync/average`` event's payload and
        the bench leg's amortized comms_bytes)."""
        mesh = self.mesh

        def mean_bcast(a):
            if not jnp.issubdtype(a.dtype, jnp.floating):
                return a
            m = jnp.mean(a, axis=0, keepdims=True)
            out = jnp.broadcast_to(m, a.shape).astype(a.dtype)
            if mesh is not None:
                out = jax.lax.with_sharding_constraint(
                    out, self._island_sharding(a.ndim))
            return out

        def avg(params, buffers):
            return (jax.tree.map(mean_bcast, params),
                    jax.tree.map(mean_bcast, buffers))

        return avg

    def _avg_executable(self):
        if self._avg_cache is None:
            lowered = jax.jit(self._avg_fn(),
                              donate_argnums=(0, 1)).lower(
                self.params, self.buffers)
            self._avg_cache = lowered.compile()
        return self._avg_cache

    def average_islands(self) -> None:
        """One parameter-averaging round across THIS process's islands,
        in-graph (single-process local SGD; the multi-process barrier in
        parallel/local_sync.py composes :meth:`island_mean_host` +
        :meth:`load_island_state` over files instead — a jitted mean
        over a cross-process axis would be exactly the blocking
        collective the staleness barrier exists to avoid)."""
        if self.parameter_sync != "local":
            raise RuntimeError("average_islands needs "
                               "parameter_sync='local'")
        self.params, self.buffers = self._avg_executable()(
            self.params, self.buffers)

    # -- the pure step -----------------------------------------------------
    def _step_fn(self, with_health: bool = False, local: bool = False):
        """The pure (params, opt_state, buffers, x, y, key[, grad_scale])
        -> (params, opt_state, buffers, loss[, health]) function, shared
        by the per-iteration jit and the scan-of-iterations jit.
        ``with_health`` appends the fused health 5-vector output (the
        per-iteration path only — the scan path keeps the 4-tuple).
        The optional trailing ``grad_scale`` scalar is the fault-plan
        input (``grad_fault=True`` dispatches pass it; omitted, the
        multiply never enters the trace).  ``local`` traces the step
        with NO mesh in scope — the single-island body the local-SGD
        wrapper vmaps over the island axis (every sharding constraint
        would otherwise re-introduce the collectives local mode
        removes)."""
        model, criterion, optim = self.model, self.criterion, self.optim
        meta = self._meta
        comp = self.gradient_compression
        cdt = self.compute_dtype
        mesh = None if local else self.mesh
        skip_nonfinite = self.skip_nonfinite

        from bigdl_tpu.nn.layers import embedding as _embed

        sparse_tables = self._sparse_tables
        cap_paths = {id(m): p for p, m in sparse_tables.items()}

        def loss_fn(params, buffers, x, y, key, proxies=None):
            call_params = params
            if cdt is not None:
                call_params = {k: v.astype(cdt) for k, v in params.items()}
                x = jax.tree.map(lambda a: a.astype(cdt) if jnp.issubdtype(a.dtype, jnp.floating) else a, x)
            if proxies is None:
                out, new_state = functional_call(
                    model, {**call_params, **buffers}, x, training=True,
                    rng=key)
                sparse_aux = {}
            else:
                # sparse capture: active embedding layers fetch their
                # cotangent proxies and record their coalesced unique
                # indices, returned as aux so the update can scatter-add
                with _embed.SparseCapture(cap_paths, proxies) as cap:
                    out, new_state = functional_call(
                        model, {**call_params, **buffers}, x,
                        training=True, rng=key)
                # arrays ONLY (jax.checkpoint rejects static leaves in
                # traced outputs): the static facts (path/slots/vocab)
                # come from the discovery pass's metas
                sparse_aux = {k: v["u"] for k, v in cap.aux.items()}
            loss = criterion.update_output(out, y)
            reg_loss = 0.0
            for path, (_, frozen, reg) in meta.items():
                if reg is not None and not frozen:
                    reg_loss = reg_loss + reg.loss(params[path])
            new_buffers = {k: new_state[k] for k in buffers}
            return loss + reg_loss, (loss, new_buffers, out, sparse_aux)

        if self.remat:
            # whole-model rematerialization: the backward recomputes the
            # forward instead of saving every activation — HBM for FLOPs
            # (finer-grained boundaries: wrap blocks in nn.Remat instead)
            loss_fn = jax.checkpoint(loss_fn, static_argnums=())

        def step(params, opt_state, buffers, x, y, key, grad_scale=None):
            if mesh is not None:
                from jax.sharding import PartitionSpec as P

                ax = self.batch_axes[0] if len(self.batch_axes) == 1 else self.batch_axes
                x = jax.tree.map(
                    lambda a: jax.lax.with_sharding_constraint(
                        a, jax.sharding.NamedSharding(mesh, P(ax, *([None] * (a.ndim - 1))))), x)
            proxies, metas = {}, {}
            if sparse_tables and _embed.sparse_enabled():
                # discovery (one eval_shape, no FLOPs): which tables go
                # sparse for THIS batch shape, and their proxy shapes —
                # the layer's density rule decides per trace, so a
                # long-sequence batch over a small vocab stays dense
                # loss_fn is called WITHOUT proxies here: the discover
                # capture discover_proxies sets is ambient, so the
                # layers request shapes from it instead of binding
                shapes, metas = _embed.discover_proxies(
                    lambda: loss_fn(params, buffers, x, y, key),
                    cap_paths)
                proxies = {k: jnp.zeros(s.shape, s.dtype)
                           for k, s in shapes.items()}
            if proxies:
                active_tables = {m["path"] for m in metas.values()}
                dense_view = {k: v for k, v in params.items()
                              if k not in active_tables}

                def inner(dp, pr):
                    # active tables ride the closure (non-differentiated
                    # — their gradient IS the proxies'); everything else
                    # differentiates as before
                    full = dict(params)
                    full.update(dp)
                    return loss_fn(full, buffers, x, y, key, pr)

                (grads, prox_grads), (loss, new_buffers, _, aux) = \
                    jax.grad(inner, argnums=(0, 1), has_aux=True)(
                        dense_view, proxies)
            else:
                grads, (loss, new_buffers, _, aux) = jax.grad(
                    loss_fn, has_aux=True)(params, buffers, x, y, key)
                prox_grads = {}
            if grad_scale is not None:
                # fault injection BEFORE scaling/clipping/compression:
                # the probe must see nonfinite GRADS, exactly as a real
                # divergence would present
                grads = {k: g * grad_scale for k, g in grads.items()}
                prox_grads = {k: g * grad_scale
                              for k, g in prox_grads.items()}
            if cdt is not None:
                grads = {k: g.astype(jnp.float32) for k, g in grads.items()}
                prox_grads = {k: g.astype(jnp.float32)
                              for k, g in prox_grads.items()}
            def replicate_pair(u, g):
                # pin the sync collective onto the SMALL arrays: the
                # partitioner must replicate the coalesced rows (an
                # all-reduce over [slots, dim]) before any scatter —
                # never partial-scatter into [vocab, dim] and
                # all-reduce that
                if mesh is None:
                    return u, g
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                rep = NamedSharding(mesh, P())
                return (jax.lax.with_sharding_constraint(u, rep),
                        jax.lax.with_sharding_constraint(g, rep))

            # group the proxy cotangents by table; a table used MORE
            # THAN ONCE per forward densifies LOCALLY here, BEFORE the
            # nonlinear grad legs (bf16 truncate / value clip / global
            # norm): those must see the cross-call SUM exactly as the
            # dense path does, and the lazy Adagrad sum-then-square
            # also requires pre-summed rows.  Single-call tables (the
            # norm) stay row-sparse through every leg.
            by_path = {}
            for pkey, g in prox_grads.items():
                by_path.setdefault(metas[pkey]["path"], []).append(
                    (aux[pkey], g))
            sparse_entries = {}
            for path, entries in by_path.items():
                if len(entries) == 1:
                    sparse_entries[path] = entries[0]
                else:
                    dense = jnp.zeros_like(params[path])
                    for u, g in entries:
                        u, g = replicate_pair(u, g)
                        dense = dense.at[u].add(g.astype(dense.dtype),
                                                mode="drop")
                    grads[path] = dense  # rides the dense legs below
            # per-layer scales & freeze
            scaled = {}
            for k, g in grads.items():
                scale, frozen, _ = meta[k]
                if frozen:
                    g = jnp.zeros_like(g)
                elif scale != 1.0:
                    g = g * scale
                scaled[k] = g
            # sparse rows ride the same legs keyed by their table's path
            for path, (u, g) in list(sparse_entries.items()):
                scale, frozen, _ = meta[path]
                if frozen:
                    g = jnp.zeros_like(g)
                elif scale != 1.0:
                    g = g * scale
                sparse_entries[path] = (u, g)
            if comp == "bf16":
                scaled = {k: bf16_truncate(v) for k, v in scaled.items()}
                sparse_entries = {
                    k: (u, bf16_truncate(g))
                    for k, (u, g) in sparse_entries.items()}
            if self.gradient_clipping is not None:
                lo, hi = self.gradient_clipping
                scaled = {k: jnp.clip(v, lo, hi) for k, v in scaled.items()}
                # constructor guarantees lo <= 0 <= hi when sparse
                # tables are live, so untouched (zero) rows stay zero
                sparse_entries = {
                    k: (u, jnp.clip(g, lo, hi))
                    for k, (u, g) in sparse_entries.items()}
            if self.max_norm is not None:
                gn = jnp.sqrt(sum(jnp.sum(v * v) for v in scaled.values())
                              + sum(jnp.sum(g * g)
                                    for _, g in sparse_entries.values()))
                factor = jnp.minimum(1.0, self.max_norm / (gn + 1e-12))
                scaled = {k: v * factor for k, v in scaled.items()}
                sparse_entries = {
                    k: (u, g * factor)
                    for k, (u, g) in sparse_entries.items()}
            sparse_g = {path: replicate_pair(u, g)
                        for path, (u, g) in sparse_entries.items()}
            # ZeRO-1/3: constrain optimizer state onto the batch axis so
            # XLA lowers the gradient collective to reduce-scatter +
            # all-gather; TP-ruled params' moment buffers follow the TP
            # layout instead (per-leaf alignment, _opt_state_shardings)
            if mesh is not None and self.parameter_sync in ("sharded",
                                                            "fsdp"):
                opt_state = jax.tree.map(
                    lambda a, s: jax.lax.with_sharding_constraint(a, s)
                    if hasattr(a, "ndim") else a,
                    opt_state, self._opt_state_shardings(opt_state))
            # trace-time bookkeeping for the `train/sparse` instant:
            # static per-step sync accounting (what a dense all-reduce
            # of each table would move vs the coalesced rows)
            if sparse_g:
                self._sparse_stats = _embed.sparse_sync_stats(
                    {k: m for k, m in metas.items()
                     if m["path"] in sparse_g})
            if sparse_g and hasattr(optim, "update_mixed"):
                new_params, new_opt = optim.update_mixed(
                    scaled, sparse_g, params, opt_state,
                    scatter=self._row_scatter())
            else:
                # the pre-sparse contract: a duck-typed method needs
                # only update().  With sparse grads in hand, densify
                # them LOCALLY (zero collectives — the sync already
                # happened on the rows) so such a method still trains
                # exactly.
                for path, (u, g) in sparse_g.items():
                    scaled[path] = jnp.zeros_like(params[path]).at[u].add(
                        g.astype(params[path].dtype), mode="drop")
                new_params, new_opt = optim.update(scaled, params,
                                                   opt_state)
            if mesh is not None:
                new_params = {
                    k: jax.lax.with_sharding_constraint(v, self._param_sharding(k, v))
                    for k, v in new_params.items()}
            health = None
            if with_health or skip_nonfinite:
                # ONE fused reduction pass over the grad/param trees:
                # global grad/param/update norms + nonfinite counts.
                # XLA fuses the per-leaf partial sums into the step's
                # existing elementwise work; the scalars ride the step's
                # output fetch (no extra device->host sync).
                gsq = psq = usq = jnp.float32(0.0)
                gbad = pbad = jnp.int32(0)
                for k, g in scaled.items():
                    g32 = g.astype(jnp.float32)
                    p32 = params[k].astype(jnp.float32)
                    n32 = new_params[k].astype(jnp.float32)
                    d32 = n32 - p32
                    gsq += jnp.sum(g32 * g32)
                    psq += jnp.sum(p32 * p32)
                    usq += jnp.sum(d32 * d32)
                    gbad += jnp.sum((~jnp.isfinite(g32)).astype(jnp.int32))
                    pbad += jnp.sum((~jnp.isfinite(n32)).astype(jnp.int32))
                for k, (_u, g) in sparse_g.items():
                    # a row-sparse grad's norm IS the dense grad's norm
                    # (the zeros contribute nothing); param/update norms
                    # read the full table like any other param
                    g32 = g.astype(jnp.float32)
                    p32 = params[k].astype(jnp.float32)
                    n32 = new_params[k].astype(jnp.float32)
                    d32 = n32 - p32
                    gsq += jnp.sum(g32 * g32)
                    psq += jnp.sum(p32 * p32)
                    usq += jnp.sum(d32 * d32)
                    gbad += jnp.sum((~jnp.isfinite(g32)).astype(jnp.int32))
                    pbad += jnp.sum((~jnp.isfinite(n32)).astype(jnp.int32))
                health = jnp.stack(
                    [jnp.sqrt(gsq), jnp.sqrt(psq), jnp.sqrt(usq),
                     gbad.astype(jnp.float32), pbad.astype(jnp.float32)])
                if skip_nonfinite:
                    # poisoned step: keep the previous state wholesale
                    # (params, optimizer moments, BN buffers) — the
                    # in-graph analogue of drop-gradients-and-continue
                    ok = (gbad == 0) & (pbad == 0) & jnp.isfinite(loss)
                    keep = lambda n, o: jnp.where(ok, n, o)
                    new_params = {k: keep(v, params[k])
                                  for k, v in new_params.items()}
                    new_opt = jax.tree.map(keep, new_opt, opt_state)
                    new_buffers = {k: keep(v, buffers[k])
                                   for k, v in new_buffers.items()}
            if with_health:
                return new_params, new_opt, new_buffers, loss, health
            return new_params, new_opt, new_buffers, loss

        return step

    def _row_scatter(self):
        """The sparse update's row scatter, pinned against GSPMD's
        parallel-scatter lowering (docs/sparse.md).

        Left to itself the partitioner re-tiles the (replicated,
        free-to-slice) coalesced rows along the slots axis and lowers
        ``table.at[u].add(rows)`` as per-shard partial scatter + a dense
        ``[vocab, dim]`` all-reduce — re-creating the exact collective
        the sparse path removes, and sharding constraints on the
        operands alone do not dissuade it.  So: a REPLICATED target runs
        the scatter inside ``shard_map`` with fully-replicated specs
        (per-device identical local code — structurally no collective;
        the rows' own small all-reduce happens at the replication
        constraint, which IS the sync).  A dim0-SHARDED target (ZeRO
        moments, fsdp/row-sharded tables) keeps the GSPMD path with its
        layout pinned on both sides — each shard masks and applies the
        rows that land in its range.  Returns None off-mesh (the plain
        ``.at[]`` scatter is already local)."""
        mesh = self.mesh
        if mesh is None or mesh.devices.size <= 1:
            return None
        from functools import partial

        from jax.sharding import NamedSharding, PartitionSpec as P

        try:  # jax >= 0.6 exports shard_map at top level (check_vma)
            from jax import shard_map as _sm
            smap = partial(_sm, check_vma=False)
        except ImportError:  # this jaxlib (0.4.x): experimental
            from jax.experimental.shard_map import shard_map as _sm
            smap = partial(_sm, check_rep=False)
        rep = NamedSharding(mesh, P())

        def spec_of(kind, path, arr):
            if kind == "param":
                sh = self._param_sharding(path, arr)
                return sh.spec if sh is not None else P()
            if self.extra_sharding_rules is not None:
                s = self.extra_sharding_rules(path, arr)
                if s is not None:
                    return s
            sh = self._opt_leaf_sharding(arr)
            return sh.spec if sh is not None else P()

        def scatter(target, idx, updates, op, kind, path):
            idx = jax.lax.with_sharding_constraint(idx, rep)
            updates = jax.lax.with_sharding_constraint(updates, rep)
            spec = spec_of(kind, path, target)

            def body(t, i, u):
                if op == "set":
                    return t.at[i].set(u, mode="drop")
                return t.at[i].add(u, mode="drop")

            if tuple(spec) == ():
                return smap(body, mesh=mesh, in_specs=(P(), P(), P()),
                            out_specs=P())(target, idx, updates)
            sharding = NamedSharding(mesh, spec)
            target = jax.lax.with_sharding_constraint(target, sharding)
            return jax.lax.with_sharding_constraint(
                body(target, idx, updates), sharding)

        return scatter

    def _local_step_fn(self, with_health: bool = False):
        """The local-SGD island step: the mesh-free single-island body
        vmapped over the leading island axis.  Same external signature
        as :meth:`_step_fn`'s step — the driver cannot tell the modes
        apart — but every state leaf carries the island axis, the batch
        splits island-wise in-graph, and the per-island RNG key forks by
        island index so islands explore distinct stochastic paths.

        On a mesh the island axis is mapped with ``shard_map``, not a
        sharding-constrained vmap.  vmap's conv batching rule folds the
        island axis into the convolution batch/feature-group dims, and
        the SPMD partitioner answers the island sharding riding on those
        merged dims with per-step all-gathers of the full parameter set
        (measured at 33x the bytes of the allreduce this mode replaces);
        boundary sharding constraints cannot reach those interior ops.
        shard_map makes island-locality STRUCTURAL: each batch-axis
        shard runs the body on its own island block, so the compiled
        program contains ZERO cross-island collectives and a
        desynchronized (or shed) peer can never block a dispatch."""
        from functools import partial

        from jax.sharding import PartitionSpec as P

        inner = self._step_fn(with_health=with_health, local=True)
        n = self.island_count()
        mesh = self.mesh

        def islands(params, opt_state, buffers, xs, ys, keys, *rest):
            # leading axis = the islands of THIS shard (all of them
            # when mesh-free); the fault scalar broadcasts to each
            if rest:
                one = lambda p, o, b, xi, yi, k: inner(p, o, b, xi, yi,
                                                       k, rest[0])
            else:
                one = lambda p, o, b, xi, yi, k: inner(p, o, b, xi, yi,
                                                       k)
            return jax.vmap(one)(params, opt_state, buffers, xs, ys,
                                 keys)

        def many(params, opt_state, buffers, x, y, key, grad_scale=None):
            def split(a):
                if a.shape[0] % n:
                    raise ValueError(
                        f"local-SGD batch axis {a.shape[0]} not "
                        f"divisible by {n} island(s)")
                return a.reshape((n, a.shape[0] // n) + a.shape[1:])

            xs = jax.tree.map(split, x)
            ys = jax.tree.map(split, y)
            keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
                jnp.arange(n))
            args = (params, opt_state, buffers, xs, ys, keys)
            if grad_scale is not None:
                args += (grad_scale,)
            if mesh is None:
                return islands(*args)
            try:  # jax >= 0.6 exports shard_map at top level
                from jax import shard_map as _sm
                smap = partial(_sm, check_vma=False)
            except ImportError:  # this jaxlib (0.4.x): experimental
                from jax.experimental.shard_map import shard_map as _sm
                smap = partial(_sm, check_rep=False)
            isl = P(self._zero_axis())
            in_specs = (isl,) * 6
            if grad_scale is not None:
                in_specs += (P(),)  # the fault scalar is replicated
            return smap(islands, mesh=mesh, in_specs=in_specs,
                        out_specs=isl)(*args)

        return many

    def _build(self):
        if self.parameter_sync == "local":
            return jax.jit(self._local_step_fn(
                with_health=self.health_probe), donate_argnums=(0, 1, 2))
        return jax.jit(self._step_fn(with_health=self.health_probe),
                       donate_argnums=(0, 1, 2))

    def _build_scan(self, n: int, stacked: bool):
        """n train iterations inside ONE compiled call via ``lax.scan`` —
        amortizes per-dispatch latency (remote/tunneled devices pay a full
        round-trip per dispatch) and lets XLA overlap steps.  ``stacked``:
        x/y carry a leading iteration axis (one minibatch per step);
        otherwise the same batch repeats (the perf-harness protocol).
        In local mode the body is the vmapped island step, so the scan's
        per-iteration losses carry an island axis."""
        step = self._local_step_fn() \
            if self.parameter_sync == "local" else self._step_fn()

        def many(params, opt_state, buffers, x, y, key):
            def body(carry, it):
                p, o, b = carry
                if stacked:
                    i, xi, yi = it
                else:
                    i, xi, yi = it, x, y
                p, o, b, loss = step(p, o, b, xi, yi,
                                     jax.random.fold_in(key, i))
                return (p, o, b), loss

            xs = (jnp.arange(n), x, y) if stacked else jnp.arange(n)
            (params, opt_state, buffers), losses = jax.lax.scan(
                body, (params, opt_state, buffers), xs)
            return params, opt_state, buffers, losses

        return jax.jit(many, donate_argnums=(0, 1, 2))

    # -- host API ----------------------------------------------------------
    def run(self, x, y, key, grad_scale=None) -> float:
        """One training iteration; returns the loss.

        Single-host callers pass the GLOBAL batch; multi-host callers pass
        this process's LOCAL shard of it (per-process data sharding, the
        reference's per-node partition feeding)."""
        if _hooks.hooks_active():  # retrace detector sees the RAW args
            _hooks.dispatch_event(self, "TrainStep.run",
                                  {"x": x, "y": y, "key": key})
        x, y = self._shard_batch(x, y)
        # set only once run_sharded is definitely next — names both the
        # hooks cache event and the telemetry compile event after it
        self._dispatch_observed = "TrainStep.run"
        return self.run_sharded(x, y, key, grad_scale=grad_scale)

    def run_sharded(self, x, y, key, grad_scale=None):
        """One iteration over batch arrays already placed on the mesh
        (``_shard_batch``) — lets the host loop time the h2d transfer and
        the dispatch as separate Metrics stages."""
        # direct callers (the Optimizer's h2d/dispatch Metrics split)
        # bypass run(); the retrace detector still needs to see the args
        # or every recompile is misattributed as retrace/recompile.  A
        # DISTINCT event kind keeps the raw-args view from run() and the
        # mesh-placed view here from diffing against each other.
        kind = getattr(self, "_dispatch_observed", None)
        if kind is None:
            kind = "TrainStep.run_sharded"
            if _hooks.hooks_active():
                _hooks.dispatch_event(self, kind,
                                      {"x": x, "y": y, "key": key})
        self._dispatch_observed = None
        if self._compiled is None:
            self._compiled = self._build()
        if self.parameter_sync == "local":
            # the driver may insert UNSTACKED scalars into opt_state
            # mid-run (the epoch counter at epoch boundaries); the
            # vmapped step needs every leaf to carry the island axis
            self.opt_state = jax.tree.map(
                lambda a: self._stack_island(a)
                if getattr(a, "ndim", 0) == 0 else a, self.opt_state)
        tracer = _telemetry.get()
        before = _jit_cache_size(self._compiled) if tracer else None
        t0 = time.perf_counter()
        args = (self.params, self.opt_state, self.buffers, x, y, key)
        if self.grad_fault:
            # always pass the scalar once armed — a consistent arity
            # keeps one executable (the scalar is a traced input, so
            # 1.0 vs NaN cannot retrace)
            args += (jnp.float32(1.0 if grad_scale is None
                                 else grad_scale),)
        try:
            out = self._compiled(*args)
        except Exception as e:  # noqa: BLE001 - OOM forensics only
            self._maybe_raise_oom(e, "TrainStep.run_sharded",
                                  x=x, y=y)
            raise
        if self.health_probe:
            (self.params, self.opt_state, self.buffers, loss,
             self.last_health) = out
        else:
            self.params, self.opt_state, self.buffers, loss = out
        if self.parameter_sync == "local":
            # stacked-island outputs: fold host-side over the
            # ADDRESSABLE islands only — an in-graph cross-island
            # reduce would be the collective local mode exists to
            # remove (and would block on a shed peer)
            if self.health_probe and self.last_health is not None:
                self.last_health = self._fold_island_health(
                    self.last_health)
            loss = self._island_rows(loss).mean()
        if tracer is not None:
            first = _note_compile(tracer, self, kind, before,
                                  t0, self._compiled)
            if first:
                self._emit_device_facts(tracer, x, y, key)
                self._emit_sparse_instant(tracer)
        if _hooks.hooks_active():
            _hooks.cache_event(self, kind,
                               _jit_cache_size(self._compiled))
        return loss

    def _emit_device_facts(self, tracer, x, y, key) -> None:
        """Once per step object: pull the compiled program's cost/memory
        story (telemetry/device.py) so throughput numbers in the log come
        with an explanation.  ``auto`` re-lowers the already-traced step
        (no XLA compile); ``full`` additionally AOT-compiles for the HBM
        breakdown; ``off`` skips."""
        from bigdl_tpu.telemetry import device as _tdev
        from bigdl_tpu.utils.config import get_config

        cfg = get_config()
        level = cfg.telemetry_device
        comms_on = self._comms_enabled(cfg)
        memory_on = self._memory_enabled(cfg)
        if level == "off" and not comms_on and not memory_on:
            return

        def relower():
            largs = (self.params, self.opt_state, self.buffers, x, y, key)
            if self.grad_fault:
                largs += (jnp.float32(1.0),)
            return self._compiled.lower(*largs)

        lowered = None
        # the comms AND memory walkers both read the POST-SPMD-
        # partitioning HLO (collectives and the schedule don't exist in
        # the lowered StableHLO), so the one extra LOCAL XLA compile per
        # step object is SHARED: with both enabled, the second event is
        # a text parse.  Same class of cost as BIGDL_TELEMETRY_DEVICE=
        # full, and why both `auto` modes fire only on multi-device
        # meshes.
        compiled = None

        def recompile():
            nonlocal lowered, compiled
            if compiled is None:
                if lowered is None:
                    lowered = relower()
                compiled = lowered.compile()
            return compiled

        if level != "off":
            try:
                lowered = relower()
                facts = _tdev.collect_device_facts(
                    lowered, (self.params, self.opt_state, self.buffers),
                    level="auto" if level == "full" else level)
                if level == "full":
                    # the full-level HBM breakdown off the SAME compile
                    # the comms/memory walkers share
                    facts.update(_tdev.memory_facts(recompile()))
            except Exception:  # noqa: BLE001 - facts never fail the step
                facts = None
            if facts:
                tracer.emit("device_facts", facts=facts)
            if lowered is not None and cfg.telemetry_attribution \
                    and cfg.module_scopes:
                # per-module cost rows from the SAME lowered program — a
                # StableHLO text parse, no extra XLA compile
                try:
                    from bigdl_tpu.telemetry import attribution as _attr

                    payload = _attr.attribute_lowered(lowered, self.model)
                    payload["program"] = "train_step"
                    tracer.emit("attribution", **payload)
                except Exception:  # noqa: BLE001 - an observer
                    pass
        if comms_on:
            # Independent of the device-facts level: BIGDL_COMMS has its
            # own off switch, and TELEMETRY_DEVICE=off must not mute it.
            try:
                from bigdl_tpu.telemetry import comms as _comms

                payload = _comms.comms_facts(recompile(),
                                             mesh=self.mesh,
                                             model=self.model)
                payload["program"] = "train_step"
                tracer.emit("comms", **payload)
            except Exception:  # noqa: BLE001 - comms is an observer
                pass
        if memory_on:
            try:
                self._emit_memory_event(tracer, recompile(),
                                        program="train_step")
            except Exception:  # noqa: BLE001 - memory is an observer
                pass

    def _comms_enabled(self, cfg) -> bool:
        """Whether this step emits the per-collective ``comms`` event
        (docs/observability.md): ``BIGDL_COMMS`` on = always, off =
        never, auto = only when the mesh spans more than one device —
        the one case the compiled program contains collectives."""
        mode = (cfg.telemetry_comms or "auto").strip().lower()
        if mode in ("0", "off", "false", "no"):
            return False
        if mode in ("1", "on", "true", "yes"):
            return True
        return self.mesh is not None and self.mesh.devices.size > 1

    def _memory_enabled(self, cfg) -> bool:
        """Whether this step emits the per-step ``memory`` event
        (telemetry/memory.py): ``BIGDL_MEMORY`` on / off / auto, auto =
        multi-device meshes only — where per-device HBM is the scaling
        question and the comms event already pays the shared compile."""
        mode = (cfg.telemetry_memory or "auto").strip().lower()
        if mode in ("0", "off", "false", "no"):
            return False
        if mode in ("1", "on", "true", "yes"):
            return True
        return self.mesh is not None and self.mesh.devices.size > 1

    def _emit_memory_event(self, tracer, compiled, program: str) -> None:
        """One ``memory`` event off an in-hand executable: the walker's
        per-device peak + categories + per-module rows + live allocator
        stats; a ``memory/pressure`` instant when any device's live
        peak is within 5% of its limit."""
        from bigdl_tpu.telemetry import memory as _tmem

        payload = _tmem.memory_facts_compiled(compiled, model=self.model)
        # the event must stay a log line, not a log file: cap the row
        # and buffer tables (the CLI recomputes full tables on demand)
        payload["rows"] = sorted(payload.get("rows", []),
                                 key=lambda r: -r["total_bytes"])[:24]
        payload["largest"] = payload.get("largest", [])[:8]
        payload.pop("timeline", None)
        payload["program"] = program
        tracer.emit("memory", **payload)
        # judged per device against its OWN allocator bytes_limit (the
        # reservation-adjusted ceiling RESOURCE_EXHAUSTED fires
        # against), budget only as the fallback
        hit = _tmem.pressured_device(payload.get("live"),
                                     payload.get("hbm_limit_bytes"))
        if hit:
            tracer.instant("memory/pressure", device=hit["device"],
                           peak_bytes_in_use=hit["peak_bytes"],
                           hbm_limit_bytes=hit["limit_bytes"],
                           pct_of_limit=round(hit["peak_bytes"]
                                              / hit["limit_bytes"]
                                              * 100.0, 2))

    def _emit_sparse_instant(self, tracer) -> None:
        """Once per step object: the sparse-sync accounting recorded at
        trace time (docs/sparse.md) — per-table touched-row caps, the
        bytes the coalesced sync moves, and what the dense table
        all-reduce would have moved."""
        stats = self._sparse_stats
        if not stats:
            return
        st = dict(stats)
        st["rows"] = list(st.get("rows") or [])[:8]
        tracer.instant("train/sparse", **st)

    def _shard_batch(self, x, y, stacked: bool = False):
        if self.mesh is None:
            return jax.tree.map(jnp.asarray, x), jax.tree.map(jnp.asarray, y)
        if not stacked:
            shard = lambda a: shard_local_batch(self.mesh, a, self.batch_axes)
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from bigdl_tpu.parallel.mesh import _batch_scale

            ax = self.batch_axes[0] if len(self.batch_axes) == 1 \
                else tuple(self.batch_axes)
            multihost = mesh_process_count(self.mesh) > 1

            def shard(a):  # leading axis is ITERATION; batch is axis 1
                spec = [None] * np.ndim(a)
                if np.ndim(a) >= 2:
                    spec[1] = ax
                sharding = NamedSharding(self.mesh, P(*spec))
                if not multihost:
                    return jax.device_put(jnp.asarray(a), sharding)
                # multi-host: a is this process's LOCAL rows on axis 1
                local = np.asarray(a)
                scale = _batch_scale(self.mesh, self.batch_axes)
                gshape = (local.shape[0], local.shape[1] * scale) \
                    + local.shape[2:]
                return jax.make_array_from_process_local_data(
                    sharding, local, gshape)
        return jax.tree.map(shard, x), jax.tree.map(shard, y)

    def run_scan(self, x, y, key, n: int, stacked: bool = False):
        """Run ``n`` training iterations in one dispatch; returns the
        per-iteration losses (device array).  See ``_build_scan``."""
        if _hooks.hooks_active():
            # n/stacked are compile-key VALUES: changing either rebuilds
            # the scan, so the retrace detector must see them by value
            _hooks.dispatch_event(self, "TrainStep.run_scan",
                                  {"x": x, "y": y, "key": key,
                                   "static:n": n,
                                   "static:stacked": stacked})
        cache_key = (n, stacked)
        if getattr(self, "_scan_cache", None) is None \
                or self._scan_cache[0] != cache_key:
            self._scan_cache = (cache_key, self._build_scan(n, stacked))
        x, y = self._shard_batch(x, y, stacked)
        return self.run_scan_sharded(x, y, key)

    def run_scan_sharded(self, x, y, key):
        """The dispatch half of :meth:`run_scan` over batch arrays already
        placed on the mesh — lets benchmarks time h2d and dispatch
        separately (the scan must have been built by ``run_scan`` or
        ``aot_scan`` first)."""
        if getattr(self, "_scan_cache", None) is None:
            raise RuntimeError("no compiled scan: call run_scan/aot_scan")
        try:
            self.params, self.opt_state, self.buffers, losses = \
                self._scan_cache[1](self.params, self.opt_state,
                                    self.buffers, x, y, key)
        except Exception as e:  # noqa: BLE001 - OOM forensics only
            self._maybe_raise_oom(e, "TrainStep.run_scan_sharded",
                                  x=x, y=y)
            raise
        return losses

    def _maybe_raise_oom(self, exc: Exception, context: str,
                         x=None, y=None) -> None:
        """RESOURCE_EXHAUSTED from a dispatch (or an AOT compile)
        becomes a ``MemoryExhaustedError`` carrying the postmortem:
        largest known buffers, per-category totals, live-vs-limit —
        flight-dumped before the re-raise (docs/observability.md "my
        job OOMed — what was resident?").  Anything else returns and
        the caller re-raises the original."""
        from bigdl_tpu.telemetry import memory as _tmem

        if not _tmem.is_oom(exc):
            return
        trees = {"params": self.params, "opt_state": self.opt_state,
                 "buffers": self.buffers}
        if x is not None:
            trees["batch_x"] = x
        if y is not None:
            trees["batch_y"] = y
        _tmem.raise_oom(exc, trees, context=context)

    def aot_scan(self, x, y, key, n: int, stacked: bool = False):
        """AOT-compile the scan-of-n-steps once; installs the executable
        for ``run_scan`` and returns its XLA cost analysis (the scan BODY
        is counted once — multiply flops by n for totals).  The result is
        passed through ``normalize_cost_analysis``: some backends/JAX
        versions hand back a one-element list instead of the dict (the
        CPU quirk bench.py also guards), and callers get the dict
        contract either way."""
        # AOT is the path restarts/preemption-resumes pay repeatedly —
        # a warm restart should LOAD this executable, not rebuild it
        # (docs/compile.md; implicit: accelerator-only unless
        # BIGDL_COMPILE_CACHE opts plain CPU in, =0 opts out)
        from bigdl_tpu.utils.engine import enable_compile_cache

        enable_compile_cache(implicit=True)
        x, y = self._shard_batch(x, y, stacked)
        tracer = _telemetry.get()
        t0 = time.perf_counter()
        lowered = self._build_scan(n, stacked).lower(
            self.params, self.opt_state, self.buffers, x, y, key)
        try:
            compiled = lowered.compile()
        except Exception as e:  # noqa: BLE001 - OOM forensics only
            # compile-time RESOURCE_EXHAUSTED (the backend sizes the
            # buffer assignment here) gets the same postmortem a
            # dispatch OOM does
            self._maybe_raise_oom(e, "TrainStep.aot_scan", x=x, y=y)
            raise
        self._scan_cache = ((n, stacked), compiled)
        if tracer is not None:
            tracer.emit("compile", name="TrainStep.aot_scan",
                        dur=time.perf_counter() - t0, iters=n)
            self._emit_sparse_instant(tracer)
            from bigdl_tpu.telemetry import device as _tdev
            from bigdl_tpu.utils.config import get_config

            if get_config().telemetry_device != "off":
                # the executable is in hand: the HBM breakdown is free
                # here ("auto" suffices — "full" would only re-compile)
                facts = _tdev.collect_device_facts(
                    lowered, (self.params, self.opt_state, self.buffers),
                    level="auto")
                facts.update(_tdev.memory_facts(compiled))
                if facts:
                    tracer.emit("device_facts", facts=facts)
            if self._comms_enabled(get_config()):
                # the scan executable is in hand: comms facts are a
                # text parse here, no extra compile (the scan BODY holds
                # each collective once — already per-iteration numbers)
                try:
                    from bigdl_tpu.telemetry import comms as _comms

                    payload = _comms.comms_facts(compiled, mesh=self.mesh,
                                                 model=self.model)
                    payload["program"] = "aot_scan"
                    tracer.emit("comms", **payload)
                except Exception:  # noqa: BLE001 - comms is an observer
                    pass
            if self._memory_enabled(get_config()):
                # likewise free here: the memory walker reads the same
                # in-hand executable's scheduled text, and its while-
                # body recursion reports the peak INSIDE the scanned
                # step, not the tuple shuffle around it
                try:
                    self._emit_memory_event(tracer, compiled,
                                            program="aot_scan")
                except Exception:  # noqa: BLE001 - an observer
                    pass
        from bigdl_tpu.telemetry.device import normalize_cost_analysis
        return normalize_cost_analysis(compiled.cost_analysis())

    def gather_replicated(self, tree):
        """All-gather cross-process-sharded leaves to replicated (no-op on
        a single-host mesh).  Every process of a multi-host mesh must call
        this — it compiles to a collective; afterwards each leaf is
        addressable everywhere (the reference's getModel reassembly
        crossing the network, ``DistriOptimizer.scala:689-719``)."""
        if self.parameter_sync == "local":
            # local mode: the stacked leaves never replicate — the
            # jitted gather would be a cross-process collective that
            # hangs once a peer is shed.  The island mean over the
            # ADDRESSABLE islands is the local-SGD consensus view.
            return self.island_mean_host(tree)
        if self.mesh is not None and mesh_process_count(self.mesh) > 1:
            tree = jax.jit(lambda t: t,
                           out_shardings=replicated(self.mesh))(tree)
        return tree

    def sync_to_model(self):
        """Write the current params/buffers back into the module tree (the
        reference's getModel reassembly, ``DistriOptimizer.scala:689-719``)."""
        from bigdl_tpu.nn.module import load_state_dict

        if self.parameter_sync == "local":
            state = {**self.island_mean_host(self.params),
                     **self.island_mean_host(self.buffers)}
            load_state_dict(self.model, state, strict=False)
            return
        state = self.gather_replicated({**self.params, **self.buffers})
        load_state_dict(self.model, state, strict=False)


class EvalStep:
    """Compiled inference step sharing the TrainStep's sharding layout."""

    def __init__(self, model: Module, mesh=None, batch_axes=(DATA_AXIS,),
                 compute_dtype=None):
        from bigdl_tpu.nn.module import stamp_scope_names
        from bigdl_tpu.utils.config import get_config

        stamp_scope_names(model, enabled=get_config().module_scopes)
        self.model = model
        self.mesh = mesh
        self.batch_axes = tuple(batch_axes)
        self.compute_dtype = compute_dtype
        self._compiled = None

    def _build(self):
        model = self.model
        cdt = self.compute_dtype

        def fwd(state, x):
            if cdt is not None:
                state = {k: (v.astype(cdt) if jnp.issubdtype(v.dtype, jnp.floating) else v)
                         for k, v in state.items()}
            out, _ = functional_call(model, state, x, training=False)
            if cdt is not None:
                out = jax.tree.map(
                    lambda a: a.astype(jnp.float32) if jnp.issubdtype(a.dtype, jnp.floating) else a,
                    out)
            return out

        return jax.jit(fwd)

    def run(self, x):
        if _hooks.hooks_active():
            _hooks.dispatch_event(self, "EvalStep.run", {"x": x})
        if self._compiled is None:
            self._compiled = self._build()
        state = state_dict(self.model)
        if self.mesh is not None:
            x = jax.tree.map(
                lambda a: jax.device_put(
                    jnp.asarray(a), data_sharding(self.mesh, np.ndim(a), self.batch_axes)), x)
        else:
            x = jax.tree.map(jnp.asarray, x)
        tracer = _telemetry.get()
        before = _jit_cache_size(self._compiled) if tracer else None
        t0 = time.perf_counter()
        out = self._compiled(state, x)
        if tracer is not None:
            _note_compile(tracer, self, "EvalStep.run", before, t0,
                          self._compiled)
        if _hooks.hooks_active():
            _hooks.cache_event(self, "EvalStep.run",
                               _jit_cache_size(self._compiled))
        return out
