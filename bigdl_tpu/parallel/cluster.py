"""Cluster-level fault tolerance: peer heartbeats, a collective
watchdog, a coordinated checkpoint-commit barrier, and a supervised
elastic restart loop (docs/fault_tolerance.md "Distributed failures").

PR 5 made SINGLE-process recovery real (seeded faults, digest-verified
restore, preemption-safe resume) and un-broke the 2/4-process gloo
cluster — but the cluster itself had no fault story: one SIGKILLed or
wedged peer left every surviving host blocked inside an all-reduce
forever (gloo has no timeout on the blocking path jax uses), and each
host committed checkpoints independently, so a crash in the commit
window could leave hosts restoring *different* steps.  The reference
inherits this layer from Spark's driver/executor supervision
(``DistriOptimizer``'s retry loop assumes the cluster manager replaces
lost tasks); DeepSpark (arXiv 1602.08191) states the commodity-cluster
premise outright — worker loss is an expected event the framework
absorbs — and Blink (arXiv 1910.04940) motivates treating the
collective path itself as the thing that must degrade gracefully.

Three cooperating pieces, all file-based over a shared directory
(``BIGDL_CLUSTER_DIR``) so they work wherever the checkpoints do —
local disk for the multi-process-one-host test rig, NFS/fuse mounts for
real fleets — with no new network surface beside the gloo mesh:

1. **Peer heartbeat** (:class:`HeartbeatPublisher`): each process
   atomically rewrites ``heartbeat.p<idx>.json`` with a MONOTONIC step
   counter + wall timestamp + status (``running/done/preempted/
   failed/shed``) at iteration boundaries (throttled to
   ``BIGDL_HEARTBEAT_INTERVAL``).  No background writer thread: a
   heartbeat certifies *progress*, not mere process existence — a
   wedged process must look wedged.

2. **Collective watchdog** (:class:`ClusterMonitor`): a daemon thread
   on every process reads the peer files each poll and declares the
   cluster degraded when any ``running`` peer's heartbeat stalls past
   the deadline (``BIGDL_CLUSTER_DEADLINE``, derived from the
   straggler budget when unset) or a peer publishes ``failed``.  It
   then emits ``cluster/peer_lost``, flight-dumps a full per-peer
   liveness snapshot, and **aborts the local process cleanly with the
   distinct exit code** :data:`EXIT_PEER_LOST` — a survivor blocked in
   an all-reduce cannot run Python in its main thread, so exiting from
   the watchdog thread is the only way out of the hang.  The watchdog
   arms only after this process completes its first step (XLA compile
   is never under the deadline), and ignores heartbeat files that
   predate its own start (stale leftovers from a previous incarnation).

3. **Coordinated commit barrier** (:meth:`ClusterService.commit_step`):
   two-phase commit over the same directory.  Phase 1 — each process,
   after its LOCAL share of a step-N checkpoint is durable, writes an
   ack file (``commit.p<idx>.<N>.json``, per-host digests riding
   along).  Phase 2 — the coordinator collects all N acks (bounded
   wait) and atomically publishes ``cluster_manifest.json`` naming
   step N cluster-consistent, announced as ``cluster/commit``.
   Restore reads the manifest FIRST: checkpoints newer than the
   manifest step are structurally invisible to cluster restores, so a
   crash between a host's local write and its barrier ack can never
   produce a mixed-step restore (``latest_verified_step_dir``'s
   ``max_step`` cap is the sharded variant; the BTPU walk filters the
   same way).

The **supervisor** (:class:`Supervisor`, fronted by ``models/cli.py
supervise -n N -- <worker cmd>``) closes the loop: it launches the N
processes with the cluster env wired (fresh coordinator port and
heartbeat subdir per incarnation), watches exit codes, lets survivors
self-abort through the watchdog (their flight dumps are the
postmortem), and restarts the FULL cluster from the last
cluster-consistent checkpoint — bounded restarts, exponential backoff
reusing ``BIGDL_RETRY_BACKOFF``, auto-resume landing on the exact next
batch via the PR 5 machinery.  Deterministic fault plans
(``BIGDL_FAULTS``) are cleared for restart incarnations by default: an
injected failure describes one scenario, and replaying it every
incarnation would make recovery structurally impossible.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from bigdl_tpu.utils import file as File
from bigdl_tpu.utils.config import get_config

__all__ = ["EXIT_PEER_LOST", "HeartbeatPublisher", "ClusterMonitor",
           "ClusterService", "Supervisor", "get", "activate",
           "deactivate", "derive_deadline", "manifest_step"]

log = logging.getLogger("bigdl_tpu.cluster")

#: distinct exit code for "aborted on peer loss / cluster stall" — the
#: supervisor (and any external cluster manager) can tell a watchdog
#: abort from a crash (nonzero), a SIGKILL (negative) and success (0)
EXIT_PEER_LOST = 43

_MANIFEST = "cluster_manifest.json"

_HB_PREFIX = "heartbeat.p"


def derive_deadline(cfg=None) -> float:
    """The per-iteration cluster deadline in seconds: an explicit
    ``BIGDL_CLUSTER_DEADLINE`` wins; else it derives from the existing
    straggler budget (2x a numeric ``BIGDL_ITERATION_TIMEOUT`` — the
    cluster verdict must come strictly after the host-local one had its
    chance); else a conservative 120 s."""
    cfg = cfg or get_config()
    if cfg.cluster_deadline > 0:
        return float(cfg.cluster_deadline)
    spec = (cfg.iteration_timeout or "").strip()
    if spec and spec not in ("0", "auto"):
        try:
            return 2.0 * float(spec)
        except ValueError:
            pass
    return 120.0


def _atomic_write_json(path: str, payload: Dict[str, Any]) -> None:
    File.save(json.dumps(payload).encode(), path, overwrite=True)


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        return json.loads(File.load(path).decode())
    except (OSError, ValueError):
        return None


def manifest_step(ckpt_dir: str) -> Optional[int]:
    """The step the cluster manifest under ``ckpt_dir`` certifies as
    cluster-consistent, or None (no manifest — nothing certified)."""
    meta = _read_json(File.join(ckpt_dir, _MANIFEST))
    if meta is None:
        return None
    try:
        return int(meta["step"])
    except (KeyError, TypeError, ValueError):
        return None


class HeartbeatPublisher:
    """Publishes this process's monotonic step heartbeat as an
    atomically-replaced JSON file.  ``beat()`` is called from the
    training loop at iteration boundaries and throttled to
    ``interval`` so sub-millisecond CPU steps don't turn the heartbeat
    into an fsync storm; status changes and step-number changes always
    flush."""

    def __init__(self, directory: str, process_index: int,
                 interval: float = 1.0):
        self.directory = directory
        self.process_index = int(process_index)
        self.interval = max(float(interval), 0.05)
        self.path = File.join(directory, f"{_HB_PREFIX}{process_index}.json")
        self._lock = threading.Lock()
        self._step = 0
        self._status = "running"
        self._last_write = 0.0

    def start(self) -> "HeartbeatPublisher":
        File.makedirs(self.directory)
        # a stale file from a previous incarnation must not speak for
        # this one (the monitor also ignores pre-start timestamps)
        File.remove(self.path)
        self._write(force=True)
        return self

    def beat(self, step: int, status: str = "running") -> None:
        # only a STATUS change forces a write; step increments ride the
        # interval throttle — the monitor compares ts freshness against
        # a deadline orders of magnitude above the interval, and a
        # per-iteration forced write would put an fsync (an NFS round
        # trip on real fleets) in the training loop
        with self._lock:
            changed = status != self._status
            self._step = max(self._step, int(step))  # monotonic
            self._status = status
        self._write(force=changed)

    def stop(self, status: str = "done") -> None:
        """Final heartbeat: peers treat ``done``/``preempted`` as a
        clean exit (never a loss), ``failed`` as an immediate loss."""
        with self._lock:
            self._status = status
        self._write(force=True)

    def _write(self, force: bool = False) -> None:
        now = time.time()
        with self._lock:
            if not force and now - self._last_write < self.interval:
                return
            payload = {"process_index": self.process_index,
                       "step": self._step, "status": self._status,
                       "pid": os.getpid(), "ts": now}
            self._last_write = now
        try:
            _atomic_write_json(self.path, payload)
        except OSError as e:
            log.warning(f"[Cluster] heartbeat write failed: {e}")


class ClusterMonitor:
    """The collective watchdog: polls every peer's heartbeat file and
    fires when one stalls past the deadline (or publishes ``failed``)
    while this process is armed.  ``abort=True`` (the training wiring)
    exits the process with :data:`EXIT_PEER_LOST` after emitting
    ``cluster/peer_lost`` and flight-dumping the liveness snapshot;
    ``abort=False`` only marks the cluster degraded — the mode the
    /healthz endpoint and the unit tests observe."""

    def __init__(self, directory: str, process_index: int,
                 process_count: int, deadline: float,
                 interval: float = 1.0, abort: bool = True):
        self.directory = directory
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.deadline = float(deadline)
        self.interval = max(min(float(interval), self.deadline / 4.0), 0.05)
        self.abort = abort
        self._t0 = time.time()
        self._armed = threading.Event()
        self._stop = threading.Event()
        self._fired = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._lost: Dict[int, str] = {}     # peer -> reason
        self._seen: Dict[int, Dict] = {}    # peer -> last fresh beat
        #: peers the bounded-staleness barrier SHED
        #: (parallel/local_sync.py): excused from the deadline — a shed
        #: host going silent is the expected outcome, not a loss
        self._excused: Dict[int, str] = {}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ClusterMonitor":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="bigdl-cluster-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 4 + 1.0)

    def arm(self) -> None:
        """Called once this process has COMPLETED a step: compile and
        cluster-join time are never under the deadline."""
        self._armed.set()

    def disarm(self) -> None:
        self._armed.clear()

    def excuse(self, peer: int, reason: str) -> None:
        """Exempt ``peer`` from the watchdog deadline — the
        bounded-staleness barrier shed it, so its silence (or its exit)
        is the planned outcome, never a cluster loss."""
        with self._lock:
            self._excused[int(peer)] = reason
            self._lost.pop(int(peer), None)

    def excused(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._excused)

    # -- state ---------------------------------------------------------------
    def degraded(self) -> bool:
        with self._lock:
            return bool(self._lost)

    def peer_table(self) -> Dict[str, Dict[str, Any]]:
        """Per-peer heartbeat table for /status and the flight dump."""
        now = time.time()
        table: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            lost = dict(self._lost)
            excused = dict(self._excused)
            seen = {p: dict(d) for p, d in self._seen.items()}
        for p in range(self.process_count):
            beat = seen.get(p) or self._read_peer(p)
            row: Dict[str, Any] = {"process_index": p}
            if p == self.process_index:
                row["self"] = True
            if beat is None:
                row.update(status="unseen", step=None, age_s=None)
            else:
                row.update(status=beat.get("status", "?"),
                           step=beat.get("step"), pid=beat.get("pid"),
                           age_s=round(now - float(beat.get("ts", now)), 3))
            if p in lost:
                row["lost"] = lost[p]
            if p in excused:
                row["excused"] = excused[p]
            table[f"p{p}"] = row
        return table

    def status(self) -> Dict[str, Any]:
        return {"state": "degraded" if self.degraded() else "ok",
                "deadline_s": self.deadline,
                "armed": self._armed.is_set(),
                "process_index": self.process_index,
                "process_count": self.process_count,
                "peers": self.peer_table()}

    # -- the watchdog --------------------------------------------------------
    def _read_peer(self, p: int) -> Optional[Dict]:
        return _read_json(File.join(self.directory,
                                    f"{_HB_PREFIX}{p}.json"))

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._check(time.time())
            except Exception:  # noqa: BLE001 - the watchdog must outlive
                # transient fs hiccups on the shared dir
                log.warning("[Cluster] watchdog poll failed", exc_info=True)
            if self.degraded() and self._armed.is_set() \
                    and not self._fired.is_set():
                self._fire()

    def _check(self, now: float) -> None:
        for p in range(self.process_count):
            if p == self.process_index:
                continue
            with self._lock:
                if p in self._excused:
                    self._lost.pop(p, None)
                    continue
            beat = self._read_peer(p)
            if beat is None:
                continue
            ts = float(beat.get("ts", 0.0))
            if ts < self._t0 - 0.001 and p not in self._seen:
                continue  # leftover from a previous incarnation
            with self._lock:
                self._seen[p] = beat
            status = beat.get("status", "running")
            if status in ("done", "preempted", "shed"):
                # shed = the staleness barrier voted this host out and
                # it exited on purpose (parallel/local_sync.py) — like
                # done/preempted, never a loss
                with self._lock:
                    self._lost.pop(p, None)
                continue
            if status == "failed":
                with self._lock:
                    self._lost[p] = "peer reported failed"
                continue
            if now - ts > self.deadline:
                with self._lock:
                    self._lost[p] = (f"no heartbeat for "
                                     f"{now - ts:.1f}s (deadline "
                                     f"{self.deadline:.1f}s)")
            else:
                with self._lock:
                    self._lost.pop(p, None)

    def _fire(self) -> None:
        """Peer loss verdict: announce, flight-dump the liveness
        snapshot, abort with the distinct exit code.  A survivor's main
        thread is blocked inside the dead collective and can never run
        this — the watchdog thread is the only way out of the hang."""
        self._fired.set()
        from bigdl_tpu import telemetry

        with self._lock:
            lost = dict(self._lost)
        snapshot = self.peer_table()
        reasons = {f"p{p}": r for p, r in lost.items()}
        log.error(f"[Cluster] peer(s) presumed lost: {reasons}; "
                  f"liveness: {snapshot}")
        telemetry.instant("cluster/peer_lost", peers=sorted(lost),
                          reasons=reasons,
                          deadline_s=self.deadline,
                          process_index=self.process_index)
        recorder = telemetry.flight_recorder()
        if recorder is not None:
            evidence = {"lost": reasons, "peer_table": snapshot,
                        "deadline_s": self.deadline}
            try:
                # the coordinator's live fleet table (telemetry/fleet.py)
                # names WHO was dragging and WHY (data-wait vs comms vs
                # checkpoint) in the steps leading into the loss — the
                # flight ring also carries its cluster/skew instants
                fw = telemetry.fleet_watcher()
                if fw is not None:
                    evidence["fleet"] = fw.snapshot()
            except Exception:  # noqa: BLE001 - dying process
                pass
            try:
                recorder.dump("peer_lost", evidence)
            except Exception:  # noqa: BLE001 - dying process
                pass
        if not self.abort:
            return
        log.error(f"[Cluster] aborting this process (exit "
                  f"{EXIT_PEER_LOST}) instead of blocking in the "
                  f"collective — the supervisor restarts the cluster "
                  f"from the last cluster-consistent checkpoint")
        try:  # flush the run log so peer_lost/flight instants survive
            telemetry.end_run()
        except Exception:  # noqa: BLE001
            pass
        os._exit(EXIT_PEER_LOST)


class ClusterService:
    """One process's cluster membership: heartbeat publisher + watchdog
    + commit barrier, bound to the run by the Optimizer (``activate`` /
    ``deactivate``)."""

    def __init__(self, directory: str, process_index: int,
                 process_count: int, deadline: Optional[float] = None,
                 interval: Optional[float] = None, abort: bool = True):
        cfg = get_config()
        self.directory = directory
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.deadline = float(deadline if deadline is not None
                              else derive_deadline(cfg))
        hb = float(interval if interval is not None
                   else cfg.heartbeat_interval)
        self.heartbeat = HeartbeatPublisher(directory, process_index,
                                            interval=hb)
        self.monitor = ClusterMonitor(directory, process_index,
                                      process_count, self.deadline,
                                      interval=hb, abort=abort)

    def start(self) -> "ClusterService":
        self.heartbeat.start()
        self.monitor.start()
        return self

    def stop(self, status: str = "done") -> None:
        self.monitor.stop()
        self.heartbeat.stop(status)

    def beat(self, step: int, done: bool = False) -> None:
        self.heartbeat.beat(step)
        if done:
            self.monitor.arm()

    def status(self) -> Dict[str, Any]:
        return self.monitor.status()

    def degraded(self) -> bool:
        return self.monitor.degraded()

    def excuse_peer(self, peer: int, reason: str) -> None:
        """Excuse a SHED peer cluster-wide on this process: the
        watchdog stops holding it to the deadline and the commit
        barrier stops waiting for its acks."""
        self.monitor.excuse(peer, reason)

    # -- coordinated commit (two-phase) --------------------------------------
    def _ack_path(self, ckpt_dir: str, p: int, step: int) -> str:
        return File.join(ckpt_dir, f"commit.p{p}.{step}.json")

    def commit_step(self, ckpt_dir: str, step: int,
                    digests: Optional[Dict] = None,
                    timeout: Optional[float] = None) -> bool:
        """Two-phase checkpoint commit for step ``step``.  Called by
        every process AFTER its local share of the checkpoint is
        durable.  Phase 1: write this host's ack (its digests ride
        along).  Phase 2 (coordinator): collect all acks within
        ``timeout`` (default: the cluster deadline) and atomically
        publish the cluster manifest naming ``step``
        cluster-consistent; a missing ack leaves the manifest at the
        previous step — the checkpoint exists but is not
        restore-eligible cluster-wide.  Returns True when this
        process's part of the barrier completed (non-coordinators:
        always, once the ack is durable)."""
        from bigdl_tpu import faults, telemetry

        # fault injection: commit_crash dies HERE — after the local
        # durable write, before the barrier ack — the exact window that
        # used to make mixed-step restores reachable
        try:
            faults.get_plan().poll_commit(step)
        except Exception:  # noqa: BLE001 - injection never fails a save
            pass
        ack = {"process_index": self.process_index, "step": int(step),
               "ts": time.time(), "digests": digests or {}}
        _atomic_write_json(
            self._ack_path(ckpt_dir, self.process_index, step), ack)
        if self.process_index != 0:
            return True
        budget = float(timeout if timeout is not None else self.deadline)
        deadline = time.time() + budget
        # a shed peer will never ack again — waiting for it would turn
        # every post-shed checkpoint into a barrier timeout
        excused = set(self.monitor.excused())
        missing = [p for p in range(1, self.process_count)
                   if p not in excused]
        while missing:
            missing = [p for p in missing if not File.exists(
                self._ack_path(ckpt_dir, p, step))]
            if not missing:
                break
            if time.time() > deadline:
                log.error(f"[Cluster] commit barrier for step {step} "
                          f"timed out after {budget:.1f}s waiting for "
                          f"acks from {missing}; the manifest stays at "
                          f"the previous consistent step")
                return False
            time.sleep(min(0.05, budget / 10.0))
        acks = {f"p{p}": (_read_json(self._ack_path(ckpt_dir, p, step))
                          or {})
                for p in range(self.process_count)}
        manifest = {"step": int(step), "committed_at": time.time(),
                    "process_count": self.process_count, "acks": acks}
        _atomic_write_json(File.join(ckpt_dir, _MANIFEST), manifest)
        telemetry.instant("cluster/commit", step=int(step),
                          processes=self.process_count)
        log.info(f"[Cluster] step {step} is cluster-consistent "
                 f"({self.process_count} acks)")
        self._prune_acks(ckpt_dir, step)
        return True

    def _prune_acks(self, ckpt_dir: str, committed: int) -> None:
        """Drop ack files from steps older than the committed one."""
        import re

        pat = re.compile(r"commit\.p(\d+)\.(\d+)\.json$")
        try:
            for name in File.listdir(ckpt_dir):
                m = pat.fullmatch(name)
                if m and int(m.group(2)) < committed:
                    File.remove(File.join(ckpt_dir, name))
        except OSError:
            pass

    # -- cluster-consistent restore ------------------------------------------
    def restore_cap(self, ckpt_dir: str) -> Optional[int]:
        """Max restore-eligible step under ``ckpt_dir``: the manifest
        step when one exists, else None (nothing cluster-certified —
        pre-cluster checkpoint dirs restore uncapped for
        back-compat)."""
        return manifest_step(ckpt_dir)

    def latest_consistent_step_dir(self, root: str,
                                   prefix: str = "sharded"
                                   ) -> Optional[str]:
        """The cluster-consistent variant of
        ``sharded_ckpt.latest_verified_step_dir``: newest verified
        checkpoint AT OR BELOW the manifest step.  Newer checkpoints
        are structurally invisible — they exist, verify, and are still
        not restore-eligible until the barrier certified them."""
        from bigdl_tpu.utils.sharded_ckpt import latest_verified_step_dir

        return latest_verified_step_dir(root, prefix=prefix,
                                        max_step=self.restore_cap(root))


# -- process-wide service ----------------------------------------------------
_service: Optional[ClusterService] = None
_service_lock = threading.Lock()


def get() -> Optional[ClusterService]:
    """The active cluster service, or None (single-process runs, or
    ``BIGDL_CLUSTER_DIR`` unset)."""
    return _service


def activate() -> Optional[ClusterService]:
    """Bring up the cluster service when configured
    (``BIGDL_CLUSTER_DIR`` set and more than one process) — called by
    the Optimizer at ``optimize()`` start; idempotent."""
    global _service
    with _service_lock:
        if _service is not None:
            return _service
        cfg = get_config()
        if not cfg.cluster_dir or cfg.num_processes < 2:
            return None
        svc = ClusterService(cfg.cluster_dir, cfg.process_id,
                             cfg.num_processes)
        _service = svc.start()
        log.info(f"[Cluster] joined heartbeat mesh at {cfg.cluster_dir} "
                 f"as p{cfg.process_id}/{cfg.num_processes} "
                 f"(deadline {svc.deadline:.1f}s)")
        return _service


def deactivate(status: str = "done") -> None:
    """Tear the service down, publishing a final status so peers read
    this exit as clean (``done``/``preempted``) or as an immediate loss
    (``failed``)."""
    global _service
    with _service_lock:
        svc, _service = _service, None
    if svc is not None:
        svc.stop(status)


# -- the supervisor ----------------------------------------------------------
def _free_port() -> int:
    """A coordinator port with the two races that made the
    multi-process e2es flaky closed: (a) two rigs running bind(0)
    concurrently could be handed the SAME port in the window between
    close() and the worker's own bind — allocation is serialized under
    a cross-process flock; (b) a port could be re-issued seconds after
    a previous cluster released it, colliding with its TIME_WAIT
    sockets — a ledger of recently issued ports skips them for 30 s."""
    import socket
    import tempfile

    try:
        import fcntl
    except ImportError:  # non-posix: fall back to the bare bind(0)
        fcntl = None
    base = os.path.join(tempfile.gettempdir(),
                        f"bigdl_ports_{os.getuid()}"
                        if hasattr(os, "getuid") else "bigdl_ports")
    lock = None
    if fcntl is not None:
        try:
            lock = open(base + ".lock", "a")
            fcntl.flock(lock, fcntl.LOCK_EX)
        except OSError:
            lock = None
    try:
        now = time.time()
        recent: Dict[str, float] = {}
        try:
            with open(base + ".json") as fh:
                recent = {k: float(v) for k, v in json.load(fh).items()}
        except (OSError, ValueError):
            pass
        recent = {k: t for k, t in recent.items() if now - t < 30.0}
        port = 0
        for _ in range(64):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            if str(port) not in recent:
                break
        recent[str(port)] = now
        try:
            tmp = f"{base}.{os.getpid()}.tmp"
            with open(tmp, "w") as fh:
                json.dump(recent, fh)
            os.replace(tmp, base + ".json")
        except OSError:
            pass
        return port
    finally:
        if lock is not None:
            try:
                fcntl.flock(lock, fcntl.LOCK_UN)
            except OSError:
                pass
            lock.close()


class Supervisor:
    """Launch-and-restart driver for an N-process cluster
    (``models/cli.py supervise -n N -- <worker cmd>``).

    Per incarnation it assigns a fresh coordinator port and a fresh
    heartbeat subdir (stale heartbeats must not speak for a new
    incarnation), injects the ``BIGDL_COORDINATOR_ADDRESS`` /
    ``BIGDL_NUM_PROCESSES`` / ``BIGDL_PROCESS_ID`` /
    ``BIGDL_CLUSTER_DIR`` env, and waits.  On the first abnormal exit
    it grants the survivors a settle window to self-abort through
    their watchdogs (exit :data:`EXIT_PEER_LOST` — their flight dumps
    are the postmortem), escalates SIGTERM→SIGKILL on whatever is
    still blocked in a dead collective, then relaunches the full
    cluster: auto-resume (``BIGDL_RESUME=auto``) restores the last
    cluster-consistent checkpoint and lands on the exact next batch.
    Restarts are bounded (``max_restarts``) with exponential backoff
    reusing ``BIGDL_RETRY_BACKOFF`` semantics; SIGTERM to the
    supervisor propagates to the children (whose grace handlers commit
    final checkpoints) and ends the loop cleanly.

    **Capacity-aware recovery** (``--min-n`` /  ``min_nprocs``): when
    two consecutive restart attempts at the declared width die on the
    SAME casualty slot (exit-history signature: one slot SIGKILLed or
    crashed while the survivors abort 43/SIGABRT), the peer is presumed
    gone and the next incarnation launches DEGRADED at ``min_nprocs``
    instead of burning the restart budget waiting for it — the
    topology-portable checkpoint (docs/fault_tolerance.md "Elastic
    recovery") reshards onto the smaller mesh on load, and the workers
    announce the membership change with a ``cluster/reshard`` instant
    the fleet view folds in.  A failure at degraded width retries the
    full ``-n`` first (capacity may have returned)."""

    def __init__(self, nprocs: int, command: Sequence[str],
                 max_restarts: int = 5,
                 cluster_dir: Optional[str] = None,
                 keep_faults: bool = False,
                 settle_grace: Optional[float] = None,
                 env: Optional[Dict[str, str]] = None,
                 log_dir: Optional[str] = None,
                 min_nprocs: Optional[int] = None):
        if nprocs < 1:
            raise ValueError("nprocs must be >= 1")
        if not command:
            raise ValueError("supervise needs a worker command")
        self.nprocs = int(nprocs)
        if min_nprocs is not None and not 1 <= int(min_nprocs) <= nprocs:
            raise ValueError(f"min_nprocs must be in [1, {nprocs}]")
        #: capacity-aware floor (``--min-n``): when consecutive restart
        #: attempts at the declared width keep losing the SAME peer
        #: slot, the cluster relaunches degraded at this width instead
        #: of burning the restart budget on a slice that isn't coming
        #: back; None = fixed-width supervision (pre-elastic behavior)
        self.min_nprocs = int(min_nprocs) if min_nprocs is not None \
            else None
        #: the operator-declared full width; ``nprocs`` is the CURRENT
        #: width and shrinks/grows between incarnations
        self.declared_nprocs = int(nprocs)
        #: width of each launched incarnation, oldest first
        self.width_history: List[int] = []
        self._last_casualties: frozenset = frozenset()
        #: slots the supervisor's drain escalation terminated this
        #: incarnation (reset per launch) — excluded from casualties
        self._drained_slots: set = set()
        self.command = list(command)
        self.max_restarts = int(max_restarts)
        self.keep_faults = keep_faults
        self.base_env = dict(env if env is not None else os.environ)
        if cluster_dir is None:
            import tempfile

            cluster_dir = tempfile.mkdtemp(prefix="bigdl_cluster_")
        self.cluster_dir = cluster_dir
        #: when set, each child's stdout+stderr lands in
        #: ``<log_dir>/inc<k>.p<i>.log`` — the supervisor-side
        #: postmortem record (a SIGKILLed child leaves no flight dump)
        self.log_dir = log_dir
        self.settle_grace = (float(settle_grace) if settle_grace is not None
                             else derive_deadline() * 3.0 + 10.0)
        self.incarnation = 0
        self.restarts = 0
        #: per-incarnation exit codes, oldest first — the postmortem
        #: record of WHO died HOW (43 = watchdog abort, negative =
        #: signal); tests assert against it
        self.exit_history: List[List[int]] = []
        self._stop = threading.Event()
        self._procs: List[subprocess.Popen] = []

    # -- signals -------------------------------------------------------------
    def _install_signals(self):
        if threading.current_thread() is not threading.main_thread():
            return {}
        old = {}
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                old[sig] = signal.signal(sig, self._on_signal)
        except (ValueError, OSError):
            old.clear()
        return old

    def _on_signal(self, signum, frame):
        log.warning(f"[Supervisor] received signal {signum}: forwarding "
                    f"SIGTERM to the cluster and stopping")
        self._stop.set()

    # -- launch / wait -------------------------------------------------------
    def _child_env(self, pid_index: int, port: int) -> Dict[str, str]:
        env = dict(self.base_env)
        env.update(BIGDL_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                   BIGDL_NUM_PROCESSES=str(self.nprocs),
                   BIGDL_SUPERVISOR_DECLARED_N=str(self.declared_nprocs),
                   BIGDL_PROCESS_ID=str(pid_index),
                   BIGDL_CLUSTER_DIR=os.path.join(
                       self.cluster_dir, f"inc{self.incarnation}"),
                   BIGDL_SUPERVISED="1",
                   BIGDL_SUPERVISOR_INCARNATION=str(self.incarnation))
        if self.incarnation > 0 and not self.keep_faults:
            # a deterministic fault plan describes ONE failure scenario;
            # replaying it every incarnation would defeat recovery
            env["BIGDL_FAULTS"] = ""
        return env

    def _launch(self) -> None:
        port = _free_port()
        self.width_history.append(self.nprocs)
        self._drained_slots = set()
        os.makedirs(os.path.join(self.cluster_dir,
                                 f"inc{self.incarnation}"), exist_ok=True)
        self._log_files = []
        self._procs = []
        for i in range(self.nprocs):
            out = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                out = open(os.path.join(
                    self.log_dir,
                    f"inc{self.incarnation}.p{i}.log"), "wb")
                self._log_files.append(out)
            self._procs.append(subprocess.Popen(
                self.command, env=self._child_env(i, port),
                stdout=out, stderr=subprocess.STDOUT if out else None))
        log.info(f"[Supervisor] incarnation {self.incarnation}: launched "
                 f"{self.nprocs} processes (coordinator :{port}, "
                 f"pids {[p.pid for p in self._procs]})")

    def _signal_all(self, sig) -> None:
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.send_signal(sig)
                except OSError:
                    pass

    def _drain(self, grace: float) -> None:
        """SIGTERM the cluster, grant ``grace`` for clean exits (grace
        handlers commit final checkpoints), SIGKILL stragglers — a
        process blocked in a dead collective never sees the SIGTERM."""
        # slots still alive HERE exit by the supervisor's own escalation
        # — their SIGTERM/SIGKILL codes are a reaction the supervisor
        # caused, never the casualty that seeded the failure (the
        # --min-n signature must not blame a drained survivor)
        t0 = time.time()
        draining = [i for i, p in enumerate(self._procs)
                    if p.poll() is None]
        self._drained_slots.update(draining)
        self._signal_all(signal.SIGTERM)
        deadline = time.time() + grace
        while any(p.poll() is None for p in self._procs) \
                and time.time() < deadline:
            time.sleep(0.1)
        still = [p.pid for p in self._procs if p.poll() is None]
        if still:
            log.warning(f"[Supervisor] SIGKILLing unresponsive pids "
                        f"{still} (blocked in a dead collective)")
            self._signal_all(signal.SIGKILL)
        for p in self._procs:
            p.wait()
        from bigdl_tpu import telemetry

        # measured drain interval: the goodput ledger charges it as
        # `drain` badput rather than unattributable idle
        telemetry.instant("cluster/drain", dur=time.time() - t0,
                          grace=grace, procs=len(draining),
                          killed=len(still))

    def _wait_incarnation(self) -> List[int]:
        """Block until the incarnation resolves; returns exit codes.
        A clean incarnation = every process exits 0.  On the first
        abnormal exit, survivors get ``settle_grace`` to self-abort
        via their watchdogs before the supervisor escalates."""
        first_failure_at: Optional[float] = None
        while True:
            if self._stop.is_set():
                self._drain(grace=30.0)
                return self._collect_codes()
            codes = [p.poll() for p in self._procs]
            if all(c is not None for c in codes):
                return self._collect_codes()
            bad = [c for c in codes if c is not None and c != 0]
            if bad and first_failure_at is None:
                first_failure_at = time.time()
                log.warning(f"[Supervisor] abnormal exit(s) {bad}; "
                            f"granting survivors {self.settle_grace:.0f}s "
                            f"to self-abort via the cluster watchdog")
            if first_failure_at is not None \
                    and time.time() - first_failure_at > self.settle_grace:
                self._drain(grace=10.0)
                return self._collect_codes()
            time.sleep(0.1)

    def _collect_codes(self) -> List[int]:
        for fh in getattr(self, "_log_files", []):
            try:
                fh.close()
            except OSError:
                pass
        self._log_files = []
        return [p.returncode for p in self._procs]

    @staticmethod
    def _describe(code: int) -> str:
        if code == 0:
            return "ok"
        if code == EXIT_PEER_LOST:
            return f"peer-lost abort ({EXIT_PEER_LOST})"
        if code < 0:
            try:
                return f"killed by {signal.Signals(-code).name}"
            except ValueError:
                return f"killed by signal {-code}"
        return f"exit {code}"

    def _backoff(self) -> float:
        from bigdl_tpu.utils.config import retry_backoff_s

        return retry_backoff_s(self.restarts)

    # -- capacity-aware width (docs/fault_tolerance.md "Elastic recovery") ---
    def _shed_slots(self) -> frozenset:
        """Slots the bounded-staleness barrier SHED this incarnation
        (``shed.p<idx>.json`` markers in the incarnation's cluster dir,
        written by parallel/local_sync.py before the survivors excuse
        the peer).  A shed slot's exit — 43 on its own, or killed in
        the drain — is a planned departure, never a casualty."""
        inc = os.path.join(self.cluster_dir, f"inc{self.incarnation}")
        shed = set()
        try:
            for name in os.listdir(inc):
                if name.startswith("shed.p") and name.endswith(".json"):
                    try:
                        shed.add(int(name[len("shed.p"):-len(".json")]))
                    except ValueError:
                        pass
        except OSError:
            pass
        return frozenset(shed)

    def _casualties(self, codes: Sequence[int]) -> frozenset:
        """Slot indices that SEEDED the incarnation's failure: exits
        that are neither clean (0), a watchdog peer-loss abort
        (:data:`EXIT_PEER_LOST`), the jax runtime SIGABRTing a survivor
        when the first abort took the coordinator down, nor a slot the
        supervisor's own drain escalation terminated.  Those exits are
        all REACTIONS to a loss; the casualty is the loss itself."""
        drained = getattr(self, "_drained_slots", set())
        return frozenset(
            i for i, c in enumerate(codes)
            if i not in drained
            and c not in (0, EXIT_PEER_LOST) and c != -signal.SIGABRT)

    def _plan_width(self, codes: Sequence[int]) -> None:
        """Pick the next incarnation's width after a failed one.

        Fixed-width (no ``min_nprocs``): nothing to decide.  Elastic:
        when two consecutive incarnations at the DECLARED width die on
        the same casualty slot (the peer isn't coming back — a
        SIGKILLed host, revoked capacity), relaunch degraded at
        ``min_nprocs`` instead of burning the restart budget; the
        topology-portable checkpoint reshards onto the smaller mesh on
        load.  A failure at degraded width grows back to the declared
        width first — capacity may have returned, and a stale casualty
        verdict must not pin the cluster small forever."""
        from bigdl_tpu import telemetry

        if self.min_nprocs is None:
            return
        cas = self._casualties(codes)
        # a shed verdict is an AFFIRMATIVE "this host is not coming
        # back" from the staleness barrier — shrink immediately instead
        # of waiting for the two-round casualty signature
        shed = self._shed_slots()
        if shed and self.min_nprocs < self.nprocs \
                and self.nprocs >= self.declared_nprocs:
            missing = ",".join(f"p{i}" for i in sorted(shed))
            log.warning(
                f"[Supervisor] peer slot(s) {missing} were SHED by the "
                f"staleness barrier and the incarnation still failed; "
                f"relaunching DEGRADED at --min-n {self.min_nprocs}")
            telemetry.instant("cluster/reshard", source="supervisor",
                              from_n=self.nprocs, to_n=self.min_nprocs,
                              declared_n=self.declared_nprocs,
                              missing=sorted(shed),
                              incarnation=self.incarnation,
                              reason="shed")
            self.nprocs = self.min_nprocs
            self._last_casualties = frozenset()
            return
        if self.nprocs < self.declared_nprocs:
            log.warning(
                f"[Supervisor] degraded incarnation "
                f"({self.nprocs}/{self.declared_nprocs}) died too; "
                f"retrying at full capacity -n {self.declared_nprocs}")
            telemetry.instant("cluster/reshard", source="supervisor",
                              from_n=self.nprocs,
                              to_n=self.declared_nprocs,
                              declared_n=self.declared_nprocs,
                              incarnation=self.incarnation,
                              reason="grow_back")
            self.nprocs = self.declared_nprocs
            self._last_casualties = frozenset()
            return
        # INTERSECTION, not equality: which SURVIVOR reacts how is a
        # race (one may exit 43 via its watchdog, another may lose the
        # gloo socket first and exhaust its retry budget with a generic
        # nonzero exit, polluting the casualty set differently each
        # round) — the signature of a host that isn't coming back is a
        # slot that shows up as a casualty in BOTH consecutive rounds
        persistent = cas & self._last_casualties
        if persistent and self.min_nprocs < self.nprocs:
            missing = ",".join(f"p{i}" for i in sorted(persistent))
            log.warning(
                f"[Supervisor] restart attempts at width {self.nprocs} "
                f"keep dying on the same peer slot(s) {missing}; "
                f"relaunching DEGRADED at --min-n {self.min_nprocs} — "
                f"the topology-portable checkpoint reshards on load")
            telemetry.instant("cluster/reshard", source="supervisor",
                              from_n=self.nprocs, to_n=self.min_nprocs,
                              declared_n=self.declared_nprocs,
                              missing=sorted(persistent),
                              incarnation=self.incarnation,
                              reason="capacity_loss")
            self.nprocs = self.min_nprocs
            self._last_casualties = frozenset()
            return
        self._last_casualties = cas

    def run(self) -> int:
        """The supervision loop; returns the supervisor's exit code
        (0 = the cluster completed, or was stopped by signal after a
        clean drain; 1 = restart budget exhausted)."""
        from bigdl_tpu import telemetry

        old = self._install_signals()
        try:
            while True:
                self._launch()
                codes = self._wait_incarnation()
                self.exit_history.append(list(codes))
                summary = {f"p{i}": self._describe(c)
                           for i, c in enumerate(codes)}
                if self._stop.is_set():
                    log.warning(f"[Supervisor] stopped by signal; final "
                                f"exits {summary}")
                    return 0
                if all(c == 0 for c in codes):
                    degraded = ("" if self.nprocs == self.declared_nprocs
                                else f" at DEGRADED width {self.nprocs}/"
                                     f"{self.declared_nprocs}")
                    log.info(f"[Supervisor] cluster completed cleanly "
                             f"after {self.restarts} restart(s)"
                             f"{degraded}")
                    return 0
                # clean-with-shed: every nonzero exit belongs to a slot
                # the staleness barrier shed on purpose, and at least
                # one survivor finished the run — the cluster COMPLETED
                # (degraded), it did not fail
                shed = self._shed_slots()
                if any(c == 0 for c in codes) and all(
                        c == 0 or i in shed
                        for i, c in enumerate(codes)):
                    gone = ",".join(f"p{i}" for i in sorted(
                        i for i, c in enumerate(codes) if c != 0))
                    log.info(f"[Supervisor] cluster completed with shed "
                             f"host(s) {gone} ({summary}) — survivors "
                             f"finished the run without them")
                    return 0
                self.restarts += 1
                if self.restarts > self.max_restarts:
                    log.error(f"[Supervisor] restart budget exhausted "
                              f"({self.max_restarts}); final exits "
                              f"{summary}")
                    return 1
                # capacity-aware width for the NEXT incarnation: shrink
                # to --min-n when the same peer keeps dying, grow back
                # to -n after a degraded-width failure
                self._plan_width(codes)
                backoff = self._backoff()
                telemetry.instant("cluster/restart",
                                  incarnation=self.incarnation,
                                  restart=self.restarts,
                                  budget=self.max_restarts,
                                  width=self.nprocs,
                                  declared_n=self.declared_nprocs,
                                  exits=summary,
                                  backoff_s=round(backoff, 3))
                log.warning(f"[Supervisor] incarnation "
                            f"{self.incarnation} died ({summary}); "
                            f"restart {self.restarts}/"
                            f"{self.max_restarts} at width "
                            f"{self.nprocs} after "
                            f"{backoff:.2f}s — resuming from the last "
                            f"cluster-consistent checkpoint")
                # interruptible: a SIGTERM during backoff ends the loop
                # now, not after the full sleep
                if self._stop.wait(backoff):
                    return 0
                self.incarnation += 1
        finally:
            for sig, handler in old.items():
                try:
                    signal.signal(sig, handler)
                except (ValueError, OSError):
                    pass
