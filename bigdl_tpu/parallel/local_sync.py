"""Straggler-tolerant local SGD: H local steps between parameter
averagings, a bounded-staleness barrier over the PR 7 heartbeat mesh,
and blame-driven SHEDDING of hosts that fall too far behind
(docs/fault_tolerance.md "Straggler tolerance").

``parameter_sync=local`` (train_step.py) gives every device along the
data axis its own parameter ISLAND: the compiled step is the inner
single-replica program vmapped over a leading island axis, so it
contains ZERO cross-island collectives and a dispatch never blocks on
a peer.  What synchronous data-parallel pays per step — one
all-reduce over every gradient byte — local SGD pays once per
``BIGDL_LOCAL_SYNC_H`` steps as a parameter average (DeepSpark, arXiv
1602.08191; post-local SGD, arXiv 1808.07217), an ≈ H× reduction in
comms bytes the comms walker measures and ``bench.py local-sgd``
diff-gates alongside the achieved loss.

This module is the driver the Optimizer runs at iteration
boundaries.  Two layers:

* :class:`StalenessBarrier` — the pure decision core, fed a peer →
  latest-published-round table.  A peer whose lag is under the
  staleness bound S (``BIGDL_LOCAL_SYNC_STALE``) never delays anyone:
  survivors average whatever that peer last published (stale by < S
  rounds — the SSP contract, arXiv 1312.7651's bounded-staleness
  reading).  A peer AT the bound gets one grace window to catch up,
  then the survivors SHED it: emit ``cluster/shed``, write the
  ``shed.p<idx>.json`` marker, and excuse it from the watchdog + the
  commit barrier (parallel/cluster.py).  Unit tests drive this class
  with synthetic tables — no processes needed.

* :class:`LocalSyncDriver` — the filesystem transport.  Every H
  steps each process collapses its local islands in-graph
  (``TrainStep.average_islands``), publishes its island-mean as
  ``sync.p<idx>.r<round>.npz`` in the cluster dir, merges the latest
  contribution of every active peer host-side (weighted by island
  count), and loads the result back.  No jax collective carries the
  exchange, so membership can shrink mid-run without recompiling —
  the property that makes shedding safe.  A shed host finds its own
  marker at the next round boundary, publishes heartbeat status
  ``shed``, and exits :data:`~bigdl_tpu.parallel.cluster.EXIT_PEER_LOST`
  (43) into the supervisor, which treats survivor-completion as clean
  and relaunches degraded per ``--min-n`` otherwise.

The wall time survivors spend inside the grace window is charged to
``straggler`` badput by the goodput ledger (``sync/staleness``
``waited_s`` — telemetry/ledger.py), so "we waited on a slow host"
shows up in the same blame column whether the straggler guard or the
staleness barrier caught it.
"""

from __future__ import annotations

import io
import json
import logging
import os
import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.utils import file as File
from bigdl_tpu.utils.config import get_config

__all__ = ["StalenessBarrier", "BarrierDecision", "LocalSyncDriver"]

log = logging.getLogger("bigdl_tpu.local_sync")

_SYNC_RE = re.compile(r"^sync\.p(\d+)\.r(\d+)\.npz$")

#: heartbeat statuses that make a peer INACTIVE for the barrier — it
#: left (or is leaving) on purpose and must be neither waited for nor
#: shed.  ``failed`` is the watchdog's jurisdiction, not ours.
_INACTIVE = ("done", "preempted", "shed", "failed")


@dataclass
class BarrierDecision:
    """What the staleness bound says about one averaging round."""

    ready: bool                       #: no active peer is at the bound
    laggards: List[int] = field(default_factory=list)  #: peers at/over S
    max_lag: int = 0                  #: worst active-peer lag, rounds


class StalenessBarrier:
    """The pure bounded-staleness decision: given this process's
    averaging round and every peer's latest PUBLISHED round, which
    peers are within the bound (average with their latest
    contribution), and which are at it (wait one grace window, then
    shed)?  Stateless and filesystem-free — the unit tests feed it
    synthetic tables."""

    def __init__(self, process_index: int, process_count: int,
                 stale: int):
        if stale < 1:
            raise ValueError("staleness bound must be >= 1 round")
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.stale = int(stale)

    def decide(self, own_round: int,
               peer_rounds: Dict[int, int],
               statuses: Optional[Dict[int, str]] = None,
               excused: Any = ()) -> BarrierDecision:
        """``peer_rounds`` maps peer index → latest round it published
        (absent = 0: nothing yet).  Peers whose heartbeat status is in
        :data:`_INACTIVE`, and excused peers, are skipped entirely."""
        statuses = statuses or {}
        excused = set(excused)
        laggards: List[int] = []
        max_lag = 0
        for p in range(self.process_count):
            if p == self.process_index or p in excused:
                continue
            if statuses.get(p) in _INACTIVE:
                continue
            lag = own_round - int(peer_rounds.get(p, 0))
            max_lag = max(max_lag, lag)
            if lag >= self.stale:
                laggards.append(p)
        return BarrierDecision(ready=not laggards, laggards=laggards,
                               max_lag=max_lag)


class LocalSyncDriver:
    """Runs the local-SGD rounds for one training process: in-graph
    island averaging, the cross-process filesystem exchange, the
    bounded-staleness barrier, and both sides of the shed protocol."""

    def __init__(self, train_step, cluster=None,
                 h: Optional[int] = None, stale: Optional[int] = None,
                 grace: Optional[float] = None,
                 poll: float = 0.05):
        cfg = get_config()
        self.step = train_step
        self.cluster = cluster
        self.h = max(1, int(h if h is not None else cfg.local_sync_h))
        self.stale = max(1, int(stale if stale is not None
                                else cfg.local_sync_stale))
        #: how long survivors hold the door for a peer AT the bound
        #: before shedding it — the window the ledger charges to
        #: ``straggler`` badput.  BIGDL_LOCAL_SYNC_GRACE overrides;
        #: unset (0) derives from the heartbeat interval.
        if grace is None:
            grace = cfg.local_sync_grace or \
                max(2.0 * cfg.heartbeat_interval, 1.0)
        self.grace = float(grace)
        self.poll = float(poll)
        self.round = 0
        self._last_avg_step = 0
        self._excused: set = set()
        self._avg_bytes: Optional[int] = None
        if cluster is not None:
            self.barrier = StalenessBarrier(cluster.process_index,
                                            cluster.process_count,
                                            self.stale)
        else:
            self.barrier = None

    # -- driver entry points (Optimizer loop) --------------------------------
    def on_step(self, neval: int) -> None:
        """Called after every COMPLETED iteration ``neval``."""
        if self._multiproc():
            self._maybe_exit_shed(neval)
        if neval <= 0 or neval % self.h:
            return
        self._average(neval // self.h, neval)

    def finalize(self, neval: int) -> None:
        """One last averaging before the run's params become the
        model's: the result of local SGD is the island MEAN, not the
        island this process happened to train."""
        if self._multiproc():
            self._maybe_exit_shed(neval)
        if neval <= 0 or neval == self._last_avg_step:
            return
        # final rounds never wait and never shed: peers may legitimately
        # be finishing at different steps
        self._average(self.round + 1, neval, final=True)

    # -- the averaging round -------------------------------------------------
    def _multiproc(self) -> bool:
        return self.cluster is not None and self.cluster.process_count > 1

    def _average(self, rnd: int, neval: int, final: bool = False) -> None:
        from bigdl_tpu import telemetry

        t0 = time.perf_counter()
        self.round = rnd
        self._last_avg_step = neval
        waited, lag, peers = 0.0, 0, 1
        if self._multiproc():
            # the island axis spans processes here, so the jitted mean
            # would BE the blocking cross-process collective this
            # barrier exists to avoid: publish the host-side mean of
            # our addressable islands instead, and merge peers' files
            nbytes = self._publish(rnd)
            if not final:
                waited, lag = self._hold_the_door(rnd, neval)
            peers = self._merge_peers(rnd)
        else:
            # single process: collapse the islands in-graph (the
            # AOT-compiled mean the comms walker measures)
            self.step.average_islands()
            nbytes = self._in_graph_bytes()
        dur = time.perf_counter() - t0
        telemetry.instant("sync/average", round=rnd, step=neval,
                          h=self.h, bytes=nbytes, dur=dur, peers=peers,
                          islands=self.step.island_count())
        telemetry.instant("sync/staleness", round=rnd,
                          waited_s=round(waited, 6), lag=lag,
                          stale=self.stale, step=neval)

    def _in_graph_bytes(self) -> int:
        """Collective bytes of ONE in-graph averaging dispatch (0 on a
        single device) — measured once from the compiled program."""
        if self._avg_bytes is None:
            self._avg_bytes = 0
            try:
                from bigdl_tpu.telemetry import comms as _comms

                if self.step._avg_cache is not None:
                    facts = _comms.comms_facts(self.step._avg_cache,
                                               mesh=self.step.mesh)
                    self._avg_bytes = int(facts.get("bytes", 0))
            except Exception:  # noqa: BLE001 - telemetry never fails a round
                pass
        return self._avg_bytes

    # -- filesystem exchange -------------------------------------------------
    def _dir(self) -> str:
        return self.cluster.directory

    def _pidx(self) -> int:
        return self.cluster.process_index

    def _sync_path(self, p: int, rnd: int) -> str:
        return File.join(self._dir(), f"sync.p{p}.r{rnd}.npz")

    def _publish(self, rnd: int) -> int:
        """Write this process's island-mean contribution for ``rnd``
        (atomically, via the File layer) and prune rounds older than
        the staleness window.  Returns the bytes shipped."""
        payload = {"__islands__": np.asarray(self.step.island_count())}
        for name, arr in self.step.island_mean_host(
                self.step.params).items():
            payload[f"p::{name}"] = np.asarray(arr)
        for name, arr in self.step.island_mean_host(
                self.step.buffers).items():
            payload[f"b::{name}"] = np.asarray(arr)
        buf = io.BytesIO()
        np.savez(buf, **payload)
        blob = buf.getvalue()
        File.save(blob, self._sync_path(self._pidx(), rnd),
                  overwrite=True)
        self._prune(rnd)
        return len(blob)

    def _prune(self, rnd: int) -> None:
        cutoff = rnd - self.stale - 1
        try:
            for name in File.listdir(self._dir()):
                m = _SYNC_RE.match(name)
                if m and int(m.group(1)) == self._pidx() \
                        and int(m.group(2)) < cutoff:
                    File.remove(File.join(self._dir(), name))
        except OSError:
            pass

    def _scan_rounds(self) -> Dict[int, int]:
        """Peer → latest published round, from the sync files."""
        latest: Dict[int, int] = {}
        try:
            for name in File.listdir(self._dir()):
                m = _SYNC_RE.match(name)
                if m:
                    p, r = int(m.group(1)), int(m.group(2))
                    latest[p] = max(latest.get(p, 0), r)
        except OSError:
            pass
        return latest

    def _statuses(self) -> Dict[int, str]:
        table = self.cluster.monitor.peer_table()
        return {row["process_index"]: row.get("status", "?")
                for row in table.values()}

    # -- the bounded-staleness barrier + shed --------------------------------
    def _hold_the_door(self, rnd: int, neval: int) -> Tuple[float, int]:
        """Give peers AT the staleness bound one grace window to catch
        up; shed whoever is still at it when the window closes.
        Returns (seconds waited, worst active-peer lag) — the wait is
        what the ledger charges to ``straggler`` badput."""
        t0 = time.perf_counter()
        decision = self.barrier.decide(rnd, self._scan_rounds(),
                                       self._statuses(), self._excused)
        deadline = t0 + self.grace
        while decision.laggards and time.perf_counter() < deadline:
            time.sleep(self.poll)
            # keep our own heartbeat fresh while we hold the door — a
            # fast host waiting on a slow one must not LOOK wedged
            self.cluster.beat(neval)
            self._maybe_exit_shed(neval)
            decision = self.barrier.decide(rnd, self._scan_rounds(),
                                           self._statuses(),
                                           self._excused)
        for p in decision.laggards:
            self._shed(p, rnd, rnd - self._scan_rounds().get(p, 0))
        return time.perf_counter() - t0, decision.max_lag

    def _shed(self, peer: int, rnd: int, lag: int) -> None:
        """The survivors' verdict: peer ``peer`` fell S rounds behind
        and did not recover within the grace window.  Announce it,
        write the marker the victim (and the supervisor) will read,
        and excuse the peer from every barrier this process runs.

        Process 0 is special: it hosts the jax.distributed coordination
        service, so making it EXIT would fatally abort every survivor's
        runtime client mid-run.  A slow p0 is soft-shed instead — the
        survivors stop waiting for it (and stop merging its stale
        rounds), but it keeps running."""
        from bigdl_tpu import telemetry

        hard = peer != 0
        if hard:
            marker = File.join(self._dir(), f"shed.p{peer}.json")
            if not File.exists(marker):
                try:
                    File.save(json.dumps(
                        {"peer": peer, "by": self._pidx(), "round": rnd,
                         "lag": lag, "stale": self.stale,
                         "ts": time.time()}).encode(), marker,
                        overwrite=True)
                except OSError as e:
                    log.warning(
                        f"[LocalSync] shed marker write failed: {e}")
        self._excused.add(peer)
        self.cluster.excuse_peer(
            peer, f"shed at round {rnd} ({lag} rounds behind, "
                  f"bound {self.stale})")
        telemetry.instant("cluster/shed", peer=peer, round=rnd,
                          lag=lag, stale=self.stale,
                          process_index=self._pidx(), role="survivor",
                          mode="hard" if hard else "soft")
        # once a peer is gone it can never join jax.distributed's
        # shutdown barrier: our otherwise-clean exit would block on it
        # and the XLA client destructor turns the failed barrier into a
        # fatal abort.  Leave via os._exit instead, like the watchdog.
        _arm_survivor_exit(self._await_victims)
        log.warning(
            f"[LocalSync] SHED p{peer} at round {rnd}: {lag} averaging "
            f"rounds behind (bound {self.stale}); survivors continue "
            f"without it — the supervisor treats its exit as planned")

    def _maybe_exit_shed(self, neval: int) -> None:
        """The victim's side: the survivors voted us out.  Publish the
        ``shed`` heartbeat status (peers read the exit as planned, like
        done/preempted), flush telemetry, and exit 43 into the
        supervisor."""
        from bigdl_tpu import telemetry
        from bigdl_tpu.parallel.cluster import EXIT_PEER_LOST

        marker = _read_marker(File.join(
            self._dir(), f"shed.p{self._pidx()}.json"))
        if marker is None:
            return
        log.error(
            f"[LocalSync] this process (p{self._pidx()}) was SHED by "
            f"p{marker.get('by')} at round {marker.get('round')} "
            f"({marker.get('lag')} rounds behind, bound "
            f"{marker.get('stale')}); exiting {EXIT_PEER_LOST} — the "
            f"survivors finish without us")
        telemetry.instant("cluster/shed", peer=self._pidx(),
                          by=marker.get("by"), round=marker.get("round"),
                          lag=marker.get("lag"), stale=self.stale,
                          process_index=self._pidx(), role="victim")
        try:
            telemetry.end_run()
        except Exception:  # noqa: BLE001 - dying process
            pass
        # the ``shed`` status is the LAST act before the exit: the
        # survivors hold their own (service-killing) teardown until
        # they see it, so it must mean "os._exit is imminent", not
        # "still flushing telemetry"
        try:
            self.cluster.heartbeat.beat(neval, status="shed")
        except Exception:  # noqa: BLE001
            pass
        os._exit(EXIT_PEER_LOST)

    def _await_victims(self, timeout: float = 30.0) -> None:
        """Exit-time courtesy from the survivor: hold our own teardown
        until every hard-shed victim has published heartbeat status
        ``shed`` (meaning its own ``os._exit`` is imminent).  If this
        process hosts the coordination service (p0 usually does),
        exiting first would fatally abort a victim that is still
        draining its last slow iteration — turning its clean 43 into a
        SIGABRT casualty the supervisor would relaunch over."""
        deadline = time.time() + timeout
        victims = [p for p in sorted(self._excused)
                   if File.exists(File.join(self._dir(),
                                            f"shed.p{p}.json"))]
        while victims and time.time() < deadline:
            for p in list(victims):
                hb = _read_marker(File.join(self._dir(),
                                            f"heartbeat.p{p}.json"))
                if hb is not None and hb.get("status") == "shed":
                    victims.remove(p)
            if victims:
                time.sleep(0.1)
        if victims:
            log.warning(f"[LocalSync] shed peer(s) {victims} never "
                        f"confirmed exit within {timeout:.0f}s — "
                        f"tearing down anyway")

    # -- merging peer contributions ------------------------------------------
    def _merge_peers(self, rnd: int) -> int:
        """Average this process's island mean with the LATEST
        contribution of every active peer (weighted by island count;
        a peer's contribution may be stale by up to S rounds — the
        bounded-staleness contract) and load the result back into the
        stacked device state.  Returns how many processes the merge
        folded."""
        statuses = self._statuses()
        latest = self._scan_rounds()
        contribs: List[Tuple[float, Dict[str, np.ndarray],
                             Dict[str, np.ndarray]]] = []
        own_params = self.step.island_mean_host(self.step.params)
        own_buffers = self.step.island_mean_host(self.step.buffers)
        contribs.append((float(self.step.island_count()),
                         own_params, own_buffers))
        for p in range(self.cluster.process_count):
            if p == self._pidx() or p in self._excused:
                continue
            if statuses.get(p) == "shed":
                continue
            r = latest.get(p, 0)
            if r <= 0 or r < rnd - self.stale:
                continue  # nothing published, or beyond the bound
            loaded = self._load(p, r)
            if loaded is not None:
                contribs.append(loaded)
        # ALWAYS load the fold back: even with no peer contribution the
        # local islands must still collapse to their mean (the in-graph
        # average never ran on the multi-process path)
        params, buffers = _weighted_mean(contribs)
        self.step.load_island_state(params, buffers)
        return len(contribs)

    def _load(self, p: int, rnd: int) -> Optional[
            Tuple[float, Dict[str, np.ndarray], Dict[str, np.ndarray]]]:
        try:
            blob = File.load(self._sync_path(p, rnd))
            with np.load(io.BytesIO(blob), allow_pickle=False) as z:
                count = float(z["__islands__"]) if "__islands__" in z \
                    else 1.0
                params = {k[len("p::"):]: z[k] for k in z.files
                          if k.startswith("p::")}
                buffers = {k[len("b::"):]: z[k] for k in z.files
                           if k.startswith("b::")}
            return count, params, buffers
        except (OSError, ValueError, KeyError) as e:
            log.warning(f"[LocalSync] could not read p{p} round {rnd} "
                        f"contribution: {e}")
            return None


def _weighted_mean(contribs) -> Tuple[Dict[str, np.ndarray],
                                      Dict[str, np.ndarray]]:
    """Island-count-weighted mean of the float leaves; non-float
    leaves (step counters, integer buffers) keep this process's own
    value.  A peer missing a key (or shipping a different shape —
    mid-upgrade mixed fleets) simply doesn't contribute to it."""
    _, own_params, own_buffers = contribs[0]

    def fold(own: Dict[str, np.ndarray], which: int) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        for name, arr in own.items():
            arr = np.asarray(arr)
            if not np.issubdtype(arr.dtype, np.floating):
                out[name] = arr
                continue
            acc = np.zeros(arr.shape, dtype=np.float64)
            weight = 0.0
            for contrib in contribs:
                count, tree = contrib[0], contrib[which]
                peer = tree.get(name)
                if peer is None or np.shape(peer) != arr.shape:
                    continue
                acc += count * np.asarray(peer, dtype=np.float64)
                weight += count
            out[name] = (acc / max(weight, 1e-12)).astype(arr.dtype)
        return out

    return fold(own_params, 1), fold(own_buffers, 2)


def _read_marker(path: str) -> Optional[Dict[str, Any]]:
    try:
        return json.loads(File.load(path).decode())
    except (OSError, ValueError):
        return None


_survivor_exit_armed = False


def _arm_survivor_exit(waiter=None) -> None:
    """After shedding a peer, this process can no longer tear down
    jax.distributed cleanly: the dead peer never joins the shutdown
    barrier, and the XLA client destructor escalates the failed barrier
    into a fatal abort (SIGABRT) ~100 s after an otherwise-successful
    exit.  So the survivor leaves the way the cluster watchdog does —
    ``os._exit`` at interpreter exit, skipping the C++ teardown.
    ``waiter`` runs first (the hold-for-victims courtesy).  An
    excepthook keeps a crashed survivor reporting failure instead of
    being laundered into exit 0."""
    global _survivor_exit_armed
    if _survivor_exit_armed:
        return
    _survivor_exit_armed = True
    import atexit
    import sys

    state = {"code": 0}
    prev_hook = sys.excepthook

    def hook(tp, val, tb):
        state["code"] = 1
        prev_hook(tp, val, tb)

    sys.excepthook = hook

    def bail():
        if waiter is not None:
            try:
                waiter()
            except Exception:  # noqa: BLE001 - exiting regardless
                pass
        try:
            from bigdl_tpu import telemetry
            telemetry.end_run()
        except Exception:  # noqa: BLE001
            pass
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:  # noqa: BLE001
            pass
        os._exit(state["code"])

    atexit.register(bail)
