"""Random number generation for bigdl_tpu.

Capability parity with the reference's Torch-compatible RNG singleton
(``utils/RandomGenerator.scala:56``: Mersenne-Twister state, thread-local
``RNG``, uniform/normal/bernoulli draws used by layer initialisation) —
re-designed for JAX:

- **Init-time randomness** (weight initialisation) is host-side and eager,
  driven by a numpy ``Generator`` (MT19937, like the reference) held in the
  global ``RNG`` object.  ``RNG.set_seed`` makes model construction
  deterministic, mirroring ``RandomGenerator.RNG.setSeed``.

- **Trace-time randomness** (dropout, RReLU noise, random ops) cannot use an
  impure host RNG under ``jit``: it flows through an explicit
  ``jax.random.key`` threaded by the training step and exposed to modules via
  a dynamic *RNG context*.  Each stochastic module folds its unique static id
  into the context key (``jax.random.fold_in``), so a single key per step
  deterministically derives independent streams for every layer — the JAX
  analogue of the reference's per-thread RNG clones.
"""

from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np

__all__ = ["RandomGenerator", "RNG", "rng_context", "require_rng", "next_rng_id"]


class RandomGenerator:
    """Host-side eager RNG used for parameter initialisation.

    Mirrors the call surface of the reference's ``RandomGenerator``
    (uniform/normal/bernoulli + seed control) on top of numpy MT19937.
    """

    def __init__(self, seed: int | None = None):
        self._seed = seed if seed is not None else 0
        self._gen = np.random.Generator(np.random.MT19937(self._seed))
        # host draws can come from the driver AND the input-prefetch
        # thread (random crop/flip in the transform chain); MT19937 state
        # updates are not atomic, so serialize every draw
        self._lock = threading.Lock()

    def set_seed(self, seed: int) -> "RandomGenerator":
        with self._lock:
            self._seed = int(seed)
            self._gen = np.random.Generator(np.random.MT19937(self._seed))
        return self

    def get_seed(self) -> int:
        return self._seed

    def get_state(self) -> dict:
        """JSON/BTPU-serializable snapshot of the full MT19937 state —
        checkpoints carry it so a preempted run's resume continues the
        SAME host-random stream (transform randomness, key draws)
        instead of replaying or forking it."""
        with self._lock:
            st = self._gen.bit_generator.state
            return {"seed": self._seed,
                    "key": [int(v) for v in st["state"]["key"]],
                    "pos": int(st["state"]["pos"])}

    def set_state(self, state: dict) -> "RandomGenerator":
        """Restore a :meth:`get_state` snapshot (checkpoint resume)."""
        with self._lock:
            self._seed = int(state.get("seed", self._seed))
            gen = np.random.Generator(np.random.MT19937(self._seed))
            st = gen.bit_generator.state
            st["state"]["key"] = np.array(state["key"], dtype=np.uint32)
            st["state"]["pos"] = int(state["pos"])
            gen.bit_generator.state = st
            self._gen = gen
        return self

    def uniform(self, a: float = 0.0, b: float = 1.0, size=None) -> np.ndarray:
        with self._lock:
            return self._gen.uniform(a, b, size=size)

    def normal(self, mean: float = 0.0, stdv: float = 1.0, size=None) -> np.ndarray:
        with self._lock:
            return self._gen.normal(mean, stdv, size=size)

    def bernoulli(self, p: float, size=None) -> np.ndarray:
        with self._lock:
            return (self._gen.uniform(0.0, 1.0, size=size) < p).astype(np.float32)

    def permutation(self, n: int) -> np.ndarray:
        with self._lock:
            return self._gen.permutation(n)

    def randint(self, low: int, high: int, size=None) -> np.ndarray:
        with self._lock:
            return self._gen.integers(low, high, size=size)


#: Global init-time RNG (thread-local in the reference; a process-global here —
#: model construction is host-side and single-threaded in practice).
RNG = RandomGenerator(seed=0)


# --------------------------------------------------------------------------
# Trace-time RNG context
# --------------------------------------------------------------------------

_rng_id_lock = threading.Lock()
_rng_id_counter = [0]


def next_rng_id() -> int:
    """Allocate a unique static id for a stochastic module instance."""
    with _rng_id_lock:
        _rng_id_counter[0] += 1
        return _rng_id_counter[0]


class _RngContext(threading.local):
    def __init__(self):
        self.key = None


_ctx = _RngContext()


@contextlib.contextmanager
def rng_context(key):
    """Install a (possibly traced) ``jax.random`` key for the dynamic extent
    of a forward pass.  The training step does::

        with rng_context(step_key):
            out = model.forward(x)
    """
    prev = _ctx.key
    _ctx.key = key
    try:
        yield
    finally:
        _ctx.key = prev


def current_rng_key():
    return _ctx.key


def require_rng(module_id: int, salt: int = 0):
    """Derive this module's key from the active context.

    Falls back to a fresh host-seeded key outside any context (eager use),
    so `model.forward(x)` works interactively without ceremony.
    """
    key = _ctx.key
    if key is None:
        key = jax.random.key(int(RNG.randint(0, 2**31 - 1)))
    key = jax.random.fold_in(key, module_id)
    if salt:
        key = jax.random.fold_in(key, salt)
    return key
