"""BTPU — the versioned, safe module/optim persistence format (SURVEY
§2.9; ``utils/serializer/ModuleSerializer.scala:34`` +
``resources/serialization/bigdl.proto``).

The reference serializes modules to a schema'd protobuf (BigDLModule /
BigDLTensor / AttrValue) through a registry keyed by class name, so a
file can be loaded without executing arbitrary code and old files fail
cleanly.  This module is the TPU build's equivalent:

- **wire layout** (via ``utils/protowire``): ``b"BTPU"`` magic, a format
  version varint, then protobuf-style fields — header JSON, structure
  JSON, and one length-delimited record per tensor (dtype/shape JSON +
  raw little-endian bytes).
- **structure**: a JSON document describing the object graph.  Objects
  are recorded as ``{"__t__": "obj", "c": <class name>, ...}`` and
  resolved against a REGISTRY of classes defined inside ``bigdl_tpu``
  (modules, criterions, optim methods, schedules, regularizers, graph
  nodes) — never by unpickling, so loading a file cannot execute
  attacker-controlled code.
- **sharing & cycles**: every object gets a memo id at first visit;
  later visits emit ``{"__t__": "ref"}``, preserving shared weights and
  the (possibly cyclic) Graph node topology.
- **versioning**: unknown format versions and unregistered class names
  are rejected with a clear error instead of a best-effort parse.
"""

from __future__ import annotations

import importlib
import json
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.utils import protowire

__all__ = ["dumps", "loads", "SerializationError", "register",
           "FORMAT_VERSION", "MAGIC"]

MAGIC = b"BTPU"
FORMAT_VERSION = 1

#: modules scanned for serializable classes (class name -> class).
_SCAN_MODULES = (
    "bigdl_tpu.nn",
    "bigdl_tpu.nn.module",
    "bigdl_tpu.nn.graph",
    "bigdl_tpu.nn.init",
    "bigdl_tpu.nn.criterion",
    "bigdl_tpu.nn.fuse",
    "bigdl_tpu.optim.optim_method",
    "bigdl_tpu.optim.regularizer",
    "bigdl_tpu.models.transformer",
    "bigdl_tpu.models.resnet",
    "bigdl_tpu.models.inception",
    "bigdl_tpu.models.vgg",
    "bigdl_tpu.models.lenet",
    "bigdl_tpu.ops.control",
)

_DTYPES = ("float32", "float64", "float16", "bfloat16", "int8", "int16",
           "int32", "int64", "uint8", "uint16", "uint32", "uint64", "bool")


class SerializationError(Exception):
    pass


_extra_registry: Dict[str, type] = {}
_registry_cache: Optional[Dict[str, type]] = None


def register(cls: type) -> type:
    """Register a user-defined class for BTPU persistence (the
    reference's ``ModuleSerializer.registerModule``)."""
    global _registry_cache
    _extra_registry[cls.__name__] = cls
    _registry_cache = None
    return cls


def _registry() -> Dict[str, type]:
    global _registry_cache
    if _registry_cache is not None:
        return _registry_cache
    reg: Dict[str, type] = {}
    for modname in _SCAN_MODULES:
        mod = importlib.import_module(modname)
        for name, obj in vars(mod).items():
            if isinstance(obj, type) and obj.__module__.startswith("bigdl_tpu"):
                reg.setdefault(obj.__name__, obj)
    reg.update(_extra_registry)
    _registry_cache = reg
    return reg


def _np_dtype(name: str) -> np.dtype:
    if name not in _DTYPES:
        raise SerializationError(f"disallowed tensor dtype {name!r}")
    if name == "bfloat16":
        import jax.numpy as jnp

        return np.dtype(jnp.bfloat16)
    return np.dtype(name)


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------

class _Encoder:
    def __init__(self):
        self.memo: Dict[int, int] = {}
        self.next_id = 0
        self.tensors: List[Tuple[str, Tuple[int, ...], bytes]] = []
        self.tensor_memo: Dict[int, int] = {}
        # id()-keyed memos are only sound while the objects stay alive —
        # CPython reuses addresses of freed temporaries
        self._keepalive: List[Any] = []

    def tensor(self, arr) -> int:
        key = id(arr)
        if key in self.tensor_memo:
            return self.tensor_memo[key]
        self._keepalive.append(arr)
        a = np.asarray(arr)
        name = a.dtype.name
        if name not in _DTYPES:
            raise SerializationError(f"cannot persist dtype {a.dtype}")
        idx = len(self.tensors)
        self.tensors.append((name, tuple(a.shape),
                             np.ascontiguousarray(a).tobytes()))
        self.tensor_memo[key] = idx
        return idx

    def value(self, v) -> Any:  # noqa: C901 — one dispatch table
        import jax

        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        if isinstance(v, (np.bool_, np.integer)):
            return {"__t__": "npscalar", "dtype": v.dtype.name, "v": int(v)}
        if isinstance(v, np.floating):
            return {"__t__": "npscalar", "dtype": v.dtype.name, "v": float(v)}
        if isinstance(v, bytes):
            import base64

            return {"__t__": "bytes", "v": base64.b64encode(v).decode()}
        if isinstance(v, (list, tuple, set, frozenset)):
            kind = {list: "list", tuple: "tuple", set: "set",
                    frozenset: "frozenset"}[type(v)]
            return {"__t__": kind, "v": [self.value(x) for x in v]}
        if isinstance(v, dict):
            return {"__t__": "dict",
                    "v": [[self.value(k), self.value(x)]
                          for k, x in v.items()]}
        if isinstance(v, jax.Array):
            if jax.dtypes.issubdtype(v.dtype, jax.dtypes.prng_key):
                return {"__t__": "prngkey",
                        "impl": str(jax.random.key_impl(v)),
                        "i": self.tensor(jax.random.key_data(v))}
            return {"__t__": "tensor", "i": self.tensor(v), "jax": True}
        if isinstance(v, np.ndarray):
            return {"__t__": "tensor", "i": self.tensor(v)}
        if isinstance(v, np.dtype):
            return {"__t__": "dtype", "v": v.name}
        if isinstance(v, type):
            # dtype-like classes (jnp.bfloat16 is a type) and registered classes
            if np.issubdtype(v, np.generic) or v.__name__ in _DTYPES:
                return {"__t__": "dtype", "v": np.dtype(v).name}
            if _registry().get(v.__name__) is v:
                return {"__t__": "class", "c": v.__name__}
            raise SerializationError(f"cannot persist class {v!r}")
        if callable(v) and hasattr(v, "__module__") and hasattr(v, "__qualname__") \
                and not isinstance(v, type):
            m, q = v.__module__ or "", v.__qualname__
            if m.startswith("bigdl_tpu") and "<" not in q and "." not in q:
                return {"__t__": "fn", "m": m, "q": q}
            raise SerializationError(
                f"cannot persist callable {q} from {m} (only module-level "
                f"bigdl_tpu functions are serializable)")
        cls = type(v)
        if _registry().get(cls.__name__) is cls:
            if id(v) in self.memo:
                return {"__t__": "ref", "id": self.memo[id(v)]}
            oid = self.next_id
            self.next_id += 1
            self.memo[id(v)] = oid
            self._keepalive.append(v)
            # runtime-only scratch (compiled backward memos) never persists
            attrs = {k: self.value(x) for k, x in vars(v).items()
                     if k != "_bwd_cache"}
            return {"__t__": "obj", "c": cls.__name__, "id": oid, "a": attrs}
        raise SerializationError(
            f"cannot persist {cls.__module__}.{cls.__name__} — register it "
            f"with bigdl_tpu.utils.module_format.register")


def dumps(obj, kind: str = "module") -> bytes:
    enc = _Encoder()
    structure = enc.value(obj)
    header = {"format": "bigdl_tpu", "kind": kind,
              "tensors": len(enc.tensors)}
    out = [MAGIC, protowire.write_varint(FORMAT_VERSION),
           protowire.emit_bytes(1, json.dumps(header).encode()),
           protowire.emit_bytes(2, json.dumps(structure).encode())]
    for dtype, shape, raw in enc.tensors:
        meta = json.dumps({"dtype": dtype, "shape": list(shape)}).encode()
        entry = protowire.emit_bytes(1, meta) + protowire.emit_bytes(2, raw)
        out.append(protowire.emit_bytes(3, entry))
    return b"".join(out)


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

class _Decoder:
    def __init__(self, tensors: List[np.ndarray]):
        self.tensors = tensors
        self.memo: Dict[int, Any] = {}

    def value(self, v) -> Any:  # noqa: C901
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        if not isinstance(v, dict):
            raise SerializationError(f"malformed structure node {v!r}")
        t = v.get("__t__")
        if t == "npscalar":
            return _np_dtype(v["dtype"]).type(v["v"])
        if t == "bytes":
            import base64

            return base64.b64decode(v["v"])
        if t in ("list", "tuple", "set", "frozenset"):
            items = [self.value(x) for x in v["v"]]
            return {"list": list, "tuple": tuple, "set": set,
                    "frozenset": frozenset}[t](items)
        if t == "dict":
            return {self.value(k): self.value(x) for k, x in v["v"]}
        if t == "tensor":
            arr = self.tensors[self._index(v["i"])]
            if v.get("jax"):
                import jax.numpy as jnp

                return jnp.asarray(arr)
            return arr
        if t == "prngkey":
            import jax

            return jax.random.wrap_key_data(
                jax.numpy.asarray(self.tensors[self._index(v["i"])]),
                impl=v["impl"])
        if t == "dtype":
            return _np_dtype(v["v"])
        if t == "class":
            return self._resolve(v["c"])
        if t == "fn":
            m = v["m"]
            if not m.startswith("bigdl_tpu"):
                raise SerializationError(f"refusing function module {m!r}")
            fn = getattr(importlib.import_module(m), v["q"], None)
            if fn is None or not callable(fn):
                raise SerializationError(f"unknown function {m}:{v['q']}")
            return fn
        if t == "obj":
            cls = self._resolve(v["c"])
            obj = cls.__new__(cls)
            self.memo[v["id"]] = obj  # before attrs: cycles resolve to obj
            for k, x in v["a"].items():
                obj.__dict__[k] = self.value(x)
            return obj
        if t == "ref":
            if v["id"] not in self.memo:
                raise SerializationError(f"dangling ref {v['id']}")
            return self.memo[v["id"]]
        raise SerializationError(f"unknown structure tag {t!r}")

    def _index(self, i) -> int:
        if not isinstance(i, int) or not 0 <= i < len(self.tensors):
            raise SerializationError(f"tensor index {i!r} out of range")
        return i

    @staticmethod
    def _resolve(name: str) -> type:
        cls = _registry().get(name)
        if cls is None:
            raise SerializationError(
                f"unknown class {name!r} — produced by a newer version or "
                f"an unregistered extension")
        return cls


def loads(blob: bytes, kind: Optional[str] = None):
    if not blob.startswith(MAGIC):
        raise SerializationError(
            "not a BTPU file (bad magic); legacy pickle checkpoints are "
            "not supported — re-save with the current version")
    version, pos = protowire.read_varint(blob, len(MAGIC))
    if version != FORMAT_VERSION:
        raise SerializationError(
            f"unsupported BTPU format version {version} "
            f"(this build reads version {FORMAT_VERSION})")
    header = structure = None
    tensors: List[np.ndarray] = []
    try:
        for field, wt, val in protowire.fields(blob[pos:]):
            if field == 1 and wt == 2:
                header = json.loads(val.decode())
            elif field == 2 and wt == 2:
                structure = json.loads(val.decode())
            elif field == 3 and wt == 2:
                meta = raw = None
                for f2, w2, v2 in protowire.fields(val):
                    if f2 == 1 and w2 == 2:
                        meta = json.loads(v2.decode())
                    elif f2 == 2 and w2 == 2:
                        raw = v2
                if meta is None or raw is None:
                    raise SerializationError("malformed tensor record")
                dt = _np_dtype(meta["dtype"])
                shape = tuple(int(s) for s in meta["shape"])
                n = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
                if len(raw) != n:
                    raise SerializationError(
                        f"tensor byte count {len(raw)} != expected {n}")
                tensors.append(np.frombuffer(raw, dtype=dt).reshape(shape)
                               .copy())
    except (IndexError, struct.error, UnicodeDecodeError,
            json.JSONDecodeError) as e:
        raise SerializationError(f"corrupted BTPU file: {e}") from e
    if header is None or structure is None:
        raise SerializationError("corrupted BTPU file: missing header/structure")
    if kind is not None and header.get("kind") != kind:
        raise SerializationError(
            f"expected a {kind!r} file, found {header.get('kind')!r}")
    if header.get("tensors") != len(tensors):
        raise SerializationError("corrupted BTPU file: tensor count mismatch")
    return _Decoder(tensors).value(structure)
