"""Managed persistent-compile-cache telemetry (docs/compile.md).

``Engine.enable_compile_cache`` turns JAX's persistent executable cache
on; this module makes that cache **measured** instead of assumed:

- :class:`CompileCacheMonitor` (a process-wide singleton) hooks
  ``jax.monitoring`` and counts persistent-cache **hits**, **misses**
  and requests, plus cumulative backend **compile seconds**, cache
  retrieval seconds and the compile seconds a hit saved.  Every hit and
  miss is mirrored into the active telemetry run as a
  ``compile/cache_hit`` / ``compile/cache_miss`` instant, so
  ``telemetry diff`` and the run summary can count them per run, and
  ``/metrics``/``/status`` (telemetry/metrics_http.py) export the
  totals live.
- :func:`cache_key_ingredients` names everything that participates in
  (or invalidates) the cache key — jax/jaxlib versions, platform and
  device kind, the mesh layout, the cache dir and thresholds, and the
  XLA flag env — emitted once per run as a ``compile/cache`` instant so
  an "expected a warm restart, got a cold one" incident can be diffed
  against the previous run's ingredients instead of guessed at.

The monitor is passive and advisory: listener registration failures
degrade to "no counts", never to a broken compile path.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

__all__ = ["CompileCacheMonitor", "monitor", "cache_key_ingredients",
           "initialized_platform"]

#: jax.monitoring keys this build observes (probed on jax 0.4.37)
_HIT_KEY = "/jax/compilation_cache/cache_hits"
_MISS_KEY = "/jax/compilation_cache/cache_misses"
_REQUEST_KEY = "/jax/compilation_cache/compile_requests_use_cache"
_COMPILE_DUR_KEY = "/jax/core/compile/backend_compile_duration"
_SAVED_DUR_KEY = "/jax/compilation_cache/compile_time_saved_sec"
_RETRIEVAL_DUR_KEY = "/jax/compilation_cache/cache_retrieval_time_sec"


class CompileCacheMonitor:
    """Counts persistent-cache traffic via ``jax.monitoring`` listeners.

    One per process (:func:`monitor`).  ``install()`` is idempotent;
    listeners stay registered for process lifetime (jax offers no
    public unregister, and the monitor is a passive counter)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._installed = False
        self._announced_ref = None  # weakref: id() reuse must not dedupe
        self.hits = 0
        self.misses = 0
        self.requests = 0
        self.compile_s = 0.0      # backend compile wall (cache or not)
        self.saved_s = 0.0        # compile seconds a hit skipped
        self.retrieval_s = 0.0    # seconds spent loading cached entries

    # -- listeners ---------------------------------------------------------
    def install(self) -> bool:
        """Register the ``jax.monitoring`` listeners (once).  Returns
        whether the monitor is live."""
        with self._lock:
            if self._installed:
                return True
            try:
                from jax._src import monitoring as _mon

                _mon.register_event_listener(self._on_event)
                _mon.register_event_duration_secs_listener(
                    self._on_duration)
            except Exception:  # noqa: BLE001 - advisory: no counts, ever
                return False
            self._installed = True
            return True

    def _on_event(self, name: str, **kwargs) -> None:
        if name == _HIT_KEY:
            with self._lock:
                self.hits += 1
                self.requests += 1
            self._mirror(hit=True)
        elif name == _MISS_KEY:
            with self._lock:
                self.misses += 1
                self.requests += 1
            self._mirror(hit=False)

    def _on_duration(self, name: str, dur: float, **kwargs) -> None:
        with self._lock:
            if name == _COMPILE_DUR_KEY:
                self.compile_s += float(dur)
            elif name == _SAVED_DUR_KEY:
                # jax reports (cached compile time - retrieval time);
                # clamp: a hit that retrieved slower than it would have
                # compiled saved nothing, it didn't owe time
                self.saved_s += max(0.0, float(dur))
            elif name == _RETRIEVAL_DUR_KEY:
                self.retrieval_s += float(dur)

    def _mirror(self, hit: bool) -> None:
        """One instant per hit/miss into the active run (no-op off-run);
        the first mirror of a run also announces the cache-key
        ingredients as a ``compile/cache`` instant."""
        try:
            from bigdl_tpu import telemetry

            tracer = telemetry.get()
            if tracer is None:
                return
            self.announce(tracer)
            if hit:
                tracer.instant("compile/cache_hit")
            else:
                tracer.instant("compile/cache_miss")
        except Exception:  # noqa: BLE001 - observers never fail a compile
            pass

    def announce(self, tracer) -> None:
        """Emit the ``compile/cache`` ingredients instant once per run
        (a live reference to the announced tracer, NOT its id — CPython
        reuses addresses of collected objects, and a later run allocated
        at the old address must still get its announcement)."""
        import weakref

        with self._lock:
            if self._announced_ref is not None \
                    and self._announced_ref() is tracer:
                return
            try:
                self._announced_ref = weakref.ref(tracer)
            except TypeError:  # unweakrefable tracer: announce each time
                self._announced_ref = None
        try:
            tracer.instant("compile/cache", **cache_key_ingredients())
        except Exception:  # noqa: BLE001
            pass

    # -- views -------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"installed": self._installed,
                    "hits": self.hits, "misses": self.misses,
                    "requests": self.requests,
                    "compile_s": round(self.compile_s, 4),
                    "saved_s": round(self.saved_s, 4),
                    "retrieval_s": round(self.retrieval_s, 4)}


_MONITOR = CompileCacheMonitor()


def monitor() -> CompileCacheMonitor:
    """The process-wide monitor singleton."""
    return _MONITOR


def initialized_platform() -> Optional[str]:
    """Platform of an ALREADY-initialized jax backend, else None —
    without initializing one (a status scrape or an import-time check
    must never be the first device touch; ``Engine.probe_backend`` owns
    that, with its wedge/singleton guards).  The one home of the
    private ``xla_bridge._backends`` probe, shared by
    ``enable_compile_cache``'s implicit gate and
    :func:`cache_key_ingredients`."""
    try:
        import jax
        from jax._src import xla_bridge as _xb

        if _xb._backends:
            return jax.default_backend()
    except Exception:  # noqa: BLE001 - internal probe is best-effort
        pass
    return None


def cache_key_ingredients(mesh=None) -> Dict[str, Any]:
    """Everything that feeds (or invalidates) the persistent cache key:
    jax/jaxlib versions, backend platform + device kind/count, the mesh
    layout, the cache dir and persistence thresholds, and the XLA flag
    environment.  Two runs with equal ingredients should hit each
    other's entries; a surprise recompile means one of these moved.

    ``mesh=None`` reads the Engine's mesh WITHOUT forcing backend init
    (a status scrape must never be the first device touch)."""
    out: Dict[str, Any] = {}
    try:
        import jax
        import jaxlib

        out["jax"] = jax.__version__
        out["jaxlib"] = getattr(jaxlib, "__version__", "?")
        out["cache_dir"] = jax.config.jax_compilation_cache_dir or ""
        out["min_compile_s"] = float(
            jax.config.jax_persistent_cache_min_compile_time_secs)
        try:
            if initialized_platform() is not None:
                dev = jax.devices()[0]
                out["platform"] = dev.platform
                out["device_kind"] = dev.device_kind
                out["device_count"] = jax.device_count()
        except Exception:  # noqa: BLE001 - backend facts are optional
            pass
    except Exception:  # noqa: BLE001 - ingredients must work sans jax
        pass
    if mesh is None:
        try:
            from bigdl_tpu.utils.engine import Engine

            mesh = Engine.__dict__.get("_mesh")
        except Exception:  # noqa: BLE001
            mesh = None
    if mesh is not None:
        try:
            out["mesh"] = {str(k): int(v)
                           for k, v in dict(mesh.shape).items()}
        except Exception:  # noqa: BLE001
            pass
    for var in ("XLA_FLAGS", "LIBTPU_INIT_ARGS", "JAX_PLATFORMS"):
        if os.environ.get(var):
            out[f"env_{var.lower()}"] = os.environ[var]
    return out
