"""Engine — the global runtime singleton.

Capability parity with ``utils/Engine.scala``: the reference's
``Engine.init`` discovers node count and cores per executor from the Spark
conf, owns the task/model thread pools, and verifies the runtime contract.
On TPU the executor topology is the **device mesh**: ``Engine.init``
discovers ``jax.devices()``, builds the default ``jax.sharding.Mesh``, and
owns host-side worker pools for the input pipeline (the reference's
``ThreadPool``/``Engine.default`` role — compute parallelism itself lives
inside XLA, so there is no ``_model`` pool).

Config parity (``Engine.scala:113-154`` system properties): environment
variables ``BIGDL_*`` replace JVM ``-Dbigdl.*`` properties.

Multi-host runtime (``Engine.scala:93-106,344-418`` capability): where the
reference's ``Engine.init`` discovers the executor topology from the Spark
master and coordinates N JVMs, here ``Engine.init`` calls
``jax.distributed.initialize`` when the coordinator env vars are present —
``BIGDL_COORDINATOR_ADDRESS`` (host:port), ``BIGDL_NUM_PROCESSES``,
``BIGDL_PROCESS_ID`` — and builds the **global** mesh over every device of
every process.  Each process then feeds its own shard of the global batch
(``jax.make_array_from_process_local_data`` inside TrainStep) and XLA's
collectives ride ICI/DCN; there is no user-level parameter server.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.utils.config import get_config

__all__ = ["Engine", "honor_platform_request", "enable_compile_cache"]


def enable_compile_cache(path: str = None, implicit: bool = False) -> str:
    """Turn on JAX's persistent executable cache (no-op if already set)
    and install the hit/miss monitor (``utils/compile_cache.py``).

    Re-runs then LOAD the serialized executable instead of re-compiling
    — which besides the usual compile-latency win matters doubly under a
    remote-compile device tunnel (``PALLAS_AXON_REMOTE_COMPILE=1``):
    the compile RPC is the tunnel's observed wedge point, and a cache
    hit skips that RPC entirely.  Reference analogue: the engine-level
    environment bootstrap in ``utils/Engine.scala:165`` owns
    process-wide runtime knobs the same way.

    The cache is MANAGED, not just enabled (docs/compile.md): every hit
    and miss is counted (and mirrored into the telemetry run as
    ``compile/cache_hit``/``compile/cache_miss`` instants), and the
    cache-key ingredients are announced per run so a cold restart that
    should have been warm is diagnosable.  Callers on the paths that
    repay warm restarts invoke this themselves — ``TrainStep.aot_scan``
    (restart/preemption-resume compile), ``BucketedExecutor.warmup``
    (serving cold start) and bench.py at import.

    ``path`` defaults to ``BIGDL_COMPILE_CACHE`` (set to ``0``/empty to
    disable) else ``~/.cache/bigdl_tpu/xla``; the entry floor defaults
    to 0.1 s compile time (``BIGDL_COMPILE_CACHE_MIN_S`` overrides —
    the jax default 1 s floor skips little probe programs whose
    wedge-window removal is exactly what we want).  Returns the
    directory (or ``""`` when disabled).

    ``implicit=True`` is the hot-path spelling (aot_scan, serving
    warmup): it additionally requires EITHER an accelerator backend or
    an explicit ``BIGDL_COMPILE_CACHE`` opt-in before touching the
    cache.  On this jaxlib, (de)serializing CPU executables built under
    a forced multi-device host platform (the tier-1 rig's
    ``--xla_force_host_platform_device_count=8``) segfaults inside XLA —
    and plain CPU pays no compile bill worth caching anyway, so the
    implicit path stays out of the blast radius while TPU/GPU restarts
    get the cache without configuration."""
    from bigdl_tpu.utils import compile_cache as _cc

    env = os.environ.get("BIGDL_COMPILE_CACHE")
    if env is not None and env.strip() in ("", "0", "off", "false"):
        # cache OFF is exactly when the compile bill needs measuring
        # (e.g. disabled to rule out a corrupt cache mid-incident):
        # keep the compile_s accounting alive
        _cc.monitor().install()
        return ""
    if implicit and env is None:
        # Platform WITHOUT initializing the backend: an import-time
        # implicit call (bench.py) must not become the first device
        # touch — probe_backend owns that, with its wedge/singleton
        # guards.  An already-initialized backend answers exactly;
        # otherwise trust the env request; with neither, DEFER — the
        # post-init implicit callers (aot_scan, serving warmup) run
        # again before the first real compile and enable it then.
        platform = _cc.initialized_platform()
        if platform is None:
            req = (os.environ.get("JAX_PLATFORMS")
                   or os.environ.get("JAX_PLATFORM_NAME") or "").strip()
            platform = req.split(",")[0].strip().lower() or None
        if platform is None or platform == "cpu":
            _cc.monitor().install()  # compile_s still counts, cache off
            return ""

    path = path or env or os.path.join(
        os.path.expanduser("~"), ".cache", "bigdl_tpu", "xla")
    import jax

    if jax.config.jax_compilation_cache_dir:  # user already configured
        _cc.monitor().install()
        return jax.config.jax_compilation_cache_dir
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    try:
        min_s = float(os.environ.get("BIGDL_COMPILE_CACHE_MIN_S", "0.1"))
    except ValueError:
        min_s = 0.1
    jax.config.update("jax_persistent_cache_min_compile_time_secs", min_s)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    try:
        # jax memoizes its cache-enabled check on the FIRST compile of
        # the process (is_cache_used's _cache_checked latch) — any jit
        # that ran before this call (model construction, a probe) would
        # otherwise have silently pinned "no cache" for process
        # lifetime.  reset the latch so the next compile re-evaluates.
        from jax._src import compilation_cache as _jcc

        _jcc.reset_cache()
    except Exception:  # noqa: BLE001 - older jax: latch absent, no-op
        pass
    _cc.monitor().install()
    return path


def honor_platform_request() -> None:
    """Re-assert an explicit ``JAX_PLATFORMS`` request via ``jax.config``.

    An externally-registered PJRT plugin (e.g. the axon TPU tunnel's
    sitecustomize hook) can win platform selection even when the user
    exported ``JAX_PLATFORMS=cpu`` — so a CLI run the user explicitly
    pinned to CPU would still dial the device tunnel.  Call this before
    the first backend touch; no-op when no explicit request exists or the
    request includes the plugin platform."""
    req = (os.environ.get("JAX_PLATFORMS") or "").strip()
    if req and "axon" not in req and "tpu" not in req:
        import jax

        jax.config.update("jax_platforms", req)


class _Engine:
    def __init__(self):
        self._initialized = False
        self._mesh = None
        self._devices = None
        self._node_number = 1
        self._core_number = 1
        self._process_count = 1
        self._process_index = 0
        self._distributed = False
        self._pool: Optional[ThreadPoolExecutor] = None
        self._singleton_fd: Optional[int] = None

    @property
    def local_mode(self) -> bool:
        # read per use, not baked into the import-time singleton, so
        # set_config()/env overrides behave like every other knob
        return get_config().local_mode

    # -- multi-host ---------------------------------------------------------
    def _init_distributed(self):
        """Join the cluster when coordinator env vars are present — the
        reference's topology discovery (``Engine.scala:344-418``), with
        ``jax.distributed`` as the control plane instead of Spark."""
        import jax

        cfg = get_config()
        if cfg.coordinator_address is None or self._distributed:
            return
        try:
            # a multi-process CPU cluster (the test rig, and any
            # CPU-fleet deployment) needs a real cross-process
            # collectives backend — without it every device_put onto a
            # cross-process sharding dies with "Multiprocess
            # computations aren't implemented on the CPU backend".
            # Must be set BEFORE the backend client is created; a no-op
            # for TPU/GPU platforms, best-effort where the knob or gloo
            # build is absent.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 - older jax: knob missing
            pass
        # bounded join: under the cluster supervisor
        # (parallel/cluster.py) a restart incarnation re-dials a FRESH
        # coordinator — if the coordinator slot died before serving, an
        # unbounded initialize would hang this incarnation forever and
        # eat the supervisor's restart budget as a silent stall
        kwargs = {}
        timeout = int(float(os.environ.get("BIGDL_COORDINATOR_TIMEOUT",
                                           "300")))
        if timeout > 0:
            # feature-detect BEFORE calling: a TypeError from inside
            # initialize leaves jax's global state half-set and a
            # retry then dies on "should only be called once"
            import inspect

            try:
                params = inspect.signature(
                    jax.distributed.initialize).parameters
            except (TypeError, ValueError):
                params = {}
            if "initialization_timeout" in params:
                kwargs["initialization_timeout"] = timeout
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id, **kwargs)
        self._distributed = True

    # -- init ---------------------------------------------------------------
    def init(self, devices=None, mesh_shape: Optional[Sequence[int]] = None,
             axis_names: Sequence[str] = ("data",)) -> "_Engine":
        """Discover devices and build the default mesh.

        ``mesh_shape=None`` puts every addressable device on the leading
        axis (pure data parallelism, the reference's only mode); richer
        layouts (data × model × sequence) are first-class via
        ``bigdl_tpu.parallel.mesh``.
        """
        import jax

        honor_platform_request()
        # BEFORE the first jax.devices(): a second driver must be caught
        # while this process can still report it rather than hang in the
        # device claim (see check_singleton)
        self.check_singleton()
        self._init_distributed()
        self._devices = list(devices) if devices is not None else jax.devices()
        n = len(self._devices)
        if mesh_shape is None:
            mesh_shape = (n,)
            axis_names = tuple(axis_names[:1])
        arr = np.array(self._devices).reshape(tuple(mesh_shape))
        from jax.sharding import Mesh

        self._mesh = Mesh(arr, tuple(axis_names))
        cfg = get_config()
        self._process_count = jax.process_count()
        self._process_index = jax.process_index()
        self._node_number = cfg.node_number or self._process_count
        self._core_number = cfg.core_number or os.cpu_count() or 1
        pool_size = cfg.default_pool_size or max(4, self._core_number)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        self._pool = ThreadPoolExecutor(max_workers=pool_size, thread_name_prefix="bigdl")
        self._initialized = True
        return self

    def _require_init(self):
        if not self._initialized:
            self.init()

    # -- accessors (Engine.coreNumber/nodeNumber/default parity) ------------
    @property
    def mesh(self):
        self._require_init()
        return self._mesh

    @property
    def devices(self):
        self._require_init()
        return self._devices

    def node_number(self) -> int:
        self._require_init()
        return self._node_number

    def core_number(self) -> int:
        self._require_init()
        return self._core_number

    def device_count(self) -> int:
        self._require_init()
        return len(self._devices)

    def process_count(self) -> int:
        """Number of host processes in the cluster (the reference's node
        count, ``Engine.nodeNumber``)."""
        self._require_init()
        return self._process_count

    def process_index(self) -> int:
        """This process's rank; drives per-process data sharding."""
        self._require_init()
        return self._process_index

    def is_coordinator(self) -> bool:
        """True on the single process that owns checkpoint writes."""
        return self.process_index() == 0

    def local_devices(self):
        """Devices attached to THIS process (vs the global ``devices``)."""
        self._require_init()
        return [d for d in self._devices
                if d.process_index == self._process_index]

    @property
    def default(self) -> ThreadPoolExecutor:
        """Host-side worker pool (data loading / IO), the analogue of
        ``Engine.default`` (``Engine.scala:241-246``)."""
        self._require_init()
        return self._pool

    def invoke_and_wait(self, fns, timeout: Optional[float] = None):
        """Run thunks on the pool and gather results — ``ThreadPool.
        invokeAndWait`` (``utils/ThreadPool.scala:92-104``)."""
        self._require_init()
        futures = [self._pool.submit(f) for f in fns]
        return [f.result(timeout=timeout) for f in futures]

    # -- singleton guard ----------------------------------------------------
    def _singleton_platform(self) -> str:
        """Normalized platform tag WITHOUT touching jax (initializing the
        backend IS the device claim the guard exists to protect): first
        entry of JAX_PLATFORMS (falling back to the legacy
        JAX_PLATFORM_NAME alias jax still honors), lowercased;
        empty/unset -> 'default'."""
        plats = (os.environ.get("JAX_PLATFORMS")
                 or os.environ.get("JAX_PLATFORM_NAME") or "").strip().lower()
        return plats.split(",")[0].strip() or "default"

    def _singleton_lock_path(self) -> str:
        """Lock identity from env/config only.  Best-effort by design:
        two processes must agree on JAX_PLATFORMS/TPU_VISIBLE_DEVICES
        spelling to collide on the same lockfile (an advisory guard for
        the common same-launcher case, not a security boundary).  The
        path is scoped per-user (XDG_RUNTIME_DIR when available, else a
        uid-tagged name under the shared tmpdir) so one user's lockfile
        can neither be pre-planted nor flock-held by another.  Deliberate
        tradeoff: CROSS-user double-driver contention is no longer
        pre-empted here — a world-writable rendezvous path is exactly the
        symlink/DoS surface this scoping removes; cross-user claims
        surface as the device claim error instead."""
        import tempfile

        parts = [self._singleton_platform(),
                 (os.environ.get("TPU_VISIBLE_DEVICES") or "").strip(),
                 f"p{get_config().process_id}"]
        tag = "".join(c if c.isalnum() or c in "p_" else "_"
                      for c in "_".join(parts))
        uid = os.getuid() if hasattr(os, "getuid") else 0
        run_dir = os.environ.get("XDG_RUNTIME_DIR")
        if run_dir and os.path.isdir(run_dir):
            return os.path.join(run_dir, f"bigdl_tpu_{tag}.lock")
        return os.path.join(tempfile.gettempdir(),
                            f"bigdl_tpu_u{uid}_{tag}.lock")

    def check_singleton(self, raise_on_conflict: Optional[bool] = None,
                        force: bool = False, wait_s: float = 0.0) -> bool:
        """Detect a SECOND process about to drive the same accelerator —
        the reference's ``Engine.checkSingleton`` (``Engine.scala:165``,
        enforced at ``DistriOptimizer.scala:543-554``) which catches two
        task-sets sharing one JVM.  The TPU failure mode is two host
        processes contending for one chip's PJRT client: the loser
        blocks indefinitely in device claim, which looks exactly like a
        hang — so this guard deliberately never touches jax itself
        (``Engine.init`` runs it BEFORE the first ``jax.devices()``).
        Advisory ``flock`` on a per-platform, per-process-slot lockfile,
        released on process exit.

        Returns True when this process holds (or newly acquired) the
        lock, or when the lockfile is unusable (permissions on a shared
        tmpdir) — the guard is advisory, never a new failure mode.  On
        conflict: warns and returns False, or raises when
        ``raise_on_conflict`` (default: the ``BIGDL_CHECK_SINGLETON``
        config, mirroring ``bigdl.check.singleton``) is true.

        ``wait_s`` > 0 retries the claim until the deadline before
        declaring a conflict — for callers whose contender's claim is
        known to be BOUNDED (a health-probe watcher holds the lock for
        at most its probe timeout), where fail-fast turns a transient
        handoff race into a lost measurement (the round-4 bench
        failure)."""
        import fcntl
        import logging
        import time

        log = logging.getLogger("bigdl_tpu")
        if self._singleton_fd is not None:
            return True
        # CPU backends support unlimited concurrent processes — the claim
        # deadlock is an accelerator failure mode (force=True for tests)
        if self._singleton_platform() == "cpu" and not force:
            return True
        if raise_on_conflict is None:
            raise_on_conflict = get_config().check_singleton_strict
        path = self._singleton_lock_path()
        flags = os.O_CREAT | os.O_RDWR
        # never follow a pre-planted symlink at the (predictable) path;
        # ELOOP from O_NOFOLLOW lands in the advisory-skip branch below
        flags |= getattr(os, "O_NOFOLLOW", 0) | getattr(os, "O_CLOEXEC", 0)
        try:
            fd = os.open(path, flags, 0o600)
        except OSError as e:
            log.warning(f"singleton check skipped: cannot open {path}: {e}")
            return True
        import errno

        deadline = time.monotonic() + max(0.0, wait_s)
        waited = False
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError as e:
                if e.errno not in (errno.EWOULDBLOCK, errno.EAGAIN,
                                   errno.EACCES):
                    # not contention (e.g. ENOLCK on a no-flock fs):
                    # advisory-skip, never a new failure mode — and never
                    # a misdiagnosed "second driver" after a full wait
                    os.close(fd)
                    log.warning(f"singleton check skipped: flock on {path} "
                                f"failed: {e}")
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    os.close(fd)
                    msg = (f"another process already drives this platform "
                           f"(lock {path}); two device clients on one chip "
                           f"deadlock in claim")
                    if waited:
                        msg += f" (waited {wait_s:.0f}s for the holder)"
                    if raise_on_conflict:
                        raise RuntimeError(msg) from None
                    log.warning(msg)
                    return False
                if not waited:
                    log.warning(
                        f"platform lock {path} held; waiting up to "
                        f"{wait_s:.0f}s for the holder's bounded claim")
                    waited = True
                time.sleep(min(2.0, remaining))
        os.ftruncate(fd, 0)
        os.write(fd, str(os.getpid()).encode())
        self._singleton_fd = fd
        return True

    def probe_backend(self, timeout_s: Optional[float] = None,
                      lock_wait_s: Optional[float] = None):
        """Bounded first touch of the jax backend.  PJRT client creation
        blocks INDEFINITELY on a wedged device tunnel (e.g. a stale pool
        grant), so drivers call this instead of a bare ``jax.devices()``.
        Runs :meth:`check_singleton` first and RAISES on conflict — a
        second-driver conflict must be diagnosed as such, not as the
        timeout it would otherwise become.  ``timeout_s`` defaults to the
        ``BENCH_BACKEND_TIMEOUT`` env var (300 s).  ``lock_wait_s``
        (default: ``BIGDL_SINGLETON_WAIT`` env, 0) waits that long for a
        held singleton lock before declaring a conflict — set it above
        the watcher's probe bound so a scripted bench rides out a
        transient probe claim instead of losing the measurement.
        Returns the device list; raises ``RuntimeError`` on conflict,
        timeout, or backend error."""
        import threading

        if timeout_s is None:
            timeout_s = float(os.environ.get("BENCH_BACKEND_TIMEOUT", "300"))
        if lock_wait_s is None:
            lock_wait_s = float(os.environ.get("BIGDL_SINGLETON_WAIT", "0"))
        honor_platform_request()
        self.check_singleton(raise_on_conflict=True, wait_s=lock_wait_s)
        done = threading.Event()
        state: dict = {}

        def probe():
            try:
                import jax

                state["devices"] = jax.devices()
            except Exception as e:  # noqa: BLE001
                state["error"] = f"{type(e).__name__}: {e}"
            done.set()

        threading.Thread(target=probe, daemon=True).start()
        if not done.wait(timeout_s):
            raise RuntimeError(
                f"backend init exceeded {timeout_s:.0f}s (wedged device "
                f"tunnel?); the probe thread is stuck in native code")
        if "error" in state:
            raise RuntimeError(f"backend init failed: {state['error']}")
        return state["devices"]

    def reset(self):
        self._initialized = False
        self._mesh = None
        if self._singleton_fd is not None:
            os.close(self._singleton_fd)  # closing drops the flock
            self._singleton_fd = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None


Engine = _Engine()
