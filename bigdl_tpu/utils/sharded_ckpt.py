"""Sharded (per-host) checkpointing for pod-scale state — orbax-backed.

The default checkpoint path (``optim/optimizer.py`` + BTPU) gathers
every parameter to the coordinator and writes one file: exactly the
reference's driver-side ``saveModel`` (``Optimizer.scala:284-322``), and
fine at BigDL model sizes.  At pod scale that gather is the bottleneck
(and an OOM for models larger than one host), so this module writes each
array AS SHARDED — every host persists only its own shards, restores
re-place them under the live mesh sharding — via orbax's
StandardCheckpointer (the TPU ecosystem's checkpoint layer; async by
design, Tensorstore underneath).

Path semantics: local paths are resolved to absolute; remote paths
(``gs://...``) are passed to orbax VERBATIM — Tensorstore owns the
scheme — and the small driver-state meta file rides ``utils.file``
(fsspec) next to the shards.  The meta file doubles as the
checkpoint-COMPLETE marker: it is written only after the state write has
finished, only by the coordinator, and atomically (tmp+rename via
``File.save``), so ``latest_step_dir`` can never resume from a torn
checkpoint.

Wire in through ``Optimizer.set_checkpoint(path, trigger,
backend="sharded")`` or use directly::

    save_train_step(step, path, extra={"neval": 7})
    extra = restore_train_step(step, path)   # in-place, shardings kept

Async composition: ``save_train_step(..., wait=False)`` returns a
``finish()`` callable — orbax's internal async write proceeds while
training continues; ``finish()`` blocks until the shards are durable and
then commits the meta marker.  ``Optimizer`` uses this under
``BIGDL_ASYNC_CHECKPOINT`` through the same ``_join_checkpoint_write``
barrier as the BTPU backend.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional

import jax
import numpy as np

from bigdl_tpu.utils import file as File

__all__ = ["save_train_step", "restore_train_step", "latest_step_dir",
           "prune_old"]

_META = "bigdl_meta.json"

#: process-lifetime checkpointer — orbax serializes saves per instance,
#: so one shared instance gives in-order async writes for free
_CKPTR = None


def _checkpointer():
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp

        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def _resolve(path: str) -> str:
    """Absolute for local paths; VERBATIM for remote — ``os.path.abspath``
    on ``gs://...`` would mangle it into ``$CWD/gs:/...``."""
    return path if File.is_remote(path) else os.path.abspath(path)


_join = File.join


def _tree(step):
    # one pytree for everything device-resident; orbax wants arrays only
    return {"params": step.params, "opt_state": step.opt_state,
            "buffers": step.buffers}


def _sanitize(tree):
    """orbax rejects raw python/np scalars; lift them to 0-d ndarrays."""
    def fix(v):
        if isinstance(v, jax.Array):
            return v
        a = np.asarray(v)
        return a
    return jax.tree.map(fix, tree)


def _is_coordinator() -> bool:
    from bigdl_tpu.utils.engine import Engine

    try:
        return Engine.is_coordinator()
    except Exception:  # engine not initialized (direct library use)
        return True


def save_train_step(step, path: str, extra: Optional[Dict] = None,
                    wait: bool = True) -> Optional[Callable[[], None]]:
    """Write the TrainStep's params/opt-state/buffers sharded under
    ``path`` (a directory), then commit the meta marker (coordinator
    only, atomic).  ``wait=True`` blocks until both are durable so the
    caller's trigger semantics match the BTPU backend; ``wait=False``
    returns a ``finish()`` callable that performs the blocking tail —
    orbax's internal async write overlaps the next training steps until
    ``finish()`` is called."""
    path = _resolve(path)
    ckptr = _checkpointer()
    # a REUSED dir (overwrite_checkpoint) may carry a committed meta from
    # a previous run: retract the complete-marker BEFORE the state is
    # deleted/rewritten, or a crash mid-write leaves latest_step_dir
    # advertising a torn checkpoint
    if _is_coordinator():
        File.remove(_join(path, _META))
    ckptr.save(_join(path, "state"), _sanitize(_tree(step)), force=True)

    def finish():
        ckptr.wait_until_finished()
        if _is_coordinator():
            meta = {"extra": extra or {}}
            File.save(json.dumps(meta).encode(), _join(path, _META),
                      overwrite=True)

    if wait:
        finish()
        return None
    return finish


def restore_train_step(step, path: str) -> Dict:
    """Restore into ``step`` IN PLACE, preserving the live shardings
    (each leaf restores against the step's current array as the abstract
    target, so placement follows the current mesh).  Returns the saved
    ``extra`` dict."""
    path = _resolve(path)
    target = _sanitize(_tree(step))
    ckptr = _checkpointer()
    ckptr.wait_until_finished()  # never race an in-flight save
    restored = ckptr.restore(_join(path, "state"), target)
    step.params = restored["params"]
    step.opt_state = restored["opt_state"]
    step.buffers = restored["buffers"]
    try:
        return json.loads(File.load(_join(path, _META))).get("extra", {})
    except OSError:
        return {}


def _numbered(root: str, prefix: str) -> List[tuple]:
    """``(n, path)`` for every complete ``<prefix>.<n>`` checkpoint under
    ``root`` (meta marker present), local or remote."""
    out = []
    for name in File.listdir(root):
        if not name.startswith(prefix + "."):
            continue
        try:
            n = int(name.rsplit(".", 1)[1])
        except ValueError:
            continue
        p = _join(root, name)
        if File.exists(_join(p, _META)):
            out.append((n, p))
    return out


def latest_step_dir(root: str, prefix: str = "sharded") -> Optional[str]:
    """Newest complete ``<prefix>.<n>`` checkpoint directory under
    ``root`` — local or remote (the resume path must see the same
    ``gs://`` directories the save path wrote)."""
    done = _numbered(root, prefix)
    return max(done)[1] if done else None


def prune_old(root: str, keep: int, prefix: str = "sharded") -> List[str]:
    """Delete all but the newest ``keep`` complete checkpoints under
    ``root``; returns the pruned paths.  Retention policy the reference
    lacks (its ``model.n`` files accumulate forever) but pod-scale
    sharded state demands."""
    if keep < 1:
        raise ValueError("keep must be >= 1")
    done = sorted(_numbered(root, prefix))
    pruned = []
    for _, p in done[:-keep]:
        File.remove(p)
        pruned.append(p)
    return pruned
