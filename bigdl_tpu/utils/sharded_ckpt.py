"""Sharded (per-host) checkpointing for pod-scale state — orbax-backed.

The default checkpoint path (``optim/optimizer.py`` + BTPU) gathers
every parameter to the coordinator and writes one file: exactly the
reference's driver-side ``saveModel`` (``Optimizer.scala:284-322``), and
fine at BigDL model sizes.  At pod scale that gather is the bottleneck
(and an OOM for models larger than one host), so this module writes each
array AS SHARDED — every host persists only its own shards, restores
re-place them under the live mesh sharding — via orbax's
StandardCheckpointer (the TPU ecosystem's checkpoint layer; async by
design, Tensorstore underneath).

Path semantics: local paths are resolved to absolute; remote paths
(``gs://...``) are passed to orbax VERBATIM — Tensorstore owns the
scheme — and the small driver-state meta file rides ``utils.file``
(fsspec) next to the shards.  The meta file doubles as the
checkpoint-COMPLETE marker: it is written only after the state write has
finished, only by the coordinator, and atomically (tmp+rename via
``File.save``), so ``latest_step_dir`` can never resume from a torn
checkpoint.

Wire in through ``Optimizer.set_checkpoint(path, trigger,
backend="sharded")`` or use directly::

    save_train_step(step, path, extra={"neval": 7})
    extra = restore_train_step(step, path)   # in-place, shardings kept

Async composition: ``save_train_step(..., wait=False)`` returns a
``finish()`` callable — orbax's internal async write proceeds while
training continues; ``finish()`` blocks until the shards are durable and
then commits the meta marker.  ``Optimizer`` uses this under
``BIGDL_ASYNC_CHECKPOINT`` through the same ``_join_checkpoint_write``
barrier as the BTPU backend.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from bigdl_tpu.utils import ckpt_digest, ckpt_topology
from bigdl_tpu.utils import file as File
from bigdl_tpu.utils.ckpt_topology import TopologyMismatchError

__all__ = ["save_train_step", "restore_train_step", "latest_step_dir",
           "latest_verified_step_dir", "verify_step_dir", "quarantine",
           "prune_old", "CorruptCheckpointError", "TopologyMismatchError",
           "read_topology", "restorable_onto_fn"]

_META = "bigdl_meta.json"

log = logging.getLogger("bigdl_tpu.ckpt")


class CorruptCheckpointError(RuntimeError):
    """A checkpoint's content digests do not match its payload — it is
    torn or bit-rotted and must not be loaded (restore quarantines it
    and falls back to the previous good step)."""

#: process-lifetime checkpointer — orbax serializes saves per instance,
#: so one shared instance gives in-order async writes for free
_CKPTR = None


def _checkpointer():
    global _CKPTR
    if _CKPTR is None:
        import orbax.checkpoint as ocp

        _CKPTR = ocp.StandardCheckpointer()
    return _CKPTR


def _resolve(path: str) -> str:
    """Absolute for local paths; VERBATIM for remote — ``os.path.abspath``
    on ``gs://...`` would mangle it into ``$CWD/gs:/...``."""
    return path if File.is_remote(path) else os.path.abspath(path)


_join = File.join


def _tree(step):
    # one pytree for everything device-resident; orbax wants arrays only
    return {"params": step.params, "opt_state": step.opt_state,
            "buffers": step.buffers}


def _sanitize(tree):
    """orbax rejects raw python/np scalars; lift them to 0-d ndarrays."""
    def fix(v):
        if isinstance(v, jax.Array):
            return v
        a = np.asarray(v)
        return a
    return jax.tree.map(fix, tree)


def _is_coordinator() -> bool:
    from bigdl_tpu.utils.engine import Engine

    try:
        return Engine.is_coordinator()
    except Exception:  # engine not initialized (direct library use)
        return True


def save_train_step(step, path: str, extra: Optional[Dict] = None,
                    wait: bool = True) -> Optional[Callable[[], None]]:
    """Write the TrainStep's params/opt-state/buffers sharded under
    ``path`` (a directory), then commit the meta marker (coordinator
    only, atomic).  ``wait=True`` blocks until both are durable so the
    caller's trigger semantics match the BTPU backend; ``wait=False``
    returns a ``finish()`` callable that performs the blocking tail —
    orbax's internal async write overlaps the next training steps until
    ``finish()`` is called."""
    path = _resolve(path)
    ckptr = _checkpointer()
    # a REUSED dir (overwrite_checkpoint) may carry a committed meta from
    # a previous run: retract the complete-marker BEFORE the state is
    # deleted/rewritten, or a crash mid-write leaves latest_step_dir
    # advertising a torn checkpoint
    if _is_coordinator():
        File.remove(_join(path, _META))
    # topology is recorded at dispatch time (shapes/specs don't change
    # while the async write overlaps training) and committed with the
    # meta marker: a restore onto a DIFFERENT mesh validates against it
    # pre-load (docs/fault_tolerance.md "Elastic recovery")
    topo = ckpt_topology.topology_of(step)
    ckptr.save(_join(path, "state"), _sanitize(_tree(step)), force=True)

    def finish():
        ckptr.wait_until_finished()
        if _is_coordinator():
            # digest the payload AFTER the write is durable: the meta
            # marker then certifies both completeness (it exists) and
            # integrity (the digests match) — restore verifies before
            # any state is touched
            digests = ckpt_digest.digest_dir(path, exclude=(_META,))
            meta = {"extra": extra or {}, "digests": digests,
                    "topology": topo,
                    "topology_digest": ckpt_topology.digest(topo)}
            File.save(json.dumps(meta).encode(), _join(path, _META),
                      overwrite=True)
        # fault injection (bigdl_tpu/faults.py): a torn_ckpt plan entry
        # corrupts a committed shard NOW — marker valid, payload torn —
        # which is precisely the failure the digests exist to catch
        _poll_torn_fault(path, extra)

    if wait:
        finish()
        return None
    return finish


def _poll_torn_fault(path: str, extra: Optional[Dict]) -> None:
    """Give the fault plan its post-commit shot at this checkpoint.
    Coordinator-only: a torn file is a storage event with ONE writer —
    every process XOR-flipping the same seeded bytes on a shared dir
    would undo the tear on the second pass (and race the writes)."""
    try:
        from bigdl_tpu import faults

        plan = faults.get_plan()
        if plan.has("torn_ckpt") and _is_coordinator() \
                and not File.is_remote(path):
            driver = (extra or {}).get("driver_state", {})
            step_no = int(driver.get("neval", (extra or {}).get("neval", 0)))
            plan.poll_checkpoint(path, step_no)
    except Exception:  # noqa: BLE001 - injection must not fail a save
        log.warning("torn_ckpt fault injection failed", exc_info=True)


def _read_meta(path: str) -> Optional[Dict]:
    try:
        return json.loads(File.load(_join(path, _META)))
    except (OSError, ValueError):
        return None


def verify_step_dir(path: str) -> Tuple[bool, List[str]]:
    """Integrity check of one checkpoint directory: the meta marker must
    parse, every recorded digest must match the payload on disk, and
    the topology record (when present) must match ITS digest — a
    mangled topology would corrupt reshard decisions exactly like a
    torn payload corrupts state.  Metas without digests (pre-digest
    checkpoints) pass as complete but unverifiable — rejecting them
    would strand every existing checkpoint."""
    meta = _read_meta(_resolve(path))
    if meta is None:
        return False, ["meta marker missing or unparseable"]
    problems = list(ckpt_topology.verify_digest(meta))
    digests = meta.get("digests")
    if digests:
        problems.extend(ckpt_digest.verify_digests(_resolve(path),
                                                   digests))
    return not problems, problems


def read_topology(path: str) -> Optional[Dict]:
    """The topology record a checkpoint directory carries, or None
    (pre-topology checkpoint)."""
    meta = _read_meta(_resolve(path))
    return (meta or {}).get("topology")


def restorable_onto_fn(mesh) -> Callable[[str], bool]:
    """Predicate for the discovery walk and retention: whether a step
    dir's recorded topology can restore onto ``mesh``
    (``ckpt_topology.reshardable_onto``; pre-topology checkpoints pass
    — they predate sharded-contract recording)."""
    def restorable(path: str) -> bool:
        topo = read_topology(path)
        if not topo:
            return True
        ok, _problems = ckpt_topology.reshardable_onto(topo, mesh)
        return ok

    return restorable


def quarantine(path: str, problems: Optional[List[str]] = None) -> str:
    """Move a torn/corrupt checkpoint aside as ``<path>.corrupt`` (kept
    as postmortem evidence, and so discovery can never pick it again),
    announce it (``checkpoint/quarantined`` instant + flight-recorder
    ring), and return the new path."""
    from bigdl_tpu import telemetry

    path = _resolve(path)
    dest = path.rstrip("/") + ".corrupt"
    n = 1
    while File.exists(dest):
        dest = path.rstrip("/") + f".corrupt.{n}"
        n += 1
    File.rename(path, dest)
    log.error(f"[Checkpoint] quarantined {path} -> {dest}: "
              f"{'; '.join(problems or ['integrity check failed'])}")
    telemetry.instant("checkpoint/quarantined", path=path, moved_to=dest,
                      problems=list(problems or []))
    return dest


def restore_train_step(step, path: str) -> Dict:
    """Restore into ``step`` IN PLACE, placing every leaf under the
    step's CURRENT mesh sharding — orbax's restore is driven by the
    target, so a checkpoint written by a different mesh reshards on
    load (each process reads the slices it needs off shared storage).
    Returns the saved ``extra`` dict.

    Two pre-load gates, both before any state is touched:

    - content digests (PR 5): a torn/bit-flipped checkpoint raises
      :class:`CorruptCheckpointError`;
    - topology (docs/fault_tolerance.md "Elastic recovery"): the
      recorded leaf set / global shapes / dtypes must match the live
      target, and every recorded-sharded leaf must keep a sharded
      placement on the live mesh — otherwise
      :class:`TopologyMismatchError` (the checkpoint is NOT quarantined;
      it is intact, merely not restorable at this width).

    A restore whose topology legitimately differs (the cluster shrank
    or grew) is announced with a ``cluster/reshard`` instant carrying
    the old→new topology."""
    path = _resolve(path)
    ckptr = _checkpointer()
    ckptr.wait_until_finished()  # never race an in-flight save
    ok, problems = verify_step_dir(path)
    if not ok:
        raise CorruptCheckpointError(
            f"checkpoint {path} failed integrity verification: "
            f"{'; '.join(problems)}")
    meta = _read_meta(path)
    topo = (meta or {}).get("topology")
    reshard = None
    if topo:
        ckpt_topology.check_target(topo, _tree(step), step.mesh)
        reshard = ckpt_topology.reshard_fields(topo, step.mesh,
                                               source="restore",
                                               path=path)
        if reshard is not None:
            log.info(f"[Reshard] restoring a checkpoint "
                     f"{ckpt_topology.describe(topo)} onto "
                     f"{reshard['to_processes']} process(es) / "
                     f"{reshard['to_devices']} device(s)")
    target = _sanitize(_tree(step))
    restored = ckptr.restore(_join(path, "state"), target)
    step.params = restored["params"]
    step.opt_state = restored["opt_state"]
    step.buffers = restored["buffers"]
    if reshard is not None:
        # announced only AFTER the restore landed: a failed restore
        # must not tell the fleet the membership legitimately changed
        from bigdl_tpu import telemetry

        telemetry.instant("cluster/reshard", **reshard)
    return (meta or {}).get("extra", {})


def _numbered(root: str, prefix: str) -> List[tuple]:
    """``(n, path)`` for every complete ``<prefix>.<n>`` checkpoint under
    ``root`` (meta marker present), local or remote.  The match is
    EXACT — ``<prefix>.<n>`` and nothing more — so a quarantined
    ``<prefix>.<n>.corrupt[.k]`` (which still contains the meta marker)
    can never re-enter discovery as a checkpoint."""
    import re

    pat = re.compile(re.escape(prefix) + r"\.(\d+)")
    out = []
    for name in File.listdir(root):
        m = pat.fullmatch(name)
        if m is None:
            continue
        p = _join(root, name)
        if File.exists(_join(p, _META)):
            out.append((int(m.group(1)), p))
    return out


def latest_step_dir(root: str, prefix: str = "sharded") -> Optional[str]:
    """Newest complete ``<prefix>.<n>`` checkpoint directory under
    ``root`` — local or remote (the resume path must see the same
    ``gs://`` directories the save path wrote)."""
    done = _numbered(root, prefix)
    return max(done)[1] if done else None


def latest_verified_step_dir(root: str, prefix: str = "sharded",
                             do_quarantine: bool = True,
                             max_step: Optional[int] = None,
                             restorable_fn: Optional[
                                 Callable[[str], bool]] = None
                             ) -> Optional[str]:
    """Newest complete checkpoint that also passes digest verification.
    Candidates that fail are quarantined (``*.corrupt``) on the way down
    so discovery converges — the caller gets the newest GOOD step or
    None, never a torn one.

    ``max_step`` is the cluster-consistent variant
    (``parallel/cluster.py``): steps ABOVE the cap are skipped without
    quarantine — they are intact, merely never certified by the
    cluster commit barrier, so a cluster restore must not see them.

    ``restorable_fn`` is the elastic variant (``restorable_onto_fn``):
    verified checkpoints whose recorded topology cannot restore onto
    the CURRENT mesh are likewise skipped WITHOUT quarantine — in a
    mixed-topology dir the walk falls back to the newest step the
    current width can actually take."""
    for _n, p in sorted(_numbered(root, prefix), reverse=True):
        if max_step is not None and _n > max_step:
            continue
        ok, problems = verify_step_dir(p)
        if ok:
            if restorable_fn is not None and not restorable_fn(p):
                log.warning(f"[Checkpoint] {p} is verified but its "
                            f"topology cannot restore onto the current "
                            f"mesh; trying the step before it")
                continue
            return p
        if do_quarantine:
            try:
                quarantine(p, problems)
            except OSError:
                log.error(f"[Checkpoint] could not quarantine {p}")
    return None


def prune_old(root: str, keep: int, prefix: str = "sharded",
              trusted: Optional[str] = None,
              keep_step: Optional[int] = None,
              restorable_fn: Optional[Callable[[str], bool]] = None
              ) -> List[str]:
    """Delete all but the newest ``keep`` complete checkpoints under
    ``root``; returns the pruned paths.  Retention policy the reference
    lacks (its ``model.n`` files accumulate forever) but pod-scale
    sharded state demands.

    The newest VERIFIED-good checkpoint is never deleted, even when it
    falls outside the keep window — if every newer checkpoint turns out
    torn, it is the only state a restore can still fall back to.
    ``trusted`` names a checkpoint the caller certifies as good (the
    one it JUST wrote and digested) so the retention guard need not
    re-read and re-hash it on every save.

    ``keep_step`` additionally pins one step number (the cluster
    manifest's — ``parallel/cluster.py``): cluster restores are CAPPED
    at that step, so deleting it would strand the cluster even though
    newer (uncertified) checkpoints exist on disk.

    ``restorable_fn`` (``restorable_onto_fn``) extends the guard to
    mixed-topology dirs: retention must also never delete the last
    checkpoint RESTORABLE ONTO THE CURRENT WIDTH — when every survivor
    in the keep window carries a topology the current mesh cannot take,
    the newest verified+restorable victim is retained as the elastic
    fallback anchor (the checkpoint a degraded-width restore would
    land on)."""
    if keep < 1:
        raise ValueError("keep must be >= 1")
    done = sorted(_numbered(root, prefix))
    victims = [v for v in done[:-keep] if v[0] != keep_step]
    if victims:
        trusted = _resolve(trusted) if trusted else None
        survivors = [p for _n, p in sorted(done[-keep:], reverse=True)]
        # per-call verdict memos: verify_step_dir re-hashes every
        # payload file in a step dir, and the two retention passes plus
        # restorable_fn would otherwise re-read the same multi-GB dirs
        # on every checkpoint save tail
        _verified: Dict[str, bool] = {}
        _restorable: Dict[str, bool] = {}

        def good(p: str, need_restorable: bool) -> bool:
            # trusted = the checkpoint this very save just wrote and
            # digested — by construction verified AND written at (hence
            # restorable onto) the current width
            if trusted is not None and p == trusted:
                return True
            if p not in _verified:
                _verified[p] = verify_step_dir(p)[0]
            if not _verified[p]:
                return False
            if not need_restorable or restorable_fn is None:
                return True
            if p not in _restorable:
                _restorable[p] = bool(restorable_fn(p))
            return _restorable[p]

        # two retention anchors, each the newest qualifying victim when
        # no survivor qualifies: (1) verified AND restorable onto the
        # current width (mixed-topology dirs), (2) verified at all (the
        # pre-existing torn-fallback guard).  An anchor retained by (1)
        # also satisfies (2), so the second pass sees it as a keeper.
        retained: List[str] = []
        needs = ([True] if restorable_fn is not None else []) + [False]
        for need in needs:
            if any(good(p, need) for p in survivors + retained):
                continue
            for item in sorted(victims, reverse=True):
                if good(item[1], need):
                    victims = [v for v in victims if v != item]
                    retained.append(item[1])
                    log.warning(
                        f"[Checkpoint] retaining {item[1]} beyond keep="
                        f"{keep}: it is the last "
                        f"{'current-width-restorable' if need else 'verified-good'}"
                        f" checkpoint")
                    break
    pruned = []
    for _, p in victims:
        File.remove(p)
        pruned.append(p)
    return pruned
