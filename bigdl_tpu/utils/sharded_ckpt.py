"""Sharded (per-host) checkpointing for pod-scale state — orbax-backed.

The default checkpoint path (``optim/optimizer.py`` + BTPU) gathers
every parameter to the coordinator and writes one file: exactly the
reference's driver-side ``saveModel`` (``Optimizer.scala:284-322``), and
fine at BigDL model sizes.  At pod scale that gather is the bottleneck
(and an OOM for models larger than one host), so this module writes each
array AS SHARDED — every host persists only its own shards, restores
re-place them under the live mesh sharding — via orbax's
StandardCheckpointer (the TPU ecosystem's checkpoint layer; async by
design, Tensorstore underneath).

Wire in through ``Optimizer.set_checkpoint(path, trigger,
backend="sharded")`` or use directly::

    save_train_step(step, path, extra={"neval": 7})
    extra = restore_train_step(step, path)   # in-place, shardings kept
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax
import numpy as np

__all__ = ["save_train_step", "restore_train_step", "latest_step_dir"]

_META = "bigdl_meta.json"


def _tree(step):
    # one pytree for everything device-resident; orbax wants arrays only
    return {"params": step.params, "opt_state": step.opt_state,
            "buffers": step.buffers}


def _sanitize(tree):
    """orbax rejects raw python/np scalars; lift them to 0-d ndarrays."""
    def fix(v):
        if isinstance(v, jax.Array):
            return v
        a = np.asarray(v)
        return a
    return jax.tree.map(fix, tree)


def save_train_step(step, path: str, extra: Optional[Dict] = None):
    """Write the TrainStep's params/opt-state/buffers sharded under
    ``path`` (a directory), plus a small json with host-side driver
    state.  Blocking on completion (orbax saves async internally, we
    wait so the caller's trigger semantics match the BTPU backend)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, "state"), _sanitize(_tree(step)),
                   force=True)
    meta = {"extra": extra or {}}
    with open(os.path.join(path, _META), "w") as f:
        json.dump(meta, f)


def restore_train_step(step, path: str) -> Dict:
    """Restore into ``step`` IN PLACE, preserving the live shardings
    (each leaf restores against the step's current array as the abstract
    target, so placement follows the current mesh).  Returns the saved
    ``extra`` dict."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    target = _sanitize(_tree(step))
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(os.path.join(path, "state"), target)
    step.params = restored["params"]
    step.opt_state = restored["opt_state"]
    step.buffers = restored["buffers"]
    try:
        with open(os.path.join(path, _META)) as f:
            return json.load(f).get("extra", {})
    except FileNotFoundError:
        return {}


def latest_step_dir(root: str, prefix: str = "sharded") -> Optional[str]:
    """Newest ``<prefix>.<n>`` checkpoint directory under ``root``."""
    if not os.path.isdir(root):
        return None
    best, best_n = None, -1
    for name in os.listdir(root):
        if not name.startswith(prefix + "."):
            continue
        try:
            n = int(name.rsplit(".", 1)[1])
        except ValueError:
            continue
        if n > best_n and os.path.exists(
                os.path.join(root, name, _META)):
            best_n, best = n, os.path.join(root, name)
    return best
