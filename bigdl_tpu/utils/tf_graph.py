"""TensorFlow GraphDef import/export (``utils/tf/TensorflowLoader.scala:39``,
``utils/tf/loaders/`` 45 per-op files, ``utils/tf/TensorflowSaver.scala``,
``BigDLToTensorflow.scala`` — SURVEY §2.9).

Import decodes a binary GraphDef straight off the protobuf wire
(``bigdl_tpu.utils.protowire``) into NodeDef dicts, then builds a
``bigdl_tpu.nn.Graph`` whose nodes are TF-style ops (``bigdl_tpu.nn.ops`` /
``nn.tf``): Const tensors become ``tf.Const`` (or trainable
``tf.Variable`` with ``train_consts=True`` — the analogue of the
reference's Session training path), Placeholders become Inputs, and each
compute op maps to the matching forward-only op module.  The reference
instead pattern-matches subgraphs into parameterized layers
(``TensorflowToBigDL.scala``); mapping op-for-op is both simpler and
XLA-idiomatic since the whole graph flattens under jit anyway.

Export (``save_graphdef``) walks a module tree and emits NodeDefs for
the supported layer set; ``load_graphdef``/``TensorflowLoader`` can
re-import the result (round-trip tested — TF itself is not a
dependency).

Wire subset decoded: GraphDef.node(1); NodeDef name(1)/op(2)/input(3)/
attr(5, map<string, AttrValue>); AttrValue list(1)/s(2)/i(3)/f(4)/b(5)/
type(6)/shape(7)/tensor(8); TensorProto dtype(1)/shape(2)/content(4)/
float_val(5)/double_val(6)/int_val(7)/string_val(8)/int64_val(10)/
bool_val(11); TensorShapeProto.dim(2).size(1).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.utils import protowire as pw

__all__ = ["parse_graphdef", "load_graphdef", "TensorflowLoader",
           "save_graphdef"]

_DT_FLOAT, _DT_INT32, _DT_INT64, _DT_BOOL = 1, 3, 9, 10
_DTYPES = {_DT_FLOAT: np.float32, 2: np.float64, _DT_INT32: np.int32,
           4: np.uint8, 5: np.int16, 6: np.int8, _DT_INT64: np.int64,
           _DT_BOOL: np.bool_, 14: np.float16}


# ---------------------------------------------------------------------------
# decoding
# ---------------------------------------------------------------------------

def _parse_shape(buf: bytes) -> List[int]:
    dims = []
    for f, _, val in pw.fields(buf):
        if f == 2:  # Dim
            size = 0
            for f2, _, v2 in pw.fields(val):
                if f2 == 1:
                    size = v2 if isinstance(v2, int) else 0
            if size >= (1 << 63):
                size -= 1 << 64
            dims.append(size)
    return dims


def _parse_tensor(buf: bytes) -> np.ndarray:
    dtype = np.float32
    shape: List[int] = []
    content = b""
    floats: List[float] = []
    ints: List[int] = []
    strs: List[bytes] = []
    for f, wt, val in pw.fields(buf):
        if f == 1:
            dtype = _DTYPES.get(val, np.float32)
        elif f == 2:
            shape = _parse_shape(val)
        elif f == 4:
            content = val
        elif f == 5:
            floats.extend(pw.packed_floats(val, wt))
        elif f == 6:  # double_val
            if wt == 2:
                floats.extend(struct.unpack(f"<{len(val) // 8}d", val))
            else:
                floats.append(struct.unpack("<d", val)[0])
        elif f in (7, 10, 11):  # int_val / int64_val / bool_val
            ints.extend(pw.packed_varints(val, wt))
        elif f == 8:  # string_val (DT_STRING tensors: filenames, keys)
            strs.append(val)
    if strs:
        arr = np.empty(len(strs), dtype=object)
        arr[:] = strs
        return arr.reshape(shape) if shape and arr.size == int(
            np.prod(shape)) else arr
    if content:
        arr = np.frombuffer(content, dtype).copy()
    elif floats:
        arr = np.asarray(floats, dtype)
    elif ints:
        arr = np.asarray(ints, dtype)
    else:
        arr = np.zeros(0, dtype)
    if shape:
        if arr.size == int(np.prod(shape)):
            arr = arr.reshape(shape)
        elif arr.size == 1:  # scalar fill (TF packs repeated values)
            arr = np.full(shape, arr.reshape(-1)[0], dtype)
    return arr


def _parse_attr(buf: bytes):
    for f, wt, val in pw.fields(buf):
        if f == 2:
            return val  # bytes (s)
        if f == 3:
            v = val
            return v - (1 << 64) if v >= (1 << 63) else v
        if f == 4:
            return struct.unpack("<f", val)[0]
        if f == 5:
            return bool(val)
        if f == 6:
            return ("dtype", val)
        if f == 7:
            return _parse_shape(val)
        if f == 8:
            return _parse_tensor(val)
        if f == 1:  # list
            ints, floats, strs, shapes = [], [], [], []
            for f2, wt2, v2 in pw.fields(val):
                if f2 == 2:
                    strs.append(v2)
                elif f2 == 3:
                    ints.extend(pw.packed_varints(v2, wt2))
                elif f2 == 4:
                    floats.extend(pw.packed_floats(v2, wt2))
                elif f2 == 6:  # type list (e.g. Tdense) — dtype enums
                    ints.extend(pw.packed_varints(v2, wt2))
                elif f2 == 7:  # shape list (e.g. dense_shapes)
                    shapes.append(_parse_shape(v2))
            return ints or floats or strs or shapes
    return None


def parse_graphdef(data: bytes) -> List[Dict]:
    """Binary GraphDef -> [{name, op, inputs, attrs}]."""
    nodes = []
    for f, _, val in pw.fields(data):
        if f != 1:
            continue
        node = {"name": "", "op": "", "inputs": [], "attrs": {}}
        for f2, _, v2 in pw.fields(val):
            if f2 == 1:
                node["name"] = v2.decode()
            elif f2 == 2:
                node["op"] = v2.decode()
            elif f2 == 3:
                node["inputs"].append(v2.decode())
            elif f2 == 5:
                key = None
                av = None
                for f3, _, v3 in pw.fields(v2):
                    if f3 == 1:
                        key = v3.decode()
                    elif f3 == 2:
                        av = _parse_attr(v3)
                if key is not None:
                    node["attrs"][key] = av
        nodes.append(node)
    return nodes


# ---------------------------------------------------------------------------
# import: GraphDef -> bigdl_tpu Graph
# ---------------------------------------------------------------------------

class TensorflowLoader:
    """Map parsed NodeDefs onto a ``nn.Graph`` (the op table mirrors the
    reference's ``utils/tf/loaders``)."""

    def __init__(self, graphdef, inputs: Sequence[str],
                 outputs: Sequence[str], train_consts: bool = False):
        """``graphdef``: binary GraphDef bytes, or an already-parsed node
        list (as from ``parse_graphdef`` — used by the Session path after
        input-pipeline rewriting)."""
        if isinstance(graphdef, (bytes, bytearray)):
            graphdef = parse_graphdef(graphdef)
        self.nodes = {n["name"]: n for n in graphdef}
        self.input_names = list(inputs)
        self.output_names = list(outputs)
        self.train_consts = train_consts
        self._multi_output: Dict[str, int] = {}  # name -> n outputs

    @staticmethod
    def _clean(name: str) -> str:
        name = name.lstrip("^")
        return name.split(":")[0]

    def _const_value(self, name: str) -> np.ndarray:
        node = self.nodes[self._clean(name)]
        if node["op"] != "Const":
            raise NotImplementedError(
                f"expected Const input, got {node['op']} for {name}")
        return node["attrs"]["value"]

    def _convert(self, node, graph_nodes, module_inputs):
        """Return (module, input node names) for one NodeDef."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.nn import ops, tf as nntf

        op = node["op"]
        a = node["attrs"]
        # keep ":k" output-index suffixes — build() routes them through
        # the Graph's from_index edges (multi-output ops: Split/Unpack)
        ins = [i for i in node["inputs"] if not i.startswith("^")]
        fmt = (a.get("data_format") or b"NHWC")
        fmt = fmt.decode() if isinstance(fmt, bytes) else fmt

        if op == "Const":
            v = a["value"]
            if self.train_consts and v.dtype == np.float32 and v.size > 0:
                return nntf.Variable(v), []
            return nntf.Const(v), []
        if op in ("Identity", "StopGradient", "CheckNumerics", "NoOp"):
            return nn.Identity(), ins[:1]
        if op in ("Add", "AddV2", "AddN"):
            return nn.CAddTable(), ins
        if op == "Sub":
            return nn.CSubTable(), ins
        if op == "Mul":
            return nn.CMulTable(), ins
        if op == "RealDiv" or op == "Div":
            return nn.CDivTable(), ins
        if op == "Maximum":
            return nn.CMaxTable(), ins
        if op == "Minimum":
            return nn.CMinTable(), ins
        if op == "MatMul":
            if a.get("transpose_a"):
                raise NotImplementedError("MatMul transpose_a")
            return ops.ModuleToOperation(_MatMul(
                bool(a.get("transpose_b", False)))), ins
        if op == "BiasAdd":
            return ops.BiasAdd(format=fmt), ins
        if op == "Conv2D":
            strides = a.get("strides", [1, 1, 1, 1])
            pad = a.get("padding") or b"SAME"
            pad = pad.decode() if isinstance(pad, bytes) else pad
            dil = a.get("dilations") or [1, 1, 1, 1]
            if fmt == "NHWC":
                sh, sw = int(strides[1]), int(strides[2])
                dh, dw = int(dil[1]), int(dil[2])
            else:
                sh, sw = int(strides[2]), int(strides[3])
                dh, dw = int(dil[2]), int(dil[3])
            return ops.Conv2D(sh, sw, pad, fmt,
                              dilation_h=dh, dilation_w=dw), ins
        if op in ("MaxPool", "AvgPool"):
            ks = a.get("ksize", [1, 1, 1, 1])
            strides = a.get("strides", [1, 1, 1, 1])
            pad = (a.get("padding") or b"VALID")
            pad = pad.decode() if isinstance(pad, bytes) else pad
            if fmt == "NHWC":
                k = (int(ks[1]), int(ks[2]))
                s = (int(strides[1]), int(strides[2]))
            else:
                k = (int(ks[2]), int(ks[3]))
                s = (int(strides[2]), int(strides[3]))
            cls = ops.MaxPool if op == "MaxPool" else ops.AvgPool
            return cls(k, s, pad, fmt), ins
        if op == "Relu":
            return nn.ReLU(), ins
        if op == "Relu6":
            return nn.ReLU6(), ins
        if op == "Sigmoid":
            return nn.Sigmoid(), ins
        if op == "Tanh":
            return nn.Tanh(), ins
        if op == "Softmax":
            return nn.SoftMax(axis=-1), ins
        if op == "LogSoftmax":
            return nn.LogSoftMax(axis=-1), ins
        if op == "Rsqrt":
            return nn.Power(-0.5), ins
        if op == "Sqrt":
            return nn.Sqrt(), ins
        if op == "Square":
            return nn.Square(), ins
        if op == "Exp":
            return nn.Exp(), ins
        if op == "Log":
            return nn.Log(), ins
        if op == "Abs":
            return nn.Abs(), ins
        if op == "Floor":
            return ops.Floor(), ins
        if op == "Cast":
            dt = a.get("DstT")
            if isinstance(dt, tuple):
                dt = dt[1]
            return ops.Cast(_DTYPES.get(dt, np.float32)), ins
        if op == "Reshape":
            shape = [int(s) for s in self._const_value(ins[1]).reshape(-1)]
            return nn.InferReshape(shape), ins[:1]
        if op == "Squeeze":
            dims = sorted(int(d) for d in (a.get("squeeze_dims") or []))
            if any(d < 0 for d in dims):
                raise NotImplementedError(
                    "Squeeze with negative squeeze_dims is unsupported")
            if not dims:
                return nn.Squeeze(), ins[:1]
            if len(dims) == 1:
                return nn.Squeeze(dims[0]), ins[:1]
            seq = nn.Sequential()
            for d in reversed(dims):  # squeeze from the back, dims stay valid
                seq.add(nn.Squeeze(d))
            return seq, ins[:1]
        if op == "ExpandDims":
            axis = int(self._const_value(ins[1]).reshape(-1)[0])
            return nn.Unsqueeze(axis), ins[:1]
        if op == "Pad":
            paddings = self._const_value(ins[1])
            return ops.Pad(paddings), ins[:1]
        if op in ("ConcatV2", "Concat"):
            if op == "ConcatV2":
                axis = int(self._const_value(ins[-1]).reshape(-1)[0])
                data_ins = ins[:-1]
            else:
                axis = int(self._const_value(ins[0]).reshape(-1)[0])
                data_ins = ins[1:]
            return nn.JoinTable(axis, 0), data_ins
        if op == "Mean":
            axes = [int(x) for x in self._const_value(ins[1]).reshape(-1)]
            keep = bool(a.get("keep_dims", False))
            return ops.ModuleToOperation(_Mean(axes, keep)), ins[:1]
        if op == "Shape":
            return nntf.Shape(), ins
        if op == "Fill":
            return nntf.Fill(), ins
        if op == "Transpose":
            perm = [int(p) for p in self._const_value(ins[1]).reshape(-1)]
            return ops.ModuleToOperation(_Transpose(perm)), ins[:1]
        if op == "Split":
            axis = int(self._const_value(ins[0]).reshape(-1)[0])
            num = int(a.get("num_split", 1))
            self._multi_output[node["name"]] = num
            return ops.ModuleToOperation(_Split(axis, num)), ins[1:]
        if op in ("Unpack", "Unstack"):
            axis = int(a.get("axis", 0))
            num = int(a.get("num", 0))
            self._multi_output[node["name"]] = num
            return ops.ModuleToOperation(_Unpack(axis, num)), ins[:1]
        if op in ("Pack", "Stack"):
            axis = int(a.get("axis", 0))
            return ops.ModuleToOperation(_Pack(axis)), ins
        if op == "OneHot":
            axis = int(a.get("axis", -1))
            depth = int(self._const_value(ins[1]).reshape(-1)[0])
            on = float(self._const_value(ins[2]).reshape(-1)[0])
            off = float(self._const_value(ins[3]).reshape(-1)[0])
            return ops.OneHot(axis, depth, on, off), ins[:1]
        if op == "Slice":
            begin = [int(b) for b in self._const_value(ins[1]).reshape(-1)]
            size = [int(s) for s in self._const_value(ins[2]).reshape(-1)]
            return ops.Slice(begin, size), ins[:1]
        if op == "StridedSlice":
            begin = [int(b) for b in self._const_value(ins[1]).reshape(-1)]
            end = [int(e) for e in self._const_value(ins[2]).reshape(-1)]
            strides = [int(s) for s in self._const_value(ins[3]).reshape(-1)]
            return ops.ModuleToOperation(_StridedSlice(
                begin, end, strides, int(a.get("begin_mask", 0)),
                int(a.get("end_mask", 0)), int(a.get("shrink_axis_mask", 0)),
                int(a.get("ellipsis_mask", 0)),
                int(a.get("new_axis_mask", 0)))), ins[:1]
        if op == "Conv2DBackpropInput":
            strides = a.get("strides", [1, 1, 1, 1])
            pad = a.get("padding") or b"SAME"
            pad = pad.decode() if isinstance(pad, bytes) else pad
            out_shape = [int(s) for s in
                         self._const_value(ins[0]).reshape(-1)]
            if fmt == "NHWC":
                sh, sw = int(strides[1]), int(strides[2])
            else:
                sh, sw = int(strides[2]), int(strides[3])
            return ops.ModuleToOperation(_Conv2DBackpropInput(
                out_shape, sh, sw, pad, fmt)), ins[1:]
        if op == "ResizeBilinear":
            return ops.ResizeBilinearOps(
                bool(a.get("align_corners", False)),
                bool(a.get("half_pixel_centers", False))), ins
        if op in ("DecodeJpeg", "DecodePng", "DecodeImage", "DecodeBmp"):
            return ops.DecodeImage(int(a.get("channels", 3) or 3)), ins
        if op == "Placeholder":
            return None, []
        raise NotImplementedError(
            f"TensorflowLoader: unsupported op {op!r} (node {node['name']!r})")

    def load(self):
        import bigdl_tpu.nn as nn
        from bigdl_tpu.nn.graph import Node, node_from_module

        graph_nodes: Dict[str, Node] = {}
        inputs: List[Node] = []
        for name in self.input_names:
            node = nn.Input(name=self._clean(name))
            graph_nodes[self._clean(name)] = node
            inputs.append(node)

        def build(ref: str):
            """Build the node for ``ref``; multi-output refs ("name:k",
            or any consumer of Split/Unpack) return (node, k) pairs that
            node_from_module turns into from_index edges."""
            name = self._clean(ref)
            _, _, suffix = ref.lstrip("^").partition(":")
            out_idx = int(suffix) if suffix.isdigit() else 0
            if name not in graph_nodes:
                nd = self.nodes.get(name)
                if nd is None:
                    raise KeyError(f"unknown node {name!r}")
                mod, ins = self._convert(nd, graph_nodes, inputs)
                if mod is None:  # placeholder not listed as input
                    node = nn.Input(name=name)
                    inputs.append(node)
                    graph_nodes[name] = node
                else:
                    mod.set_name(name)
                    src = [build(i) for i in ins]
                    graph_nodes[name] = (node_from_module(mod, src)
                                         if src else Node(mod))
            node = graph_nodes[name]
            if name in self._multi_output:
                return (node, out_idx)
            return node

        def as_node(ref: str) -> Node:
            built = build(ref)
            if isinstance(built, tuple):  # multi-output graph output:
                src, idx = built         # select via a routing identity
                sel = node_from_module(nn.Identity(), [(src, idx)])
                return sel
            return built

        outputs = [as_node(n) for n in self.output_names]
        return nn.Graph(inputs, outputs)


class _Transpose:
    def __init__(self, perm):
        self.perm = tuple(perm)

    def forward(self, input):
        import jax.numpy as jnp

        return jnp.transpose(input, self.perm)


class _Split:
    """TF Split: equal chunks along axis; a MULTI-OUTPUT node (list)."""

    def __init__(self, axis, num):
        self.axis, self.num = axis, num

    def forward(self, input):
        import jax.numpy as jnp

        return list(jnp.split(input, self.num, axis=self.axis))


class _Unpack:
    """TF Unpack/Unstack: split + squeeze along axis (multi-output)."""

    def __init__(self, axis, num):
        self.axis, self.num = axis, num

    def forward(self, input):
        import jax.numpy as jnp

        num = self.num or input.shape[self.axis]
        return [jnp.squeeze(s, self.axis)
                for s in jnp.split(input, num, axis=self.axis)]


class _Pack:
    def __init__(self, axis):
        self.axis = axis

    def forward(self, input):
        import jax.numpy as jnp

        parts = input if isinstance(input, (list, tuple)) else [input]
        return jnp.stack(parts, axis=self.axis)


class _StridedSlice:
    """TF StridedSlice with begin/end/shrink-axis masks (the subset the
    reference's loader handles, ``utils/tf/loaders/StridedSlice.scala``);
    ellipsis/new-axis masks are rejected explicitly."""

    def __init__(self, begin, end, strides, begin_mask, end_mask,
                 shrink_mask, ellipsis_mask, new_axis_mask):
        if ellipsis_mask or new_axis_mask:
            raise NotImplementedError(
                "StridedSlice ellipsis_mask/new_axis_mask is unsupported")
        self.begin, self.end, self.strides = begin, end, strides
        self.begin_mask, self.end_mask = begin_mask, end_mask
        self.shrink_mask = shrink_mask

    def forward(self, input):
        import jax.numpy as jnp

        slices = []
        shrink = []
        for i in range(input.ndim):
            if i >= len(self.begin):
                slices.append(slice(None))
                continue
            b = None if self.begin_mask & (1 << i) else self.begin[i]
            e = None if self.end_mask & (1 << i) else self.end[i]
            if self.shrink_mask & (1 << i):
                b0 = self.begin[i]
                slices.append(slice(b0, b0 + 1 if b0 != -1 else None))
                shrink.append(i)
            else:
                slices.append(slice(b, e, self.strides[i]))
        out = input[tuple(slices)]
        for ax in reversed(shrink):
            out = jnp.squeeze(out, ax)
        return out


class _Conv2DBackpropInput:
    """TF transposed conv (gradient-of-conv used as a forward op, e.g.
    deconvolution layers; ``utils/tf/loaders/Conv2DBackpropInput.scala``).
    Inputs: (filter HWIO, out_backprop)."""

    def __init__(self, out_shape, sh, sw, padding, fmt):
        self.out_shape = tuple(out_shape)
        self.sh, self.sw = sh, sw
        self.padding, self.fmt = padding, fmt

    def forward(self, input):
        import jax.numpy as jnp
        from jax import lax

        w, y = input
        if self.fmt == "NCHW":
            y = y.transpose(0, 2, 3, 1)
        out_h = self.out_shape[1] if self.fmt == "NHWC" else self.out_shape[2]
        out_w = self.out_shape[2] if self.fmt == "NHWC" else self.out_shape[3]
        kh, kw = int(w.shape[0]), int(w.shape[1])
        # effective padding of the FORWARD conv this op inverts
        if self.padding == "SAME":
            pad_h = max(0, (-(-out_h // self.sh) - 1) * self.sh + kh - out_h)
            pad_w = max(0, (-(-out_w // self.sw) - 1) * self.sw + kw - out_w)
            pads = [(pad_h // 2, pad_h - pad_h // 2),
                    (pad_w // 2, pad_w - pad_w // 2)]
        else:
            pads = [(0, 0), (0, 0)]
        dn = lax.conv_dimension_numbers(
            (1, out_h, out_w, 1), w.shape, ("NHWC", "HWIO", "NHWC"))
        # transpose of the forward conv: dilate the grads by the stride,
        # pad by kernel-1 minus forward padding, flip + swap the filter
        wt = jnp.flip(jnp.swapaxes(w, 2, 3), axis=(0, 1))
        out = lax.conv_general_dilated(
            y, wt.astype(y.dtype), (1, 1),
            [(kh - 1 - pads[0][0], kh - 1 - pads[0][1]
              + (out_h + sum(pads[0]) - kh) % self.sh),
             (kw - 1 - pads[1][0], kw - 1 - pads[1][1]
              + (out_w + sum(pads[1]) - kw) % self.sw)],
            lhs_dilation=(self.sh, self.sw), dimension_numbers=dn)
        if self.fmt == "NCHW":
            out = out.transpose(0, 3, 1, 2)
        return out


class _MatMul:
    """Minimal forward module for TF MatMul (y = a @ b^T?)."""

    def __init__(self, transpose_b: bool):
        self.transpose_b = transpose_b

    def forward(self, input):
        a, b = input
        return a @ (b.T if self.transpose_b else b)


class _Mean:
    def __init__(self, axes, keep_dims):
        self.axes = tuple(axes)
        self.keep_dims = keep_dims

    def forward(self, input):
        import jax.numpy as jnp

        return jnp.mean(input, axis=self.axes, keepdims=self.keep_dims)


def load_graphdef(path_or_bytes, inputs: Sequence[str],
                  outputs: Sequence[str], train_consts: bool = False):
    """Load a binary GraphDef file/bytes into a Graph module."""
    if isinstance(path_or_bytes, (str,)):
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    else:
        data = bytes(path_or_bytes)
    return TensorflowLoader(data, inputs, outputs,
                            train_consts=train_consts).load()


# ---------------------------------------------------------------------------
# export: module tree -> GraphDef
# ---------------------------------------------------------------------------

def _attr(key: str, payload: bytes) -> bytes:
    return pw.emit_bytes(5, pw.emit_bytes(1, key.encode())
                         + pw.emit_bytes(2, payload))


def _attr_type(key: str, dt: int) -> bytes:
    return _attr(key, pw.emit_varint(6, dt))


def _attr_s(key: str, s: bytes) -> bytes:
    return _attr(key, pw.emit_bytes(2, s))


def _attr_ints(key: str, ints: Sequence[int]) -> bytes:
    lst = b"".join(pw.emit_varint(3, i) for i in ints)
    return _attr(key, pw.emit_bytes(1, lst))


def _attr_i(key: str, v: int) -> bytes:
    return _attr(key, pw.emit_varint(3, v))


def _tensor_proto(arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    dt = {np.dtype(np.float32): _DT_FLOAT, np.dtype(np.int32): _DT_INT32,
          np.dtype(np.int64): _DT_INT64}[arr.dtype]
    shape = b"".join(pw.emit_bytes(2, pw.emit_varint(1, d))
                     for d in arr.shape)
    return (pw.emit_varint(1, dt) + pw.emit_bytes(2, shape)
            + pw.emit_bytes(4, arr.tobytes()))


def _node_def(name: str, op: str, inputs: Sequence[str],
              attrs: bytes = b"") -> bytes:
    body = pw.emit_bytes(1, name.encode()) + pw.emit_bytes(2, op.encode())
    for i in inputs:
        body += pw.emit_bytes(3, i.encode())
    body += attrs
    return pw.emit_bytes(1, body)


def save_graphdef(model, path: str, input_name: str = "input") -> List[str]:
    """Serialize a module tree to a binary GraphDef; returns output node
    names.  Supported (the reference ``BigDLToTensorflow.scala`` set):
    Sequential chains AND branching structures — Concat /
    ConcatTable+CAddTable/CMulTable/JoinTable (Inception- and
    ResNet-style DAGs) — of Linear, SpatialConvolution (NCHW; explicit
    pads become a Pad node), max/avg pooling, BatchNormalization (both
    variants, exported as the frozen running-stats affine like the
    reference's BatchNorm2DToTF), ReLU/ReLU6/Tanh/Sigmoid,
    SoftMax/LogSoftMax, Reshape/InferReshape/View, Squeeze, Mean,
    SpatialZeroPadding, Dropout (exported as Identity), Identity."""
    import bigdl_tpu.nn as nn

    out = [_node_def(input_name, "Placeholder", [],
                     _attr_type("dtype", _DT_FLOAT))]
    counter = [0]

    def fresh(op):
        counter[0] += 1
        return f"{op.lower()}_{counter[0]}"

    def const(name, arr):
        out.append(_node_def(name, "Const", [],
                             _attr_type("dtype", _DT_FLOAT)
                             + _attr("value", pw.emit_bytes(
                                 8, _tensor_proto(np.asarray(arr,
                                                             np.float32))))))

    def iconst(name, arr):
        out.append(_node_def(name, "Const", [],
                             _attr_type("dtype", _DT_INT32)
                             + _attr("value", pw.emit_bytes(
                                 8, _tensor_proto(np.asarray(arr,
                                                             np.int32))))))

    def concat_v2(name, parts, axis):
        iconst(name + "/axis", axis)
        out.append(_node_def(name, "ConcatV2",
                             list(parts) + [name + "/axis"],
                             _attr_type("T", _DT_FLOAT)
                             + _attr_type("Tidx", _DT_INT32)
                             + _attr_i("N", len(parts))))
        return name

    def pad_node(name, cur, hpair, wpair):
        iconst(name + "/pads",
               [[0, 0], [0, 0], list(hpair), list(wpair)])
        out.append(_node_def(name, "Pad", [cur, name + "/pads"],
                             _attr_type("T", _DT_FLOAT)
                             + _attr_type("Tpaddings", _DT_INT32)))
        return name

    def emit(module, cur: str) -> str:
        if isinstance(module, nn.Sequential):
            for m in module.__dict__["_modules"].values():
                cur = emit(m, cur)
            return cur
        name = fresh(type(module).__name__)
        if isinstance(cur, list) and not isinstance(
                module, (nn.CAddTable, nn.CMulTable, nn.JoinTable)):
            raise NotImplementedError(
                f"table output (ConcatTable upstream) consumed by "
                f"non-table layer {type(module).__name__}")
        if getattr(module, "format", "NCHW") != "NCHW":
            raise NotImplementedError(
                f"{type(module).__name__} export supports NCHW only "
                f"(module format {module.format!r})")
        if isinstance(module, nn.Linear):
            wname, bname = name + "/w", name + "/b"
            const(wname, np.asarray(module._params["weight"]).T)
            out.append(_node_def(name + "/mm", "MatMul", [cur, wname],
                                 _attr_type("T", _DT_FLOAT)))
            cur = name + "/mm"
            if "bias" in module._params:
                const(bname, module._params["bias"])
                out.append(_node_def(name, "BiasAdd", [cur, bname],
                                     _attr_type("T", _DT_FLOAT)))
                cur = name
            return cur
        if isinstance(module, nn.SpatialConvolution):
            if module.n_group != 1:
                raise NotImplementedError("grouped conv export")
            w = np.asarray(module._params["weight"])  # OIHW
            const(name + "/w", w.transpose(2, 3, 1, 0))  # HWIO
            # NCHW input; TF Conv2D with data_format NCHW.  TF knows
            # only SAME/VALID, so explicit pads become a zero Pad node
            # before a VALID conv (exact for convolution)
            if (module.pad_w, module.pad_h) == (-1, -1):
                padding = b"SAME"
            elif module.pad_w < 0 or module.pad_h < 0:
                raise NotImplementedError(
                    "per-axis SAME / negative conv padding export")
            else:
                padding = b"VALID"
                if (module.pad_w, module.pad_h) != (0, 0):
                    cur = pad_node(name + "/pad", cur,
                                   (module.pad_h, module.pad_h),
                                   (module.pad_w, module.pad_w))
            out.append(_node_def(
                name + "/conv", "Conv2D", [cur, name + "/w"],
                _attr_type("T", _DT_FLOAT)
                + _attr_s("padding", padding)
                + _attr_s("data_format", b"NCHW")
                + _attr_ints("strides",
                             [1, 1, module.stride_h, module.stride_w])))
            cur = name + "/conv"
            if "bias" in module._params:
                const(name + "/b", module._params["bias"])
                out.append(_node_def(name, "BiasAdd", [cur, name + "/b"],
                                     _attr_type("T", _DT_FLOAT)
                                     + _attr_s("data_format", b"NCHW")))
                cur = name
            return cur
        if isinstance(module, (nn.SpatialMaxPooling,
                               nn.SpatialAveragePooling)):
            # SpatialAveragePooling SUBCLASSES SpatialMaxPooling — test
            # the derived class, not the base
            is_max = not isinstance(module, nn.SpatialAveragePooling)
            if (module.pad_w, module.pad_h) not in ((0, 0), (-1, -1)) \
                    or module.ceil_mode \
                    or getattr(module, "global_pooling", False):
                raise NotImplementedError(
                    "pooling export supports pad (0, 0) or SAME (-1, -1), "
                    "floor mode, non-global only")
            if not is_max:
                # TF AvgPool divides by the UNPADDED window count; SAME
                # with count_include_pad (the module default) divides by
                # k*k at borders — silently different numbers
                if not module.divide:
                    raise NotImplementedError("sum (divide=False) "
                                              "pooling export")
                if module.pad_w == -1 and module.count_include_pad:
                    raise NotImplementedError(
                        "SAME avg pooling with count_include_pad "
                        "(TF AvgPool excludes padding from the divisor)")
            out.append(_node_def(
                name, "MaxPool" if is_max else "AvgPool", [cur],
                _attr_type("T", _DT_FLOAT)
                + _attr_s("padding", b"SAME" if module.pad_w == -1
                          else b"VALID")
                + _attr_s("data_format", b"NCHW")
                + _attr_ints("ksize", [1, 1, module.kh, module.kw])
                + _attr_ints("strides", [1, 1, module.dh, module.dw])))
            return name
        if isinstance(module, nn.BatchNormalization):
            # frozen running-stats affine, like the reference's
            # BatchNorm2DToTF: y = x * scale + offset with
            # scale = w/sqrt(var+eps), offset = b - mean*scale
            eps = float(module.eps)
            mean = np.asarray(module.running_mean, np.float64)
            var = np.asarray(module.running_var, np.float64)
            scale = 1.0 / np.sqrt(var + eps)
            offset = -mean * scale
            if module.affine:
                w = np.asarray(module.weight, np.float64)
                b = np.asarray(module.bias, np.float64)
                scale, offset = scale * w, offset * w + b
            # (1, -1) for the dense variant: axis-1 broadcast for 2-D
            # inputs, and a SHAPE ERROR (not silently-wrong numbers) if
            # a >2-D input reaches it — the module normalizes axis 1
            # at any rank, which a static const cannot express
            shape = (1, -1, 1, 1) \
                if isinstance(module, nn.SpatialBatchNormalization) \
                else (1, -1)
            const(name + "/scale", scale.reshape(shape))
            const(name + "/offset", offset.reshape(shape))
            out.append(_node_def(name + "/mul", "Mul",
                                 [cur, name + "/scale"],
                                 _attr_type("T", _DT_FLOAT)))
            out.append(_node_def(name, "AddV2",
                                 [name + "/mul", name + "/offset"],
                                 _attr_type("T", _DT_FLOAT)))
            return name
        if isinstance(module, nn.Concat):
            parts = [emit(m, cur)
                     for m in module.__dict__["_modules"].values()]
            return concat_v2(name, parts, int(module.dim))
        if isinstance(module, nn.ConcatTable):
            return [emit(m, cur)
                    for m in module.__dict__["_modules"].values()]
        if isinstance(module, nn.CAddTable):
            if not isinstance(cur, list):
                raise NotImplementedError(
                    "CAddTable export needs a table input "
                    "(ConcatTable upstream)")
            out.append(_node_def(name, "AddN", cur,
                                 _attr_type("T", _DT_FLOAT)
                                 + _attr_i("N", len(cur))))
            return name
        if isinstance(module, nn.CMulTable):
            if not isinstance(cur, list):
                raise NotImplementedError(
                    "CMulTable export needs a table input")
            acc = cur[0]
            for i, other in enumerate(cur[1:]):
                nm = name if i == len(cur) - 2 else f"{name}/mul{i}"
                out.append(_node_def(nm, "Mul", [acc, other],
                                     _attr_type("T", _DT_FLOAT)))
                acc = nm
            return acc
        if isinstance(module, nn.JoinTable):
            if not isinstance(cur, list):
                raise NotImplementedError(
                    "JoinTable export needs a table input")
            if module.n_input_dims:
                raise NotImplementedError(
                    "JoinTable export with n_input_dims (dynamic axis)")
            return concat_v2(name, cur, int(module.dim))
        if isinstance(module, nn.Squeeze):
            if module.num_input_dims:
                raise NotImplementedError(
                    "Squeeze export with num_input_dims (dynamic axis)")
            if module.dim is not None and module.dim < 0:
                raise NotImplementedError(
                    "Squeeze export with a negative dim (the loader "
                    "rejects negative squeeze_dims)")
            dims = [] if module.dim is None else [int(module.dim)]
            out.append(_node_def(name, "Squeeze", [cur],
                                 _attr_type("T", _DT_FLOAT)
                                 + _attr_ints("squeeze_dims", dims)))
            return name
        if isinstance(module, nn.Mean):
            if module.num_input_dims:
                raise NotImplementedError(
                    "Mean export with num_input_dims (dynamic axis)")
            iconst(name + "/axis", [int(module.dim)])
            keep = b"" if module.squeeze else _attr(
                "keep_dims", pw.emit_varint(5, 1))  # AttrValue.b
            out.append(_node_def(name, "Mean", [cur, name + "/axis"],
                                 _attr_type("T", _DT_FLOAT)
                                 + _attr_type("Tidx", _DT_INT32) + keep))
            return name
        if isinstance(module, nn.SpatialZeroPadding):
            if min(module.l, module.r, module.t, module.b) < 0:
                raise NotImplementedError(
                    "negative (cropping) zero-padding export")
            return pad_node(name, cur, (module.t, module.b),
                            (module.l, module.r))
        simple = {nn.ReLU: "Relu", nn.ReLU6: "Relu6", nn.Tanh: "Tanh",
                  nn.Sigmoid: "Sigmoid", nn.SoftMax: "Softmax",
                  nn.LogSoftMax: "LogSoftmax", nn.Identity: "Identity",
                  nn.Dropout: "Identity"}
        for cls, opname in simple.items():
            if type(module) is cls:
                out.append(_node_def(name, opname, [cur],
                                     _attr_type("T", _DT_FLOAT)))
                return name
        if isinstance(module, (nn.Reshape, nn.InferReshape, nn.View)):
            # note: 0 entries use the importer's copy-input-dim semantics
            # (InferReshape), not TF's literal zero-size dimension
            if isinstance(module, nn.InferReshape):
                shape = np.asarray([int(s) for s in module.size], np.int32)
            else:
                sizes = [int(s) for s in getattr(
                    module, "size", getattr(module, "sizes", None))]
                if -1 in sizes:
                    shape = np.asarray(sizes, np.int32)
                else:
                    shape = np.asarray([-1] + [s for s in sizes if s != 0],
                                       np.int32)
            cname = name + "/shape"
            out.append(_node_def(cname, "Const", [],
                                 _attr_type("dtype", _DT_INT32)
                                 + _attr("value", pw.emit_bytes(
                                     8, _tensor_proto(shape)))))
            out.append(_node_def(name, "Reshape", [cur, cname],
                                 _attr_type("T", _DT_FLOAT)))
            return name
        raise NotImplementedError(
            f"save_graphdef: unsupported layer {type(module).__name__}")

    final = emit(model, input_name)

    def flat(o):
        # a model ending in ConcatTable has several outputs
        return [n for e in o for n in flat(e)] if isinstance(o, list) \
            else [o]

    with open(path, "wb") as f:
        f.write(b"".join(out))
    return flat(final)
