"""PyTorch interop: import/export module trees with weights.

The reference's external-format interop is Torch7 (.t7 load/save,
``utils/TorchFile.scala:67``) and Caffe (``utils/caffe/``); the living
equivalent of "load a Torch model" is a ``torch.nn`` module.
``from_torch`` converts a torch module tree (on CPU) into the
corresponding bigdl_tpu modules with weights copied; ``to_torch`` goes
the other way.  Both are host-side, used for parity testing (oracle
comparisons against torch forward passes) and model migration.
"""

from __future__ import annotations

from typing import Any

import numpy as np

__all__ = ["from_torch", "to_torch"]


def _np(t) -> np.ndarray:
    return t.detach().cpu().numpy()


def from_torch(tmod) -> Any:
    """Convert a ``torch.nn`` module (tree) to bigdl_tpu modules."""
    import torch.nn as tnn

    import bigdl_tpu.nn as nn

    if isinstance(tmod, tnn.Sequential):
        out = nn.Sequential()
        for child in tmod:
            out.add(from_torch(child))
        return out
    if isinstance(tmod, tnn.Linear):
        m = nn.Linear(tmod.in_features, tmod.out_features,
                      with_bias=tmod.bias is not None)
        m.weight = _np(tmod.weight)
        if tmod.bias is not None:
            m.bias = _np(tmod.bias)
        return m
    if isinstance(tmod, tnn.Conv2d):
        if tmod.padding_mode != "zeros":
            raise NotImplementedError(
                f"from_torch: Conv2d padding_mode={tmod.padding_mode!r} "
                "is unsupported (zeros only)")
        if isinstance(tmod.padding, str):
            # torch 'same'/'valid' -> SAME (-1) / 0 per the conv layers'
            # TF-style pad convention
            pad_w = pad_h = {"same": -1, "valid": 0}[tmod.padding]
        else:
            pad_w, pad_h = tmod.padding[1], tmod.padding[0]
        if tmod.dilation != (1, 1):
            if tmod.groups != 1:
                raise NotImplementedError(
                    "from_torch: dilated grouped Conv2d is unsupported")
            m = nn.SpatialDilatedConvolution(
                tmod.in_channels, tmod.out_channels,
                tmod.kernel_size[1], tmod.kernel_size[0],
                tmod.stride[1], tmod.stride[0],
                pad_w, pad_h,
                tmod.dilation[1], tmod.dilation[0])
            if tmod.bias is None:
                m.bias = np.zeros((tmod.out_channels,), np.float32)
        else:
            m = nn.SpatialConvolution(
                tmod.in_channels, tmod.out_channels,
                tmod.kernel_size[1], tmod.kernel_size[0],
                tmod.stride[1], tmod.stride[0],
                pad_w, pad_h,
                n_group=tmod.groups,
                with_bias=tmod.bias is not None)
        m.weight = _np(tmod.weight)  # both OIHW
        if tmod.bias is not None:
            m.bias = _np(tmod.bias)
        return m
    if isinstance(tmod, tnn.ConvTranspose2d):
        if tmod.dilation not in (1, (1, 1)):
            raise NotImplementedError(
                "from_torch: dilated ConvTranspose2d is unsupported")
        if tmod.groups != 1:
            raise NotImplementedError(
                "from_torch: grouped ConvTranspose2d is unsupported")
        m = nn.SpatialFullConvolution(
            tmod.in_channels, tmod.out_channels,
            tmod.kernel_size[1], tmod.kernel_size[0],
            tmod.stride[1], tmod.stride[0],
            tmod.padding[1], tmod.padding[0],
            tmod.output_padding[1], tmod.output_padding[0],
            no_bias=tmod.bias is None)
        m.weight = _np(tmod.weight)
        if tmod.bias is not None:
            m.bias = _np(tmod.bias)
        return m
    if isinstance(tmod, (tnn.BatchNorm1d, tnn.BatchNorm2d)):
        cls = (nn.SpatialBatchNormalization
               if isinstance(tmod, tnn.BatchNorm2d) else nn.BatchNormalization)
        m = cls(tmod.num_features, eps=tmod.eps, momentum=tmod.momentum,
                affine=tmod.affine)
        if tmod.affine:
            m.weight = _np(tmod.weight)
            m.bias = _np(tmod.bias)
        m.running_mean = _np(tmod.running_mean)
        m.running_var = _np(tmod.running_var)
        return m
    if isinstance(tmod, tnn.LayerNorm):
        if len(tmod.normalized_shape) != 1:
            raise NotImplementedError(
                "from_torch: LayerNorm over multiple trailing dims is "
                "unsupported (last-dim only)")
        m = nn.LayerNorm(tmod.normalized_shape[-1], eps=tmod.eps,
                         affine=tmod.elementwise_affine)
        if tmod.elementwise_affine:
            m.weight = _np(tmod.weight)
            m.bias = _np(tmod.bias)
        return m
    if isinstance(tmod, tnn.MaxPool2d):
        if tmod.dilation not in (1, (1, 1)):
            raise NotImplementedError(
                "from_torch: dilated MaxPool2d is unsupported")
        k = tmod.kernel_size if isinstance(tmod.kernel_size, tuple) \
            else (tmod.kernel_size,) * 2
        s = tmod.stride if isinstance(tmod.stride, tuple) \
            else (tmod.stride,) * 2
        p = tmod.padding if isinstance(tmod.padding, tuple) \
            else (tmod.padding,) * 2
        m = nn.SpatialMaxPooling(k[1], k[0], s[1], s[0], p[1], p[0])
        if tmod.ceil_mode:
            m.ceil()
        return m
    if isinstance(tmod, tnn.AvgPool2d):
        if tmod.divisor_override is not None:
            raise NotImplementedError(
                "from_torch: AvgPool2d divisor_override is unsupported")
        k = tmod.kernel_size if isinstance(tmod.kernel_size, tuple) \
            else (tmod.kernel_size,) * 2
        s = tmod.stride if isinstance(tmod.stride, tuple) \
            else (tmod.stride,) * 2
        p = tmod.padding if isinstance(tmod.padding, tuple) \
            else (tmod.padding,) * 2
        m = nn.SpatialAveragePooling(
            k[1], k[0], s[1], s[0], p[1], p[0],
            count_include_pad=tmod.count_include_pad)
        if tmod.ceil_mode:
            m.ceil()
        return m
    if isinstance(tmod, tnn.Embedding):
        m = nn.LookupTable(tmod.num_embeddings, tmod.embedding_dim)
        m.weight = _np(tmod.weight)
        return m
    if isinstance(tmod, tnn.Dropout):
        return nn.Dropout(tmod.p)
    if isinstance(tmod, tnn.Flatten):
        return nn.InferReshape([0, -1])  # keep batch, flatten the rest
    if isinstance(tmod, tnn.ReLU):
        return nn.ReLU()
    if isinstance(tmod, tnn.ReLU6):
        return nn.ReLU6()
    if isinstance(tmod, tnn.LeakyReLU):
        return nn.LeakyReLU(tmod.negative_slope)
    if isinstance(tmod, tnn.PReLU):
        m = nn.PReLU(tmod.num_parameters if tmod.num_parameters > 1 else 0)
        m.weight = _np(tmod.weight)
        return m
    if isinstance(tmod, tnn.ELU):
        return nn.ELU(tmod.alpha)
    if isinstance(tmod, tnn.Sigmoid):
        return nn.Sigmoid()
    if isinstance(tmod, tnn.Tanh):
        return nn.Tanh()
    if isinstance(tmod, tnn.Softmax):
        if tmod.dim is None:
            raise NotImplementedError(
                "from_torch: Softmax without an explicit dim is unsupported")
        return nn.SoftMax(axis=tmod.dim)
    if isinstance(tmod, tnn.LogSoftmax):
        if tmod.dim is None:
            raise NotImplementedError(
                "from_torch: LogSoftmax without an explicit dim is "
                "unsupported")
        return nn.LogSoftMax(axis=tmod.dim)
    if isinstance(tmod, tnn.Identity):
        return nn.Identity()
    raise NotImplementedError(
        f"from_torch: no converter for {type(tmod).__name__}")


def to_torch(module) -> Any:
    """Convert a bigdl_tpu module (tree) to ``torch.nn`` modules."""
    import torch
    import torch.nn as tnn

    import bigdl_tpu.nn as nn

    def tensor(a):
        return torch.from_numpy(np.asarray(a).copy())

    if isinstance(module, nn.Sequential):
        return tnn.Sequential(*[to_torch(m)
                                for m in module.__dict__["_modules"].values()])
    if isinstance(module, nn.Linear):
        t = tnn.Linear(module.input_size, module.output_size,
                       bias=module.with_bias)
        with torch.no_grad():
            t.weight.copy_(tensor(module._params["weight"]))
            if module.with_bias:
                t.bias.copy_(tensor(module._params["bias"]))
        return t
    if isinstance(module, nn.SpatialConvolution):
        t = tnn.Conv2d(module.n_input_plane, module.n_output_plane,
                       (module.kernel_h, module.kernel_w),
                       (module.stride_h, module.stride_w),
                       (module.pad_h, module.pad_w),
                       groups=module.n_group,
                       bias="bias" in module._params)
        with torch.no_grad():
            t.weight.copy_(tensor(module._params["weight"]))
            if "bias" in module._params:
                t.bias.copy_(tensor(module._params["bias"]))
        return t
    if isinstance(module, nn.SpatialBatchNormalization):
        t = tnn.BatchNorm2d(module.n_output, eps=module.eps,
                            momentum=module.momentum, affine=module.affine)
        with torch.no_grad():
            if module.affine:
                t.weight.copy_(tensor(module._params["weight"]))
                t.bias.copy_(tensor(module._params["bias"]))
            t.running_mean.copy_(tensor(module._buffers["running_mean"]))
            t.running_var.copy_(tensor(module._buffers["running_var"]))
        return t
    if isinstance(module, nn.BatchNormalization):
        t = tnn.BatchNorm1d(module.n_output, eps=module.eps,
                            momentum=module.momentum, affine=module.affine)
        with torch.no_grad():
            if module.affine:
                t.weight.copy_(tensor(module._params["weight"]))
                t.bias.copy_(tensor(module._params["bias"]))
            t.running_mean.copy_(tensor(module._buffers["running_mean"]))
            t.running_var.copy_(tensor(module._buffers["running_var"]))
        return t
    if isinstance(module, nn.SpatialMaxPooling):
        return tnn.MaxPool2d((module.kh, module.kw), (module.dh, module.dw),
                             (module.pad_h, module.pad_w),
                             ceil_mode=module.ceil_mode)
    if isinstance(module, nn.SpatialAveragePooling):
        if module.global_pooling or not module.divide:
            raise NotImplementedError(
                "to_torch: global_pooling / divide=False AvgPooling has no "
                "AvgPool2d equivalent (use AdaptiveAvgPool2d manually)")
        return tnn.AvgPool2d((module.kh, module.kw), (module.dh, module.dw),
                             (module.pad_h, module.pad_w),
                             ceil_mode=module.ceil_mode,
                             count_include_pad=module.count_include_pad)
    if isinstance(module, nn.LookupTable):
        t = tnn.Embedding(module.n_index, module.n_output)
        with torch.no_grad():
            t.weight.copy_(tensor(module._params["weight"]))
        return t
    if isinstance(module, nn.Dropout):
        return tnn.Dropout(module.p)
    if isinstance(module, nn.ReLU):
        return tnn.ReLU()
    if isinstance(module, nn.Tanh):
        return tnn.Tanh()
    if isinstance(module, nn.Sigmoid):
        return tnn.Sigmoid()
    if isinstance(module, (nn.SoftMax, nn.LogSoftMax)):
        # axis=None means "dim 1 for ndim>=2, dim 0 for 1-D" on our side;
        # torch needs one static dim, so export the ndim>=2 meaning (dim=1)
        # and keep explicit axes verbatim.
        dim = module.axis if module.axis is not None else 1
        return (tnn.Softmax(dim=dim) if isinstance(module, nn.SoftMax)
                else tnn.LogSoftmax(dim=dim))
    if isinstance(module, nn.Identity):
        return tnn.Identity()
    if isinstance(module, nn.InferReshape) and module.size == (0, -1):
        return tnn.Flatten()
    raise NotImplementedError(
        f"to_torch: no converter for {type(module).__name__}")
