"""Log management — the reference's ``LoggerFilter``
(``utils/LoggerFilter.scala:33-134``) rebuilt on :mod:`logging`.

The reference's problem: Spark/Akka/Breeze INFO spam drowns the training
progress lines, so ``redirectSparkInfoLogs`` sends third-party INFO to a
file (default ``$PWD/bigdl.log``), keeps third-party console output at
ERROR, and leaves framework logs on the console.  The TPU-native noise
sources are different (jax/absl compile chatter, TensorFlow import
banners, fsspec/urllib3 wire logs) but the operability contract is the
same:

1. ``redirect_thirdparty_logs()`` — everything still lands in the log
   file; the console only shows third-party ERRORs and framework INFO.
2. ``BIGDL_LOGGER_DISABLE=true`` disables redirection entirely
   (``bigdl.utils.LoggerFilter.disable``).
3. ``BIGDL_LOG_FILE`` overrides the file path
   (``bigdl.utils.LoggerFilter.logFile``).
4. ``BIGDL_LOG_THIRDPARTY=false`` keeps third-party records out of the
   file too (``bigdl.utils.LoggerFilter.enableSparkLog``).
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional, Sequence

from bigdl_tpu.utils.config import get_config

__all__ = ["redirect_thirdparty_logs", "undo_redirect", "FRAMEWORK_LOGGER",
           "NOISY_LOGGERS"]

FRAMEWORK_LOGGER = "bigdl_tpu"

# the tpu-stack analogue of the reference's List("org", "akka", "breeze")
NOISY_LOGGERS = ("jax", "jaxlib", "absl", "tensorflow", "orbax", "flax",
                 "fsspec", "urllib3", "etils")

_PATTERN = "%(asctime)s %(levelname)-5s %(name)s:%(lineno)d - %(message)s"
_DATEFMT = "%Y-%m-%d %H:%M:%S"

# handlers we installed, so redirect is idempotent and undoable
_installed: List[tuple] = []
_saved_levels: List[tuple] = []
_removed_child: List[tuple] = []  # child-logger handlers lifted during redirect


def _formatter() -> logging.Formatter:
    return logging.Formatter(_PATTERN, _DATEFMT)


def _file_handler(path: str, level=logging.INFO) -> logging.FileHandler:
    # delay=True: don't create the file until a record actually lands
    h = logging.FileHandler(path, mode="a", encoding="utf-8", delay=True)
    h.setLevel(level)
    h.setFormatter(_formatter())
    h.set_name("bigdl_file")
    return h


def _console_handler(level=logging.INFO) -> logging.StreamHandler:
    import sys

    h = logging.StreamHandler(sys.stdout)
    h.setLevel(level)
    h.setFormatter(_formatter())
    h.set_name("bigdl_console")
    return h


def redirect_thirdparty_logs(log_path: Optional[str] = None,
                             noisy: Sequence[str] = NOISY_LOGGERS) -> Optional[str]:
    """Route noisy third-party INFO to a file, keep the console clean.

    Mirrors ``LoggerFilter.redirectSparkInfoLogs`` (``LoggerFilter.scala:91``):

    - each noisy logger gets a console handler at ERROR and (when
      ``log_thirdparty``) a file handler at INFO, with propagation cut
      (the reference's ``setAdditivity(false)``);
    - the framework logger keeps console INFO and also writes the file;
    - idempotent — calling twice replaces, not duplicates, handlers.

    Returns the log-file path, or ``None`` when disabled.
    """
    cfg = get_config()
    if cfg.log_disable:
        return None
    path = cfg.log_file or log_path or os.path.join(os.getcwd(), "bigdl.log")
    if os.path.isdir(path):
        logging.getLogger(FRAMEWORK_LOGGER).error(
            "%s exists and is a directory; can't redirect to it", path)
        return None
    undo_redirect()

    file_h = _file_handler(path)  # ONE shared fd for every logger
    for name in noisy:
        lg = logging.getLogger(name)
        console = _console_handler(logging.ERROR)
        lg.addHandler(console)
        _installed.append((lg, console, lg.propagate))
        if cfg.log_thirdparty:
            lg.addHandler(file_h)
            _installed.append((lg, file_h, lg.propagate))
        lg.propagate = False
        # a NOTSET noisy logger would inherit root's WARNING and drop the
        # INFO records before the file handler sees them
        _saved_levels.append((lg, lg.level))
        if lg.level == logging.NOTSET or lg.level > logging.INFO:
            lg.setLevel(logging.INFO)

    fw = logging.getLogger(FRAMEWORK_LOGGER)
    for h in (_console_handler(logging.INFO), file_h):
        fw.addHandler(h)
        _installed.append((fw, h, fw.propagate))
    fw.propagate = False
    # child framework loggers (e.g. bigdl_tpu.optim) install a fallback
    # StreamHandler when imported before this redirect; records would now
    # be emitted twice (child handler + propagate to fw's console) — lift
    # the child handlers for the redirect's lifetime
    for name, lg in list(logging.root.manager.loggerDict.items()):
        if (isinstance(lg, logging.Logger)
                and name.startswith(FRAMEWORK_LOGGER + ".")):
            for h in list(lg.handlers):
                lg.removeHandler(h)
                _removed_child.append((lg, h))
    _saved_levels.append((fw, fw.level))
    if fw.level == logging.NOTSET:
        fw.setLevel(logging.INFO)

    # everything else still reaches the file through the root logger
    root = logging.getLogger()
    root.addHandler(file_h)
    _installed.append((root, file_h, root.propagate))
    return path


def undo_redirect() -> None:
    """Remove every handler :func:`redirect_thirdparty_logs` installed and
    restore propagation (tests / embedding apps)."""
    seen_propagate = {}
    for lg, h, propagate in _installed:
        lg.removeHandler(h)
        try:
            h.close()
        except Exception:
            pass
        seen_propagate.setdefault(id(lg), (lg, propagate))
    for lg, propagate in seen_propagate.values():
        lg.propagate = propagate
    for lg, level in _saved_levels:
        lg.setLevel(level)
    for lg, h in _removed_child:
        lg.addHandler(h)
    _installed.clear()
    _saved_levels.clear()
    _removed_child.clear()
