"""Caffe model import: prototxt structure + caffemodel weights
(``utils/caffe/CaffeLoader.scala:56``, ``Converter.scala``,
``LayerConverter.scala``/``V1LayerConverter.scala`` — SURVEY §2.9).
The save direction lives in ``bigdl_tpu.utils.caffe_persister``
(``CaffePersister.scala:47``); the two round-trip.

Two pieces, neither needing a protobuf runtime:

- ``parse_prototxt``: a parser for protobuf *text* format (the grammar
  prototxt uses: ``key: value`` scalars and ``key { ... }`` nested
  messages, repeated keys collected into lists).
- ``load_caffemodel_blobs``: binary NetParameter decoding via
  ``bigdl_tpu.utils.protowire`` — handles both V2 ``layer`` (field 100)
  and legacy V1 ``layers`` (field 2) with per-layer BlobProtos (shape /
  legacy num-channels-height-width dims, packed float data).

``CaffeLoader.load`` builds a ``Graph`` from the layer DAG (bottom/top
wiring, TRAIN-phase layers skipped) with weights copied by layer name,
covering the converter table: Convolution/Deconvolution, InnerProduct,
Pooling(MAX/AVE), ReLU, TanH, Sigmoid, Softmax(+WithLoss), LRN, Dropout,
Concat, Eltwise(SUM/PROD/MAX), BatchNorm(+Scale), Flatten, Reshape,
Power, AbsVal, Exp, Log.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.utils import protowire as pw

__all__ = ["parse_prototxt", "load_caffemodel_blobs", "CaffeLoader",
           "load_caffe"]


# ---------------------------------------------------------------------------
# prototxt (protobuf text format)
# ---------------------------------------------------------------------------

_TOKEN = re.compile(r"""
    \s*                               # whitespace (comments pre-stripped)
    (?P<tok>
        [A-Za-z_][A-Za-z0-9_]* |      # identifier
        "(?:[^"\\]|\\.)*"        |    # string
        '(?:[^'\\]|\\.)*'        |    # string
        -?[0-9.][0-9.eE+\-]*     |    # number
        [{}:,]                        # punctuation
    )""", re.VERBOSE)


def _tokenize(text: str) -> List[str]:
    text = re.sub(r"#[^\n]*", "", text)  # strip comments up-front
    toks = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m:
            if text[pos:].strip() == "":
                break
            raise ValueError(f"prototxt parse error near {text[pos:pos+40]!r}")
        toks.append(m.group("tok"))
        pos = m.end()
    return toks


def _convert_scalar(tok: str):
    if tok and tok[0] in "\"'":
        return tok[1:-1]
    if tok in ("true", "True"):
        return True
    if tok in ("false", "False"):
        return False
    try:
        return int(tok)
    except ValueError:
        try:
            return float(tok)
        except ValueError:
            return tok  # enum identifier (MAX, AVE, TRAIN, ...)


def _parse_message(toks: List[str], i: int) -> Tuple[Dict, int]:
    msg: Dict = {}

    def put(key, value):
        if key in msg:
            if not isinstance(msg[key], list):
                msg[key] = [msg[key]]
            msg[key].append(value)
        else:
            msg[key] = value

    while i < len(toks) and toks[i] != "}":
        key = toks[i]
        i += 1
        if i < len(toks) and toks[i] == ":":
            i += 1
            if toks[i] == "{":
                sub, i = _parse_message(toks, i + 1)
                assert toks[i] == "}"
                put(key, sub)
                i += 1
            else:
                put(key, _convert_scalar(toks[i]))
                i += 1
        elif i < len(toks) and toks[i] == "{":
            sub, i = _parse_message(toks, i + 1)
            assert toks[i] == "}"
            put(key, sub)
            i += 1
        else:
            raise ValueError(f"prototxt parse error at token {key!r}")
        if i < len(toks) and toks[i] == ",":
            i += 1
    return msg, i


def parse_prototxt(text: str) -> Dict:
    """Parse protobuf text format into nested dicts; repeated keys become
    lists."""
    toks = _tokenize(text)
    msg, i = _parse_message(toks, 0)
    return msg


def _as_list(v) -> List:
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


# ---------------------------------------------------------------------------
# caffemodel binary (NetParameter)
# ---------------------------------------------------------------------------

def _parse_blob(buf: bytes) -> np.ndarray:
    shape: List[int] = []
    legacy = {}
    data: List[float] = []
    for f, wt, val in pw.fields(buf):
        if f == 7:  # BlobShape { repeated int64 dim = 1 }
            for f2, wt2, v2 in pw.fields(val):
                if f2 == 1:
                    shape.extend(pw.packed_varints(v2, wt2))
        elif f == 5:  # repeated float data
            data.extend(pw.packed_floats(val, wt))
        elif f in (1, 2, 3, 4):  # legacy num/channels/height/width
            legacy[f] = val
    arr = np.asarray(data, np.float32)
    if not shape and legacy:
        shape = [legacy.get(i, 1) for i in (1, 2, 3, 4)]
        while len(shape) > 1 and shape[0] == 1:
            shape = shape[1:]
    return arr.reshape(shape) if shape else arr


def load_caffemodel_blobs(path: str) -> Dict[str, List[np.ndarray]]:
    """{layer_name: [blob arrays]} from a binary .caffemodel (V1 + V2)."""
    with open(path, "rb") as f:
        buf = f.read()
    blobs: Dict[str, List[np.ndarray]] = {}
    for f_no, wt, val in pw.fields(buf):
        if f_no not in (100, 2):  # layer (V2) / layers (V1)
            continue
        name = None
        layer_blobs: List[np.ndarray] = []
        name_field = 1 if f_no == 100 else 4
        blob_field = 7 if f_no == 100 else 6
        for f2, wt2, v2 in pw.fields(val):
            if f2 == name_field and isinstance(v2, bytes):
                name = v2.decode("utf-8", "replace")
            elif f2 == blob_field:
                layer_blobs.append(_parse_blob(v2))
        if name and layer_blobs:
            blobs[name] = layer_blobs
    return blobs


# ---------------------------------------------------------------------------
# layer conversion
# ---------------------------------------------------------------------------

def _pair(param, key, default=0):
    """Caffe's h/w convention: ``key_h``/``key_w`` override scalar/repeated
    ``key`` (the pair fields for ``kernel_size`` are ``kernel_h/w``)."""
    base = "kernel" if key == "kernel_size" else key
    h = param.get(f"{base}_h")
    w = param.get(f"{base}_w")
    if h is not None or w is not None:
        return int(h or default), int(w or default)
    v = _as_list(param.get(key, default))
    if not v:
        v = [default]
    if len(v) == 1:
        return int(v[0]), int(v[0])
    return int(v[0]), int(v[1])


class CaffeLoader:
    """Build a bigdl_tpu ``Graph`` from prototxt (+ optional caffemodel
    weights), mirroring ``CaffeLoader.scala``'s converter table."""

    def __init__(self, prototxt_path: str,
                 caffemodel_path: Optional[str] = None,
                 customized_converters: Optional[Dict] = None):
        """``customized_converters``: {layer_type: fn(layer_dict,
        in_channels, blobs) -> (module, out_channels)} for layer types
        outside the built-in table (the reference's customizedConverters
        hook, ``CaffeLoader.scala:56``)."""
        with open(prototxt_path) as f:
            self.net = parse_prototxt(f.read())
        self.blobs = (load_caffemodel_blobs(caffemodel_path)
                      if caffemodel_path else {})
        self.customized = dict(customized_converters or {})

    # -- channel inference -------------------------------------------------
    def _input_channels(self) -> Dict[str, int]:
        chans: Dict[str, int] = {}
        names = _as_list(self.net.get("input"))
        if names:
            if "input_shape" in self.net:
                shapes = _as_list(self.net["input_shape"])
                for nm, sh in zip(names, shapes):
                    dims = _as_list(sh.get("dim"))
                    if len(dims) >= 2:
                        chans[nm] = int(dims[1])
            elif "input_dim" in self.net:
                dims = _as_list(self.net["input_dim"])
                for i, nm in enumerate(names):
                    if 4 * i + 1 < len(dims):
                        chans[nm] = int(dims[4 * i + 1])
        for lay in self._layers():
            if lay.get("type") == "Input":
                dims = _as_list(lay.get("input_param", {})
                                .get("shape", {}).get("dim"))
                if len(dims) >= 2:
                    for top in _as_list(lay.get("top")):
                        chans[top] = int(dims[1])
        return chans

    def _input_spatial(self) -> Dict[str, Tuple[int, int]]:
        """(H, W) per declared input blob, when the prototxt gives 4-D
        dims — needed to size an InnerProduct that has no weight blob
        (the emitted Sequential flattens C*H*W)."""
        spatial: Dict[str, Tuple[int, int]] = {}
        names = _as_list(self.net.get("input"))
        if names:
            if "input_shape" in self.net:
                for nm, sh in zip(names, _as_list(self.net["input_shape"])):
                    dims = _as_list(sh.get("dim"))
                    if len(dims) >= 4:
                        spatial[nm] = (int(dims[2]), int(dims[3]))
            elif "input_dim" in self.net:
                dims = _as_list(self.net["input_dim"])
                for i, nm in enumerate(names):
                    if 4 * i + 3 < len(dims):
                        spatial[nm] = (int(dims[4 * i + 2]),
                                       int(dims[4 * i + 3]))
        for lay in self._layers():
            if lay.get("type") == "Input":
                dims = _as_list(lay.get("input_param", {})
                                .get("shape", {}).get("dim"))
                if len(dims) >= 4:
                    for top in _as_list(lay.get("top")):
                        spatial[top] = (int(dims[2]), int(dims[3]))
        return spatial

    def _out_spatial(self, lay: Dict,
                     hw: Optional[Tuple[int, int]]
                     ) -> Optional[Tuple[int, int]]:
        """Propagate (H, W) through one layer; None when unknown."""
        t = str(lay.get("type"))
        if t in ("InnerProduct", "14"):
            return (1, 1)
        if hw is None:
            return None
        if t in ("Convolution", "Deconvolution", "4", "39"):
            p = lay.get("convolution_param", {})
        elif t in ("Pooling", "17"):
            p = lay.get("pooling_param", {})
            if bool(p.get("global_pooling", False)):
                return (1, 1)
        else:
            return hw
        kh, kw = _pair(p, "kernel_size")
        dh, dw = _pair(p, "stride", 1)
        ph, pw_ = _pair(p, "pad", 0)
        if t in ("Deconvolution", "39"):
            return ((hw[0] - 1) * dh - 2 * ph + kh,
                    (hw[1] - 1) * dw - 2 * pw_ + kw)
        if t in ("Pooling", "17"):  # caffe pooling rounds up (ceil mode)
            from bigdl_tpu.nn.layers.pooling import _pool_out_size

            return (_pool_out_size(hw[0], kh, dh, ph, ceil_mode=True),
                    _pool_out_size(hw[1], kw, dw, pw_, ceil_mode=True))
        return ((hw[0] + 2 * ph - kh) // dh + 1,
                (hw[1] + 2 * pw_ - kw) // dw + 1)

    def _layers(self) -> List[Dict]:
        return _as_list(self.net.get("layer")) + _as_list(
            self.net.get("layers"))

    @staticmethod
    def _is_train_only(lay) -> bool:
        for inc in _as_list(lay.get("include")):
            if isinstance(inc, dict) and inc.get("phase") == "TRAIN":
                return True
        return False

    # -- conversion --------------------------------------------------------
    def _convert(self, lay: Dict, in_channels: Optional[int],
                 in_spatial: Optional[Tuple[int, int]] = None):
        """Return a module or None (passthrough/skip)."""
        import bigdl_tpu.nn as nn

        t = str(lay.get("type"))
        name = lay.get("name", "?")
        if t in ("Convolution", "Deconvolution", "4", "39"):
            p = lay.get("convolution_param", {})
            n_out = int(p["num_output"])
            kh, kw = _pair(p, "kernel_size")
            dh, dw = _pair(p, "stride", 1)
            ph, pw_ = _pair(p, "pad", 0)
            groups = int(p.get("group", 1))
            bias = bool(p.get("bias_term", True))
            n_in = in_channels
            if n_in is None:
                w = self.blobs.get(name)
                if w and w[0].ndim == 4:
                    # conv blobs are (out, in/g, kh, kw); deconv (in, out/g, ...)
                    n_in = (w[0].shape[0] if t in ("Deconvolution", "39")
                            else w[0].shape[1] * groups)
            if n_in is None:
                raise ValueError(
                    f"cannot infer input channels for layer {name}")
            if t in ("Deconvolution", "39"):
                m = nn.SpatialFullConvolution(n_in, n_out, kw, kh, dw, dh,
                                              pw_, ph, no_bias=not bias)
            else:
                m = nn.SpatialConvolution(n_in, n_out, kw, kh, dw, dh,
                                          pw_, ph, n_group=groups,
                                          with_bias=bias)
            w = self.blobs.get(name)
            if w:
                m.weight = w[0].reshape(m._params["weight"].shape)
                if bias and len(w) > 1:
                    m.bias = w[1].reshape(-1)
            return m, n_out
        if t in ("InnerProduct", "14"):
            p = lay.get("inner_product_param", {})
            n_out = int(p["num_output"])
            bias = bool(p.get("bias_term", True))
            w = self.blobs.get(name)
            if w:
                weight = w[0].reshape(n_out, -1)
                n_in = weight.shape[1]
            elif in_channels is not None:
                # no weight blob: the Linear follows a C*H*W flatten, so
                # fold the tracked spatial extent into the input size
                n_in = (in_channels * in_spatial[0] * in_spatial[1]
                        if in_spatial is not None else in_channels)
                weight = None
            else:
                raise ValueError(f"cannot infer input size for {name}")
            lin = nn.Linear(n_in, n_out, with_bias=bias)
            if w:
                lin.weight = weight
                if bias and len(w) > 1:
                    lin.bias = w[1].reshape(-1)
            return nn.Sequential(nn.InferReshape([0, -1]), lin), n_out
        if t in ("Pooling", "17"):
            p = lay.get("pooling_param", {})
            kh, kw = _pair(p, "kernel_size")
            dh, dw = _pair(p, "stride", 1)
            ph, pw_ = _pair(p, "pad", 0)
            pool = p.get("pool", "MAX")
            glob = bool(p.get("global_pooling", False))
            # caffe defaults to CEIL output rounding; FLOOR is explicit
            ceil = p.get("round_mode", "CEIL") in ("CEIL", 0)
            if pool in ("MAX", 0):
                m = nn.SpatialMaxPooling(kw or 1, kh or 1, dw, dh, pw_, ph,
                                         global_pooling=glob)
                if ceil:
                    m.ceil()
            else:
                m = nn.SpatialAveragePooling(kw or 1, kh or 1, dw, dh,
                                             pw_, ph,
                                             global_pooling=glob,
                                             ceil_mode=ceil)
            return m, in_channels
        if t in ("ReLU", "18"):
            return nn.ReLU(), in_channels
        if t in ("TanH", "23"):
            return nn.Tanh(), in_channels
        if t in ("Sigmoid", "19"):
            return nn.Sigmoid(), in_channels
        if t in ("Softmax", "20", "SoftmaxWithLoss", "21"):
            return nn.SoftMax(), in_channels
        if t in ("LRN", "15"):
            p = lay.get("lrn_param", {})
            return nn.SpatialCrossMapLRN(
                int(p.get("local_size", 5)), float(p.get("alpha", 1.0)),
                float(p.get("beta", 0.75)), float(p.get("k", 1.0))), \
                in_channels
        if t in ("Dropout", "6"):
            p = lay.get("dropout_param", {})
            return nn.Dropout(float(p.get("dropout_ratio", 0.5))), \
                in_channels
        if t == "Concat":
            axis = int(lay.get("concat_param", {}).get(
                "axis", lay.get("concat_dim", 1)))
            return ("concat", axis), None
        if t == "Eltwise":
            p = lay.get("eltwise_param", {})
            op = p.get("operation", "SUM")
            coeff = [float(c) for c in _as_list(p.get("coeff"))]
            if op in ("SUM", 1) and coeff and coeff != [1.0] * len(coeff):
                if coeff == [1.0, -1.0]:
                    return "sub", in_channels
                raise NotImplementedError(
                    f"Eltwise SUM with coeff {coeff} is unsupported "
                    "(only all-ones or [1, -1])")
            return {"SUM": "add", 1: "add", "PROD": "mul", 0: "mul",
                    "MAX": "max", 2: "max"}[op], in_channels
        if t == "BatchNorm":
            w = self.blobs.get(name)
            n = w[0].size if w else in_channels
            eps = float(lay.get("batch_norm_param", {}).get("eps", 1e-5))
            m = nn.SpatialBatchNormalization(n, eps, affine=False)
            if w:
                scale = 1.0 / w[2].reshape(-1)[0] if len(w) > 2 and \
                    w[2].reshape(-1)[0] != 0 else 1.0
                m.running_mean = w[0].reshape(-1) * scale
                m.running_var = w[1].reshape(-1) * scale
            m.evaluate()
            return m, in_channels
        if t == "Scale":
            w = self.blobs.get(name)
            n = w[0].size if w else (in_channels or 1)
            m = nn.CMul((1, n, 1, 1))
            if w:
                m.weight = w[0].reshape(1, n, 1, 1)
            if w and len(w) > 1:
                m = nn.Sequential(m, _make_cadd(n, w[1]))
            return m, in_channels
        if t == "Flatten":
            return nn.InferReshape([0, -1]), in_channels
        if t == "Reshape":
            dims = _as_list(lay.get("reshape_param", {})
                            .get("shape", {}).get("dim"))
            return nn.InferReshape([int(d) for d in dims]), None
        if t == "Power":
            p = lay.get("power_param", {})
            return nn.Power(float(p.get("power", 1.0)),
                            float(p.get("scale", 1.0)),
                            float(p.get("shift", 0.0))), in_channels
        if t == "AbsVal":
            return nn.Abs(), in_channels
        if t == "Exp":
            return nn.Exp(), in_channels
        if t == "Log":
            return nn.Log(), in_channels
        if t in ("Input", "Data", "5", "12", "Accuracy", "Silence"):
            return None, in_channels
        if t in self.customized:
            return self.customized[t](lay, in_channels,
                                      self.blobs.get(name))
        raise NotImplementedError(
            f"CaffeLoader: unsupported layer type {t!r} (layer {name!r})")

    def load(self):
        """Build the Graph.  Returns (model, input_names, output_names)."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.nn.graph import Node, node_from_module

        chans = self._input_channels()
        produced: Dict[str, Node] = {}
        channels: Dict[str, Optional[int]] = dict(chans)
        spatial: Dict[str, Optional[Tuple[int, int]]] = dict(
            self._input_spatial())
        inputs: Dict[str, Node] = {}

        def blob_node(bname: str) -> Node:
            if bname not in produced:
                node = nn.Input(name=bname)
                produced[bname] = node
                inputs[bname] = node
            return produced[bname]

        consumed = set()
        for lay in self._layers():
            if self._is_train_only(lay):
                continue
            bottoms = _as_list(lay.get("bottom"))
            tops = _as_list(lay.get("top"))
            name = lay.get("name", tops[0] if tops else "?")
            in_ch = channels.get(bottoms[0]) if bottoms else None
            in_hw = spatial.get(bottoms[0]) if bottoms else None
            mod, out_ch = self._convert(lay, in_ch, in_hw)
            out_hw = self._out_spatial(lay, in_hw)
            if mod is None:  # data/input/accuracy layer
                for tpn in tops:
                    if tpn in chans or not bottoms:
                        blob_node(tpn)
                continue
            consumed.update(bottoms)
            if isinstance(mod, (str, tuple)):  # concat/eltwise fan-in
                srcs = [blob_node(b) for b in bottoms]
                if isinstance(mod, tuple):  # ("concat", axis)
                    join = nn.JoinTable(mod[1], 0)
                    out_ch = (sum(channels.get(b) or 0 for b in bottoms)
                              or None) if mod[1] == 1 \
                        else channels.get(bottoms[0])
                else:
                    join = {"add": nn.CAddTable(),
                            "sub": nn.CSubTable(),
                            "mul": nn.CMulTable(),
                            "max": nn.CMaxTable()}[mod]
                    out_ch = channels.get(bottoms[0])
                join.set_name(name)
                node = node_from_module(join, srcs)
            else:
                mod.set_name(name)
                node = node_from_module(mod, [blob_node(b) for b in bottoms])
            for tpn in tops:
                produced[tpn] = node
                channels[tpn] = out_ch
                spatial[tpn] = out_hw

        outputs = [produced[b] for b in produced
                   if b not in consumed and produced[b] not in
                   inputs.values()]
        model = nn.Graph(list(inputs.values()), outputs)
        return model, list(inputs.keys()), \
            [b for b in produced if b not in consumed
             and produced[b] not in inputs.values()]


def _make_cadd(n: int, bias: np.ndarray):
    import bigdl_tpu.nn as nn

    m = nn.CAdd((1, n, 1, 1))
    m.bias = bias.reshape(1, n, 1, 1)
    return m


def load_caffe(prototxt_path: str, caffemodel_path: Optional[str] = None):
    """Load a Caffe model; returns the bigdl_tpu Graph module."""
    model, _, _ = CaffeLoader(prototxt_path, caffemodel_path).load()
    return model
