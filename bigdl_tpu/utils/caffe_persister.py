"""Caffe model export — the save side of the Caffe interop
(``utils/caffe/CaffePersister.scala:47`` + the save-direction emitters in
``Converter.scala``/``LayerConverter.scala``, SURVEY §2.9).

Emits the two Caffe artifacts:

- **prototxt** (NetParameter text format): the layer DAG with typed
  parameter blocks, written by a small inverse of
  ``bigdl_tpu.utils.caffe.parse_prototxt``.
- **caffemodel** (binary NetParameter via ``protowire``): per-layer
  name/type/bottom/top plus weight BlobProtos (V2 ``layer`` field 100,
  BlobShape + packed float data).  Structure parameters live in the
  prototxt — like the reference, loading pairs the two files.

Round-trips with ``bigdl_tpu.utils.caffe.CaffeLoader``: the emitter table
below is the inverse of the loader's converter table, so
save → load → forward is identity for every supported layer type.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from bigdl_tpu.utils import protowire as pw

__all__ = ["CaffePersister", "save_caffe"]


class _Enum(str):
    """Marker: render without quotes in prototxt (enum identifier)."""


def _fmt_scalar(v) -> str:
    if isinstance(v, _Enum):
        return str(v)
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return f'"{v}"'
    if isinstance(v, float):
        # repr keeps round-trip precision; prototxt accepts it
        return repr(v)
    return str(v)


def to_prototxt(msg: Dict, indent: int = 0) -> str:
    """Inverse of ``caffe.parse_prototxt``: nested dicts to text format."""
    pad = "  " * indent
    out = []
    for key, value in msg.items():
        for v in (value if isinstance(value, list) else [value]):
            if isinstance(v, dict):
                out.append(f"{pad}{key} {{")
                out.append(to_prototxt(v, indent + 1))
                out.append(f"{pad}}}")
            else:
                out.append(f"{pad}{key}: {_fmt_scalar(v)}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# binary NetParameter
# ---------------------------------------------------------------------------

def _blob_proto(arr: np.ndarray) -> bytes:
    a = np.ascontiguousarray(np.asarray(arr), dtype=np.float32)
    dims = b"".join(pw.write_varint(int(d)) for d in a.shape)
    shape = pw.emit_bytes(1, dims)                      # BlobShape.dim packed
    data = pw.emit_bytes(5, struct.pack(f"<{a.size}f", *a.ravel().tolist()))
    return pw.emit_bytes(7, shape) + data               # BlobProto.shape


def _layer_param(name: str, type_: str, bottoms: Sequence[str],
                 tops: Sequence[str], blobs: Sequence[np.ndarray]) -> bytes:
    payload = pw.emit_bytes(1, name.encode())
    payload += pw.emit_bytes(2, type_.encode())
    for b in bottoms:
        payload += pw.emit_bytes(3, b.encode())
    for t in tops:
        payload += pw.emit_bytes(4, t.encode())
    for blob in blobs:
        payload += pw.emit_bytes(7, _blob_proto(blob))
    return payload


# ---------------------------------------------------------------------------
# module -> layer emission
# ---------------------------------------------------------------------------

class CaffePersister:
    """Walk a module tree (Sequential chain, Concat branches, or a Graph
    DAG as built by CaffeLoader) and persist it as prototxt + caffemodel.

    ``input_shapes``: {blob_name: (N, C, H, W)} (or one tuple for the
    single-input case) — emitted as Caffe ``Input`` layers so the loader
    can re-infer channel counts without weight blobs.
    ``customized_emitters``: {ModuleClass: fn(module, name, bottoms,
    persister) -> top_name} to extend the table (the save-side mirror of
    the loader's customizedConverters hook)."""

    def __init__(self, model, input_shapes=None, net_name: str = "bigdl_tpu",
                 customized_emitters: Optional[Dict] = None):
        self.model = model
        self.net_name = net_name
        self.layers: List[Dict] = []   # prototxt layer dicts
        self.blobs: Dict[str, List[np.ndarray]] = {}
        self.customized = dict(customized_emitters or {})
        self._counter = 0
        self._taken = self._user_names(model, set())
        if input_shapes is None:
            self.input_shapes = {}
        elif isinstance(input_shapes, dict):
            self.input_shapes = dict(input_shapes)
        else:
            self.input_shapes = {"data": tuple(input_shapes)}

    # -- plumbing ----------------------------------------------------------
    def _user_names(self, module, out: set) -> set:
        """Every user-set ``_name`` reachable from ``module`` (container
        children and graph nodes) — minted names must dodge ALL of them,
        including ones the emit walk has not reached yet."""
        nm = getattr(module, "_name", None)
        if nm:
            out.add(nm)
        for sub in getattr(module, "_modules", {}).values():
            if sub is not None:
                self._user_names(sub, out)
        for node in (getattr(module, "_sorted", None) or []):
            el = getattr(node, "element", None)
            if el is not None:
                self._user_names(el, out)
        return out

    def _fresh(self, hint: str) -> str:
        while True:
            self._counter += 1
            name = f"{hint}{self._counter}"
            if name not in self._taken:
                self._taken.add(name)
                return name

    def _name_of(self, module, hint: str) -> str:
        # only a user-set name is stable enough to persist: get_name()'s
        # fallback derives from id() mod 1e5, so two unnamed modules can
        # collide and silently shadow each other's prototxt layer + blobs
        # (wrong channel wiring on reload) — auto names regenerate fresh
        name = getattr(module, "_name", None)
        if name:
            return name
        return self._fresh(hint)

    def _add(self, name: str, type_: str, bottoms: Sequence[str],
             top: str, params: Optional[Dict] = None,
             blobs: Optional[List[np.ndarray]] = None) -> str:
        layer = {"name": name, "type": type_,
                 "bottom": list(bottoms), "top": top}
        if params:
            layer.update(params)
        self.layers.append(layer)
        if blobs:
            self.blobs[name] = [np.asarray(b, np.float32) for b in blobs]
        return top

    # -- emitters ----------------------------------------------------------
    def _emit(self, module, bottoms: List[str]) -> str:
        """Emit ``module`` fed by blob names ``bottoms``; return its top."""
        import bigdl_tpu.nn as nn

        m = module
        for cls, fn in self.customized.items():
            if isinstance(m, cls):
                return fn(m, self._name_of(m, "custom"), bottoms, self)

        # ---- containers -------------------------------------------------
        if isinstance(m, nn.Graph):
            return self._emit_graph(m, bottoms)
        if isinstance(m, nn.Sequential):
            fused = self._fused_sequential(m, bottoms)
            if fused is not None:
                return fused
            top = bottoms
            for child in m.layers:
                top = [self._emit(child, top)]
            return top[0]
        if isinstance(m, nn.Concat):
            name = self._name_of(m, "concat")
            tops = [self._emit(child, bottoms) for child in m.layers]
            return self._add(name, "Concat", tops, name,
                             {"concat_param": {"axis": int(m.dim)}})

        # ---- weighted layers --------------------------------------------
        if isinstance(m, nn.SpatialFullConvolution):
            name = self._name_of(m, "deconv")
            p = {"num_output": int(m.n_output_plane),
                 "kernel_h": int(m.kh), "kernel_w": int(m.kw),
                 "stride_h": int(m.dh), "stride_w": int(m.dw),
                 "pad_h": int(m.pad_h), "pad_w": int(m.pad_w)}
            if m.n_group != 1:
                p["group"] = int(m.n_group)
            if not m.with_bias:
                p["bias_term"] = False
            blobs = [np.asarray(m.weight)]
            if m.with_bias:
                blobs.append(np.asarray(m.bias))
            return self._add(name, "Deconvolution", bottoms, name,
                             {"convolution_param": p}, blobs)
        if isinstance(m, nn.SpatialConvolution):
            name = self._name_of(m, "conv")
            p = {"num_output": int(m.n_output_plane),
                 "kernel_h": int(m.kernel_h), "kernel_w": int(m.kernel_w),
                 "stride_h": int(m.stride_h), "stride_w": int(m.stride_w),
                 "pad_h": int(m.pad_h), "pad_w": int(m.pad_w)}
            if m.n_group != 1:
                p["group"] = int(m.n_group)
            if not m.with_bias:
                p["bias_term"] = False
            blobs = [np.asarray(m.weight)]
            if m.with_bias:
                blobs.append(np.asarray(m.bias))
            return self._add(name, "Convolution", bottoms, name,
                             {"convolution_param": p}, blobs)
        if isinstance(m, nn.Linear):
            name = self._name_of(m, "fc")
            p = {"num_output": int(m.weight.shape[0])}
            blobs = [np.asarray(m.weight)]
            if getattr(m, "with_bias", True) and "bias" in m.__dict__["_params"]:
                blobs.append(np.asarray(m.bias))
            else:
                p["bias_term"] = False
            return self._add(name, "InnerProduct", bottoms, name,
                             {"inner_product_param": p}, blobs)
        if isinstance(m, nn.BatchNormalization):
            # ONE branch for both variants (SpatialBatchNormalization is
            # a subclass with identical math): caffe's BatchNorm
            # normalizes axis 1 of ANY blob shape, so the same
            # BatchNorm(+Scale) pair serves (N,C) and (N,C,H,W)
            name = self._name_of(m, "bn")
            top = self._add(
                name, "BatchNorm", bottoms, name,
                {"batch_norm_param": {"use_global_stats": True,
                                      "eps": float(m.eps)}},
                [np.asarray(m.running_mean), np.asarray(m.running_var),
                 np.ones((1,), np.float32)])
            if m.affine:
                sname = self._fresh("scale")
                top = self._add(sname, "Scale", [top], sname,
                                {"scale_param": {"bias_term": True}},
                                [np.asarray(m.weight), np.asarray(m.bias)])
            return top
        if isinstance(m, nn.CMul):
            name = self._name_of(m, "scale")
            return self._add(name, "Scale", bottoms, name,
                             {"scale_param": {}}, [np.asarray(m.weight)])

        # ---- pooling ----------------------------------------------------
        if isinstance(m, nn.SpatialAveragePooling) or \
                isinstance(m, nn.SpatialMaxPooling):
            is_avg = isinstance(m, nn.SpatialAveragePooling)
            name = self._name_of(m, "pool")
            p: Dict[str, object] = {"pool": _Enum("AVE" if is_avg else "MAX")}
            if m.global_pooling:
                p["global_pooling"] = True
            else:
                p.update({"kernel_h": int(m.kh), "kernel_w": int(m.kw),
                          "stride_h": int(m.dh), "stride_w": int(m.dw),
                          "pad_h": int(m.pad_h), "pad_w": int(m.pad_w)})
            if not m.ceil_mode:
                p["round_mode"] = _Enum("FLOOR")
            return self._add(name, "Pooling", bottoms, name,
                             {"pooling_param": p})

        # ---- parameter-free layers --------------------------------------
        simple = {nn.ReLU: "ReLU", nn.Tanh: "TanH", nn.Sigmoid: "Sigmoid",
                  nn.SoftMax: "Softmax", nn.Abs: "AbsVal", nn.Exp: "Exp",
                  nn.Log: "Log"}
        for cls, caffe_type in simple.items():
            if type(m) is cls:
                name = self._name_of(m, caffe_type.lower())
                return self._add(name, caffe_type, bottoms, name)
        if isinstance(m, nn.LogSoftMax):
            # caffe has no LogSoftmax layer: emit Softmax -> Log (both
            # in the loader's converter set), mathematically identical
            name = self._name_of(m, "softmax")
            top = self._add(name, "Softmax", bottoms, name)
            lname = self._fresh("log")
            return self._add(lname, "Log", [top], lname)
        if isinstance(m, nn.SpatialCrossMapLRN):
            name = self._name_of(m, "lrn")
            return self._add(name, "LRN", bottoms, name, {"lrn_param": {
                "local_size": int(m.size), "alpha": float(m.alpha),
                "beta": float(m.beta), "k": float(m.k)}})
        if isinstance(m, nn.Dropout):
            name = self._name_of(m, "drop")
            return self._add(name, "Dropout", bottoms, name, {
                "dropout_param": {"dropout_ratio": float(m.p)}})
        if isinstance(m, nn.Power):
            name = self._name_of(m, "power")
            return self._add(name, "Power", bottoms, name, {"power_param": {
                "power": float(m.power), "scale": float(m.scale),
                "shift": float(m.shift)}})
        if isinstance(m, nn.InferReshape):
            name = self._name_of(m, "reshape")
            if tuple(m.size) == (0, -1):
                return self._add(name, "Flatten", bottoms, name)
            return self._add(name, "Reshape", bottoms, name, {
                "reshape_param": {"shape": {
                    "dim": [int(d) for d in m.size]}}})
        if isinstance(m, (nn.Reshape, nn.View)):
            sizes = m.size if isinstance(m, nn.Reshape) else m.sizes
            name = self._name_of(m, "reshape")
            return self._add(name, "Reshape", bottoms, name, {
                "reshape_param": {"shape": {
                    "dim": [0] + [int(d) for d in sizes]}}})
        if isinstance(m, nn.JoinTable):
            name = self._name_of(m, "concat")
            return self._add(name, "Concat", bottoms, name,
                             {"concat_param": {"axis": int(m.dim)}})
        if isinstance(m, nn.CAddTable):
            name = self._name_of(m, "eltwise")
            return self._add(name, "Eltwise", bottoms, name,
                             {"eltwise_param": {"operation": _Enum("SUM")}})
        if isinstance(m, nn.CSubTable):
            name = self._name_of(m, "eltwise")
            return self._add(name, "Eltwise", bottoms, name, {
                "eltwise_param": {"operation": _Enum("SUM"),
                                  "coeff": [1.0, -1.0]}})
        if isinstance(m, nn.CMulTable):
            name = self._name_of(m, "eltwise")
            return self._add(name, "Eltwise", bottoms, name,
                             {"eltwise_param": {"operation": _Enum("PROD")}})
        if isinstance(m, nn.CMaxTable):
            name = self._name_of(m, "eltwise")
            return self._add(name, "Eltwise", bottoms, name,
                             {"eltwise_param": {"operation": _Enum("MAX")}})
        if isinstance(m, nn.Identity):
            return bottoms[0]
        raise NotImplementedError(
            f"CaffePersister: no emitter for {type(m).__name__} "
            f"(register one via customized_emitters)")

    def _fused_sequential(self, seq, bottoms: List[str]) -> Optional[str]:
        """Recognize the loader's composite emissions so they round-trip
        as ONE caffe layer: [InferReshape(0,-1), Linear] -> InnerProduct,
        [CMul, CAdd] -> Scale(+bias)."""
        import bigdl_tpu.nn as nn

        ch = seq.layers
        if len(ch) == 2 and isinstance(ch[0], nn.InferReshape) \
                and tuple(ch[0].size) == (0, -1) \
                and isinstance(ch[1], nn.Linear):
            return self._emit(ch[1], bottoms)
        if len(ch) == 2 and isinstance(ch[0], nn.CMul) \
                and isinstance(ch[1], nn.CAdd):
            name = self._name_of(ch[0], "scale")
            return self._add(name, "Scale", bottoms, name,
                             {"scale_param": {"bias_term": True}},
                             [np.asarray(ch[0].weight),
                              np.asarray(ch[1].bias)])
        return None

    def _emit_graph(self, graph, bottoms: List[str]) -> str:
        """DAG walk: graph input nodes bind to ``bottoms`` in order."""
        tops: Dict[int, str] = {}
        free = list(bottoms)
        for node in graph.input_nodes:
            nm = getattr(node.element, "_name", None) or self._fresh("data")
            tops[node.id] = free.pop(0) if free else nm
        for node in graph._sorted:
            if node.id in tops:
                continue
            node_bottoms = [tops[p.id] for p, _ in node.prev]
            tops[node.id] = self._emit(node.element, node_bottoms)
        outs = [tops[o.id] for o in graph.output_nodes]
        return outs[0]

    # -- output ------------------------------------------------------------
    def build(self) -> Tuple[Dict, bytes]:
        """(prototxt dict, caffemodel bytes)."""
        self.layers, self.blobs, self._counter = [], {}, 0
        self._taken = self._user_names(self.model, set())
        net: Dict = {"name": self.net_name}
        input_layers = []
        data_blobs = list(self.input_shapes) or ["data"]
        for blob in data_blobs:
            lay = {"name": blob, "type": "Input", "top": blob}
            if blob in self.input_shapes:
                lay["input_param"] = {"shape": {
                    "dim": [int(d) for d in self.input_shapes[blob]]}}
            input_layers.append(lay)
        self._emit(self.model, data_blobs)
        net["layer"] = input_layers + self.layers
        payload = pw.emit_bytes(1, self.net_name.encode())
        for lay in self.layers:
            payload += pw.emit_bytes(100, _layer_param(
                lay["name"], lay["type"], lay["bottom"],
                [lay["top"]], self.blobs.get(lay["name"], [])))
        return net, payload

    def save(self, prototxt_path: str, caffemodel_path: str,
             overwrite: bool = False) -> None:
        net, payload = self.build()
        from bigdl_tpu.utils.file import save as file_save

        file_save(to_prototxt(net).encode(), prototxt_path, overwrite)
        file_save(payload, caffemodel_path, overwrite)


def save_caffe(model, prototxt_path: str, caffemodel_path: str,
               input_shapes=None, overwrite: bool = False) -> None:
    """Persist ``model`` as Caffe prototxt + caffemodel
    (``CaffePersister.scala:47``)."""
    CaffePersister(model, input_shapes).save(prototxt_path, caffemodel_path,
                                             overwrite)
