"""TF Session equivalent: train an imported TF graph END-TO-END,
interpreting its queue/reader input pipeline (SURVEY §2.9; reference
``utils/tf/Session.scala:48,150-263,435-461`` ``BigDLSessionImpl``).

The reference walks a TF 1.x input pipeline — filename queue ->
TFRecordReader -> ParseExample -> batch queue -> dequeue — and turns it
into an RDD feeding a DistriOptimizer.  Here the same node patterns are
interpreted HOST-side into a :class:`~bigdl_tpu.dataset.dataset.DataSet`
(the queues never execute on device; TPU feeding is the train step's
sharded batch path), while the compute subgraph downstream of the
dequeue becomes a trainable ``nn.Graph`` via ``TensorflowLoader`` with
Const weights promoted to Variables.

Supported pipeline ops (the reference's set, ``Session.scala:150-263``):
``FIFOQueueV2``/``PaddingFIFOQueueV2``/``RandomShuffleQueueV2`` (+ V1
names), ``QueueEnqueue(Many)V2``, ``QueueDequeue(Many/UpTo)V2``,
``ReaderReadV2`` over ``TFRecordReaderV2``, ``ParseExampleV2`` /
``ParseSingleExample`` / legacy variadic-key ``ParseExample`` (v1), with
``Identity``/control-dep and shape-only (``Reshape``/``ExpandDims``/
``Squeeze``) hops between.

Beyond the reference's reader set (its ``handleReaderNode`` matches ONLY
``TFRecordReaderV2``, ``Session.scala:128-131``): ``TextLineReaderV2``
(+V1, incl. ``skip_header_lines``) feeding ``DecodeCSV`` — the classic
TF 1.x CSV pipeline (filename queue -> TextLineReader -> decode_csv ->
batch queue), record defaults and field delimiter honored — and
``FixedLengthRecordReaderV2`` (+V1, incl. header/footer bytes) whose
raw records flow through ``DecodeRaw``/``StridedSlice``/``Reshape``/
``Cast`` chains: the classic CIFAR-10 binary pipeline.

Supported topologies (round 4): several enqueues into one queue (streams
union, ``handleDistriDequeue``); several dequeues over one queue (the
stream splits round-robin between them, ``handleLocalDequeue``);
dequeues over different queues (rows zip by index);
``RandomShuffleQueue`` (host-side seeded shuffle); and queue-less graphs
whose compute reads ``ParseExample`` outputs directly.  Round 5 adds
shuffled filename PRODUCERS (``string_input_producer(shuffle=True)``:
the RandomShuffle on the filename tensor becomes a reproducible
host-side permutation, one order per queue).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.utils.tf_graph import TensorflowLoader, parse_graphdef

__all__ = ["TFTrainingSession"]

_QUEUE_OPS = {"FIFOQueueV2", "PaddingFIFOQueueV2", "RandomShuffleQueueV2",
              "FIFOQueue", "PaddingFIFOQueue", "RandomShuffleQueue"}
_DEQUEUE_OPS = {"QueueDequeueManyV2", "QueueDequeueUpToV2", "QueueDequeueV2",
                "QueueDequeueMany", "QueueDequeueUpTo", "QueueDequeue"}
_ENQUEUE_OPS = {"QueueEnqueueV2", "QueueEnqueueManyV2", "QueueEnqueue",
                "QueueEnqueueMany"}
_PARSE_OPS = {"ParseExampleV2", "ParseExample", "ParseSingleExample"}
_READER_OPS = {"ReaderReadV2", "ReaderRead"}

from bigdl_tpu.utils.tf_graph import _DTYPES as _TF_DTYPES  # one table


def _split_ref(ref: str) -> Tuple[str, int]:
    ref = ref.lstrip("^")
    if ":" in ref:
        name, port = ref.rsplit(":", 1)
        return name, int(port)
    return ref, 0


class _Source(tuple):
    """Record source behind a pipeline endpoint: ``("tfrecord", files)``,
    ``("textline", files, skip_header_lines, delim, defaults)``, or
    ``("fixedlen", files, header_bytes, "", (record_bytes,
    footer_bytes))`` — a plain tuple so the existing source-equality
    checks ("components read different files") keep working."""

    def __new__(cls, kind, files, skip=0, delim=",", defaults=()):
        return super().__new__(cls, (kind, tuple(files), skip, delim,
                                     tuple(defaults)))

    kind = property(lambda s: s[0])
    files = property(lambda s: list(s[1]))
    skip = property(lambda s: s[2])  # textline: header LINES; fixedlen: BYTES
    delim = property(lambda s: s[3])
    defaults = property(lambda s: s[4])


def _union_sources(a: _Source, b: _Source) -> _Source:
    """Union the file lists of two same-shape sources (multi-enqueue
    streams); incompatible reader/CSV configs cannot share a queue."""
    if not isinstance(a, _Source) or not isinstance(b, _Source) \
            or a.kind != b.kind or tuple(a)[2:] != tuple(b)[2:]:
        raise NotImplementedError(
            "enqueues into one queue read incompatible sources")
    return _Source(a.kind, a.files + b.files, a.skip, a.delim, a.defaults)


class TFTrainingSession:
    """Interpret a GraphDef's input pipeline and train its compute graph.

    ``train(outputs, criterion, optim_method, ...)`` returns the trained
    ``nn.Graph``; dequeue components consumed by the compute graph become
    its inputs (in graph order) and the remaining component is the label
    fed to the criterion — matching how ``BigDLSessionImpl.train``
    splits endpoints (``Session.scala:435-461``)."""

    def __init__(self, graphdef):
        self.nodes: List[Dict] = (parse_graphdef(graphdef)
                                  if isinstance(graphdef, (bytes, bytearray))
                                  else list(graphdef))
        self.by_name = {n["name"]: n for n in self.nodes}
        # one resolved file order per filename queue: several components
        # of one parse op must see the SAME (possibly shuffled) order
        self._filename_cache: Dict[str, List[str]] = {}

    # -- pipeline interpretation ------------------------------------------
    def _node(self, ref: str) -> Dict:
        name, _ = _split_ref(ref)
        if name not in self.by_name:
            raise KeyError(f"unknown node {name!r}")
        return self.by_name[name]

    def _follow_identity(self, ref: str) -> Dict:
        """Skip Identity/control-dep hops to the producing node."""
        node = self._node(ref)
        while node["op"] in ("Identity", "StopGradient"):
            data_ins = [i for i in node["inputs"] if not i.startswith("^")]
            node = self._node(data_ins[0])
        return node

    def _find_enqueues(self, queue_name: str) -> List[Dict]:
        """ALL enqueue ops feeding a queue, in graph order — several
        producers union into one stream (``Session.scala:216-226``
        ``handleDistriDequeue`` reduces enqueue RDDs with union)."""
        out = [n for n in self.nodes
               if n["op"] in _ENQUEUE_OPS and n["inputs"]
               and _split_ref(n["inputs"][0])[0] == queue_name]
        if not out:
            raise ValueError(f"no enqueue op found for queue {queue_name!r}")
        return out

    def _find_enqueue(self, queue_name: str) -> Dict:
        return self._find_enqueues(queue_name)[0]

    def _filenames(self, queue_ref: str) -> List[str]:
        """Filename queue -> the Const string list enqueued into it."""
        qnode = self._follow_identity(queue_ref)
        if qnode["op"] not in _QUEUE_OPS:
            raise ValueError(f"reader's queue is {qnode['op']}, not a queue")
        if qnode["name"] in self._filename_cache:
            return self._filename_cache[qnode["name"]]
        enq = self._find_enqueue(qnode["name"])
        names: List[str] = []
        for ref in enq["inputs"][1:]:
            if ref.startswith("^"):  # control dep, not a data component
                continue
            src = self._follow_identity(ref)
            shuffle = False
            if src["op"] == "RandomShuffle":
                # string_input_producer(shuffle=True) shuffles the
                # filename tensor before the enqueue; interpret it as a
                # host-side permutation of the file list (seeded by the
                # global RNG, so runs are reproducible)
                shuffle = True
                data_ins = [i for i in src["inputs"]
                            if not i.startswith("^")]
                src = self._follow_identity(data_ins[0])
            if src["op"] != "Const":
                raise NotImplementedError(
                    f"filename source {src['op']} unsupported (want Const)")
            val = src["attrs"].get("value")
            batch = [f.decode() if isinstance(f, bytes) else str(f)
                     for f in np.asarray(val).reshape(-1)]
            if shuffle and len(batch) > 1:
                from bigdl_tpu.utils.rng import RNG

                order = np.asarray(RNG.permutation(len(batch)))
                batch = [batch[int(i)] for i in order]
            names.extend(batch)
        self._filename_cache[qnode["name"]] = names
        return names

    def _dense_spec(self, pe: Dict) -> Tuple[List[str], List, List[List[int]], int]:
        """(dense keys, dtypes, shapes, first dense output port)."""
        a = pe["attrs"]
        if pe["op"] == "ParseSingleExample":
            keys = [k.decode() if isinstance(k, bytes) else k
                    for k in (a.get("dense_keys") or [])]
            num_sparse = int(a.get("num_sparse") or 0)
            first_dense = 3 * num_sparse
        elif pe["op"] == "ParseExampleV2":
            # inputs: serialized, names, sparse_keys, dense_keys,
            # ragged_keys, dense_defaults...
            keys_node = self._follow_identity(pe["inputs"][3])
            raw = np.asarray(keys_node["attrs"].get("value")).reshape(-1)
            keys = [k.decode() if isinstance(k, bytes) else str(k)
                    for k in raw]
            num_sparse = int(a.get("num_sparse") or 0)
            # output order: sparse_indices*, sparse_values*,
            # sparse_shapes*, dense_values*
            first_dense = 3 * num_sparse
        elif pe["op"] == "ParseExample":
            # v1: keys arrive as VARIADIC Const string inputs —
            # [serialized, names, sparse_keys x Nsparse,
            #  dense_keys x Ndense, dense_defaults x Ndense]
            num_sparse = int(a.get("Nsparse") or 0)
            ndense = int(a.get("Ndense") or 0)
            keys = []
            data_ins = [i for i in pe["inputs"] if not i.startswith("^")]
            for ref in data_ins[2 + num_sparse:2 + num_sparse + ndense]:
                raw = self._const_of(ref).reshape(-1)[0]
                keys.append(raw.decode() if isinstance(raw, bytes)
                            else str(raw))
            first_dense = 3 * num_sparse
        else:
            raise NotImplementedError(
                f"unsupported parse op {pe['op']!r}")
        dtypes = a.get("Tdense") or []
        dtypes = [_TF_DTYPES.get(int(d), np.float32) for d in dtypes]
        shapes = a.get("dense_shapes") or [[] for _ in keys]
        return keys, dtypes, shapes, first_dense

    def _serialized_source(self, pe: Dict) -> List[str]:
        """The ParseExample's serialized input -> TFRecord filenames."""
        reader = self._follow_identity(pe["inputs"][0])
        # v1 ParseExample requires a VECTOR serialized input, so graphs
        # wrap the reader's scalar in shape-only ops — skip through them
        while reader["op"] in ("Reshape", "ExpandDims", "Squeeze"):
            data_ins = [i for i in reader["inputs"] if not i.startswith("^")]
            reader = self._follow_identity(data_ins[0])
        if reader["op"] not in _READER_OPS:
            raise NotImplementedError(
                f"serialized source {reader['op']} unsupported "
                f"(want ReaderReadV2)")
        reader_impl = self._follow_identity(reader["inputs"][0])
        if reader_impl["op"] not in ("TFRecordReaderV2", "TFRecordReader"):
            raise NotImplementedError(
                f"reader {reader_impl['op']} unsupported for a "
                f"ParseExample source (want TFRecord; text-line "
                f"pipelines go through DecodeCSV)")
        return _Source("tfrecord", self._filenames(reader["inputs"][1]))

    def _csv_source(self, csv_node: Dict) -> _Source:
        """``DecodeCSV``'s records input -> the TextLineReader's files,
        skip_header_lines, field delimiter, and per-field record
        defaults (which also carry the field dtypes)."""
        reader = self._follow_identity(csv_node["inputs"][0])
        while reader["op"] in ("Reshape", "ExpandDims", "Squeeze"):
            data_ins = [i for i in reader["inputs"]
                        if not i.startswith("^")]
            reader = self._follow_identity(data_ins[0])
        if reader["op"] not in _READER_OPS:
            raise NotImplementedError(
                f"DecodeCSV records source {reader['op']} unsupported "
                f"(want ReaderReadV2)")
        reader_impl = self._follow_identity(reader["inputs"][0])
        if reader_impl["op"] not in ("TextLineReaderV2", "TextLineReader"):
            raise NotImplementedError(
                f"reader {reader_impl['op']} unsupported for a CSV "
                f"source (want TextLineReader)")
        skip = int(reader_impl["attrs"].get("skip_header_lines") or 0)
        delim = csv_node["attrs"].get("field_delim", b",")
        if isinstance(delim, bytes):
            delim = delim.decode() or ","
        defaults = []  # hashable (dtype str, value) | (dtype str, None)
        for ref in csv_node["inputs"][1:]:
            if ref.startswith("^"):
                continue
            d = self._const_of(ref).reshape(-1)
            if d.dtype.kind in ("S", "U", "O"):
                raise NotImplementedError(
                    "string CSV fields have no dense-tensor "
                    "representation here (numeric fields only)")
            # empty default Const = required field (DecodeCSV semantics)
            defaults.append((d.dtype.str,
                             d.reshape(-1)[0].item() if d.size else None))
        return _Source("textline", self._filenames(reader["inputs"][1]),
                       skip, delim, tuple(defaults))

    def _fixedlen_source(self, reader: Dict) -> _Source:
        """``ReaderReadV2`` over a FixedLengthRecordReader -> the files
        plus (record_bytes, footer_bytes); header bytes ride ``skip``."""
        reader_impl = self._follow_identity(reader["inputs"][0])
        if reader_impl["op"] not in ("FixedLengthRecordReaderV2",
                                     "FixedLengthRecordReader"):
            raise NotImplementedError(
                f"reader {reader_impl['op']} unsupported for a raw-record "
                f"source (want FixedLengthRecordReader)")
        a = reader_impl["attrs"]
        record_bytes = int(a.get("record_bytes") or 0)
        if record_bytes <= 0:
            raise ValueError("FixedLengthRecordReader needs record_bytes")
        if int(a.get("hop_bytes") or 0):
            raise NotImplementedError("hop_bytes (overlapping records)")
        return _Source("fixedlen", self._filenames(reader["inputs"][1]),
                       int(a.get("header_bytes") or 0), "",
                       (record_bytes, int(a.get("footer_bytes") or 0)))

    def _enqueue_spec(self, enq: Dict):
        """One enqueue op -> (filenames, comps)."""
        filenames: Optional[List[str]] = None
        comps: List[Tuple[str, object, List[int], List]] = []
        for ref in enq["inputs"][1:]:
            if ref.startswith("^"):  # control dep, not a data component
                continue
            src, port, chain, fp = self._component_chain(ref)
            if src["op"] == "DecodeCSV":
                files = self._csv_source(src)
                if not 0 <= port < len(files.defaults):
                    raise NotImplementedError(
                        f"DecodeCSV output port {port} out of range")
                # key = the CSV field index; dtype from its default Const
                comps.append((port, np.dtype(files.defaults[port][0]).type,
                              [], chain))
            elif src["op"] in _READER_OPS:
                # fixed-length raw record: port 1 is the value output;
                # the chain (DecodeRaw -> slices/reshape/cast) owns the
                # typing, so the KEY carries the chain fingerprint —
                # (port, uint8, []) alone is indistinct, which would
                # make the multi-enqueue same-spec guard vacuous
                if port != 1:
                    raise NotImplementedError(
                        f"reader output port {port} enqueued (only the "
                        f"value, port 1, is supported)")
                if not chain:
                    raise NotImplementedError(
                        "raw reader value reaches the queue undecoded "
                        "(no DecodeRaw in its chain)")
                files = self._fixedlen_source(src)
                comps.append(((port, fp), np.uint8, [], chain))
            else:
                keys, dtypes, shapes, first_dense = self._dense_spec(src)
                di = port - first_dense
                if not 0 <= di < len(keys):
                    raise NotImplementedError(
                        f"component port {port} is not a dense output")
                dtype = dtypes[di] if di < len(dtypes) else np.float32
                shape = list(shapes[di]) if di < len(shapes) else []
                comps.append((keys[di], dtype, shape, chain))
                files = self._serialized_source(src)
            if filenames is None:
                filenames = files
            elif filenames != files:
                raise NotImplementedError("components read different files")
        if filenames is None:
            raise ValueError(f"enqueue {enq['name']!r} has no components")
        return filenames, comps

    def interpret_pipeline(self, dequeue_name: str):
        """dequeue node -> (filenames, [(key, dtype, shape)] per component).

        Walks: dequeue -> its queue -> every enqueue feeding it -> each
        enqueued component -> ParseExample dense output -> reader files.
        Several enqueues union their files (their component specs must
        agree); kept for API compatibility — ``_dequeue_records`` is the
        record-producing superset."""
        deq = self.by_name[dequeue_name]
        queue = self._follow_identity(deq["inputs"][0])
        enqs = self._find_enqueues(queue["name"])
        filenames, comps = self._enqueue_spec(enqs[0])
        for other in enqs[1:]:
            more_files, more_comps = self._enqueue_spec(other)
            if [c[:3] for c in more_comps] != [c[:3] for c in comps]:
                raise NotImplementedError(
                    "enqueues into one queue carry different component "
                    "specs")
            filenames = _union_sources(filenames, more_files)
        return filenames, comps

    def _dequeue_records(self, dequeue_name: str):
        """(records, comps) for one dequeue: the union of its queue's
        enqueue streams, shuffled when the queue is a RandomShuffleQueue
        (host-side analogue of the queue's runtime semantics; seeded by
        the global RNG so runs are reproducible)."""
        deq = self.by_name[dequeue_name]
        queue = self._follow_identity(deq["inputs"][0])
        enqs = self._find_enqueues(queue["name"])
        records: List[tuple] = []
        comps = None
        for enq in enqs:
            files, c = self._enqueue_spec(enq)
            if comps is None:
                comps = c
            elif [x[:3] for x in c] != [x[:3] for x in comps]:
                raise NotImplementedError(
                    "enqueues into one queue carry different component "
                    "specs")
            records.extend(self._records(files, c))
        if queue["op"] in ("RandomShuffleQueueV2", "RandomShuffleQueue"):
            from bigdl_tpu.utils.rng import RNG

            order = np.asarray(RNG.permutation(len(records)))
            records = [records[int(i)] for i in order]
        return records, comps

    #: per-record host ops allowed between ParseExample and the enqueue —
    #: the image-decode pipelines of ``Session.scala:173-263``
    _HOST_OPS = {"DecodeJpeg", "DecodePng", "DecodeImage", "DecodeBmp",
                 "DecodeRaw", "Cast", "Reshape", "ExpandDims", "Squeeze",
                 "Sub", "Add", "AddV2", "Mul", "RealDiv", "Div",
                 "ResizeBilinear", "StridedSlice", "Slice", "Transpose"}

    def _component_chain(self, ref: str):
        """Walk one enqueue component back to its ParseExample output,
        collecting the host-op chain as compiled per-record CLOSURES in
        APPLICATION order (consts resolved ONCE, not per record).
        Returns (parse_node, parse_port, [fn(value) -> value, ...],
        fingerprint) — the fingerprint is a hashable summary of the
        chain's SEMANTICS (ops + const operands + attrs, not node
        names), so two enqueues union only when their decode chains
        compute the same thing."""
        chain = []
        fp = []
        cur = ref
        while True:
            # step Identity hops one at a time so the ":port" suffix of
            # the ref that directly names the parse op is preserved
            name, port = _split_ref(cur)
            src = self.by_name.get(name)
            if src is None:
                raise KeyError(f"unknown node {name!r}")
            if src["op"] in ("Identity", "StopGradient"):
                cur = [i for i in src["inputs"]
                       if not i.startswith("^")][0]
                continue
            if src["op"] in _PARSE_OPS or src["op"] == "DecodeCSV" \
                    or src["op"] in _READER_OPS:
                # terminals: parse op (tfrecord), DecodeCSV (textline),
                # or the ReaderRead itself (fixed-length raw records)
                chain.reverse()
                fp.reverse()
                return src, port, chain, tuple(fp)
            if src["op"] not in self._HOST_OPS:
                raise NotImplementedError(
                    f"enqueued component from {src['op']} unsupported "
                    f"(want ParseExample* or host ops "
                    f"{sorted(self._HOST_OPS)})")
            data_ins = [i for i in src["inputs"] if not i.startswith("^")]
            data_idx = 0
            if len(data_ins) > 1 and \
                    self._follow_identity(data_ins[0])["op"] == "Const":
                data_idx = 1
            chain.append(self._compile_host_op(src, data_idx))
            fp.append(self._node_fingerprint(src, data_ins, data_idx))
            cur = data_ins[data_idx]

    def _node_fingerprint(self, src: Dict, data_ins, data_idx: int):
        """Semantic identity of one chain node: op + const operand
        contents + attrs — stable across graph-unique node names."""
        parts = [src["op"]]
        for i, ref in enumerate(data_ins):
            if i == data_idx:
                continue
            try:
                c = self._const_of(ref)
                parts.append((c.dtype.str, tuple(c.shape), c.tobytes()))
            except (NotImplementedError, KeyError):
                parts.append(("nonconst", _split_ref(ref)[1]))
        parts.append(tuple(sorted(
            (k, repr(v)) for k, v in src["attrs"].items())))
        return tuple(parts)

    def _const_of(self, ref: str) -> np.ndarray:
        node = self._follow_identity(ref)
        if node["op"] == "Fill":
            # constant-folded Fill(dims, value) — TF emits these for
            # e.g. default stride vectors
            ins = [i for i in node["inputs"] if not i.startswith("^")]
            dims = self._const_of(ins[0]).reshape(-1)
            val = self._const_of(ins[1]).reshape(-1)[0]
            return np.full(tuple(int(d) for d in dims), val)
        if node["op"] != "Const":
            raise NotImplementedError(
                f"expected Const operand, got {node['op']}")
        return np.asarray(node["attrs"]["value"])

    def _compile_host_op(self, node: Dict, data_idx: int):
        """Turn one pipeline node into a per-record closure; Const
        operands and helper modules are resolved HERE, once."""
        op = node["op"]
        a = node["attrs"]
        ins = [i for i in node["inputs"] if not i.startswith("^")]
        if op in ("DecodeJpeg", "DecodePng", "DecodeImage", "DecodeBmp"):
            from bigdl_tpu.nn import ops as nnops

            dec = nnops.DecodeImage(int(a.get("channels", 3) or 3))
            return lambda value: np.asarray(dec.update_output(value))
        if op == "DecodeRaw":
            dt = a.get("out_type")
            dt = np.dtype(_TF_DTYPES.get(
                dt[1] if isinstance(dt, tuple) else dt, np.uint8))
            # little_endian defaults True in TF; big-endian formats
            # (IDX/network-order records) would otherwise decode
            # byte-swapped with no error
            le = a.get("little_endian")
            if le is not None and not le and dt.itemsize > 1:
                dt = dt.newbyteorder(">")
            return lambda value: np.frombuffer(bytes(value), dt) \
                .astype(dt.newbyteorder("="), copy=True)
        if op == "Cast":
            dt = a.get("DstT")
            dt = _TF_DTYPES.get(dt[1] if isinstance(dt, tuple) else dt,
                                np.float32)
            return lambda value: np.asarray(value).astype(dt)
        if op == "Reshape":
            shape = [int(s) for s in self._const_of(ins[1]).reshape(-1)]
            return lambda value: np.asarray(value).reshape(shape)
        if op == "ExpandDims":
            axis = int(self._const_of(ins[1]).reshape(-1)[0])
            return lambda value: np.expand_dims(np.asarray(value), axis)
        if op == "Squeeze":
            dims = tuple(int(d) for d in (a.get("squeeze_dims") or []))
            return lambda value: np.squeeze(np.asarray(value), dims or None)
        if op == "Transpose":
            perm = tuple(int(p) for p in self._const_of(ins[1]).reshape(-1))
            return lambda value: np.transpose(np.asarray(value), perm)
        if op == "Slice":
            begin = self._const_of(ins[1]).reshape(-1)
            size = self._const_of(ins[2]).reshape(-1)
            sl = tuple(slice(int(b), None if s == -1 else int(b + s))
                       for b, s in zip(begin, size))
            return lambda value: np.asarray(value)[sl]
        if op == "StridedSlice":
            begin = self._const_of(ins[1]).reshape(-1)
            end = self._const_of(ins[2]).reshape(-1)
            strides = self._const_of(ins[3]).reshape(-1)
            bm = int(a.get("begin_mask") or 0)
            em = int(a.get("end_mask") or 0)
            sam = int(a.get("shrink_axis_mask") or 0)
            if int(a.get("ellipsis_mask") or 0) \
                    or int(a.get("new_axis_mask") or 0):
                raise NotImplementedError(
                    "StridedSlice ellipsis/new-axis masks")
            idx = []
            for i in range(len(begin)):
                if sam & (1 << i):  # integer index: selects + drops dim
                    idx.append(int(begin[i]))
                else:
                    idx.append(slice(
                        None if bm & (1 << i) else int(begin[i]),
                        None if em & (1 << i) else int(end[i]),
                        int(strides[i])))
            idx = tuple(idx)
            return lambda value: np.asarray(value)[idx]
        if op == "ResizeBilinear":
            from bigdl_tpu.nn.layers.shape import ResizeBilinear

            size = self._const_of(ins[1]).reshape(-1)
            resize = ResizeBilinear(
                int(size[0]), int(size[1]),
                bool(a.get("align_corners", False)), format="NHWC",
                half_pixel_centers=bool(a.get("half_pixel_centers", False)))
            return lambda value: np.asarray(
                resize.forward(np.asarray(value, np.float32)[None]))[0]
        if op in ("Sub", "Add", "AddV2", "Mul", "RealDiv", "Div"):
            other = self._const_of(ins[1 - data_idx]).astype(np.float32)

            def arith(value):
                v = np.asarray(value, np.float32)
                if op == "Sub":
                    return v - other if data_idx == 0 else other - v
                if op in ("Add", "AddV2"):
                    return v + other
                if op == "Mul":
                    return v * other
                return v / other if data_idx == 0 else other / v

            return arith
        raise NotImplementedError(op)

    def _walk_compute(self, output_names: Sequence[str]):
        """One ancestor walk of ``outputs``: (compute-node keep set,
        dequeue nodes found, direct parse feeds found).  Dequeues AND
        directly-consumed ParseExample nodes end the walk — the pipeline
        behind them is interpreted host-side, not compiled (the
        no-batching-queue reader pattern)."""
        seen, dequeues, parse_feeds = set(), [], []
        stack = [_split_ref(o)[0] for o in output_names]
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            node = self.by_name.get(name)
            if node is None:
                continue
            if node["op"] in _DEQUEUE_OPS:
                if name not in dequeues:
                    dequeues.append(name)
                continue
            if node["op"] in _PARSE_OPS:
                if name not in parse_feeds:
                    parse_feeds.append(name)
                continue
            seen.add(name)
            stack.extend(_split_ref(i)[0] for i in node["inputs"])
        # deterministic graph order, not DFS-stack order
        order = {n["name"]: i for i, n in enumerate(self.nodes)}
        dequeues.sort(key=lambda n: order.get(n, 0))
        parse_feeds.sort(key=lambda n: order.get(n, 0))
        return seen, dequeues, parse_feeds

    # -- dataset construction ---------------------------------------------
    def _records(self, source, comps) -> List[Tuple[np.ndarray, ...]]:
        from bigdl_tpu.dataset.tfrecord import TFRecordIterator, parse_example

        if isinstance(source, _Source) and source.kind == "textline":
            return self._textline_rows(source, comps)
        if isinstance(source, _Source) and source.kind == "fixedlen":
            return self._fixedlen_rows(source, comps)
        filenames = source.files if isinstance(source, _Source) else source
        out = []
        for path in filenames:
            for rec in TFRecordIterator(path):
                feats = parse_example(rec)
                row = []
                for key, dtype, shape, chain in comps:
                    if key not in feats:
                        raise KeyError(f"record missing feature {key!r}")
                    v = feats[key]
                    if isinstance(v, list):  # bytes feature
                        # scalar bytes (e.g. an encoded image) stays raw
                        # for the decode chain; lists of bytes have no
                        # dense-tensor representation here
                        if len(v) != 1:
                            raise NotImplementedError(
                                f"multi-value bytes feature {key!r}")
                        v = v[0]
                        if not chain:
                            raise NotImplementedError(
                                f"bytes feature {key!r} reaches the queue "
                                "undecoded (no Decode* op in its chain)")
                    for fn in chain:
                        v = fn(v)
                    arr = np.asarray(v)
                    if not chain:  # raw dense feature: apply declared spec
                        arr = arr.astype(dtype)
                        arr = (arr.reshape(shape) if shape else
                               (arr.reshape(()) if arr.size == 1 else arr))
                    row.append(arr)
                out.append(tuple(row))
        return out

    def _textline_rows(self, source: _Source, comps
                       ) -> List[Tuple[np.ndarray, ...]]:
        """CSV lines -> per-record component tuples.  DecodeCSV
        semantics: empty field takes its record default; an empty
        default marks the field REQUIRED (error when absent)."""
        import csv as _csv

        rows = []
        for path in source.files:
            with open(path, newline="") as f:
                lines = f.read().splitlines()[source.skip:]
            for line in lines:
                if not line:
                    continue
                fields = next(_csv.reader([line], delimiter=source.delim))
                row = []
                for key, dtype, shape, chain in comps:
                    if key >= len(fields):
                        raise ValueError(
                            f"CSV line has {len(fields)} fields; "
                            f"component wants index {key} ({path!r})")
                    raw = fields[key].strip()
                    if raw == "":
                        dts, dval = source.defaults[key]
                        if dval is None:
                            raise ValueError(
                                f"required CSV field {key} is empty "
                                f"({path!r})")
                        v = np.dtype(dts).type(dval)
                    else:
                        v = dtype(raw)
                    for fn in chain:
                        v = fn(v)
                    row.append(np.asarray(v))
                rows.append(tuple(row))
        return rows

    def _fixedlen_rows(self, source: _Source, comps
                       ) -> List[Tuple[np.ndarray, ...]]:
        """Fixed-length binary records (CIFAR-10 binary layout): skip
        ``header_bytes`` (rides ``skip``), step ``record_bytes`` chunks,
        stop ``footer_bytes`` short of the end; every component's chain
        (DecodeRaw -> slices -> reshape -> cast ...) runs per record."""
        record_bytes, footer = source.defaults
        rows = []
        for path in source.files:
            with open(path, "rb") as f:
                data = f.read()
            end = len(data) - footer
            off = source.skip
            if (end - off) % record_bytes:
                # TF's FixedLengthRecordReader silently drops a partial
                # tail (it returns OutOfRange there); warn, don't raise
                import logging

                logging.getLogger("bigdl_tpu").warning(
                    f"{path!r}: dropping {(end - off) % record_bytes} "
                    f"trailing bytes (not a whole "
                    f"record_bytes={record_bytes} record)")
            while off + record_bytes <= end:
                rec = data[off:off + record_bytes]
                off += record_bytes
                row = []
                for _key, _dtype, _shape, chain in comps:
                    v = rec
                    for fn in chain:
                        v = fn(v)
                    row.append(np.asarray(v))
                rows.append(tuple(row))
        return rows

    def _parse_feed_records(self, parse_name: str):
        """Direct (non-queue) reader pattern: the compute graph consumes
        ParseExample outputs with no batching queue between — interpret
        the parse node itself as the pipeline endpoint."""
        pe = self.by_name[parse_name]
        keys, dtypes, shapes, first_dense = self._dense_spec(pe)
        comps = [(k, dtypes[i] if i < len(dtypes) else np.float32,
                  list(shapes[i]) if i < len(shapes) else [], [])
                 for i, k in enumerate(keys)]
        files = self._serialized_source(pe)
        return self._records(files, comps), comps, first_dense

    # -- the public API ----------------------------------------------------
    def build(self, output_names: Sequence[str], train_consts: bool = True):
        """Return (model, dataset_records, graph_component_indices,
        label_component_indices).

        Input topologies handled (``Session.scala:173-263`` family):
        one dequeue; several dequeues over ONE queue (the stream splits
        round-robin between them — ``handleLocalDequeue``'s split);
        dequeues over DIFFERENT queues (rows zip by index, e.g. a feature
        queue + a label queue); several enqueues into one queue (streams
        union); RandomShuffleQueue (host-side shuffle); and queue-less
        graphs reading ParseExample directly."""
        keep, dequeues, parse_feeds = self._walk_compute(output_names)
        if not dequeues and not parse_feeds:
            raise ValueError("no input pipeline (dequeue or ParseExample) "
                             "feeds the requested outputs")

        # one record stream per endpoint; same-queue dequeues share one
        # stream split round-robin in dequeue order
        streams = []  # (endpoint name, rows, n components, port offset)
        by_queue: Dict[str, List[str]] = {}
        for deq in dequeues:
            qname = self._follow_identity(
                self.by_name[deq]["inputs"][0])["name"]
            by_queue.setdefault(qname, []).append(deq)
        for qname, deqs in by_queue.items():
            records, comps = self._dequeue_records(deqs[0])
            k = len(deqs)
            for j, d in enumerate(deqs):
                rows = records[j::k] if k > 1 else records
                streams.append((d, rows, len(comps), 0))
        for pf in parse_feeds:
            rows, comps, first_dense = self._parse_feed_records(pf)
            streams.append((pf, rows, len(comps), first_dense))

        # zip the streams: every endpoint advances once per sample row
        n_rows = min(len(rows) for _, rows, _, _ in streams)
        col_of = {}  # (endpoint, port) -> combined-row column
        col = 0
        for name, _rows, n_comps, off in streams:
            for p in range(n_comps):
                col_of[(name, off + p)] = col
                col += 1
        combined = [sum((tuple(rows[i]) for _, rows, _, _ in streams), ())
                    for i in range(n_rows)]

        endpoints = {name for name, *_ in streams}

        def rewrite(ref: str) -> str:
            name, port = _split_ref(ref)
            return f"{name}__{port}" if name in endpoints else ref

        used = set()
        compute_nodes = []
        for n in self.nodes:
            if n["name"] not in keep:
                continue
            n2 = dict(n)
            n2["inputs"] = [rewrite(i) for i in n["inputs"]
                            if not i.startswith("^")]
            for i in n["inputs"]:
                if i.startswith("^"):  # control dep: not a data port
                    continue
                nm, port = _split_ref(i)
                if nm in endpoints:
                    used.add((nm, port))
            compute_nodes.append(n2)
        graph_keys = sorted(used, key=lambda kp: col_of[kp])
        graph_ports = [col_of[kp] for kp in graph_keys]
        label_ports = [c for c in range(col) if c not in graph_ports]
        loader = TensorflowLoader(
            compute_nodes, [f"{nm}__{p}" for nm, p in graph_keys],
            list(output_names), train_consts=train_consts)
        return loader.load(), combined, graph_ports, label_ports

    def _compute_closure(self, output_names, deq):
        seen = set()
        stack = [_split_ref(o)[0] for o in output_names]
        while stack:
            name = stack.pop()
            if name in seen or name == deq:
                continue
            seen.add(name)
            node = self.by_name.get(name)
            if node is None:
                continue
            stack.extend(_split_ref(i)[0] for i in node["inputs"])
        return seen

    def train(self, output_names: Sequence[str], criterion, optim_method,
              batch_size: int = 32, end_trigger=None, optimizer_cls=None,
              **optimizer_kwargs):
        """Assemble the pipeline + compute graph and run the Optimizer —
        the whole ``BigDLSessionImpl.train`` flow (``Session.scala:435-461``).
        Returns the trained ``nn.Graph``."""
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset.sample import Sample

        model, records, graph_ports, label_ports = self.build(output_names)
        if len(label_ports) > 1:
            raise NotImplementedError(
                f"more than one non-graph dequeue component: {label_ports}")
        samples = []
        for row in records:
            feats = [row[p] for p in graph_ports]
            labels = [row[p] for p in label_ports] or None
            samples.append(Sample(feats, labels))
        cls = optimizer_cls or optim.Optimizer
        o = cls(model=model, dataset=samples, criterion=criterion,
                batch_size=batch_size,
                end_trigger=end_trigger or optim.Trigger.max_epoch(1),
                **optimizer_kwargs)
        o.set_optim_method(optim_method)
        self.optimizer = o
        return o.optimize()
