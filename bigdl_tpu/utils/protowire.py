"""Minimal protobuf wire-format decoding, shared by the TFRecord Example
parser (``bigdl_tpu.dataset.tfrecord``) and the Caffe model loader
(``bigdl_tpu.utils.caffe``).

The reference ships ~180k lines of protoc-generated Java for its caffe/
tensorflow/serialization schemas (SURVEY §2.1); here the handful of
message shapes actually needed are decoded directly from the wire."""

from __future__ import annotations

import struct
from typing import Iterator, List, Tuple, Union

__all__ = ["read_varint", "fields", "packed_floats", "packed_varints"]


def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def fields(buf: bytes) -> Iterator[Tuple[int, int, Union[int, bytes]]]:
    """Yield (field_number, wire_type, value) over a message buffer.
    Length-delimited and fixed-width values come back as bytes; varints
    as unsigned ints."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            val, pos = read_varint(buf, pos)
        elif wt == 1:
            val = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:
            ln, pos = read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def packed_floats(val: Union[int, bytes], wt: int) -> List[float]:
    """Decode one occurrence of a repeated-float field (packed or not)."""
    if wt == 2:
        return list(struct.unpack(f"<{len(val) // 4}f", val))
    return [struct.unpack("<f", val)[0]]


def packed_varints(val: Union[int, bytes], wt: int) -> List[int]:
    """Decode one occurrence of a repeated-varint field (packed or not),
    folding unsigned wire values back to signed int64."""
    if wt == 2:
        out = []
        pos = 0
        while pos < len(val):
            x, pos = read_varint(val, pos)
            out.append(x)
    else:
        out = [val]
    return [x - (1 << 64) if x >= (1 << 63) else x for x in out]



# ---------------------------------------------------------------------------
# encoding (used by the TF GraphDef exporter)
# ---------------------------------------------------------------------------

def write_varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        out += bytes([b7 | (0x80 if n else 0)])
        if not n:
            return out


def emit_varint(field: int, value: int) -> bytes:
    return write_varint((field << 3) | 0) + write_varint(value)


def emit_bytes(field: int, payload: bytes) -> bytes:
    return write_varint((field << 3) | 2) + write_varint(len(payload)) \
        + payload


def emit_float(field: int, value: float) -> bytes:
    return write_varint((field << 3) | 5) + struct.pack("<f", value)
