"""Byte-blob persistence (``utils/File.scala:25``: save/load to local FS,
HDFS, S3).  TPU-native equivalent: local FS + GCS-style ``gs://`` via
fsspec when available (gated — zero-egress environments fall back to a
clear error), with atomic local writes.

Unlike the reference (Java serialization), this layer moves OPAQUE BYTES
only; object encoding is owned by the safe, versioned BTPU format
(``utils/module_format.py``), so nothing in the IO path can execute code
on load.
"""

from __future__ import annotations

import os
import tempfile

__all__ = ["save", "load", "is_remote", "makedirs", "listdir", "exists",
           "isdir", "remove", "rename", "join"]


def is_remote(path: str) -> bool:
    return "://" in path


def _open(path: str, mode: str):
    if is_remote(path):
        try:
            import fsspec  # type: ignore

            return fsspec.open(path, mode).open()
        except ImportError as e:
            raise RuntimeError(
                f"remote path {path!r} requires fsspec/gcsfs which are not "
                f"installed in this environment") from e
    return open(path, mode)


def save(data: bytes, path: str, overwrite: bool = False):
    """(``File.save``) — atomic for local paths; raw bytes only."""
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise TypeError(
            f"File.save moves bytes, got {type(data).__name__}; encode "
            f"objects with utils.module_format first")
    if not overwrite and _exists(path):
        raise FileExistsError(f"{path} exists and overwrite=False")
    if is_remote(path):
        with _open(path, "wb") as f:
            f.write(data)
        return
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load(path: str) -> bytes:
    with _open(path, "rb") as f:
        return f.read()


def _fs(path: str):
    import fsspec  # type: ignore

    fs, rel = fsspec.core.url_to_fs(path)
    return fs, rel


def makedirs(path: str):
    """Directory creation that also understands remote schemes (object
    stores treat directories as prefixes; mkdirs is a no-op there but
    validates the scheme/credentials early — ``File.scala:67-171``
    resolves the Hadoop FileSystem the same way)."""
    if is_remote(path):
        try:
            fs, rel = _fs(path)
            fs.makedirs(rel, exist_ok=True)
        except ImportError as e:
            raise RuntimeError(
                f"remote path {path!r} requires fsspec which is not "
                f"installed in this environment") from e
        return
    os.makedirs(path, exist_ok=True)


def listdir(path: str):
    """Base names under a local or remote directory ([] when absent)."""
    if is_remote(path):
        try:
            fs, rel = _fs(path)
        except ImportError:
            return []
        if not fs.exists(rel):
            return []
        return [p.rstrip("/").rsplit("/", 1)[-1]
                for p in fs.ls(rel, detail=False)]
    if not os.path.isdir(path):
        return []
    return os.listdir(path)


def _exists(path: str) -> bool:
    if is_remote(path):
        try:
            fs, rel = _fs(path)
            return fs.exists(rel)
        except Exception:
            return False
    return os.path.exists(path)


def exists(path: str) -> bool:
    """Local or remote existence check."""
    return _exists(path)


def isdir(path: str) -> bool:
    """Local or remote directory check (object stores answer by
    prefix)."""
    if is_remote(path):
        try:
            fs, rel = _fs(path)
            return fs.isdir(rel)
        except Exception:
            return False
    return os.path.isdir(path)


def rename(src: str, dst: str):
    """Rename a file or directory tree, local or remote — the
    quarantine half of crash-consistent restore (a torn checkpoint is
    moved aside as ``*.corrupt``, never deleted: it is postmortem
    evidence)."""
    if is_remote(src):
        fs, rel_src = _fs(src)
        _, rel_dst = _fs(dst)
        fs.mv(rel_src, rel_dst, recursive=True)
        return
    os.replace(src, dst) if os.path.isfile(src) else os.rename(src, dst)


def join(path: str, name: str) -> str:
    """Path join that keeps remote URLs intact (``os.path.join`` on a
    ``gs://...`` base works but hand-rolled variants proliferated; ONE
    implementation so save/prune/discovery can't diverge)."""
    if is_remote(path):
        return path.rstrip("/") + "/" + name
    return os.path.join(path, name)


def remove(path: str):
    """Delete a file or directory tree, local or remote — the retention
    half of checkpoint management (the reference leaves old ``model.n``
    files behind forever; pod-scale sharded checkpoints are too large
    for that)."""
    if is_remote(path):
        fs, rel = _fs(path)
        if fs.exists(rel):
            fs.rm(rel, recursive=True)
        return
    if os.path.isdir(path):
        import shutil

        shutil.rmtree(path)
    elif os.path.exists(path):
        os.unlink(path)
