"""Object persistence (``utils/File.scala:25``: save/load to local FS,
HDFS, S3).  TPU-native equivalent: local FS + GCS-style ``gs://`` via
fsspec when available (gated — zero-egress environments fall back to a
clear error), with atomic local writes."""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any

__all__ = ["save", "load", "is_remote"]


def is_remote(path: str) -> bool:
    return "://" in path


def _open(path: str, mode: str):
    if is_remote(path):
        try:
            import fsspec  # type: ignore

            return fsspec.open(path, mode).open()
        except ImportError as e:
            raise RuntimeError(
                f"remote path {path!r} requires fsspec/gcsfs which are not "
                f"installed in this environment") from e
    return open(path, mode)


def save(obj: Any, path: str, overwrite: bool = False):
    """(``File.save``) — atomic for local paths."""
    if not overwrite and _exists(path):
        raise FileExistsError(f"{path} exists and overwrite=False")
    if is_remote(path):
        with _open(path, "wb") as f:
            pickle.dump(obj, f, protocol=pickle.HIGHEST_PROTOCOL)
        return
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(obj, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load(path: str) -> Any:
    with _open(path, "rb") as f:
        return pickle.load(f)


def _exists(path: str) -> bool:
    if is_remote(path):
        return False
    return os.path.exists(path)
