"""Checkpoint content digests — the crash-consistency half the
complete-marker cannot provide.

The meta marker (``bigdl_meta.json`` / ``ckptmeta.N.json``) proves a
checkpoint write *finished*; it says nothing about whether the payload
bytes on disk are the bytes that were written (a torn shard under a
hard kill, a bit flip on a flaky disk, a partially-synced object-store
blob).  This module computes per-file SHA-256 digests at save time,
recorded inside the meta marker, and verifies them at restore time —
so a restore either loads a byte-identical checkpoint or rejects it
BEFORE any state is touched (``utils/sharded_ckpt.py`` and the
Optimizer's BTPU path both quarantine on rejection and fall back to the
previous good step; docs/fault_tolerance.md).

All functions speak ``utils.file`` so local and remote (``gs://``)
checkpoints verify the same way.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List

from bigdl_tpu.utils import file as File

__all__ = ["digest_bytes", "digest_file", "digest_dir", "verify_digests"]

_CHUNK = 1 << 20


def digest_bytes(data: bytes) -> str:
    return "sha256:" + hashlib.sha256(data).hexdigest()


def digest_file(path: str) -> str:
    h = hashlib.sha256()
    with File._open(path, "rb") as fh:
        while True:
            chunk = fh.read(_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    return "sha256:" + h.hexdigest()


def _walk(root: str, prefix: str = "") -> List[str]:
    """Relative paths of every file under ``root`` (local or remote)."""
    out: List[str] = []
    for name in sorted(File.listdir(root)):
        p = File.join(root, name)
        rel = f"{prefix}{name}"
        if File.isdir(p):
            out.extend(_walk(p, rel + "/"))
        else:
            out.append(rel)
    return out


def digest_dir(root: str, exclude=()) -> Dict[str, str]:
    """``{relative path: digest}`` for every file under ``root``,
    skipping ``exclude`` basenames (the meta marker digests everything
    but itself)."""
    digests: Dict[str, str] = {}
    for rel in _walk(root):
        base = rel.rsplit("/", 1)[-1]
        if base in exclude:
            continue
        digests[rel] = digest_file(File.join(root, rel))
    return digests


def verify_digests(root: str, digests: Dict[str, str]) -> List[str]:
    """Compare the files under ``root`` against recorded ``digests``;
    returns human-readable problems (empty = verified).  Extra files are
    tolerated (orbax writes backend-private metadata alongside shards);
    missing or content-changed files are not."""
    problems: List[str] = []
    for rel, want in sorted(digests.items()):
        p = File.join(root, rel)
        if not File.exists(p):
            problems.append(f"missing file {rel}")
            continue
        try:
            got = digest_file(p)
        except OSError as e:
            problems.append(f"unreadable file {rel} ({e})")
            continue
        if got != want:
            problems.append(f"digest mismatch on {rel} "
                            f"(want {want[:23]}…, got {got[:23]}…)")
    return problems
