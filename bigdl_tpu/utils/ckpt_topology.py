"""Topology-portable checkpoint metadata (docs/fault_tolerance.md
"Elastic recovery").

PR 7 made the cluster survive failures, but a checkpoint still restored
only onto the exact process count / mesh shape that wrote it, so
preemption recovery could only wait for the identical slice to come
back.  Real fleets shrink and grow (DeepSpark, arXiv 1602.08191, treats
membership change as the normal case), and ZeRO-style sharded optimizer
state (arXiv 2004.13336) makes same-shape-only restore actively
dangerous: every moment tensor is a 1/N shard, and a silent
fall-back-to-replicated restore multiplies per-device HBM by N.

This module is the shared topology record both checkpoint backends
write and verify:

- :func:`topology_of` — the writing run's mesh (axis names + sizes),
  process/device counts, parameter-sync mode, and one record per state
  leaf (global shape, dtype, ``PartitionSpec``).  Stored in
  ``bigdl_meta.json`` (sharded backend) / ``ckptmeta.N.json`` (BTPU)
  and covered by its own digest (:func:`digest`) so a mangled topology
  record fails integrity verification exactly like a torn payload.
- :func:`reshardable_onto` — the pre-load POLICY check: a checkpoint
  restores onto any mesh where every recorded-sharded leaf can keep a
  sharded placement (the target mesh carries the writing axes and each
  sharded dimension divides by the target axis size).  Meshes of size
  <= 1 are exempt — a single device holds the whole state by
  definition (the gather-restore path).  Violations raise
  :class:`TopologyMismatchError` *before any state is touched* — the
  alternative is a silently-replicated ZeRO restore whose memory
  contract is N× the writing run's.
- :func:`check_target` — the full pre-load validation run by
  ``sharded_ckpt.restore_train_step``: leaf-set / global-shape / dtype
  equality against the live target tree, then the reshardability check.
- :func:`restorable_mesh_sizes` — the widths a checkpoint can restore
  onto (divisors of the gcd of every sharded dimension), printed by the
  ``cli train`` preemption resume hint and the supervisor recipe.

The actual data movement needs no new machinery: the sharded backend's
orbax restore is driven by the TARGET shardings (each process reads the
slices it needs off shared storage — gather-then-re-place), and BTPU
checkpoints are gathered whole-model files, portable by construction.
What this module adds is the contract: record the writing topology,
validate the restore topology loudly, and announce an accepted reshard
(``cluster/reshard`` instant) so the fleet view knows the membership
legitimately changed.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.utils import ckpt_digest

__all__ = ["TopologyMismatchError", "topology_of", "digest",
           "verify_digest", "reshardable_onto", "check_target",
           "differs_from_live", "restorable_mesh_sizes", "describe",
           "leaf_records", "declared_width", "reshard_fields"]

FORMAT = 1


class TopologyMismatchError(RuntimeError):
    """A checkpoint's recorded topology cannot restore onto the live
    mesh (shape/dtype/leaf-set mismatch, missing mesh axis, or a
    ZeRO-sharded leaf that cannot re-shard at the requested width).
    Raised BEFORE any state is touched — the sibling of
    ``CorruptCheckpointError`` for topology rather than integrity."""


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------

def _path_str(key_path) -> str:
    parts = []
    for k in key_path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def _spec_of(arr) -> Optional[List[Any]]:
    """JSON-able PartitionSpec of a jax array under a NamedSharding:
    one entry per dim — None | axis name | [axis names].  None for
    replicated/unsharded/host arrays."""
    sharding = getattr(arr, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    out: List[Any] = []
    for entry in tuple(spec):
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append([str(a) for a in entry])
        else:
            out.append(str(entry))
    # trailing Nones carry no information; an all-None spec is replicated
    while out and out[-1] is None:
        out.pop()
    return out or None


def leaf_records(tree) -> Dict[str, Dict[str, Any]]:
    """``path -> {shape, dtype[, spec]}`` over a state pytree, the same
    scalar normalization the sharded writer applies (``_sanitize``:
    python/np scalars become 0-d arrays)."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out: Dict[str, Dict[str, Any]] = {}
    for key_path, leaf in flat:
        a = leaf if isinstance(leaf, jax.Array) else np.asarray(leaf)
        rec: Dict[str, Any] = {"shape": [int(s) for s in a.shape],
                               "dtype": np.dtype(a.dtype).name}
        spec = _spec_of(leaf)
        if spec:
            rec["spec"] = spec
        out[_path_str(key_path)] = rec
    return out


def _mesh_axes(mesh) -> Dict[str, int]:
    if mesh is None:
        return {}
    return {str(name): int(mesh.shape[name]) for name in mesh.axis_names}


def _live_process_count() -> int:
    try:
        from bigdl_tpu.utils.engine import Engine

        return int(Engine.process_count())
    except Exception:  # noqa: BLE001 - engine not initialized
        return 1


def topology_of(step) -> Dict[str, Any]:
    """The writing run's topology record for a TrainStep-shaped object
    (``params``/``opt_state``/``buffers`` + ``mesh``): what a restore
    needs to decide — loudly, pre-load — whether a different mesh can
    take this checkpoint."""
    mesh = getattr(step, "mesh", None)
    tree = {"params": step.params, "opt_state": step.opt_state,
            "buffers": step.buffers}
    return {"format": FORMAT,
            "process_count": _live_process_count(),
            "device_count": int(mesh.devices.size) if mesh is not None
            else 1,
            "mesh": _mesh_axes(mesh),
            "parameter_sync": getattr(step, "parameter_sync", None),
            "leaves": leaf_records(tree)}


def digest(topo: Dict[str, Any]) -> str:
    """Content digest of the canonical JSON of a topology record — the
    meta marker carries it so a mangled topology fails integrity
    verification like a torn payload (the PR-5 discipline applied to
    the record that gates resharding decisions)."""
    blob = json.dumps(topo, sort_keys=True, separators=(",", ":"))
    return ckpt_digest.digest_bytes(blob.encode())


def verify_digest(meta: Dict[str, Any]) -> List[str]:
    """Problems with a meta marker's topology record (empty = fine or
    absent — pre-topology checkpoints stay restorable)."""
    topo = meta.get("topology")
    want = meta.get("topology_digest")
    if topo is None and want is None:
        return []
    if topo is None or want is None:
        return ["topology record and its digest must travel together"]
    got = digest(topo)
    if got != want:
        return [f"topology record digest mismatch (recorded {want}, "
                f"computed {got})"]
    return []


# ---------------------------------------------------------------------------
# restore-side validation
# ---------------------------------------------------------------------------

def _axis_product(axes, sizes: Dict[str, int]) -> Optional[int]:
    n = 1
    for a in axes:
        if a not in sizes:
            return None
        n *= int(sizes[a])
    return n


def reshardable_onto(topo: Dict[str, Any], mesh) -> Tuple[bool, List[str]]:
    """Whether the recorded topology can restore onto ``mesh`` without
    changing the sharded-memory contract.  Rule: every recorded-sharded
    leaf must keep a sharded placement — the target mesh carries the
    writing axes and each sharded dimension divides by the target axis
    size.  Meshes of size <= 1 (or None) are exempt: one device holds
    the whole state by definition."""
    if mesh is None or int(mesh.devices.size) <= 1:
        return True, []
    sizes = _mesh_axes(mesh)
    problems: List[str] = []
    for path, rec in sorted((topo.get("leaves") or {}).items()):
        spec = rec.get("spec")
        if not spec:
            continue
        shape = rec.get("shape") or []
        for d, entry in enumerate(spec):
            if entry is None:
                continue
            axes = entry if isinstance(entry, list) else [entry]
            n = _axis_product(axes, sizes)
            if n is None:
                problems.append(
                    f"{path}: dim {d} was sharded over axis "
                    f"{'x'.join(axes)!r} which the restore mesh lacks "
                    f"(axes {sorted(sizes)})")
                continue
            if n > 1 and (d >= len(shape) or shape[d] % n != 0):
                dim = shape[d] if d < len(shape) else "?"
                problems.append(
                    f"{path}: shape {shape} dim {d} ({dim}) was sharded "
                    f"over {'x'.join(axes)} "
                    f"(size {topo.get('mesh', {}).get(axes[0], '?')}) and "
                    f"cannot re-shard at size {n} — restoring here would "
                    f"silently replicate a ZeRO shard (N× the writing "
                    f"run's per-device memory); pick a width dividing "
                    f"{dim}")
    return not problems, problems


def check_target(topo: Dict[str, Any], target_tree, mesh) -> None:
    """Full pre-load validation of a restore: the recorded leaf set must
    match the live target (global shapes and dtypes included — a
    checkpoint cannot be resharded onto a *different model*), and the
    target mesh must pass :func:`reshardable_onto`.  Raises
    :class:`TopologyMismatchError` listing every problem; on success the
    restore is a pure re-placement of bit-identical global arrays."""
    recorded = topo.get("leaves") or {}
    got = leaf_records(target_tree)
    problems: List[str] = []
    for path in sorted(set(recorded) - set(got)):
        problems.append(f"checkpoint leaf {path} missing from the "
                        f"restore target")
    for path in sorted(set(got) - set(recorded)):
        problems.append(f"restore target leaf {path} absent from the "
                        f"checkpoint")
    multi_device = mesh is not None and int(mesh.devices.size) > 1
    for path in sorted(set(recorded) & set(got)):
        r, g = recorded[path], got[path]
        if list(r.get("shape") or []) != g["shape"]:
            problems.append(f"{path}: checkpoint shape {r.get('shape')} "
                            f"!= target shape {g['shape']}")
        elif r.get("dtype") != g["dtype"]:
            problems.append(f"{path}: checkpoint dtype {r.get('dtype')} "
                            f"!= target dtype {g['dtype']}")
        elif r.get("spec") and multi_device and not g.get("spec"):
            # a leaf the writer SHARDED landing replicated in the
            # target is the silent N×-memory restore this gate exists
            # to prevent — typically a parameter_sync mismatch (ZeRO
            # checkpoint, allreduce restore).  Single-device targets
            # are exempt (the gather path holds everything anyway).
            problems.append(
                f"{path}: was sharded {r['spec']} at write but the "
                f"restore target places it REPLICATED — restoring "
                f"would multiply per-device memory by the writing "
                f"shard count (parameter_sync mismatch? checkpoint "
                f"says {topo.get('parameter_sync')!r}); restore with "
                f"a sharded layout or onto a single device")
    ok, reshard_problems = reshardable_onto(topo, mesh)
    problems.extend(reshard_problems)
    if problems:
        raise TopologyMismatchError(
            "checkpoint topology cannot restore onto this mesh: "
            + "; ".join(problems))


def differs_from_live(topo: Dict[str, Any], mesh) -> bool:
    """Whether restoring this checkpoint here is a RESHARD (announced
    as a ``cluster/reshard`` instant) rather than a same-topology
    restore."""
    live_devices = int(mesh.devices.size) if mesh is not None else 1
    if int(topo.get("device_count") or 1) != live_devices:
        return True
    if int(topo.get("process_count") or 1) != _live_process_count():
        return True
    return _mesh_axes(mesh) != {k: int(v) for k, v
                                in (topo.get("mesh") or {}).items()}


def reshard_fields(topo: Dict[str, Any], mesh, source: str,
                   **extra) -> Optional[Dict[str, Any]]:
    """The ``cluster/reshard`` instant fields for restoring ``topo``
    onto ``mesh`` — one construction shared by both checkpoint
    backends so the emitted schema cannot drift.  None when the
    topologies match (no reshard to announce); otherwise logs the
    restore-in-progress line and returns old→new process/device
    counts + meshes, ``declared_n`` when the supervisor exported it,
    and any caller ``extra`` (step, path).  The CALLER logs (on its
    own wired logger) and emits the instant — the sharded backend only
    after the restore actually lands."""
    if not differs_from_live(topo, mesh):
        return None
    live_procs = _live_process_count()
    live_devs = int(mesh.devices.size) if mesh is not None else 1
    fields: Dict[str, Any] = dict(
        source=source,
        from_processes=int(topo.get("process_count") or 1),
        to_processes=live_procs,
        from_devices=int(topo.get("device_count") or 1),
        to_devices=live_devs,
        from_mesh={k: int(v) for k, v in (topo.get("mesh") or {}).items()},
        to_mesh=_mesh_axes(mesh), **extra)
    declared = declared_width()
    if declared:
        fields["declared_n"] = declared
    return fields


def declared_width() -> Optional[int]:
    """The supervisor-declared full width, exported into every
    supervised worker as ``BIGDL_SUPERVISOR_DECLARED_N`` — restore-path
    ``cluster/reshard`` instants carry it so the fleet view can report
    current vs declared without depending on the old-width run logs
    surviving rotation."""
    v = os.environ.get("BIGDL_SUPERVISOR_DECLARED_N")
    try:
        return int(v) if v else None
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# operator-facing summaries (resume hint, supervise recipe)
# ---------------------------------------------------------------------------

def restorable_mesh_sizes(topo: Dict[str, Any]) -> Optional[List[int]]:
    """Widths (data-axis mesh sizes) this checkpoint can restore onto
    under the :func:`reshardable_onto` rule: divisors of the gcd of
    every sharded dimension (1 always qualifies — the gather-restore
    path).  ``None`` = no sharded leaves recorded, any width works."""
    g = 0
    for rec in (topo.get("leaves") or {}).values():
        spec = rec.get("spec")
        if not spec:
            continue
        shape = rec.get("shape") or []
        for d, entry in enumerate(spec):
            if entry is not None and d < len(shape):
                g = math.gcd(g, int(shape[d]))
    if g == 0:
        return None
    # O(sqrt(g)) divisor walk: g can be a multi-million-element shard
    # dim and this runs on the restore/preemption hot path (describe)
    out = set()
    for i in range(1, math.isqrt(g) + 1):
        if g % i == 0:
            out.add(i)
            out.add(g // i)
    return sorted(out)


def describe(topo: Dict[str, Any]) -> str:
    """One-line human summary for logs and the resume hint."""
    mesh = topo.get("mesh") or {}
    mesh_s = ",".join(f"{k}={v}" for k, v in mesh.items()) or "single-device"
    sizes = restorable_mesh_sizes(topo)
    onto = ("any width (no sharded state)" if sizes is None
            else f"mesh sizes {{{','.join(str(s) for s in sizes)}}}")
    return (f"written by {topo.get('process_count', 1)} process(es) on "
            f"{topo.get('device_count', 1)} device(s) ({mesh_s}, "
            f"sync={topo.get('parameter_sync')}); restores onto {onto}")
