"""Module / optim-method persistence (SURVEY §2.9).

The reference has two formats: Java serialization (default checkpoints,
``AbstractModule.save`` / ``Module.load``) and a versioned protobuf module
format (``utils/serializer/*.scala`` + ``bigdl.proto``).  Here:

- **Checkpoint format** (this module): the full module object is pickled
  with every device array converted to numpy — host-portable, no device
  state, loadable without model code changes.  Optim methods likewise.
- **Structured format**: ``save_state_dict``/``load_state_dict_file``
  persist only ``{path: array}`` (npz), the analogue of weight-only
  protobuf round-trips, usable across re-implementations of a model.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Dict

import numpy as np

from bigdl_tpu.utils import file as File

__all__ = [
    "save_module", "load_module", "save_optim_method", "load_optim_method",
    "save_state_dict", "load_state_dict_file",
]


def _to_numpy_tree(obj):
    import jax

    def conv(x):
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x

    return jax.tree.map(conv, obj)


class _NumpyfyingPickler(pickle.Pickler):
    def persistent_id(self, obj):
        return None

    def reducer_override(self, obj):  # numpy-ify jax arrays on the fly
        import jax

        if isinstance(obj, jax.Array):
            return (np.asarray, (np.asarray(obj),))
        return NotImplemented


def _dumps(obj) -> bytes:
    buf = io.BytesIO()
    _NumpyfyingPickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(obj)
    return buf.getvalue()


def save_module(module, path: str, overwrite: bool = False):
    File.save(_dumps(module), path, overwrite)


def load_module(path: str):
    blob = File.load(path)
    module = pickle.loads(blob)
    _rehydrate(module)
    return module


def _rehydrate(module):
    """numpy arrays -> jnp on first use happens lazily via jnp.asarray in
    forward paths; convert eagerly for params/buffers so dtypes are exact."""
    import jax.numpy as jnp

    from bigdl_tpu.nn.module import Module

    if not isinstance(module, Module):
        return
    for m in module.modules():
        for table in ("_params", "_buffers"):
            t = m.__dict__.get(table, {})
            for k, v in list(t.items()):
                t[k] = jnp.asarray(v)


def save_optim_method(method, path: str, overwrite: bool = False):
    File.save(_dumps(method), path, overwrite)


def load_optim_method(path: str):
    return pickle.loads(File.load(path))


def save_state_dict(state: Dict[str, Any], path: str, overwrite: bool = False):
    import os

    if not overwrite and os.path.exists(path):
        raise FileExistsError(path)
    np.savez(path, **{k: np.asarray(v) for k, v in state.items()})


def load_state_dict_file(path: str) -> Dict[str, np.ndarray]:
    with np.load(path) as z:
        return {k: z[k] for k in z.files}
