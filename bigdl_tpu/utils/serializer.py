"""Module / optim-method persistence (SURVEY §2.9).

The reference has two formats: Java serialization (default checkpoints,
``AbstractModule.save`` / ``Module.load``) and a versioned protobuf module
format (``utils/serializer/*.scala`` + ``bigdl.proto``).  Here ONE format
serves both roles: **BTPU** (``utils/module_format.py``) — a versioned,
registry-driven, no-code-execution-on-load encoding (wire framing via
``utils/protowire``, class names resolved against the framework's own
registry, raw little-endian tensors).  Unknown versions and classes are
rejected cleanly; pickle is not used anywhere.

``save_state_dict``/``load_state_dict_file`` additionally persist bare
``{path: array}`` maps (npz) for weight-only interchange.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from bigdl_tpu.utils import file as File
from bigdl_tpu.utils.module_format import (SerializationError, dumps, loads,
                                           register)

__all__ = [
    "save_module", "load_module", "save_optim_method", "load_optim_method",
    "save_state_dict", "load_state_dict_file", "SerializationError",
    "register",
]


def save_module(module, path: str, overwrite: bool = False):
    File.save(dumps(module, kind="module"), path, overwrite)


def load_module(path: str):
    module = loads(File.load(path), kind="module")
    _rehydrate(module)
    return module


def _rehydrate(module):
    """Params/buffers come back as numpy; convert eagerly to jnp so
    dtypes are exact before the first forward."""
    import jax.numpy as jnp

    from bigdl_tpu.nn.module import Module

    if not isinstance(module, Module):
        return
    for m in module.modules():
        for table in ("_params", "_buffers"):
            t = m.__dict__.get(table, {})
            for k, v in list(t.items()):
                t[k] = jnp.asarray(v)


def save_optim_method(method, path: str, overwrite: bool = False):
    File.save(dumps(method, kind="optim"), path, overwrite)


def load_optim_method(path: str):
    return loads(File.load(path), kind="optim")


def save_state_dict(state: Dict[str, Any], path: str, overwrite: bool = False):
    import os

    if not overwrite and os.path.exists(path):
        raise FileExistsError(path)
    np.savez(path, **{k: np.asarray(v) for k, v in state.items()})


def load_state_dict_file(path: str) -> Dict[str, np.ndarray]:
    with np.load(path) as z:
        return {k: z[k] for k in z.files}
