"""Torch7 ``.t7`` binary serialization — read/write WITHOUT any Torch
installation (the reference capability: ``utils/TorchFile.scala:67``,
SURVEY §2.2).  Complements ``utils/torch_interop.py`` (live-PyTorch
conversion): this module speaks the *file format* itself.

The format (public, defined by torch7's ``File:writeObject``): a stream of
little-endian records, each ``int32 type-tag`` + payload:

====  =========  ====================================================
tag   kind       payload
====  =========  ====================================================
0     nil        —
1     number     float64
2     string     int32 length + bytes
3     table      int32 memo-index, then int32 n + n (key, value) pairs
4     torch obj  int32 memo-index, then version string ``V 1`` +
                 class-name string (legacy files omit the version), then
                 class-specific payload
5     boolean    int32 0/1
====  =========  ====================================================

Torch classes handled natively: ``torch.{Float,Double,Long,Byte,Int}Tensor``
(int32 ndim, int64 sizes, int64 strides, int64 1-based storage offset,
then the storage object) and their Storages (int64 count + raw elements).
``nn.*`` classes are converted to/from bigdl_tpu modules by the table at
the bottom; unknown classes load as :class:`TorchObject` so callers can
inspect them.

Memo indices are shared between tables and torch objects; re-references
resolve to the same Python object (shared storages round-trip)."""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["load_torch", "save_torch", "TorchObject", "TorchTensor"]

TYPE_NIL, TYPE_NUMBER, TYPE_STRING, TYPE_TABLE, TYPE_TORCH, TYPE_BOOLEAN = \
    0, 1, 2, 3, 4, 5

_STORAGE_DTYPES = {
    "torch.FloatStorage": np.float32,
    "torch.DoubleStorage": np.float64,
    "torch.LongStorage": np.int64,
    "torch.IntStorage": np.int32,
    "torch.ByteStorage": np.uint8,
    "torch.CharStorage": np.int8,
    "torch.ShortStorage": np.int16,
}
_TENSOR_TO_STORAGE = {
    "torch.FloatTensor": "torch.FloatStorage",
    "torch.DoubleTensor": "torch.DoubleStorage",
    "torch.LongTensor": "torch.LongStorage",
    "torch.IntTensor": "torch.IntStorage",
    "torch.ByteTensor": "torch.ByteStorage",
    "torch.CharTensor": "torch.CharStorage",
    "torch.ShortTensor": "torch.ShortStorage",
}
_DTYPE_TO_TENSOR = {
    np.dtype(np.float32): "torch.FloatTensor",
    np.dtype(np.float64): "torch.DoubleTensor",
    np.dtype(np.int64): "torch.LongTensor",
    np.dtype(np.int32): "torch.IntTensor",
    np.dtype(np.uint8): "torch.ByteTensor",
    np.dtype(np.int8): "torch.CharTensor",
    np.dtype(np.int16): "torch.ShortTensor",
}


class TorchObject:
    """An unconverted ``torch.*``/``nn.*`` object: class name + field
    table (or raw payload for unknown storages)."""

    def __init__(self, torch_class: str, table: Optional[Dict] = None):
        self.torch_class = torch_class
        self.table = table if table is not None else {}

    def __repr__(self):
        return f"TorchObject({self.torch_class}, {list(self.table)})"


class TorchTensor:
    """A strided view over a (possibly shared) storage; ``array`` gives
    the dense ndarray."""

    def __init__(self, storage: Optional[np.ndarray], sizes, strides,
                 offset: int):
        self.storage, self.offset = storage, offset  # offset is 0-based
        self.sizes, self.strides = tuple(sizes), tuple(strides)

    @property
    def array(self) -> np.ndarray:
        if self.storage is None or not self.sizes:
            return np.zeros((0,), np.float32)
        itemsize = self.storage.dtype.itemsize
        return np.lib.stride_tricks.as_strided(
            self.storage[self.offset:],
            self.sizes, [s * itemsize for s in self.strides]).copy()


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------

class _Reader:
    def __init__(self, buf: bytes, convert_modules: bool):
        self.buf, self.pos = buf, 0
        self.memo: Dict[int, Any] = {}
        self.convert = convert_modules

    def _take(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise EOFError("truncated .t7 file")
        self.pos += n
        return b

    def _i32(self) -> int:
        return struct.unpack("<i", self._take(4))[0]

    def _i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def _f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def _string(self) -> str:
        return self._take(self._i32()).decode("utf-8", "replace")

    def read(self) -> Any:
        tag = self._i32()
        if tag == TYPE_NIL:
            return None
        if tag == TYPE_NUMBER:
            v = self._f64()
            return int(v) if v.is_integer() else v
        if tag == TYPE_STRING:
            return self._string()
        if tag == TYPE_BOOLEAN:
            return self._i32() != 0
        if tag == TYPE_TABLE:
            return self._read_table()
        if tag == TYPE_TORCH:
            return self._read_torch()
        raise ValueError(f".t7 parse error: unknown type tag {tag}")

    def _read_table(self):
        idx = self._i32()
        if idx in self.memo:
            return self.memo[idx]
        table: Dict = {}
        self.memo[idx] = table
        n = self._i32()
        for _ in range(n):
            k = self.read()
            table[k] = self.read()
        return table

    def _read_torch(self):
        idx = self._i32()
        if idx in self.memo:
            return self.memo[idx]
        # version + class name are RAW strings (length + bytes, untagged)
        version = self._string()
        if version.startswith("V "):
            cls = self._string()
        else:
            cls = version  # legacy: no version record
        if cls in _TENSOR_TO_STORAGE:
            nd = self._i32()
            sizes = [self._i64() for _ in range(nd)]
            strides = [self._i64() for _ in range(nd)]
            offset = self._i64() - 1
            tensor = TorchTensor(None, sizes, strides, max(offset, 0))
            self.memo[idx] = tensor
            storage = self.read()
            tensor.storage = storage
            return tensor
        if cls in _STORAGE_DTYPES:
            dt = np.dtype(_STORAGE_DTYPES[cls]).newbyteorder("<")
            n = self._i64()
            arr = np.frombuffer(self._take(n * dt.itemsize), dt).astype(
                _STORAGE_DTYPES[cls])
            self.memo[idx] = arr
            return arr
        obj = TorchObject(cls)
        self.memo[idx] = obj
        payload = self.read()
        obj.table = payload if isinstance(payload, dict) else {"_": payload}
        if self.convert and cls.startswith("nn."):
            converted = _to_module(obj)
            if converted is not None:
                self.memo[idx] = converted
                return converted
        return obj


# ---------------------------------------------------------------------------
# writing
# ---------------------------------------------------------------------------

class _Writer:
    def __init__(self):
        self.out: List[bytes] = []
        self.memo: Dict[int, int] = {}   # id(obj) -> index
        self.keep: List[Any] = []        # prevent id reuse under gc
        self.next_index = 1

    def _i32(self, v: int):
        self.out.append(struct.pack("<i", v))

    def _i64(self, v: int):
        self.out.append(struct.pack("<q", v))

    def _string(self, s: str):
        b = s.encode()
        self._i32(len(b))
        self.out.append(b)

    def _memoize(self, obj) -> Optional[int]:
        """Returns the existing index (and writes it) or None if new."""
        key = id(obj)
        if key in self.memo:
            self._i32(self.memo[key])
            return self.memo[key]
        self.memo[key] = self.next_index
        self.keep.append(obj)
        self._i32(self.next_index)
        self.next_index += 1
        return None

    def write(self, obj: Any):
        import jax

        if obj is None:
            self._i32(TYPE_NIL)
        elif isinstance(obj, bool):
            self._i32(TYPE_BOOLEAN)
            self._i32(1 if obj else 0)
        elif isinstance(obj, (int, float)):
            self._i32(TYPE_NUMBER)
            self.out.append(struct.pack("<d", float(obj)))
        elif isinstance(obj, str):
            self._i32(TYPE_STRING)
            self._string(obj)
        elif isinstance(obj, (np.ndarray, jax.Array, TorchTensor)):
            self._write_tensor(obj)
        elif isinstance(obj, dict):
            self._i32(TYPE_TABLE)
            if self._memoize(obj) is None:
                self._i32(len(obj))
                for k, v in obj.items():
                    self.write(k)
                    self.write(v)
        elif isinstance(obj, (list, tuple)):
            # Lua array-table: 1-based integer keys
            self._i32(TYPE_TABLE)
            if self._memoize(obj) is None:
                self._i32(len(obj))
                for i, v in enumerate(obj):
                    self.write(i + 1)
                    self.write(v)
        elif isinstance(obj, TorchObject):
            self._i32(TYPE_TORCH)
            if self._memoize(obj) is None:
                self._string("V 1")
                self._string(obj.torch_class)
                self.write(obj.table)
        else:
            module = _from_module(obj)
            if module is None:
                raise TypeError(f"cannot serialize {type(obj).__name__} "
                                "to .t7")
            self.write(module)

    def _write_tensor(self, obj):
        if isinstance(obj, TorchTensor):
            arr = obj.array
        else:
            arr = np.asarray(obj)
        if arr.dtype == np.float16 or str(arr.dtype) == "bfloat16":
            arr = arr.astype(np.float32)
        if arr.dtype not in _DTYPE_TO_TENSOR:
            arr = arr.astype(np.float32)
        cls = _DTYPE_TO_TENSOR[arr.dtype]
        self._i32(TYPE_TORCH)
        if self._memoize(obj) is not None:
            return
        self._string("V 1")
        self._string(cls)
        arr = np.ascontiguousarray(arr)
        self._i32(arr.ndim)
        for s in arr.shape:
            self._i64(s)
        strides = [int(s // arr.itemsize) for s in arr.strides]
        for s in strides:
            self._i64(s)
        self._i64(1)  # storage offset, 1-based
        # the storage object
        self._i32(TYPE_TORCH)
        storage_key = object()  # storages are written per-tensor
        if self._memoize(storage_key) is None:
            self._string("V 1")
            self._string(_TENSOR_TO_STORAGE[cls])
            self._i64(arr.size)
            self.out.append(arr.tobytes())


# ---------------------------------------------------------------------------
# nn.* <-> bigdl_tpu module conversion
# ---------------------------------------------------------------------------

def _arr(v) -> Optional[np.ndarray]:
    if isinstance(v, TorchTensor):
        return v.array
    if isinstance(v, np.ndarray):
        return v
    return None


def _to_module(obj: TorchObject):
    """nn.<Class> table -> bigdl_tpu module, or None when unknown."""
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn

    t = obj.table
    cls = obj.torch_class.split(".", 1)[1]

    def modules():
        mods = t.get("modules", {})
        items = sorted(((k, v) for k, v in mods.items()
                        if isinstance(k, int)), key=lambda kv: kv[0])
        return [v for _, v in items]

    if cls == "Sequential":
        seq = nn.Sequential()
        for m in modules():
            seq.add(m)
        return seq
    if cls == "Concat":
        c = nn.Concat(int(t.get("dimension", 2)) - 1)
        for m in modules():
            c.add(m)
        return c
    if cls == "ConcatTable":
        c = nn.ConcatTable()
        for m in modules():
            c.add(m)
        return c
    if cls == "CAddTable":
        return nn.CAddTable()
    if cls == "JoinTable":
        return nn.JoinTable(int(t.get("dimension", 2)) - 1, 0)
    if cls == "Linear":
        w, b = _arr(t.get("weight")), _arr(t.get("bias"))
        m = nn.Linear(w.shape[1], w.shape[0], with_bias=b is not None)
        m.weight = jnp.asarray(w, jnp.float32)
        if b is not None:
            m.bias = jnp.asarray(b, jnp.float32)
        return m
    if cls == "SpatialConvolution":
        kw, kh = int(t["kW"]), int(t["kH"])
        groups = int(t.get("groups", 1))
        m = nn.SpatialConvolution(
            int(t["nInputPlane"]), int(t["nOutputPlane"]), kw, kh,
            int(t.get("dW", 1)), int(t.get("dH", 1)),
            int(t.get("padW", 0)), int(t.get("padH", 0)), n_group=groups)
        w = _arr(t.get("weight"))
        m.weight = jnp.asarray(
            w.reshape(m.n_output_plane, m.n_input_plane // groups, kh, kw),
            jnp.float32)
        b = _arr(t.get("bias"))
        if b is not None:
            m.bias = jnp.asarray(b, jnp.float32)
        return m
    if cls == "SpatialMaxPooling":
        m = nn.SpatialMaxPooling(
            int(t["kW"]), int(t["kH"]), int(t.get("dW", 1)),
            int(t.get("dH", 1)), int(t.get("padW", 0)), int(t.get("padH", 0)))
        if t.get("ceil_mode"):
            m.ceil()
        return m
    if cls == "SpatialAveragePooling":
        return nn.SpatialAveragePooling(
            int(t["kW"]), int(t["kH"]), int(t.get("dW", 1)),
            int(t.get("dH", 1)), int(t.get("padW", 0)), int(t.get("padH", 0)),
            ceil_mode=bool(t.get("ceil_mode", False)),
            count_include_pad=not bool(t.get("count_include_pad") is False))
    if cls == "ReLU":
        return nn.ReLU(bool(t.get("inplace", False)))
    if cls == "Tanh":
        return nn.Tanh()
    if cls == "Sigmoid":
        return nn.Sigmoid()
    if cls == "SoftMax":
        return nn.SoftMax()
    if cls == "LogSoftMax":
        return nn.LogSoftMax()
    if cls == "Dropout":
        return nn.Dropout(float(t.get("p", 0.5)))
    if cls == "InferReshape":
        size = t.get("size")
        dims = list(size.array if isinstance(size, TorchTensor)
                    else np.asarray(size).ravel())
        return nn.InferReshape([int(d) for d in dims],
                               bool(t.get("batchMode", False)))
    if cls == "Reshape":
        size = t.get("size")
        dims = list(size.array if isinstance(size, TorchTensor)
                    else np.asarray(size).ravel())
        return nn.Reshape([int(d) for d in dims])
    if cls == "View":
        size = t.get("size")
        dims = list(size.array if isinstance(size, TorchTensor)
                    else np.asarray(size).ravel())
        return nn.View(*[int(d) for d in dims])
    if cls in ("SpatialBatchNormalization", "BatchNormalization"):
        w, b = _arr(t.get("weight")), _arr(t.get("bias"))
        n = int(t.get("nOutput", len(w) if w is not None
                      else len(_arr(t["running_mean"]))))
        ctor = nn.SpatialBatchNormalization \
            if cls == "SpatialBatchNormalization" else nn.BatchNormalization
        m = ctor(n, eps=float(t.get("eps", 1e-5)),
                 momentum=float(t.get("momentum", 0.1)),
                 affine=w is not None)
        if w is not None:
            m.weight = jnp.asarray(w, jnp.float32)
            m.bias = jnp.asarray(b, jnp.float32)
        rm, rv = _arr(t.get("running_mean")), _arr(t.get("running_var"))
        if rm is not None:
            m.running_mean = jnp.asarray(rm, jnp.float32)
        if rv is not None:
            m.running_var = jnp.asarray(rv, jnp.float32)
        return m
    if cls == "SpatialCrossMapLRN":
        return nn.SpatialCrossMapLRN(
            int(t.get("size", 5)), float(t.get("alpha", 1.0)),
            float(t.get("beta", 0.75)), float(t.get("k", 1.0)))
    return None


def _from_module(m) -> Optional[TorchObject]:
    """bigdl_tpu module -> nn.<Class> TorchObject, or None."""
    import bigdl_tpu.nn as nn

    def mods(children):
        return {"modules": {i + 1: c for i, c in enumerate(children)},
                "train": bool(m.training)}

    if isinstance(m, nn.Concat):
        return TorchObject("nn.Concat",
                           {**mods(m.layers), "dimension": m.dim + 1})
    if isinstance(m, nn.ConcatTable):
        return TorchObject("nn.ConcatTable", mods(m.layers))
    if isinstance(m, nn.Sequential):
        return TorchObject("nn.Sequential", mods(m.layers))
    if isinstance(m, nn.CAddTable):
        return TorchObject("nn.CAddTable", {"train": bool(m.training)})
    if isinstance(m, nn.JoinTable):
        return TorchObject("nn.JoinTable", {"dimension": m.dim + 1})
    if isinstance(m, nn.Linear):
        t = {"weight": np.asarray(m.weight)}
        if "bias" in m.__dict__["_params"]:
            t["bias"] = np.asarray(m.bias)
        return TorchObject("nn.Linear", t)
    if type(m) in (nn.SpatialConvolution, nn.SpatialShareConvolution):
        t = {"nInputPlane": m.n_input_plane, "nOutputPlane": m.n_output_plane,
             "kW": m.kernel_w, "kH": m.kernel_h, "dW": m.stride_w,
             "dH": m.stride_h, "padW": m.pad_w, "padH": m.pad_h,
             "weight": np.asarray(m.weight)}
        if m.n_group != 1:
            t["groups"] = m.n_group  # no Lua-nn analogue; our reader honors it
        if m.with_bias:
            t["bias"] = np.asarray(m.bias)
        return TorchObject("nn.SpatialConvolution", t)
    if isinstance(m, nn.SpatialAveragePooling):
        return TorchObject("nn.SpatialAveragePooling", {
            "kW": m.kw, "kH": m.kh, "dW": m.dw, "dH": m.dh,
            "padW": m.pad_w, "padH": m.pad_h, "ceil_mode": m.ceil_mode,
            "count_include_pad": m.count_include_pad})
    if isinstance(m, nn.SpatialMaxPooling):
        return TorchObject("nn.SpatialMaxPooling", {
            "kW": m.kw, "kH": m.kh, "dW": m.dw, "dH": m.dh,
            "padW": m.pad_w, "padH": m.pad_h, "ceil_mode": m.ceil_mode})
    if type(m) is nn.ReLU:
        return TorchObject("nn.ReLU", {"inplace": False})
    if type(m) is nn.Tanh:
        return TorchObject("nn.Tanh", {})
    if type(m) is nn.Sigmoid:
        return TorchObject("nn.Sigmoid", {})
    if type(m) is nn.SoftMax:
        return TorchObject("nn.SoftMax", {})
    if type(m) is nn.LogSoftMax:
        return TorchObject("nn.LogSoftMax", {})
    if isinstance(m, nn.Dropout):
        return TorchObject("nn.Dropout", {"p": float(m.p)})
    if isinstance(m, nn.InferReshape):
        # no exact Lua-nn analogue (closest is dpnn); round-trips through
        # our own reader, like the reference writes BigDL-only layers
        return TorchObject("nn.InferReshape", {
            "size": np.asarray(m.size, np.int64),
            "batchMode": bool(m.batch_mode)})
    if isinstance(m, nn.Reshape):
        return TorchObject("nn.Reshape", {
            "size": np.asarray(m.size, np.int64),
            "nelement": int(np.prod(m.size))})
    if isinstance(m, nn.View):
        return TorchObject("nn.View", {
            "size": np.asarray(m.sizes, np.int64),
            "numElements": int(np.prod(m.sizes))})
    if isinstance(m, (nn.SpatialBatchNormalization, nn.BatchNormalization)):
        cls = "nn.SpatialBatchNormalization" \
            if isinstance(m, nn.SpatialBatchNormalization) \
            else "nn.BatchNormalization"
        t = {"nOutput": m.n_output, "eps": float(m.eps),
             "momentum": float(m.momentum), "affine": bool(m.affine),
             "running_mean": np.asarray(m.running_mean),
             "running_var": np.asarray(m.running_var),
             "train": bool(m.training)}
        if m.affine:
            t["weight"] = np.asarray(m.weight)
            t["bias"] = np.asarray(m.bias)
        return TorchObject(cls, t)
    if isinstance(m, nn.SpatialCrossMapLRN):
        return TorchObject("nn.SpatialCrossMapLRN", {
            "size": m.size, "alpha": float(m.alpha), "beta": float(m.beta),
            "k": float(m.k)})
    return None


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def load_torch(path: str, convert_modules: bool = True):
    """Load a ``.t7`` file (``TorchFile.scala:79 load``).  ``nn.*`` objects
    convert to bigdl_tpu modules when possible; tensors become
    :class:`TorchTensor` (``.array`` for the ndarray); tables become
    dicts."""
    from bigdl_tpu.utils.file import load as file_load

    r = _Reader(file_load(path), convert_modules)
    return r.read()


def save_torch(obj, path: str, overwrite: bool = False):
    """Save a module / tensor / number / table to ``.t7``
    (``TorchFile.scala:90 save``)."""
    from bigdl_tpu.utils.file import save as file_save

    w = _Writer()
    w.write(obj)
    file_save(b"".join(w.out), path, overwrite)
